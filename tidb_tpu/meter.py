"""Continuous per-tenant resource metering: who is consuming the device.

ROADMAP items 1 and 2 are blocked on a measurement question the system
could not answer before this module: PR 14's traces attribute ONE
statement's microseconds (and only for retained trees), metrics.py
holds process-cumulative counters with no tenant dimension, and
memtrack accounts bytes *held*, not work *done*. The meter is the
missing ledger of work: device busy-time, host-fallback time, encoded/
decoded bytes dispatched, rows served, scheduler slot-wait and
admission-wait — attributed per statement and rolled up memtrack-style:

    statement meter -> session meter -> user meter -> SERVER

Charges walk the parent chain exactly like memtrack.consume (one
per-node lock at a time, never nested), so the SERVER node is the total
and each tenant level is a consistent slice of it. Work metered on a
thread with NO meter installed (internal bookkeeping sessions, library
use) charges the SERVER node alone — the gap between the SERVER total
and the per-session sum is the *attribution coverage* BENCH audits
(`utilization.attribution_coverage`, pinned to [0.9, 1.1]).

Instrumentation sites are the chokepoints every device dispatch already
passes through: `sched.device_slot` (sync kernel sites: copr aggs,
escalated retries, mesh collectives), `ops/runtime.pipeline_map`
(dispatch/finalize of every pipelined superchunk), the two
`host.fallback` regions (store/copr.py, ops/hybrid.py), the admission
controller's wait, and `runtime_stats.note_bytes_touched`. The
disarmed cost is one thread-local read per note; the armed cost is a
handful of integer adds under short per-node locks per *dispatch* (not
per row) — always-on by design, like trace.py's skeleton spans.

Cross-thread propagation follows the house pattern (runtime_stats
collector, memtrack tracker, trace span): the coprocessor fan-out
captures `current()` and re-installs it inside every pool/stream
worker with `metering()`, so storage-side dispatches credit the
session that issued them.

Retention: session meters are KEPT (bounded, LRU) after the session
closes — unlike memtrack, the meter records work already done, and a
closed session's device-seconds must still reconcile against the
SERVER total. Statement totals fold into a bounded per-digest table at
statement end, so `GET /top` can rank statement shapes without ever
minting a per-statement Prometheus series (the metric-cardinality lint
enforces that split).

Surfaces: `information_schema.resource_usage`, SHOW PROCESSLIST's
DeviceTime/RowsSent columns, `GET /top`, the history sampler's derived
`tidb_tpu_device_utilization_ratio` gauge (tidb_tpu/metrics_history.py)
and BENCH's `utilization` blocks. See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict

__all__ = ["Meter", "SERVER", "session_meter", "session_closed",
           "statement_meter",
           "metering", "suspended", "current", "note", "note_device",
           "note_host_fallback", "note_slot_wait", "note_admission_wait",
           "note_bytes", "busy_section", "finish_statement",
           "roll_interval",
           "server_snapshot", "sessions_snapshot", "users_snapshot",
           "digests_snapshot", "top_sessions", "top_digests",
           "attributed_device_ns", "reset_for_tests"]

# the metered quantities, in snapshot/rollup order. All monotone
# cumulative counters — the meter has no release() because work done is
# never handed back.
FIELDS = ("device_ns", "host_fallback_ns", "slot_wait_ns",
          "admission_wait_ns", "bytes_encoded", "bytes_decoded_equiv",
          "rows_sent", "statements")

# retention bounds: closed sessions and digest rollups kept (LRU past
# the cap). Small fixed-size counter structs — ~200 bytes each, so the
# worst case is a few hundred KB, not worth a memtrack node.
_SESSIONS_CAP = 1024
_DIGESTS_CAP = 512


class Meter:
    """One node of the metering tree. Counters are monotone cumulative;
    `last_interval` is the delta the history sampler computed at its
    most recent roll (the "current interval" resource_usage reports)."""

    __slots__ = ("label", "parent", "user", "session_id", "closed",
                 "_mu",
                 "device_ns", "host_fallback_ns", "slot_wait_ns",
                 "admission_wait_ns", "bytes_encoded",
                 "bytes_decoded_equiv", "rows_sent", "statements",
                 "_last", "last_interval")

    def __init__(self, label: str, parent: "Meter | None" = None,
                 user: str = "", session_id: int = 0):
        self.label = label
        self.parent = parent
        self.user = user
        self.session_id = session_id
        self.closed = False     # session meters: the owner went away
        self._mu = threading.Lock()
        self.device_ns = 0              # guarded-by: _mu
        self.host_fallback_ns = 0       # guarded-by: _mu
        self.slot_wait_ns = 0           # guarded-by: _mu
        self.admission_wait_ns = 0      # guarded-by: _mu
        self.bytes_encoded = 0          # guarded-by: _mu
        self.bytes_decoded_equiv = 0    # guarded-by: _mu
        self.rows_sent = 0              # guarded-by: _mu
        self.statements = 0             # guarded-by: _mu
        self._last: dict | None = None        # guarded-by: _mu
        self.last_interval: dict | None = None  # guarded-by: _mu

    def add(self, device_ns: int = 0, host_fallback_ns: int = 0,
            slot_wait_ns: int = 0, admission_wait_ns: int = 0,
            bytes_encoded: int = 0, bytes_decoded_equiv: int = 0,
            rows_sent: int = 0, statements: int = 0) -> None:
        """Charge work to this node and every ancestor (one per-node
        lock at a time while walking up, never nested — the memtrack
        consume() discipline, so the walk can join no lock cycle)."""
        node = self
        while node is not None:
            with node._mu:
                node.device_ns += device_ns
                node.host_fallback_ns += host_fallback_ns
                node.slot_wait_ns += slot_wait_ns
                node.admission_wait_ns += admission_wait_ns
                node.bytes_encoded += bytes_encoded
                node.bytes_decoded_equiv += bytes_decoded_equiv
                node.rows_sent += rows_sent
                node.statements += statements
                nxt = node.parent
            node = nxt

    def totals(self) -> dict:
        with self._mu:
            return {f: getattr(self, f) for f in FIELDS}

    def roll(self) -> dict:
        """Compute this node's delta since the previous roll, store it
        as `last_interval`, and advance the baseline (the history
        sampler drives this once per cadence tick)."""
        with self._mu:
            cur = {f: getattr(self, f) for f in FIELDS}
            prev = self._last
            self.last_interval = cur if prev is None else \
                {f: cur[f] - prev[f] for f in FIELDS}
            self._last = cur
            return self.last_interval

    def snapshot(self) -> dict:
        with self._mu:
            out = {"label": self.label, "user": self.user,
                   "session_id": self.session_id}
            out.update((f, getattr(self, f)) for f in FIELDS)
            iv = self.last_interval
            out["interval"] = dict(iv) if iv else {f: 0 for f in FIELDS}
            return out


# process root: the total of all metered work, attributed or not —
# the denominator of BENCH's attribution_coverage
SERVER = Meter("server")

_reg_mu = threading.Lock()
_users: dict[str, Meter] = {}                       # guarded-by: _reg_mu
_sessions: "OrderedDict[int, Meter]" = OrderedDict()  # guarded-by: _reg_mu
_digests: "OrderedDict[str, dict]" = OrderedDict()    # guarded-by: _reg_mu


def _user_meter(user: str) -> Meter:
    key = user or "<anonymous>"
    with _reg_mu:
        m = _users.get(key)
        if m is None:
            m = _users[key] = Meter(f"user-{key}", parent=SERVER,
                                    user=key)
        return m


def session_meter(session_id: int, user: str) -> Meter:
    """Register (and return) the meter for one client session. Kept
    after the session closes (bounded past _SESSIONS_CAP) — a closed
    session's device-seconds still reconcile against the SERVER total.
    Eviction prefers CLOSED meters in registration order: a long-lived
    live session must never drop off resource_usage/attribution while
    idle closed ones are retained."""
    m = Meter(f"session-{session_id}", parent=_user_meter(user),
              user=user or "<anonymous>", session_id=session_id)
    with _reg_mu:
        _sessions[session_id] = m
        while len(_sessions) > _SESSIONS_CAP:
            victim = next((sid for sid, old in _sessions.items()
                           if old.closed), None)
            if victim is None:      # backstop: everything claims live
                _sessions.popitem(last=False)
            else:
                _sessions.pop(victim)
    return m


def session_closed(session_id: int) -> None:
    """Mark a session's meter evictable (driven by the Session's
    finalizer — the meter itself, and its rolled-up work, stay)."""
    with _reg_mu:
        m = _sessions.get(session_id)
    if m is not None:
        m.closed = True


def statement_meter(session: Meter | None) -> Meter:
    """A statement-scoped meter under `session` (or under SERVER when
    the session has none — library use). Unregistered: its numbers roll
    up live, and finish_statement() folds its totals into the digest
    table; the object itself just gets dropped."""
    return Meter("stmt", parent=session if session is not None else SERVER)


def finish_statement(stmt: Meter, digest: str,
                     digest_text: str = "") -> None:
    """Fold one finished statement's metered totals into the bounded
    per-digest rollup (the `GET /top` digest ranking)."""
    if not digest:
        return
    tot = stmt.totals()
    with _reg_mu:
        rec = _digests.get(digest)
        if rec is None:
            rec = _digests[digest] = {
                "digest": digest,
                "digest_text": digest_text[:256],
                **{f: 0 for f in FIELDS}}
        _digests.move_to_end(digest)
        for f in FIELDS:
            rec[f] += tot[f]
        while len(_digests) > _DIGESTS_CAP:
            _digests.popitem(last=False)


# -- thread-local installation (mirrors memtrack.tracking) -------------------

_tl = threading.local()


@contextlib.contextmanager
def metering(m: Meter | None):
    """Install `m` as this thread's active meter. Passing None nests
    transparently (keeps the outer meter) — the coprocessor fan-out
    re-installs the captured meter inside pool/stream workers with
    exactly this, like the memtrack tracker and the stats collector."""
    prev = getattr(_tl, "meter", None)
    _tl.meter = m if m is not None else prev
    try:
        yield _tl.meter
    finally:
        _tl.meter = prev


@contextlib.contextmanager
def suspended():
    """Hide the active meter (internal bookkeeping sessions run inside
    a client statement but must not bill the client's tenant — their
    work lands on the SERVER node as unattributed, which is the honest
    place for it)."""
    prev = getattr(_tl, "meter", None)
    _tl.meter = None
    try:
        yield
    finally:
        _tl.meter = prev


def current() -> Meter | None:
    return getattr(_tl, "meter", None)


def note(**fields) -> None:
    """Charge work against this thread's meter, falling back to the
    SERVER node so the process total never loses a nanosecond."""
    m = getattr(_tl, "meter", None)
    (m if m is not None else SERVER).add(**fields)


def _cover(ns: int) -> None:
    """Tell the enclosing busy_section (same thread) that `ns` of its
    interval is already billed, so it charges only the remainder."""
    frames = getattr(_tl, "frames", None)
    if frames:
        frames[-1][0] += ns


def note_device(ns: int) -> None:
    """Device busy-time: one dispatch/finalize interval at a
    sched.device_slot or pipeline_map site."""
    if ns > 0:
        note(device_ns=ns)
        _cover(ns)


def note_host_fallback(ns: int) -> None:
    if ns > 0:
        note(host_fallback_ns=ns)
        _cover(ns)


def note_slot_wait(ns: int) -> None:
    """Slot-wait time also covers any enclosing busy_section: a nested
    device_slot's acquire wait is idle time for this statement, and the
    outer finalize section must not re-bill it as device busy-time."""
    if ns > 0:
        note(slot_wait_ns=ns)
        _cover(ns)


def note_admission_wait(ns: int) -> None:
    if ns > 0:
        note(admission_wait_ns=ns)
        _cover(ns)


def note_bytes(encoded: int, decoded_equiv: int) -> None:
    if encoded or decoded_equiv:
        note(bytes_encoded=encoded, bytes_decoded_equiv=decoded_equiv)


class busy_section:
    """Bill one wall interval as device busy-time (or host-fallback
    time), MINUS whatever nested metered busy intervals already billed
    on this thread — a finalize whose escalation path re-enters
    sched.device_slot (or degrades a partition to host_hash_agg,
    which notes host-fallback) must not count the same nanoseconds
    twice, and the inner, finer-grained classification wins. `kind`
    ("device" | "host") may be reassigned before exit — pipeline_map
    only learns a token's path from dispatch()'s return value."""

    __slots__ = ("kind", "_t0")

    def __init__(self, kind: str = "device"):
        self.kind = kind
        self._t0 = 0

    def __enter__(self):
        frames = getattr(_tl, "frames", None)
        if frames is None:
            frames = _tl.frames = []
        frames.append([0])      # covered-ns accumulator for this frame
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter_ns() - self._t0
        covered = _tl.frames.pop()[0]
        own = dur - covered
        if own > 0:
            if self.kind == "host":
                note(host_fallback_ns=own)
            else:
                note(device_ns=own)
        # the parent frame sees this whole interval as billed
        _cover(max(dur, covered))
        return False


# -- interval roll + snapshots (history sampler / surfaces) ------------------


def roll_interval() -> None:
    """Advance every node's interval baseline — one call per history
    sampler tick, so `last_interval` across the tree describes the SAME
    wall window."""
    SERVER.roll()
    with _reg_mu:
        nodes = list(_users.values()) + list(_sessions.values())
    for m in nodes:
        m.roll()


def server_snapshot() -> dict:
    return SERVER.snapshot()


def sessions_snapshot() -> list[dict]:
    """Per-session meter snapshots (live AND retained-closed), session
    creation order."""
    with _reg_mu:
        nodes = list(_sessions.values())
    return [m.snapshot() for m in nodes]


def users_snapshot() -> list[dict]:
    with _reg_mu:
        nodes = list(_users.values())
    return [m.snapshot() for m in nodes]


def digests_snapshot() -> list[dict]:
    with _reg_mu:
        return [dict(rec) for rec in _digests.values()]


def top_sessions(n: int = 10) -> list[dict]:
    """Sessions ranked by device busy-time over the last sampler
    interval, cumulative device-time as the tiebreak (and the ranking
    itself when the sampler has not rolled yet)."""
    snaps = sessions_snapshot()
    snaps.sort(key=lambda s: (s["interval"].get("device_ns", 0),
                              s["device_ns"]), reverse=True)
    return snaps[:n]


def top_digests(n: int = 10) -> list[dict]:
    recs = digests_snapshot()
    recs.sort(key=lambda r: r["device_ns"], reverse=True)
    return recs[:n]


def attributed_device_ns() -> int:
    """Sum of per-session device busy-time — BENCH's coverage numerator
    (the SERVER node's device_ns is the denominator)."""
    with _reg_mu:
        nodes = list(_sessions.values())
    return sum(m.device_ns for m in nodes)


def reset_for_tests() -> None:
    """Fresh tree (test isolation)."""
    global SERVER
    SERVER = Meter("server")
    with _reg_mu:
        _users.clear()
        _sessions.clear()
        _digests.clear()
