"""Scan-range extraction from predicates over index prefix columns.

Reference: /root/reference/util/ranger/ — `BuildRange` (ranger.go:387),
`Range` (types.go:28). Given the conjuncts of a WHERE clause and an index's
column list (as offsets into the reader schema), produce the list of
key ranges the scan must visit plus the score of how much of the predicate
the index consumed.

Simplifications vs the reference (documented, revisit with CBO):
* EQ/IN chains over the index prefix, then one interval on the next column
  (the reference's point-then-interval shape; ranger.go builds the same).
* All original conjuncts are retained as residual filters — rows inside
  the ranges still satisfy them, so correctness never depends on the
  detachment being exact (the reference splits accessConds/filterConds;
  we trade one redundant vectorized compare for simplicity).
* Constants are converted to the column's datum space only when exact
  (no silent rounding); inexact conversions leave the conjunct unused.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from tidb_tpu import codec, tablecodec
from tidb_tpu.expression import ColumnRef, Constant, Expression, Op, ScalarFunc
from tidb_tpu.kv import KVRange
from tidb_tpu.sqltypes import EvalType, FieldType

__all__ = ["DatumRange", "AccessPath", "detach_index_conditions",
           "detach_handle_conditions", "index_ranges_to_kv",
           "handle_ranges_to_kv", "MAX_RANGES"]

MAX_RANGES = 128  # cap the IN-list cross product; fall back to full scan


@dataclass
class DatumRange:
    """One scan range in datum space. `low`/`high` share a common prefix of
    point (EQ) values; the last element may differ (interval column).
    Open bounds are expressed by shorter lists + *_unbounded flags."""

    low: list = field(default_factory=list)
    high: list = field(default_factory=list)
    low_incl: bool = True
    high_incl: bool = True
    low_unbounded: bool = False    # no lower bound beyond the eq prefix
    high_unbounded: bool = False


@dataclass
class AccessPath:
    """Result of matching conjuncts against one index/handle column list."""

    ranges: list            # list[DatumRange]
    eq_count: int           # EQ/IN-consumed prefix columns
    has_interval: bool      # an interval condition on the next column
    consumed: list          # conjunct Expressions the ranges encode

    @property
    def score(self) -> tuple:
        return (self.eq_count, 1 if self.has_interval else 0)

    @property
    def useful(self) -> bool:
        return self.eq_count > 0 or self.has_interval


def _col_cmp_const(e: Expression, offset: int):
    """Match `col <op> const` / `const <op> col` on the given column offset.
    -> (op, const_value, const_ft) with op normalized to column-on-left,
    or None."""
    if not isinstance(e, ScalarFunc):
        return None
    flip = {Op.LT: Op.GT, Op.LE: Op.GE, Op.GT: Op.LT, Op.GE: Op.LE,
            Op.EQ: Op.EQ}
    if e.op in (Op.EQ, Op.LT, Op.LE, Op.GT, Op.GE) and len(e.args) == 2:
        a, b = e.args
        if isinstance(a, ColumnRef) and a.idx == offset and \
                isinstance(b, Constant) and b.value is not None:
            return e.op, b.value, b.ft
        if isinstance(b, ColumnRef) and b.idx == offset and \
                isinstance(a, Constant) and a.value is not None:
            return flip[e.op], a.value, a.ft
    if e.op == Op.IN and len(e.args) == 1 and \
            isinstance(e.extra, (list, tuple)) and e.extra:
        a = e.args[0]
        if isinstance(a, ColumnRef) and a.idx == offset and all(
                x is not None for x in e.extra):
            return Op.IN, list(e.extra), None
    if e.op == Op.IS_NULL and len(e.args) == 1:
        a = e.args[0]
        if isinstance(a, ColumnRef) and a.idx == offset:
            return Op.IS_NULL, None, None
    return None


def _exact_datum(v, ft: FieldType):
    """Convert a constant to the column's KV datum space, or None when the
    conversion is inexact (so range building must skip the conjunct).
    Returns (datum, cmp_bias): bias -1/+1 marks 'datum is strictly
    below/above the true constant' for inexact int bounds."""
    from tidb_tpu.table import encode_datum_for_col
    if v is None:
        return None
    et = ft.eval_type
    _I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
    if et == EvalType.INT or et == EvalType.DATETIME:
        if isinstance(v, bool):
            return int(v), 0
        if isinstance(v, int):
            if not (_I64_MIN <= v <= _I64_MAX):
                return None      # un-encodable: leave to residual filter
            return v, 0
        if isinstance(v, float):
            import math
            if not (_I64_MIN <= v <= _I64_MAX):
                return None
            if float(v).is_integer():
                return int(v), 0
            return math.floor(v), -1   # floor(v) < v always
        if et == EvalType.DATETIME and isinstance(v, str):
            try:
                return encode_datum_for_col(v, ft), 0
            except Exception:  # noqa: BLE001
                return None
        return None
    if et == EvalType.REAL:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v), 0
        return None
    if et == EvalType.DECIMAL:
        # floor to the column's scale; bias -1 marks an inexact (rounded-
        # down) bound so interval code treats it like the int floor case
        import decimal as _d
        import math
        try:
            dv = _d.Decimal(str(v)) if not isinstance(v, _d.Decimal) else v
        except _d.InvalidOperation:
            return None
        scaled_exact = dv.scaleb(ft.frac)
        scaled = int(math.floor(scaled_exact))
        if not (_I64_MIN <= scaled <= _I64_MAX):
            return None
        return (ft.frac, scaled), 0 if scaled == scaled_exact else -1
    if et == EvalType.STRING:
        if isinstance(v, (str, bytes)):
            return v, 0
        return None
    return None


def detach_index_conditions(conjuncts: list, offsets: list[int],
                            fts: list[FieldType]) -> AccessPath:
    """Match conjuncts against index columns (schema `offsets`, in index
    order). Builds the point-prefix + final-interval range set."""
    points: list[list] = []     # per consumed prefix column: datum choices
    consumed: list = []
    eq_count = 0
    for off, ft in zip(offsets, fts):
        found = None
        for c in conjuncts:
            if c in consumed:
                continue
            m = _col_cmp_const(c, off)
            if m is None:
                continue
            op, v, _cft = m
            if op == Op.EQ:
                d = _exact_datum(v, ft)
                if d is None or d[1] != 0:
                    continue
                found = ([d[0]], c)
                break
            if op == Op.IS_NULL:
                found = ([None], c)
                break
            if op == Op.IN:
                ds = [_exact_datum(x, ft) for x in v]
                if any(d is None or d[1] != 0 for d in ds):
                    continue
                found = (sorted({d[0] for d in ds},
                                key=lambda x: codec.encode_datum(x)), c)
                break
        if found is None:
            break
        vals, cond = found
        points.append(vals)
        consumed.append(cond)
        eq_count += 1

    # interval on the next column
    low_v = high_v = None
    low_incl = high_incl = True
    has_interval = False
    if eq_count < len(offsets):
        off, ft = offsets[eq_count], fts[eq_count]
        for c in conjuncts:
            if c in consumed:
                continue
            m = _col_cmp_const(c, off)
            if m is None or m[0] in (Op.EQ, Op.IN, Op.IS_NULL):
                continue
            op, v, _cft = m
            d = _exact_datum(v, ft)
            if d is None:
                continue
            dv, bias = d
            if op in (Op.GT, Op.GE):
                # col > v: with floor bias (dv < v), col > dv is implied but
                # looser; keep exclusive-at-floor which stays correct
                incl = (op == Op.GE) and bias == 0
                cand = (dv, incl)
                if low_v is None or _bound_tighter_low(cand, (low_v, low_incl)):
                    low_v, low_incl = cand
                has_interval = True
                consumed.append(c)
            elif op in (Op.LT, Op.LE):
                # col < v with floor bias: col <= floor(v) — inclusive stays
                # correct (floor(v) < v)
                incl = (op == Op.LE) or bias != 0
                cand = (dv, incl)
                if high_v is None or _bound_tighter_high(cand, (high_v, high_incl)):
                    high_v, high_incl = cand
                has_interval = True
                consumed.append(c)

    n_ranges = 1
    for p in points:
        n_ranges *= len(p)
    if n_ranges > MAX_RANGES:
        return AccessPath(ranges=[], eq_count=0, has_interval=False,
                          consumed=[])

    ranges: list[DatumRange] = []
    for combo in itertools.product(*points) if points else [()]:
        prefix = list(combo)
        if has_interval:
            r = DatumRange(
                low=prefix + ([low_v] if low_v is not None else []),
                high=prefix + ([high_v] if high_v is not None else []),
                low_incl=low_incl, high_incl=high_incl,
                low_unbounded=low_v is None,
                high_unbounded=high_v is None)
            # empty interval (low > high) -> skip
            if low_v is not None and high_v is not None:
                kl = codec.encode_datum(low_v)
                kh = codec.encode_datum(high_v)
                if kl > kh or (kl == kh and not (low_incl and high_incl)):
                    continue
        else:
            r = DatumRange(low=prefix, high=list(prefix))
        ranges.append(r)
    # _ci index columns store casefolded keys (table/_index_values):
    # fold the range bounds to match
    if any(ft.is_ci for ft in fts):
        from tidb_tpu.sqltypes import collation_key
        for r in ranges:
            for vals in (r.low, r.high):
                for i in range(min(len(vals), len(fts))):
                    if fts[i].is_ci and isinstance(vals[i], str):
                        vals[i] = collation_key(vals[i])
    return AccessPath(ranges=ranges, eq_count=eq_count,
                      has_interval=has_interval, consumed=consumed)


def _bound_tighter_low(cand, cur) -> bool:
    kc, kcur = codec.encode_datum(cand[0]), codec.encode_datum(cur[0])
    if kc != kcur:
        return kc > kcur
    return cur[1] and not cand[1]   # exclusive beats inclusive


def _bound_tighter_high(cand, cur) -> bool:
    kc, kcur = codec.encode_datum(cand[0]), codec.encode_datum(cur[0])
    if kc != kcur:
        return kc < kcur
    return cur[1] and not cand[1]


def detach_handle_conditions(conjuncts: list, offset: int) -> AccessPath:
    """Integer ranges over the pk-is-handle column."""
    from tidb_tpu.sqltypes import new_int_field
    path = detach_index_conditions(conjuncts, [offset], [new_int_field()])
    return path


# -- range -> KV key materialization ----------------------------------------


def index_ranges_to_kv(table_id: int, index_id: int,
                       ranges: list[DatumRange]) -> list[KVRange]:
    prefix = tablecodec.index_prefix(table_id, index_id)
    out = []
    for r in ranges:
        if r.low == r.high and not r.low_unbounded and not r.high_unbounded \
                and len(r.low) == len(r.high) and r.low_incl and r.high_incl:
            p = prefix + codec.encode_key(r.low)
            out.append(KVRange(p, codec.prefix_next(p)))
            continue
        # low bound
        low = prefix + codec.encode_key(r.low)
        if r.low_unbounded:
            # skip NULLs: every non-NULL datum flag sorts after NIL (0x00)
            low = low + bytes([codec.NIL_FLAG + 1])
        elif not r.low_incl:
            low = codec.prefix_next(low)
        # high bound
        high = prefix + codec.encode_key(r.high)
        if r.high_unbounded or r.high_incl:
            high = codec.prefix_next(high)
        if low < high:
            out.append(KVRange(low, high))
    return out


def handle_ranges_to_kv(table_id: int, ranges: list[DatumRange]
                        ) -> list[KVRange] | None:
    """Record-key ranges from pk-is-handle DatumRanges. Returns None when a
    range bound is not an int (planner falls back to full scan)."""
    out = []
    for r in ranges:
        lo_v = r.low[0] if r.low else None
        hi_v = r.high[0] if r.high else None
        if (lo_v is not None and not isinstance(lo_v, int)) or \
                (hi_v is not None and not isinstance(hi_v, int)):
            return None
        if lo_v is None and not r.low_unbounded and r.low == r.high:
            # IS NULL point on a NOT NULL pk: empty
            continue
        lo = lo_v if lo_v is not None else -(1 << 63)
        if not r.low_incl and lo_v is not None:
            if lo == (1 << 63) - 1:
                continue
            lo += 1
        start = tablecodec.record_key(table_id, lo)
        if hi_v is None:
            end = codec.prefix_next(tablecodec.record_prefix(table_id))
        else:
            hi = hi_v
            if r.high_incl:
                if hi == (1 << 63) - 1:
                    end = codec.prefix_next(
                        tablecodec.record_prefix(table_id))
                else:
                    end = tablecodec.record_key(table_id, hi + 1)
            else:
                end = tablecodec.record_key(table_id, hi)
        if start < end:
            out.append(KVRange(start, end))
    out.sort(key=lambda r: r.start)
    return out
