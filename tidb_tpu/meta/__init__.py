"""Schema metadata on the KV plane.

Reference: /root/reference/meta/meta.go:55-178, layered on structure/
TxStructure exactly as the reference is: databases live in one "DBs"
hash (dbID -> DBInfo json), each database's tables in a "DB:{id}" hash
(tableID -> TableInfo), counters in strings, the DDL job queue in a
list, DDL history in a hash (meta.go:443-457 EnQueue/DeQueue/history).
Every op runs inside the caller's transaction so metadata mutations
commit atomically with schema version bumps.

All structure keys live under the "m" namespace, disjoint from table
data ("t..." keys)."""

from __future__ import annotations

import json

from tidb_tpu import kv
from tidb_tpu.schema.model import DBInfo, TableInfo
from tidb_tpu.structure import TxStructure

__all__ = ["Meta", "MetaError"]


class MetaError(Exception):
    pass


def _f(n: int) -> bytes:
    return b"%020d" % n


class Meta:
    """Meta operations inside one kv.Transaction (like the reference, every
    meta op set runs in its caller's txn for atomicity with schema version
    bumps)."""

    NEXT_ID_KEY = b"NextGlobalID"
    SCHEMA_VERSION_KEY = b"SchemaVersion"
    DBS_KEY = b"DBs"
    JOB_LIST_KEY = b"DDLJobList"
    JOB_HISTORY_KEY = b"DDLJobHistory"
    SCHEMA_DIFF_KEY = b"SchemaDiffs"
    DELETE_RANGE_KEY = b"DeleteRanges"

    def __init__(self, txn: kv.Transaction):
        self.txn = txn
        self.t = TxStructure(txn, prefix=b"m")

    # -- id allocation -------------------------------------------------------

    def gen_global_id(self) -> int:
        return self.t.inc(self.NEXT_ID_KEY)

    def gen_schema_version(self) -> int:
        """Ref: meta.go:177 GenSchemaVersion."""
        return self.t.inc(self.SCHEMA_VERSION_KEY)

    def schema_version(self) -> int:
        return self.t.get_int(self.SCHEMA_VERSION_KEY)

    # -- auto increment ------------------------------------------------------

    def gen_auto_id(self, table_id: int, step: int) -> tuple[int, int]:
        """Allocate [base+1, base+step]; returns (first, last).
        Ref: meta/autoid batched allocator (autoid.go:36-46)."""
        last = self.t.inc(b"AutoID:" + _f(table_id), step)
        return last - step + 1, last

    def rebase_auto_id(self, table_id: int, at_least: int) -> None:
        key = b"AutoID:" + _f(table_id)
        if at_least > self.t.get_int(key):
            self.t.set(key, b"%d" % at_least)

    # -- databases (ref: meta.go mDBs hash) ----------------------------------

    def create_database(self, db: DBInfo) -> None:
        if self.t.hget(self.DBS_KEY, _f(db.id)) is not None:
            raise MetaError(f"db {db.id} already exists")
        self.t.hset(self.DBS_KEY, _f(db.id), db.dumps())

    def drop_database(self, db_id: int) -> None:
        self.t.hdel(self.DBS_KEY, _f(db_id))
        self.t.hclear(b"DB:" + _f(db_id))

    def get_database(self, db_id: int) -> DBInfo | None:
        raw = self.t.hget(self.DBS_KEY, _f(db_id))
        return DBInfo.loads(raw) if raw else None

    def list_databases(self) -> list[DBInfo]:
        return [DBInfo.loads(v) for _f_, v in self.t.hgetall(self.DBS_KEY)]

    # -- tables (ref: meta.go mDBPrefix hash per db) -------------------------

    def create_table(self, db_id: int, tbl: TableInfo) -> None:
        if self.get_database(db_id) is None:
            raise MetaError(f"db {db_id} does not exist")
        if self.t.hget(b"DB:" + _f(db_id), _f(tbl.id)) is not None:
            raise MetaError(f"table {tbl.id} already exists")
        self.t.hset(b"DB:" + _f(db_id), _f(tbl.id), tbl.dumps())

    def update_table(self, db_id: int, tbl: TableInfo) -> None:
        self.t.hset(b"DB:" + _f(db_id), _f(tbl.id), tbl.dumps())

    def drop_table(self, db_id: int, table_id: int) -> None:
        self.t.hdel(b"DB:" + _f(db_id), _f(table_id))

    def get_table(self, db_id: int, table_id: int) -> TableInfo | None:
        raw = self.t.hget(b"DB:" + _f(db_id), _f(table_id))
        return TableInfo.loads(raw) if raw else None

    def list_tables(self, db_id: int) -> list[TableInfo]:
        return [TableInfo.loads(v)
                for _f_, v in self.t.hgetall(b"DB:" + _f(db_id))]

    # -- DDL job queue (ref: meta.go:443-457 EnQueue/DeQueue/history) --------

    JOB_SEQ_KEY = b"DDLJobSeq"

    def enqueue_job(self, job) -> None:
        job.seq = self.t.inc(self.JOB_SEQ_KEY)
        self.t.rpush(self.JOB_LIST_KEY, job.dumps())

    def first_job(self):
        from tidb_tpu.ddl.job import Job
        raw = self.t.lindex(self.JOB_LIST_KEY, 0)
        return Job.loads(raw) if raw else None

    def _job_index(self, job) -> int | None:
        from tidb_tpu.ddl.job import Job
        for i, raw in enumerate(self.t.litems(self.JOB_LIST_KEY)):
            if Job.loads(raw).seq == job.seq:
                return i
        return None

    def update_job(self, job) -> None:
        i = self._job_index(job)
        if i is None:
            raise MetaError(f"job seq {job.seq} not in queue")
        self.t.lset(self.JOB_LIST_KEY, i, job.dumps())

    def finish_job(self, job) -> None:
        """Move from queue to history (ref: job to history queue)."""
        i = self._job_index(job)
        if i is not None:
            self.t.lrem_at(self.JOB_LIST_KEY, i)
        self.t.hset(self.JOB_HISTORY_KEY, _f(job.id), job.dumps())

    def history_job(self, job_id: int):
        from tidb_tpu.ddl.job import Job
        raw = self.t.hget(self.JOB_HISTORY_KEY, _f(job_id))
        return Job.loads(raw) if raw else None

    # -- schema diffs (ref: model.SchemaDiff; consumed by the schema
    # validator and incremental infoschema reload) ---------------------------

    def set_schema_diff(self, version: int, table_ids: list[int]) -> None:
        self.t.hset(self.SCHEMA_DIFF_KEY, _f(version),
                    json.dumps(table_ids).encode())

    def schema_diff(self, version: int) -> list[int] | None:
        raw = self.t.hget(self.SCHEMA_DIFF_KEY, _f(version))
        return json.loads(raw) if raw else None

    # -- delete-range queue (ref: ddl/delete_range.go:51 inserts into
    # mysql.gc_delete_range; drained by the GC worker) -----------------------

    DR_SEQ_KEY = b"DeleteRangeSeq"

    def add_delete_range(self, job_id: int, start: bytes, end: bytes) -> None:
        seq = self.t.inc(self.DR_SEQ_KEY)
        # ts stays 0 until the job's txn COMMITS; the worker then seals the
        # range with a fresh timestamp (>= the drop's commit ts). GC only
        # drains sealed ranges whose seal ts <= safepoint, so snapshots
        # that still see the pre-drop schema can still read the data
        # (ref: gc_delete_range.ts, written after the job finishes).
        # Fields are job-prefixed so sealing is a per-job prefix scan; GC
        # re-seals orphans (job finished but seal crashed) so nothing leaks.
        rec = json.dumps({"job": job_id, "start": start.hex(),
                          "end": end.hex(), "ts": 0}).encode()
        self.t.hset(self.DELETE_RANGE_KEY, _f(job_id) + b"/" + _f(seq), rec)

    def seal_delete_ranges(self, job_id: int, ts: int) -> None:
        """Stamp a finished job's ranges as deletable once safepoint > ts."""
        for f, v in self.t.hscan_prefix(self.DELETE_RANGE_KEY,
                                        _f(job_id) + b"/"):
            o = json.loads(v)
            if not o["ts"]:
                o["ts"] = ts
                self.t.hset(self.DELETE_RANGE_KEY, f,
                            json.dumps(o).encode())

    def pending_delete_ranges(self
                              ) -> list[tuple[bytes, int, bytes, bytes, int]]:
        """-> [(queue_field, job_id, start, end, ts)]"""
        out = []
        for f, v in self.t.hgetall(self.DELETE_RANGE_KEY):
            o = json.loads(v)
            out.append((f, o["job"], bytes.fromhex(o["start"]),
                        bytes.fromhex(o["end"]), o.get("ts", 0)))
        return out

    def remove_delete_range(self, queue_field: bytes) -> None:
        self.t.hdel(self.DELETE_RANGE_KEY, queue_field)
