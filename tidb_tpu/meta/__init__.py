"""Schema metadata on the KV plane.

Reference: /root/reference/meta/meta.go:55-178 over structure/ (TxStructure
hashes). Layout under the "m" prefix:

    m_nextID                   -> global id allocator
    m_schemaVersion            -> global schema version counter
    m_dbs/{dbID}               -> DBInfo json
    m_db/{dbID}/{tableID}      -> TableInfo json
    m_autoid/{tableID}         -> auto-increment base
    m_ddljobs / m_ddlhistory   -> DDL job queues (ddl module)

All keys sort after table-data keys ("m" > "t" is false — "m" < "t", so the
meta range precedes table ranges; either way they are disjoint).
"""

from __future__ import annotations

import json

from tidb_tpu import kv
from tidb_tpu.schema.model import DBInfo, TableInfo

__all__ = ["Meta", "MetaError"]

_PREFIX = b"m_"


class MetaError(Exception):
    pass


def _db_key(db_id: int) -> bytes:
    return b"m_dbs/%020d" % db_id


def _table_key(db_id: int, table_id: int) -> bytes:
    return b"m_db/%020d/%020d" % (db_id, table_id)


def _table_prefix(db_id: int) -> bytes:
    return b"m_db/%020d/" % db_id


class Meta:
    """Meta operations inside one kv.Transaction (like the reference, every
    meta op set runs in its caller's txn for atomicity with schema version
    bumps)."""

    NEXT_ID_KEY = b"m_nextID"
    SCHEMA_VERSION_KEY = b"m_schemaVersion"

    def __init__(self, txn: kv.Transaction):
        self.txn = txn

    # -- id allocation -------------------------------------------------------

    def _bump(self, key: bytes, step: int = 1) -> int:
        raw = self.txn.get(key)
        cur = int(raw) if raw else 0
        cur += step
        self.txn.set(key, b"%d" % cur)
        return cur

    def gen_global_id(self) -> int:
        return self._bump(self.NEXT_ID_KEY)

    def gen_schema_version(self) -> int:
        """Ref: meta.go:177 GenSchemaVersion."""
        return self._bump(self.SCHEMA_VERSION_KEY)

    def schema_version(self) -> int:
        raw = self.txn.get(self.SCHEMA_VERSION_KEY)
        return int(raw) if raw else 0

    # -- auto increment ------------------------------------------------------

    def gen_auto_id(self, table_id: int, step: int) -> tuple[int, int]:
        """Allocate [base+1, base+step]; returns (first, last).
        Ref: meta/autoid batched allocator (autoid.go:36-46)."""
        key = b"m_autoid/%020d" % table_id
        raw = self.txn.get(key)
        base = int(raw) if raw else 0
        self.txn.set(key, b"%d" % (base + step))
        return base + 1, base + step

    def rebase_auto_id(self, table_id: int, at_least: int) -> None:
        key = b"m_autoid/%020d" % table_id
        raw = self.txn.get(key)
        base = int(raw) if raw else 0
        if at_least > base:
            self.txn.set(key, b"%d" % at_least)

    # -- databases -----------------------------------------------------------

    def create_database(self, db: DBInfo) -> None:
        key = _db_key(db.id)
        if self.txn.get(key) is not None:
            raise MetaError(f"db {db.id} already exists")
        self.txn.set(key, db.dumps())

    def drop_database(self, db_id: int) -> None:
        self.txn.delete(_db_key(db_id))
        for k, _ in list(self.txn.iter_range(_table_prefix(db_id),
                                             _table_prefix(db_id + 1))):
            self.txn.delete(k)

    def get_database(self, db_id: int) -> DBInfo | None:
        raw = self.txn.get(_db_key(db_id))
        return DBInfo.loads(raw) if raw else None

    def list_databases(self) -> list[DBInfo]:
        out = []
        for _k, v in self.txn.iter_range(b"m_dbs/", b"m_dbs0"):
            out.append(DBInfo.loads(v))
        return out

    # -- tables --------------------------------------------------------------

    def create_table(self, db_id: int, tbl: TableInfo) -> None:
        if self.get_database(db_id) is None:
            raise MetaError(f"db {db_id} does not exist")
        key = _table_key(db_id, tbl.id)
        if self.txn.get(key) is not None:
            raise MetaError(f"table {tbl.id} already exists")
        self.txn.set(key, tbl.dumps())

    def update_table(self, db_id: int, tbl: TableInfo) -> None:
        self.txn.set(_table_key(db_id, tbl.id), tbl.dumps())

    def drop_table(self, db_id: int, table_id: int) -> None:
        self.txn.delete(_table_key(db_id, table_id))

    def get_table(self, db_id: int, table_id: int) -> TableInfo | None:
        raw = self.txn.get(_table_key(db_id, table_id))
        return TableInfo.loads(raw) if raw else None

    def list_tables(self, db_id: int) -> list[TableInfo]:
        out = []
        for _k, v in self.txn.iter_range(_table_prefix(db_id),
                                         _table_prefix(db_id + 1)):
            out.append(TableInfo.loads(v))
        return out

    # -- DDL job queue (ref: meta.go:443-457 EnQueue/DeQueue/history) --------

    JOB_SEQ_KEY = b"m_ddlJobSeq"

    @staticmethod
    def _job_key(seq: int) -> bytes:
        return b"m_ddlJobQ/%020d" % seq

    def enqueue_job(self, job) -> None:
        seq = self._bump(self.JOB_SEQ_KEY)
        job.seq = seq
        self.txn.set(self._job_key(seq), job.dumps())

    def first_job(self):
        from tidb_tpu.ddl.job import Job
        for _k, v in self.txn.iter_range(b"m_ddlJobQ/", b"m_ddlJobQ0"):
            return Job.loads(v)
        return None

    def update_job(self, job) -> None:
        self.txn.set(self._job_key(job.seq), job.dumps())

    def finish_job(self, job) -> None:
        """Move from queue to history (ref: job to history queue)."""
        self.txn.delete(self._job_key(job.seq))
        self.txn.set(b"m_ddlHist/%020d" % job.id, job.dumps())

    def history_job(self, job_id: int):
        from tidb_tpu.ddl.job import Job
        raw = self.txn.get(b"m_ddlHist/%020d" % job_id)
        return Job.loads(raw) if raw else None

    # -- schema diffs (ref: model.SchemaDiff; consumed by the schema
    # validator and incremental infoschema reload) ---------------------------

    def set_schema_diff(self, version: int, table_ids: list[int]) -> None:
        self.txn.set(b"m_schemaDiff/%020d" % version,
                     json.dumps(table_ids).encode())

    def schema_diff(self, version: int) -> list[int] | None:
        raw = self.txn.get(b"m_schemaDiff/%020d" % version)
        return json.loads(raw) if raw else None

    # -- delete-range queue (ref: ddl/delete_range.go:51 inserts into
    # mysql.gc_delete_range; drained by the GC worker) -----------------------

    DR_SEQ_KEY = b"m_drSeq"

    def add_delete_range(self, job_id: int, start: bytes, end: bytes) -> None:
        seq = self._bump(self.DR_SEQ_KEY)
        # ts stays 0 until the job's txn COMMITS; the worker then seals the
        # range with a fresh timestamp (>= the drop's commit ts). GC only
        # drains sealed ranges whose seal ts <= safepoint, so snapshots
        # that still see the pre-drop schema can still read the data
        # (ref: gc_delete_range.ts, written after the job finishes).
        # Keyed by job id so sealing is a per-job prefix scan; GC re-seals
        # orphans (job finished but seal crashed) so nothing leaks.
        rec = json.dumps({"job": job_id, "start": start.hex(),
                          "end": end.hex(), "ts": 0}).encode()
        self.txn.set(b"m_deleteRange/%020d/%020d" % (job_id, seq), rec)

    def seal_delete_ranges(self, job_id: int, ts: int) -> None:
        """Stamp a finished job's ranges as deletable once safepoint > ts."""
        prefix = b"m_deleteRange/%020d/" % job_id
        for k, v in self.txn.iter_range(prefix, prefix[:-1] + b"0"):
            o = json.loads(v)
            if not o["ts"]:
                o["ts"] = ts
                self.txn.set(k, json.dumps(o).encode())

    def pending_delete_ranges(self
                              ) -> list[tuple[bytes, int, bytes, bytes, int]]:
        """-> [(queue_key, job_id, start, end, ts)]"""
        out = []
        for k, v in self.txn.iter_range(b"m_deleteRange/", b"m_deleteRange0"):
            o = json.loads(v)
            out.append((k, o["job"], bytes.fromhex(o["start"]),
                        bytes.fromhex(o["end"]), o.get("ts", 0)))
        return out

    def remove_delete_range(self, queue_key: bytes) -> None:
        self.txn.delete(queue_key)
