"""Executors for mesh-routed plans (plan/mesh_route.py).

The reference's distributed aggregation pulls per-region partials onto one
root goroutine (/root/reference/distsql/distsql.go:92 fan-in feeding
executor/aggregate.go); here the heavy reduction happens ON the device
plane (ops/meshagg.py, ops/meshjoin.py) and the host only merges the
already tiny per-statement group tables and formats rows.

One pipeline: the streaming path is the SAME superchunk_batches +
pipeline_map machinery as the single-chip executors (executor/__init__.py
_superchunk_partials) — pipeline_map owns the dispatch slots, meter
sections, trace spans, failpoint seams and the abandoned-token drain;
this module only supplies the dispatch/finalize closures and their
device-ledger charges. Per-batch recovery: capacity overflow re-plans
the kernel and re-runs only that batch (group merging is associative —
already-merged batches stay valid); collisions or non-device
expressions aggregate that batch on the host.

Fallback contract: every mesh plan carries the original subtree; we
delegate to it when no process mesh is active, when expressions fail
device validation, on group-capacity overflow past the escalation cap,
on hash collisions, or on non-unique dimension build keys."""

from __future__ import annotations

import itertools
import time
from collections import OrderedDict

from tidb_tpu import config as sysconf
from tidb_tpu import devplane, memtrack, profiler, runtime_stats, sched, trace
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.ops import runtime as op_runtime
from tidb_tpu.ops.hashagg import CapacityError, CollisionError, HashAggregator
from tidb_tpu.ops.hostagg import host_hash_agg
from tidb_tpu.ops.meshagg import MeshAggKernel
from tidb_tpu.ops.meshjoin import (BuildError, LookupSpec,
                                   MeshLookupAggKernel, _BuildTable,
                                   host_lookup_agg)
from tidb_tpu.ops.runtime import bucket_size, superchunk_batches
from tidb_tpu.util import failpoint

__all__ = ["MeshAggExec", "MeshLookupAggExec", "stream_stats",
           "reset_stream_stats"]

# Streaming telemetry (tests + metrics assert bounded buffering and that
# the dispatch-ahead overlap actually happened).
_STREAM_STATS = {"streams": 0, "batches": 0, "host_batches": 0,
                 "max_batch_rows": 0, "overlapped_launches": 0}


def stream_stats() -> dict:
    return dict(_STREAM_STATS)


def reset_stream_stats() -> None:
    for k in _STREAM_STATS:
        _STREAM_STATS[k] = 0

# Initial per-chip group-table capacity; on overflow the executor re-plans
# the kernel once with 2x the observed distinct count (the re-plan the
# single-chip kernel docstring promises), then falls back to the host.
DEFAULT_CAPACITY = 4096
MAX_CAPACITY = 1 << 20

# kernel reuse across executions of cached plans: jit programs are per
# (structure, capacity); keyed by plan object identity (the entry pins
# the plan so its id cannot be recycled) PLUS the plane identity — the
# mesh generation and its structural fingerprint (axis, device count,
# platform), so a 1-chip and an 8-chip executable for the same plan can
# never collide (plan_fingerprint-keyed caches fold the same identity in
# via ops/hashagg.kernel_for).
_KERNELS: OrderedDict = OrderedDict()
_KERNELS_CAP = 64


def _kernel_cache_get(plan, capacity):
    key = (devplane.mesh_generation(),
           devplane.mesh_fingerprint(process=True), id(plan), capacity)
    hit = _KERNELS.get(key)
    if hit is not None and hit[0] is plan:
        _KERNELS.move_to_end(key)
        profiler.note_construct(profiler.profile_of(hit[1]), reuse=True)
        return hit[1]
    return None


def _kernel_cache_put(plan, capacity, kernel) -> None:
    gen = devplane.mesh_generation()
    # kernels from older mesh generations can never be hit again; drop
    # them now rather than pinning their replicated build tables
    for k in [k for k in _KERNELS if k[0] != gen]:
        del _KERNELS[k]
    key = (gen, devplane.mesh_fingerprint(process=True), id(plan), capacity)
    # one mesh-family compile unit per cache fill; the profile row keys
    # on the same plan identity + capacity the executable slot does
    prof = profiler.profile("mesh", f"plan{id(plan)}|{capacity}")
    profiler.note_construct(prof, reuse=False)
    kernel._profile = prof
    _KERNELS[key] = (plan, kernel)
    _KERNELS.move_to_end(key)
    while len(_KERNELS) > _KERNELS_CAP:
        _KERNELS.popitem(last=False)


def _concat_chunks(parts, schema) -> Chunk:
    big = Chunk.concat_all([p for p in parts if p.num_rows])
    if big is None:
        return Chunk([Column.from_values(c.ft, []) for c in schema.cols])
    return big


# Bounded registry of concat memos: each entry pins a table-sized host
# chunk (and transitively its device copy), so unlike the row-bounded
# ChunkCache these must be counted — a long-lived server executing many
# distinct cached plans would otherwise pin one table copy per plan.
_CONCATS: OrderedDict = OrderedDict()
_CONCATS_CAP = 16


def _concat_chunks_cached(holder, slot: str, parts, schema) -> Chunk:
    """Concat memoized on `holder` (a plan node): when the storage chunk
    cache serves the same per-region chunk objects again, the concatenated
    table — and therefore its memoized device copy — is reused, so a hot
    multi-region scan transfers zero bytes. Keyed by part identities; the
    parts are pinned in the cache entry so ids cannot be recycled. The
    global _CONCATS LRU bounds how many such table copies stay pinned."""
    key = tuple(id(p) for p in parts)
    cached = getattr(holder, slot, None)
    if cached is not None and cached[0] == key:
        reg_key = (id(holder), slot)
        if reg_key in _CONCATS:
            _CONCATS.move_to_end(reg_key)
        return cached[2]
    big = _concat_chunks(parts, schema)
    if len(parts) > 1:     # single-part concat returns the cached chunk
        setattr(holder, slot, (key, parts, big))
        reg_key = (id(holder), slot)
        _CONCATS[reg_key] = holder
        _CONCATS.move_to_end(reg_key)
        while len(_CONCATS) > _CONCATS_CAP:
            _rk, h = _CONCATS.popitem(last=False)
            if hasattr(h, _rk[1]):
                delattr(h, _rk[1])
    return big


def _emit_agg(plan, agg, executor_mod):
    results = agg.results()
    if not plan.group_exprs and not results:
        results = [((), [executor_mod._empty_agg_value(a)
                         for a in plan.aggs])]
    return executor_mod._agg_results_to_chunk(
        plan.schema, plan.num_group_cols, plan.aggs, results)


def _emit_results(plan, gr_or_none, executor_mod):
    agg = HashAggregator(plan.aggs, plan.group_exprs)
    if gr_or_none is not None:
        agg.update(gr_or_none)
    return _emit_agg(plan, agg, executor_mod)


def _fallback_reason(e) -> str:
    """Metric label for a per-batch host fallback — the REAL cause, not
    a blanket reason="mesh" (that label is gone: the plane shares the
    single-chip pipeline, so its fallbacks are the same taxonomy)."""
    if isinstance(e, CollisionError):
        return "collision"
    if isinstance(e, CapacityError):
        return "capacity"
    return "unsupported"


class _MeshExecBase:
    def __init__(self, plan):
        self.plan = plan
        self.schema = plan.schema

    def _fallback(self, ctx):
        from tidb_tpu.executor import build_executor
        return build_executor(self.plan.fallback).chunks(ctx)

    @staticmethod
    def _cached_scan(reader, ctx):
        """Pull a mesh operand scan through the NON-streaming copr path.

        Framed copr streaming re-encodes and re-decodes the table on
        every execution — resumable framing buys nothing for a
        plane-local scan feeding a sharded kernel, and it bypasses the
        columnar chunk cache entirely (measured: a warm TPC-H Q1 on the
        8-device plane spent ~14s of a ~14.5s statement re-draining
        stream frames). The whole-region decoded chunks served here are
        cache-hits on re-execution, which also keeps their object
        identities stable — the concat and device-transfer memos key on
        them. With the chunk cache OFF there is nothing to serve from,
        so framed streaming keeps its memory-bounded cold-scan role
        unchanged. The overlay is thread-local, so it shadows the
        session's tidb_tpu_copr_stream only while this generator is
        being pulled."""
        if not sysconf.chunk_cache_enabled():
            yield from reader.chunks(ctx)
            return
        it = reader.chunks(ctx)
        while True:
            with sysconf.session_overlay({"tidb_tpu_copr_stream": 0}):
                try:
                    c = next(it)
                except StopIteration:
                    return
            yield c

    def _whole_table_run(self, kernel, chunk, chip):
        """One whole-table kernel execution under the SAME trace-span
        pair and failpoint seams as the copr sync sites and the
        pipelined dispatch wrapper — a statement's span vocabulary must
        not depend on the mesh size that executed it."""
        prof = profiler.profile_of(kernel)
        nb = memtrack.device_put_bytes(chunk) if prof is not None else 0
        with profiler.dispatch_section(prof, nbytes=nb, plan=self.plan):
            with trace.span("dispatch", rows=chunk.num_rows, chip=chip):
                outs = kernel.launch(chunk, bucket=True)
            failpoint.eval("device/finalize")
            with trace.span("finalize"):
                return kernel.finish(outs, chunk)

    def _run_with_escalation(self, make_kernel, run):
        """Kernel-build + run with one capacity re-plan on overflow.
        The successful capacity sticks to the plan so re-executions of a
        cached plan start there instead of re-failing at the default.
        -> GroupResult or None (caller falls back)."""
        capacity = getattr(self.plan, "_mesh_capacity", DEFAULT_CAPACITY)
        for _attempt in (0, 1):
            try:
                kernel = _kernel_cache_get(self.plan, capacity)
                if kernel is None:
                    kernel = make_kernel(capacity)
                    _kernel_cache_put(self.plan, capacity, kernel)
                out = run(kernel)
                self.plan._mesh_capacity = capacity
                return out
            except CapacityError as e:
                profiler.note_escalation(profiler.profile_of(kernel))
                needed = getattr(e, "needed", None)
                if needed is None:
                    return None
                capacity = 1 << max(needed * 2 - 1, 1).bit_length()
                if capacity > MAX_CAPACITY:
                    return None
            except (CollisionError, BuildError, ValueError) as e:
                profiler.note_kernel_fallback(profiler.profile_of(kernel),
                                              _fallback_reason(e))
                return None
        return None

    def _stream_groups(self, superchunks, get_kernel, host_batch,
                       agg: HashAggregator) -> int:
        """Streaming aggregation on the shared pipeline: pipeline_map
        keeps tidb_tpu_pipeline_depth launches in flight (host→HBM
        transfer + async dispatch of superchunk k+1 overlap k's blocking
        readback) and owns the dispatch slots, meter sections, trace
        spans, failpoint seams, and the abandoned-token drain — exactly
        the machinery the single-chip executors ride. This method only
        supplies the dispatch/finalize closures: each in-flight launch
        holds its padded upload on the plan node's DEVICE ledger until
        its readback, and the merged agg state is tracked to the host
        ledger as it grows — so the mesh path answers to
        tidb_tpu_mem_quota_query and EXPLAIN ANALYZE `mem` like the
        single-chip pipeline. Returns the tracked state bytes for the
        caller to release once the results are emitted."""
        _STREAM_STATS["streams"] += 1
        plan = self.plan
        mt_node = memtrack.op_node(plan)
        state = {"kernel": None, "inflight": 0}
        try:
            state["kernel"] = get_kernel(
                getattr(plan, "_mesh_capacity", DEFAULT_CAPACITY))
        except (ValueError, BuildError):
            state["kernel"] = None      # every batch goes host

        def dispatch(sc):
            batch = sc.chunk
            _STREAM_STATS["batches"] += 1
            _STREAM_STATS["max_batch_rows"] = max(
                _STREAM_STATS["max_batch_rows"], batch.num_rows)
            k = state["kernel"]
            if k is None:
                # no device kernel for this plan (failed validation /
                # build): every batch aggregates on the host
                runtime_stats.note_fallback(plan, "unsupported")
                return None              # host path at finalize
            # device ledger: the sharded padded upload, sized from
            # shapes at dispatch; credited back at finalize
            db = memtrack.device_put_bytes(batch)
            memtrack.consume(plan, device=db)
            try:
                outs = k.launch(batch, bucket=True)
            except (ValueError, CollisionError, BuildError) as e:
                memtrack.release(plan, device=db)
                runtime_stats.note_fallback(plan, _fallback_reason(e))
                return None
            except BaseException:        # quota cancel / device fault
                memtrack.release(plan, device=db)
                raise
            if state["inflight"]:
                _STREAM_STATS["overlapped_launches"] += 1
            state["inflight"] += 1
            profiler.note_bytes(profiler.profile_of(k), nbytes=db)
            runtime_stats.note_superchunk(
                plan, batch.num_rows, bucket_size(max(batch.num_rows, 1)),
                sc.sources)
            return (k, outs, db)

        def finalize(sc, tok):
            batch = sc.chunk
            if tok is None:
                _STREAM_STATS["host_batches"] += 1
                return host_batch(batch)
            k, outs, db = tok
            state["inflight"] -= 1
            t0 = time.perf_counter_ns()
            reason = "capacity"
            try:
                return k.finish(outs, batch)
            except CapacityError as e:
                # per-batch capacity re-plan: re-run only THIS batch at
                # 2x the observed distinct count; later batches dispatch
                # with the escalated kernel
                profiler.note_escalation(profiler.profile_of(k))
                needed = getattr(e, "needed", None)
                while needed is not None:
                    cap2 = 1 << max(needed * 2 - 1, 1).bit_length()
                    if cap2 > MAX_CAPACITY:
                        break
                    try:
                        k2 = get_kernel(cap2)
                        gr = k2.finish(k2.launch(batch, bucket=True),
                                       batch)
                        state["kernel"] = k2
                        plan._mesh_capacity = cap2
                        return gr
                    except CapacityError as e2:
                        needed = getattr(e2, "needed", None)
                    except (CollisionError, BuildError, ValueError) as e2:
                        reason = _fallback_reason(e2)
                        break
            except (CollisionError, BuildError, ValueError) as e:
                reason = _fallback_reason(e)
            finally:
                memtrack.release(plan, device=db)
                runtime_stats.note_finalize_wait(
                    plan, time.perf_counter_ns() - t0)
            _STREAM_STATS["host_batches"] += 1
            runtime_stats.note_fallback(plan, reason)
            return host_batch(batch)

        tracked = 0
        try:
            for gr in op_runtime.pipeline_map(
                    superchunks, dispatch, finalize,
                    sysconf.pipeline_depth(), tracker=mt_node,
                    cost=lambda sc: memtrack.chunk_bytes(sc.chunk),
                    profile=profiler.profile_of(state["kernel"])):
                agg.update(gr)
                tracked = memtrack.track_to(plan, agg.approx_bytes(),
                                            tracked)
        except BaseException:
            # the caller's finally releases only what we report; on an
            # unwinding cancel nothing is reported, so credit here
            memtrack.release(plan, host=tracked)
            raise
        return tracked

    def _buffer_probe(self, it, limit):
        """Pull chunks until the probe proves larger than `limit`.
        -> (buffered parts, total rows, exhausted?)."""
        parts, total = [], 0
        for c in it:
            if c.num_rows:
                parts.append(c)
                total += c.num_rows
            if total > limit:
                return parts, total, False
        return parts, total, True


class MeshAggExec(_MeshExecBase):
    """Group-by aggregation on the device plane (Q1 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = devplane.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        plan = self.plan
        schema = plan.children[0].schema
        reader = ex.build_executor(plan.children[0])
        it = self._cached_scan(reader, ctx)
        limit = sysconf.stream_rows()
        parts, total, exhausted = self._buffer_probe(it, limit)

        def make(capacity):
            return MeshAggKernel(mesh, plan.filter_expr, plan.group_exprs,
                                 plan.aggs, capacity=capacity)

        if not exhausted:
            # probe larger than the streaming threshold: never materialize
            # it — feed the kernel ≤limit-row super-batches, dispatch-ahead
            def get_kernel(capacity):
                k = _kernel_cache_get(plan, capacity)
                if k is None:
                    k = make(capacity)
                    _kernel_cache_put(plan, capacity, k)
                return k

            agg = HashAggregator(plan.aggs, plan.group_exprs)
            tracked = 0
            try:
                # plane pipelines overlap async launches, so the device
                # time is the whole streaming region's wall (readback)
                with runtime_stats.device_section(plan):
                    tracked = self._stream_groups(
                        superchunk_batches(itertools.chain(parts, it),
                                           limit,
                                           tracker=memtrack.op_node(plan)),
                        get_kernel,
                        lambda b: host_hash_agg(b, plan.filter_expr,
                                                plan.group_exprs,
                                                plan.aggs),
                        agg)
                yield _emit_agg(plan, agg, ex)
            finally:
                memtrack.release(plan, host=tracked)
            return

        # small probe: whole-table path, memoized so hot re-executions of
        # a cached plan transfer zero bytes (the resident copy belongs to
        # the memo; the transfer watermark below is this query's charge)
        big = _concat_chunks_cached(plan, "_probe_cache", parts, schema)
        gr = None
        if big.num_rows:
            try:
                failpoint.eval("device/dispatch")
                with sched.device_slot() as slot, \
                        runtime_stats.device_section(plan,
                                                     errors=False), \
                        memtrack.device_scope(
                            plan, memtrack.device_put_bytes(big)):
                    gr = self._run_with_escalation(
                        make,
                        lambda k: self._whole_table_run(k, big, slot.chip))
            except failpoint.DispatchTimeoutError:
                raise   # statement already cancel-latched by the watchdog
            except failpoint.DeviceFaultError:
                sched.device_health().note_fault()
                runtime_stats.note_fallback(plan, "fault")
                yield from self._fallback(ctx)
                return
            if gr is None:
                yield from self._fallback(ctx)
                return
            # the whole table went down as ONE maximally-coalesced batch
            runtime_stats.note_superchunk(
                plan, big.num_rows, bucket_size(max(big.num_rows, 1)),
                max(len(parts), 1))
        yield _emit_results(plan, gr, ex)


class MeshLookupAggExec(_MeshExecBase):
    """Star join + aggregation on the device plane (Q3/Q5 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = devplane.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        plan = self.plan
        try:
            specs = []
            for lk in plan.lookups:
                bexec = ex.build_executor(lk.build_plan)
                bchunk = _concat_chunks_cached(lk, "_chunk_cache",
                                               list(self._cached_scan(
                                                   bexec, ctx)),
                                               lk.build_plan.schema)
                specs.append(LookupSpec(
                    key_exprs=lk.key_exprs, build_chunk=bchunk,
                    build_key_offsets=lk.build_key_offsets,
                    payload_offsets=lk.payload_offsets))
            builds = [self._build_table(d, sp)
                      for d, sp in zip(plan.lookups, specs)]
        except BuildError:
            # non-unique / NULL-heavy dimension keys: host join fallback
            yield from self._fallback(ctx)
            return

        def make(capacity):
            k = MeshLookupAggKernel(mesh, plan.filter_expr, specs,
                                    plan.group_exprs, plan.aggs,
                                    capacity=capacity, builds=builds)
            k.lookups = specs    # freshly built: skip the refresh rebuild
            return k

        def refresh(kernel):
            if kernel.lookups is not specs:
                # cached kernel: the traced program depends only on the
                # lookup STRUCTURE; swap in the current tables
                kernel.lookups = specs
                kernel.builds = builds
            return kernel

        reader = ex.build_executor(plan.children[0])
        it = self._cached_scan(reader, ctx)
        limit = sysconf.stream_rows()
        parts, total, exhausted = self._buffer_probe(it, limit)

        if not exhausted:
            # fact side larger than the streaming threshold: feed the
            # lookup-chain kernel in super-batches; dimension tables stay
            # resident on device across batches (device-memoized builds)
            def get_kernel(capacity):
                k = _kernel_cache_get(plan, capacity)
                if k is None:
                    k = make(capacity)
                    _kernel_cache_put(plan, capacity, k)
                return refresh(k)

            agg = HashAggregator(plan.aggs, plan.group_exprs)
            tracked = 0
            try:
                with runtime_stats.device_section(plan):
                    tracked = self._stream_groups(
                        superchunk_batches(itertools.chain(parts, it),
                                           limit,
                                           tracker=memtrack.op_node(plan)),
                        get_kernel,
                        lambda b: host_lookup_agg(b, plan.filter_expr,
                                                  specs, plan.group_exprs,
                                                  plan.aggs,
                                                  builds=builds),
                        agg)
                yield _emit_agg(plan, agg, ex)
            finally:
                memtrack.release(plan, host=tracked)
            return

        probe = _concat_chunks_cached(plan, "_probe_cache", parts,
                                      plan.children[0].schema)
        gr = None
        if probe.num_rows:
            try:
                failpoint.eval("device/dispatch")
                with sched.device_slot() as slot, \
                        runtime_stats.device_section(plan,
                                                     errors=False), \
                        memtrack.device_scope(
                            plan, memtrack.device_put_bytes(probe)):
                    gr = self._run_with_escalation(
                        make,
                        lambda kernel: self._whole_table_run(
                            refresh(kernel), probe, slot.chip))
            except failpoint.DispatchTimeoutError:
                raise   # statement already cancel-latched by the watchdog
            except failpoint.DeviceFaultError:
                sched.device_health().note_fault()
                runtime_stats.note_fallback(plan, "fault")
                yield from self._fallback(ctx)
                return
            if gr is None:
                yield from self._fallback(ctx)
                return
            runtime_stats.note_superchunk(
                plan, probe.num_rows, bucket_size(max(probe.num_rows, 1)),
                max(len(parts), 1))
        yield _emit_results(plan, gr, ex)

    @staticmethod
    def _build_table(desc, spec):
        """Host build-table prep (sort, exact-bit lanes, device upload)
        memoized on the plan's lookup descriptor: when the storage chunk
        cache serves the same dimension chunk object again, the prepared
        table (and its device copy) is reused as-is."""
        cached = getattr(desc, "_build_cache", None)
        if cached is not None and cached[0] is spec.build_chunk:
            return cached[1]
        bt = _BuildTable(spec)
        desc._build_cache = (spec.build_chunk, bt)
        return bt
