"""Executors for mesh-routed plans (plan/mesh_route.py).

The reference's distributed aggregation pulls per-region partials onto one
root goroutine (/root/reference/distsql/distsql.go:92 fan-in feeding
executor/aggregate.go); here the heavy reduction happens ON the mesh
(parallel/dist_agg.py, dist_join.py) and the host only merges the already
tiny per-statement group tables and formats rows.

Fallback contract: every mesh plan carries the original subtree; we
delegate to it when no process mesh is active, when expressions fail
device validation, on group-capacity overflow past the escalation cap,
on hash collisions, or on non-unique dimension build keys."""

from __future__ import annotations

import itertools
import time
from collections import OrderedDict, deque

import numpy as np

from tidb_tpu import config as sysconf
from tidb_tpu import memtrack, runtime_stats, sched
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.ops.hashagg import CapacityError, CollisionError, HashAggregator
from tidb_tpu.ops.hostagg import host_hash_agg
from tidb_tpu.ops.runtime import bucket_size, superchunk_batches
from tidb_tpu.parallel import config
from tidb_tpu.parallel.dist_agg import MeshAggKernel
from tidb_tpu.parallel.dist_join import (BuildError, LookupSpec,
                                         MeshLookupAggKernel,
                                         host_lookup_agg)

__all__ = ["MeshAggExec", "MeshLookupAggExec", "stream_stats",
           "reset_stream_stats"]

# Streaming telemetry (tests + metrics assert bounded buffering and that
# the double-buffered overlap actually happened).
_STREAM_STATS = {"streams": 0, "batches": 0, "host_batches": 0,
                 "max_batch_rows": 0, "overlapped_launches": 0}


def stream_stats() -> dict:
    return dict(_STREAM_STATS)


def reset_stream_stats() -> None:
    for k in _STREAM_STATS:
        _STREAM_STATS[k] = 0

# Initial per-chip group-table capacity; on overflow the executor re-plans
# the kernel once with 2x the observed distinct count (the re-plan the
# single-chip kernel docstring promises), then falls back to the host.
DEFAULT_CAPACITY = 4096
MAX_CAPACITY = 1 << 20

# kernel reuse across executions of cached plans: jit programs are per
# (structure, capacity); keyed by plan object identity (the entry pins
# the plan so its id cannot be recycled)
_KERNELS: OrderedDict = OrderedDict()
_KERNELS_CAP = 64


def _kernel_cache_get(plan, capacity):
    key = (config.mesh_generation(), id(plan), capacity)
    hit = _KERNELS.get(key)
    if hit is not None and hit[0] is plan:
        _KERNELS.move_to_end(key)
        return hit[1]
    return None


def _kernel_cache_put(plan, capacity, kernel) -> None:
    gen = config.mesh_generation()
    # kernels from older mesh generations can never be hit again; drop
    # them now rather than pinning their replicated build tables
    for k in [k for k in _KERNELS if k[0] != gen]:
        del _KERNELS[k]
    key = (gen, id(plan), capacity)
    _KERNELS[key] = (plan, kernel)
    _KERNELS.move_to_end(key)
    while len(_KERNELS) > _KERNELS_CAP:
        _KERNELS.popitem(last=False)


def _concat_chunks(parts, schema) -> Chunk:
    big = Chunk.concat_all([p for p in parts if p.num_rows])
    if big is None:
        return Chunk([Column.from_values(c.ft, []) for c in schema.cols])
    return big


# Bounded registry of concat memos: each entry pins a table-sized host
# chunk (and transitively its device copy), so unlike the row-bounded
# ChunkCache these must be counted — a long-lived server executing many
# distinct cached plans would otherwise pin one table copy per plan.
_CONCATS: OrderedDict = OrderedDict()
_CONCATS_CAP = 16


def _concat_chunks_cached(holder, slot: str, parts, schema) -> Chunk:
    """Concat memoized on `holder` (a plan node): when the storage chunk
    cache serves the same per-region chunk objects again, the concatenated
    table — and therefore its memoized device copy — is reused, so a hot
    multi-region scan transfers zero bytes. Keyed by part identities; the
    parts are pinned in the cache entry so ids cannot be recycled. The
    global _CONCATS LRU bounds how many such table copies stay pinned."""
    key = tuple(id(p) for p in parts)
    cached = getattr(holder, slot, None)
    if cached is not None and cached[0] == key:
        reg_key = (id(holder), slot)
        if reg_key in _CONCATS:
            _CONCATS.move_to_end(reg_key)
        return cached[2]
    big = _concat_chunks(parts, schema)
    if len(parts) > 1:     # single-part concat returns the cached chunk
        setattr(holder, slot, (key, parts, big))
        reg_key = (id(holder), slot)
        _CONCATS[reg_key] = holder
        _CONCATS.move_to_end(reg_key)
        while len(_CONCATS) > _CONCATS_CAP:
            _rk, h = _CONCATS.popitem(last=False)
            if hasattr(h, _rk[1]):
                delattr(h, _rk[1])
    return big


def _emit_agg(plan, agg, executor_mod):
    results = agg.results()
    if not plan.group_exprs and not results:
        results = [((), [executor_mod._empty_agg_value(a)
                         for a in plan.aggs])]
    return executor_mod._agg_results_to_chunk(
        plan.schema, plan.num_group_cols, plan.aggs, results)


def _emit_results(plan, gr_or_none, executor_mod):
    agg = HashAggregator(plan.aggs, plan.group_exprs)
    if gr_or_none is not None:
        agg.update(gr_or_none)
    return _emit_agg(plan, agg, executor_mod)


class _MeshExecBase:
    def __init__(self, plan):
        self.plan = plan
        self.schema = plan.schema

    def _fallback(self, ctx):
        from tidb_tpu.executor import build_executor
        return build_executor(self.plan.fallback).chunks(ctx)

    def _run_with_escalation(self, make_kernel, run):
        """Kernel-build + run with one capacity re-plan on overflow.
        The successful capacity sticks to the plan so re-executions of a
        cached plan start there instead of re-failing at the default.
        -> GroupResult or None (caller falls back)."""
        capacity = getattr(self.plan, "_mesh_capacity", DEFAULT_CAPACITY)
        for _attempt in (0, 1):
            try:
                kernel = _kernel_cache_get(self.plan, capacity)
                if kernel is None:
                    kernel = make_kernel(capacity)
                    _kernel_cache_put(self.plan, capacity, kernel)
                out = run(kernel)
                self.plan._mesh_capacity = capacity
                return out
            except CapacityError as e:
                needed = getattr(e, "needed", None)
                if needed is None:
                    return None
                capacity = 1 << max(needed * 2 - 1, 1).bit_length()
                if capacity > MAX_CAPACITY:
                    return None
            except (CollisionError, BuildError, ValueError):
                return None
        return None

    def _stream_groups(self, superchunks, get_kernel, host_batch,
                       agg: HashAggregator) -> int:
        """Streaming aggregation with dispatch-ahead: up to
        tidb_tpu_pipeline_depth superchunks' host→HBM transfers and
        kernel dispatches are issued (asynchronously) BEFORE the oldest
        one's blocking readback, so transfer/compute/readback overlap
        (BASELINE config 5; depth 2 = the classic double buffer).
        Per-batch recovery: capacity overflow re-plans the kernel and
        re-runs only that batch (group merging is associative —
        already-merged batches stay valid); collisions or non-device
        expressions aggregate that batch on the host.

        Memory: each in-flight launch holds its padded upload on the
        plan node's DEVICE ledger until its readback, and the merged agg
        state is tracked to the host ledger as it grows — so the mesh
        path answers to tidb_tpu_mem_quota_query and EXPLAIN ANALYZE
        `mem` like the single-chip pipeline. Returns the tracked state
        bytes for the caller to release once the results are emitted."""
        _STREAM_STATS["streams"] += 1
        capacity = getattr(self.plan, "_mesh_capacity", DEFAULT_CAPACITY)
        depth = sysconf.pipeline_depth()
        tracked = 0
        try:
            kernel = get_kernel(capacity)
        except (ValueError, BuildError):
            kernel = None

        def merge(gr) -> None:
            nonlocal tracked
            agg.update(gr)
            tracked = memtrack.track_to(self.plan, agg.approx_bytes(),
                                        tracked)

        def finish(pkernel, outs, batch, db, slot=None):
            nonlocal kernel, capacity
            t0 = time.perf_counter_ns()
            try:
                return pkernel.finish(outs, batch)
            except CapacityError as e:
                needed = getattr(e, "needed", None)
                while needed is not None:
                    cap2 = 1 << max(needed * 2 - 1, 1).bit_length()
                    if cap2 > MAX_CAPACITY:
                        break
                    capacity = cap2
                    try:
                        kernel = get_kernel(capacity)
                        gr = kernel.finish(
                            kernel.launch(batch, bucket=True), batch)
                        self.plan._mesh_capacity = capacity
                        return gr
                    except CapacityError as e2:
                        needed = getattr(e2, "needed", None)
                    except (CollisionError, BuildError, ValueError):
                        break
            except (CollisionError, BuildError, ValueError):
                pass
            finally:
                sched.device_scheduler().release(slot)
                if db:
                    memtrack.release(self.plan, device=db)
                # stall only (the enclosing device_section owns device
                # time — adding it here too would double-count)
                runtime_stats.note_pipeline_stall(
                    self.plan, time.perf_counter_ns() - t0)
            _STREAM_STATS["host_batches"] += 1
            runtime_stats.note_fallback(self.plan, "mesh")
            return host_batch(batch)

        pending: deque = deque()  # (kernel, outs, batch, bytes, slot)
        try:
            for sc in superchunks:
                batch = sc.chunk
                _STREAM_STATS["batches"] += 1
                _STREAM_STATS["max_batch_rows"] = max(
                    _STREAM_STATS["max_batch_rows"], batch.num_rows)
                outs = None
                db = 0
                slot = None
                launch_kernel = kernel   # finish() may rebind `kernel` on
                if launch_kernel is not None:   # a capacity re-plan; outs
                    # each in-flight mesh launch holds a global dispatch
                    # slot exactly like the single-chip pipeline — the
                    # mesh must not dodge the round-robin window
                    slot = sched.device_scheduler().acquire_or_bypass()
                    db = memtrack.device_put_bytes(batch)
                    try:
                        memtrack.consume(self.plan, device=db)
                    except BaseException:    # quota cancel mid-charge
                        sched.device_scheduler().release(slot)
                        raise
                    try:                 # read back by their own kernel
                        outs = launch_kernel.launch(batch, bucket=True)
                        if pending:
                            _STREAM_STATS["overlapped_launches"] += 1
                        runtime_stats.note_superchunk(
                            self.plan, batch.num_rows,
                            bucket_size(max(batch.num_rows, 1)),
                            sc.sources)
                    except (ValueError, CollisionError, BuildError):
                        outs = None
                    if outs is None:
                        memtrack.release(self.plan, device=db)
                        db = 0
                        sched.device_scheduler().release(slot)
                        slot = None
                if outs is not None:
                    pending.append((launch_kernel, outs, batch, db, slot))
                    while len(pending) > depth:
                        merge(finish(*pending.popleft()))
                else:
                    # host batches are synchronous: drain in-flight work
                    # first so results keep arriving in input order
                    while pending:
                        merge(finish(*pending.popleft()))
                    _STREAM_STATS["host_batches"] += 1
                    runtime_stats.note_fallback(self.plan, "mesh")
                    merge(host_batch(batch))
            while pending:
                merge(finish(*pending.popleft()))
        finally:
            # an exception unwinding past the drains (quota cancel in
            # merge, KILL interrupt) abandons launched batches: their
            # dispatch slots and device bytes must not leak for the
            # life of the process — mirror of pipeline_map's finally
            while pending:
                _k, _outs, _b, p_db, p_slot = pending.popleft()
                sched.device_scheduler().release(p_slot)
                if p_db:
                    memtrack.release(self.plan, device=p_db)
        if kernel is not None:
            self.plan._mesh_capacity = capacity
        return tracked

    def _buffer_probe(self, it, limit):
        """Pull chunks until the probe proves larger than `limit`.
        -> (buffered parts, total rows, exhausted?)."""
        parts, total = [], 0
        for c in it:
            if c.num_rows:
                parts.append(c)
                total += c.num_rows
            if total > limit:
                return parts, total, False
        return parts, total, True


class MeshAggExec(_MeshExecBase):
    """Group-by aggregation on the device mesh (Q1 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = config.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        plan = self.plan
        schema = plan.children[0].schema
        reader = ex.build_executor(plan.children[0])
        it = reader.chunks(ctx)
        limit = sysconf.stream_rows()
        parts, total, exhausted = self._buffer_probe(it, limit)

        def make(capacity):
            return MeshAggKernel(mesh, plan.filter_expr, plan.group_exprs,
                                 plan.aggs, capacity=capacity)

        if not exhausted:
            # probe larger than the streaming threshold: never materialize
            # it — feed the kernel ≤limit-row super-batches, double-buffered
            def get_kernel(capacity):
                k = _kernel_cache_get(plan, capacity)
                if k is None:
                    k = make(capacity)
                    _kernel_cache_put(plan, capacity, k)
                return k

            agg = HashAggregator(plan.aggs, plan.group_exprs)
            tracked = 0
            try:
                # mesh pipelines overlap async launches, so the device
                # time is the whole streaming region's wall (readback)
                with runtime_stats.device_section(plan):
                    tracked = self._stream_groups(
                        superchunk_batches(itertools.chain(parts, it),
                                           limit,
                                           tracker=memtrack.op_node(plan)),
                        get_kernel,
                        lambda b: host_hash_agg(b, plan.filter_expr,
                                                plan.group_exprs,
                                                plan.aggs),
                        agg)
                yield _emit_agg(plan, agg, ex)
            finally:
                memtrack.release(plan, host=tracked)
            return

        # small probe: whole-table path, memoized so hot re-executions of
        # a cached plan transfer zero bytes (the resident copy belongs to
        # the memo; the transfer watermark below is this query's charge)
        big = _concat_chunks_cached(plan, "_probe_cache", parts, schema)
        gr = None
        if big.num_rows:
            with sched.device_slot(), runtime_stats.device_section(plan), \
                    memtrack.device_scope(plan,
                                          memtrack.device_put_bytes(big)):
                gr = self._run_with_escalation(make, lambda k: k(big))
            if gr is None:
                yield from self._fallback(ctx)
                return
            # the whole table went down as ONE maximally-coalesced batch
            runtime_stats.note_superchunk(
                plan, big.num_rows, bucket_size(max(big.num_rows, 1)),
                max(len(parts), 1))
        yield _emit_results(plan, gr, ex)


class MeshLookupAggExec(_MeshExecBase):
    """Star join + aggregation on the device mesh (Q3/Q5 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = config.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        plan = self.plan
        try:
            specs = []
            for lk in plan.lookups:
                bexec = ex.build_executor(lk.build_plan)
                bchunk = _concat_chunks_cached(lk, "_chunk_cache",
                                               list(bexec.chunks(ctx)),
                                               lk.build_plan.schema)
                specs.append(LookupSpec(
                    key_exprs=lk.key_exprs, build_chunk=bchunk,
                    build_key_offsets=lk.build_key_offsets,
                    payload_offsets=lk.payload_offsets))
            builds = [self._build_table(d, sp)
                      for d, sp in zip(plan.lookups, specs)]
        except BuildError:
            # non-unique / NULL-heavy dimension keys: host join fallback
            yield from self._fallback(ctx)
            return

        def make(capacity):
            k = MeshLookupAggKernel(mesh, plan.filter_expr, specs,
                                    plan.group_exprs, plan.aggs,
                                    capacity=capacity, builds=builds)
            k.lookups = specs    # freshly built: skip the refresh rebuild
            return k

        def refresh(kernel):
            if kernel.lookups is not specs:
                # cached kernel: the traced program depends only on the
                # lookup STRUCTURE; swap in the current tables
                kernel.lookups = specs
                kernel.builds = builds
            return kernel

        reader = ex.build_executor(plan.children[0])
        it = reader.chunks(ctx)
        limit = sysconf.stream_rows()
        parts, total, exhausted = self._buffer_probe(it, limit)

        if not exhausted:
            # fact side larger than the streaming threshold: feed the
            # lookup-chain kernel in super-batches; dimension tables stay
            # resident on device across batches (device-memoized builds)
            def get_kernel(capacity):
                k = _kernel_cache_get(plan, capacity)
                if k is None:
                    k = make(capacity)
                    _kernel_cache_put(plan, capacity, k)
                return refresh(k)

            agg = HashAggregator(plan.aggs, plan.group_exprs)
            tracked = 0
            try:
                with runtime_stats.device_section(plan):
                    tracked = self._stream_groups(
                        superchunk_batches(itertools.chain(parts, it),
                                           limit,
                                           tracker=memtrack.op_node(plan)),
                        get_kernel,
                        lambda b: host_lookup_agg(b, plan.filter_expr,
                                                  specs, plan.group_exprs,
                                                  plan.aggs,
                                                  builds=builds),
                        agg)
                yield _emit_agg(plan, agg, ex)
            finally:
                memtrack.release(plan, host=tracked)
            return

        probe = _concat_chunks_cached(plan, "_probe_cache", parts,
                                      plan.children[0].schema)
        gr = None
        if probe.num_rows:
            with sched.device_slot(), runtime_stats.device_section(plan), \
                    memtrack.device_scope(plan,
                                          memtrack.device_put_bytes(probe)):
                gr = self._run_with_escalation(
                    make, lambda kernel: refresh(kernel)(probe))
            if gr is None:
                yield from self._fallback(ctx)
                return
            runtime_stats.note_superchunk(
                plan, probe.num_rows, bucket_size(max(probe.num_rows, 1)),
                max(len(parts), 1))
        yield _emit_results(plan, gr, ex)

    @staticmethod
    def _build_table(desc, spec):
        """Host build-table prep (sort, exact-bit lanes, device upload)
        memoized on the plan's lookup descriptor: when the storage chunk
        cache serves the same dimension chunk object again, the prepared
        table (and its device copy) is reused as-is."""
        from tidb_tpu.parallel.dist_join import _BuildTable
        cached = getattr(desc, "_build_cache", None)
        if cached is not None and cached[0] is spec.build_chunk:
            return cached[1]
        bt = _BuildTable(spec)
        desc._build_cache = (spec.build_chunk, bt)
        return bt
