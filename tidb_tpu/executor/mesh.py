"""Executors for mesh-routed plans (plan/mesh_route.py).

The reference's distributed aggregation pulls per-region partials onto one
root goroutine (/root/reference/distsql/distsql.go:92 fan-in feeding
executor/aggregate.go); here the heavy reduction happens ON the mesh
(parallel/dist_agg.py, dist_join.py) and the host only merges the already
tiny per-statement group tables and formats rows.

Fallback contract: every mesh plan carries the original subtree; we
delegate to it when no process mesh is active, when expressions fail
device validation, on group-capacity overflow past the escalation cap,
on hash collisions, or on non-unique dimension build keys."""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.ops.hashagg import CapacityError, CollisionError, HashAggregator
from tidb_tpu.parallel import config
from tidb_tpu.parallel.dist_agg import MeshAggKernel
from tidb_tpu.parallel.dist_join import (BuildError, LookupSpec,
                                         MeshLookupAggKernel)

__all__ = ["MeshAggExec", "MeshLookupAggExec"]

# Initial per-chip group-table capacity; on overflow the executor re-plans
# the kernel once with 2x the observed distinct count (the re-plan the
# single-chip kernel docstring promises), then falls back to the host.
DEFAULT_CAPACITY = 4096
MAX_CAPACITY = 1 << 20

# kernel reuse across executions of cached plans: jit programs are per
# (structure, capacity); keyed by plan object identity (the entry pins
# the plan so its id cannot be recycled)
_KERNELS: OrderedDict = OrderedDict()
_KERNELS_CAP = 64


def _kernel_cache_get(plan, capacity):
    key = (config.mesh_generation(), id(plan), capacity)
    hit = _KERNELS.get(key)
    if hit is not None and hit[0] is plan:
        _KERNELS.move_to_end(key)
        return hit[1]
    return None


def _kernel_cache_put(plan, capacity, kernel) -> None:
    gen = config.mesh_generation()
    # kernels from older mesh generations can never be hit again; drop
    # them now rather than pinning their replicated build tables
    for k in [k for k in _KERNELS if k[0] != gen]:
        del _KERNELS[k]
    key = (gen, id(plan), capacity)
    _KERNELS[key] = (plan, kernel)
    _KERNELS.move_to_end(key)
    while len(_KERNELS) > _KERNELS_CAP:
        _KERNELS.popitem(last=False)


def _concat_chunks(parts, schema) -> Chunk:
    big = Chunk.concat_all([p for p in parts if p.num_rows])
    if big is None:
        return Chunk([Column.from_values(c.ft, []) for c in schema.cols])
    return big


# Bounded registry of concat memos: each entry pins a table-sized host
# chunk (and transitively its device copy), so unlike the row-bounded
# ChunkCache these must be counted — a long-lived server executing many
# distinct cached plans would otherwise pin one table copy per plan.
_CONCATS: OrderedDict = OrderedDict()
_CONCATS_CAP = 16


def _concat_chunks_cached(holder, slot: str, parts, schema) -> Chunk:
    """Concat memoized on `holder` (a plan node): when the storage chunk
    cache serves the same per-region chunk objects again, the concatenated
    table — and therefore its memoized device copy — is reused, so a hot
    multi-region scan transfers zero bytes. Keyed by part identities; the
    parts are pinned in the cache entry so ids cannot be recycled. The
    global _CONCATS LRU bounds how many such table copies stay pinned."""
    key = tuple(id(p) for p in parts)
    cached = getattr(holder, slot, None)
    if cached is not None and cached[0] == key:
        reg_key = (id(holder), slot)
        if reg_key in _CONCATS:
            _CONCATS.move_to_end(reg_key)
        return cached[2]
    big = _concat_chunks(parts, schema)
    if len(parts) > 1:     # single-part concat returns the cached chunk
        setattr(holder, slot, (key, parts, big))
        reg_key = (id(holder), slot)
        _CONCATS[reg_key] = holder
        _CONCATS.move_to_end(reg_key)
        while len(_CONCATS) > _CONCATS_CAP:
            _rk, h = _CONCATS.popitem(last=False)
            if hasattr(h, _rk[1]):
                delattr(h, _rk[1])
    return big


def _emit_results(plan, gr_or_none, executor_mod):
    agg = HashAggregator(plan.aggs)
    if gr_or_none is not None:
        agg.update(gr_or_none)
    results = agg.results()
    if not plan.group_exprs and not results:
        results = [((), [executor_mod._empty_agg_value(a)
                         for a in plan.aggs])]
    return executor_mod._agg_results_to_chunk(
        plan.schema, plan.num_group_cols, plan.aggs, results)


class _MeshExecBase:
    def __init__(self, plan):
        self.plan = plan
        self.schema = plan.schema

    def _fallback(self, ctx):
        from tidb_tpu.executor import build_executor
        return build_executor(self.plan.fallback).chunks(ctx)

    def _run_with_escalation(self, make_kernel, run):
        """Kernel-build + run with one capacity re-plan on overflow.
        The successful capacity sticks to the plan so re-executions of a
        cached plan start there instead of re-failing at the default.
        -> GroupResult or None (caller falls back)."""
        capacity = getattr(self.plan, "_mesh_capacity", DEFAULT_CAPACITY)
        for _attempt in (0, 1):
            try:
                kernel = _kernel_cache_get(self.plan, capacity)
                if kernel is None:
                    kernel = make_kernel(capacity)
                    _kernel_cache_put(self.plan, capacity, kernel)
                out = run(kernel)
                self.plan._mesh_capacity = capacity
                return out
            except CapacityError as e:
                needed = getattr(e, "needed", None)
                if needed is None:
                    return None
                capacity = 1 << max(needed * 2 - 1, 1).bit_length()
                if capacity > MAX_CAPACITY:
                    return None
            except (CollisionError, BuildError, ValueError):
                return None
        return None


class MeshAggExec(_MeshExecBase):
    """Group-by aggregation on the device mesh (Q1 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = config.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        reader = ex.build_executor(self.plan.children[0])
        big = _concat_chunks_cached(self.plan, "_probe_cache",
                                    list(reader.chunks(ctx)),
                                    self.plan.children[0].schema)

        def make(capacity):
            return MeshAggKernel(mesh, self.plan.filter_expr,
                                 self.plan.group_exprs,
                                 self.plan.aggs, capacity=capacity)

        gr = None
        if big.num_rows:
            gr = self._run_with_escalation(make, lambda k: k(big))
            if gr is None:
                yield from self._fallback(ctx)
                return
        yield _emit_results(self.plan, gr, ex)


class MeshLookupAggExec(_MeshExecBase):
    """Star join + aggregation on the device mesh (Q3/Q5 shape)."""

    def chunks(self, ctx):
        import tidb_tpu.executor as ex

        mesh = config.active_mesh()
        if mesh is None:
            yield from self._fallback(ctx)
            return
        plan = self.plan
        try:
            specs = []
            for lk in plan.lookups:
                bexec = ex.build_executor(lk.build_plan)
                bchunk = _concat_chunks_cached(lk, "_chunk_cache",
                                               list(bexec.chunks(ctx)),
                                               lk.build_plan.schema)
                specs.append(LookupSpec(
                    key_exprs=lk.key_exprs, build_chunk=bchunk,
                    build_key_offsets=lk.build_key_offsets,
                    payload_offsets=lk.payload_offsets))
            reader = ex.build_executor(plan.children[0])
            probe = _concat_chunks_cached(plan, "_probe_cache",
                                          list(reader.chunks(ctx)),
                                          plan.children[0].schema)
            builds = [self._build_table(d, sp)
                      for d, sp in zip(plan.lookups, specs)]
        except BuildError:
            # non-unique / NULL-heavy dimension keys: host join fallback
            yield from self._fallback(ctx)
            return

        def make(capacity):
            k = MeshLookupAggKernel(mesh, plan.filter_expr, specs,
                                    plan.group_exprs, plan.aggs,
                                    capacity=capacity, builds=builds)
            k.lookups = specs    # freshly built: skip the refresh rebuild
            return k

        def run(kernel):
            if kernel.lookups is not specs:
                # cached kernel: the traced program depends only on the
                # lookup STRUCTURE; swap in the current tables
                kernel.lookups = specs
                kernel.builds = builds
            return kernel(probe)

        gr = None
        if probe.num_rows:
            gr = self._run_with_escalation(make, run)
            if gr is None:
                yield from self._fallback(ctx)
                return
        yield _emit_results(plan, gr, ex)

    @staticmethod
    def _build_table(desc, spec):
        """Host build-table prep (sort, exact-bit lanes, device upload)
        memoized on the plan's lookup descriptor: when the storage chunk
        cache serves the same dimension chunk object again, the prepared
        table (and its device copy) is reused as-is."""
        from tidb_tpu.parallel.dist_join import _BuildTable
        cached = getattr(desc, "_build_cache", None)
        if cached is not None and cached[0] is spec.build_chunk:
            return cached[1]
        bt = _BuildTable(spec)
        desc._build_cache = (spec.build_chunk, bt)
        return bt
