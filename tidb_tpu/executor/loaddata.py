"""LOAD DATA INFILE: bulk text-file ingestion.

Reference: /root/reference/executor/write.go:1373 (LoadDataExec) and its
field/line splitting semantics (FIELDS TERMINATED / ENCLOSED / ESCAPED,
LINES STARTING/TERMINATED, IGNORE n LINES, \\N = NULL). The reference
streams file bytes from the client connection; here the server reads the
named file in bounded chunks (host memory stays O(chunk + one line)) and
writes through the same Table.add_record path as INSERT, reusing
InsertExec's duplicate handling for REPLACE/IGNORE modes. All rows land
in the statement's transaction, exactly like the reference's single-txn
LoadDataExec."""

from __future__ import annotations

import re
from decimal import Decimal, InvalidOperation

from tidb_tpu.executor import ExecContext, ExecError, InsertExec
from tidb_tpu.plan import physical as ph
from tidb_tpu.sqltypes import EvalType, parse_datetime

__all__ = ["parse_lines", "convert_fields", "RowsInsertExec", "READ_CHUNK"]

READ_CHUNK = 1 << 20          # file read granularity (bytes of text)


def _unescape(s: str, esc: str) -> str | None:
    """Undo ESCAPED BY sequences; a lone escaped 'N' is SQL NULL."""
    if esc and s == esc + "N":
        return None
    if not esc or esc not in s:
        return s
    out = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c == esc and i + 1 < n:
            nxt = s[i + 1]
            out.append({"n": "\n", "t": "\t", "r": "\r",
                        "0": "\0"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _split_lines(chunks, lt: str, ft: str, enc: str, esc: str,
                 starting: str = "", ignore_lines: int = 0):
    """Logical lines from a stream of text chunks: a terminator inside an
    enclosed field or behind the escape character does not end the row,
    and a token straddling a chunk boundary is handled by holding back a
    small tail until more text arrives. Memory is O(chunk + current
    line). Event scanning is find-based (one regex alternation), not
    per-character. An enclosure opens only at field start (line start or
    right after a field terminator) — a stray quote mid-field is a
    literal, exactly as in MySQL's parser. With LINES STARTING BY, text
    up to the prefix is skipped RAW (quotes there carry no meaning) and
    prefix-less lines are dropped whole."""
    toks = [t for t in {esc, enc, lt, ft} if t]
    pat = re.compile("|".join(re.escape(t)
                              for t in sorted(toks, key=len, reverse=True)))
    # longest token minus one, plus one char of escape/quote lookahead;
    # a straddling line prefix needs its own length of held-back tail
    hold = max(len(lt), len(ft), len(starting) + 1, 2) - 1
    buf = ""
    cur: list[str] = []
    in_enc = False
    field_start = True
    skipping = bool(starting)      # before the line prefix
    it = iter(chunks)
    final = False
    while True:
        if not final:
            try:
                buf += next(it)
            except StopIteration:
                final = True
        # tokens starting before `limit` always fit inside buf
        limit = len(buf) if final else max(len(buf) - hold, 0)
        i = 0
        while i < limit:
            if ignore_lines > 0:
                # IGNORE n LINES skips PHYSICAL lines — raw terminator
                # scan, before any prefix/enclosure semantics (MySQL's
                # READ_INFO::next_line does the same)
                l_ = buf.find(lt, i, limit + len(lt) - 1)
                if l_ < 0:
                    i = limit
                    break
                i = l_ + len(lt)
                ignore_lines -= 1
                continue
            if skipping:
                p = buf.find(starting, i, limit + len(starting) - 1)
                l_ = buf.find(lt, i, limit + len(lt) - 1)
                if 0 <= p and (l_ < 0 or p < l_):
                    i = p + len(starting)
                    skipping = False
                    field_start = True
                    continue
                if 0 <= l_:        # prefix-less line: drop it whole
                    i = l_ + len(lt)
                    continue
                i = limit          # no event yet: discard scanned text
                break
            m = pat.search(buf, i)
            if m is None or m.start() >= limit:
                if limit > i:
                    cur.append(buf[i:limit])
                    field_start = False
                i = limit
                break
            j = m.start()
            tok = m.group()
            if j > i:
                cur.append(buf[i:j])
                field_start = False
                i = j
            if esc and buf.startswith(esc, j):
                if j + len(esc) < len(buf):
                    cur.append(buf[j:j + len(esc) + 1])
                    i = j + len(esc) + 1
                    field_start = False
                    continue
                break              # lone escape at the end: literal tail
            if enc and tok == enc:
                if in_enc:
                    if j + len(enc) < len(buf) and \
                            buf.startswith(enc, j + len(enc)):
                        cur.append(enc + enc)   # doubled quote: literal
                        i = j + 2 * len(enc)
                        continue
                    in_enc = False
                elif field_start:
                    in_enc = True
                cur.append(enc)
                i = j + len(enc)
                field_start = False
                continue
            if in_enc:             # ft/lt inside an enclosure: literal
                cur.append(tok)
                i = j + len(tok)
                continue
            if ft and tok == ft:   # longer tokens win the alternation
                cur.append(ft)
                i = j + len(ft)
                field_start = True
                continue
            # tok == lt
            i = j + len(lt)
            yield "".join(cur)
            cur = []
            field_start = True
            skipping = bool(starting)
        buf = buf[i:]
        if final:
            break
    if not skipping and ignore_lines <= 0 and (cur or buf):
        cur.append(buf)
        yield "".join(cur)


def _split_fields(line: str, ft: str, enc: str, esc: str) -> list:
    """One logical line -> fields (None for escaped-N NULLs). Terminators
    inside enclosures or behind the escape char are literal."""
    fields: list = []
    cur: list[str] = []
    field_start, in_enc = True, False
    i, n = 0, len(line)
    while i < n:
        c = line[i]
        if esc and c == esc and i + 1 < n:
            cur.append(c)
            cur.append(line[i + 1])    # keep for _unescape (incl. \N)
            i += 2
            field_start = False
            continue
        if in_enc:
            if c == enc:
                if i + 1 < n and line[i + 1] == enc:   # doubled quote
                    cur.append(enc)
                    i += 2
                    continue
                in_enc = False
                i += 1
                continue
            cur.append(c)
            i += 1
            continue
        if field_start and enc and c == enc:
            in_enc = True
            field_start = False
            i += 1
            continue
        if line.startswith(ft, i):
            fields.append(_unescape("".join(cur), esc))
            cur = []
            field_start = True
            i += len(ft)
            continue
        cur.append(c)
        field_start = False
        i += 1
    fields.append(_unescape("".join(cur), esc))
    return fields


def parse_lines(text, stmt):
    """Split file text (a str, or an iterable of str chunks) into rows of
    fields (str, or None for \\N). Honors LINES STARTING/TERMINATED,
    FIELDS TERMINATED/ENCLOSED/ESCAPED and IGNORE n LINES.

    Regular single-byte-separator inputs scan through the native C++
    loader (tidb_tpu/native/loadscan.cc) with row-aligned fallback to
    this module's general scanner on anything irregular."""
    lt = stmt.lines_terminated or "\n"
    ft = stmt.fields_terminated or "\t"
    enc = stmt.fields_enclosed
    esc = stmt.fields_escaped
    chunks = [text] if isinstance(text, str) else text
    if (len(lt.encode()) == 1 and len(ft.encode()) == 1 and
            len(enc.encode()) <= 1 and len(esc.encode()) <= 1 and
            enc != esc and not stmt.lines_starting):
        native = _parse_lines_native(chunks, stmt, lt, ft, enc, esc)
        if native is not None:
            yield from native
            return
    for line in _split_lines(chunks, lt, ft, enc, esc,
                             stmt.lines_starting or "",
                             stmt.ignore_lines):
        if not line:
            continue
        yield _split_fields(line, ft, enc, esc)


def _parse_lines_native(chunks, stmt, lt, ft, enc, esc):
    """Generator over rows via the C++ scanner, or None when the native
    library is unavailable. Streams with a row-aligned carry buffer;
    irregular remainders (and a stalled scan) run the general Python
    scanner instead."""
    from tidb_tpu.native import scan_rows_native
    probe = scan_rows_native(b"", ft.encode(), lt.encode(),
                            enc.encode(), esc.encode(), 0)
    if probe is None:
        return None

    def gen():
        import itertools
        ftb, ltb = ft.encode(), lt.encode()
        encb, escb = enc.encode(), esc.encode()
        carry = b""
        ignore = stmt.ignore_lines
        it = iter(chunks)
        final = False
        while not final:
            chunk = next(it, None)
            if chunk is None:
                final = True
            else:
                carry += chunk.encode("utf8")
                if len(carry) < (1 << 16):
                    continue
            # IGNORE n LINES: strip physical lines in the buffer first
            while ignore > 0:
                at = carry.find(ltb)
                if at < 0:
                    break
                carry = carry[at + 1:]
                ignore -= 1
            if ignore > 0:
                if not final:
                    continue
                carry = b""       # the whole tail is an ignored line
                break
            if not carry:
                continue
            res = scan_rows_native(carry, ftb, ltb, encb, escb, 0,
                                   final_chunk=final)
            consumed, rowoff, fs, fe, fl = res
            for r in range(len(rowoff) - 1):
                lo, hi = int(rowoff[r]), int(rowoff[r + 1])
                if hi - lo == 1 and fs[lo] == fe[lo] and fl[lo] == 0:
                    continue       # empty line (matches the host scanner)
                fields = []
                for j in range(lo, hi):
                    if fl[j] & 4:
                        fields.append(None)
                        continue
                    sv = carry[int(fs[j]):int(fe[j])].decode(
                        "utf8", "replace")
                    if fl[j] & 2 and enc:
                        sv = sv.replace(enc + enc, enc)
                    if fl[j] & 1 and esc:
                        sv = _unescape(sv, esc)
                    fields.append(sv)
                yield fields
            if consumed == 0 and (final or len(carry) > (1 << 20)):
                # irregular head the C scanner cannot progress past:
                # the general scanner takes the whole remainder
                rest = carry.decode("utf8", "replace")
                tail = itertools.chain(
                    [rest], (c for c in it if c is not None))
                for line in _split_lines(tail, lt, ft, enc, esc, "", 0):
                    if line:
                        yield _split_fields(line, ft, enc, esc)
                return
            carry = carry[consumed:]
        if carry:
            for line in _split_lines([carry.decode("utf8", "replace")],
                                     lt, ft, enc, esc, "", 0):
                if line:
                    yield _split_fields(line, ft, enc, esc)

    return gen()


def convert_fields(info, col_names: list[str], fields: list) -> dict:
    """One parsed row -> {col_name: value} with MySQL implicit casts.
    Extra fields are dropped, missing ones become NULL (MySQL warns).
    col_names must be lowercase (the schema's storage convention)."""
    values: dict = {}
    for cname, s in zip(col_names, fields):
        ci = info.col_by_name(cname)
        if ci is None:
            raise ExecError(f"unknown column '{cname}' in LOAD DATA")
        if s is None:
            values[cname] = None
            continue
        et = ci.ft.eval_type
        try:
            if et == EvalType.INT:
                try:
                    values[cname] = int(s)
                except ValueError:
                    values[cname] = int(float(s))   # '1.5' truncates
            elif et == EvalType.REAL:
                values[cname] = float(s)
            elif et == EvalType.DECIMAL:
                frac = max(ci.ft.frac, 0)
                scaled = int((Decimal(s) * (10 ** frac))
                             .to_integral_value(rounding="ROUND_HALF_UP"))
                values[cname] = (frac, scaled)
            elif et == EvalType.DATETIME:
                values[cname] = parse_datetime(s)
            else:
                values[cname] = s
        except (ValueError, InvalidOperation):
            raise ExecError(
                f"incorrect value {s!r} for column '{cname}'") from None
    for cname in col_names[len(fields):]:
        values[cname] = None
    return values


def read_text_chunks(f, size: int = READ_CHUNK):
    """Bounded file reader feeding parse_lines."""
    while True:
        chunk = f.read(size)
        if not chunk:
            return
        yield chunk


class RowsInsertExec(InsertExec):
    """InsertExec over pre-materialized value dicts: LOAD DATA reuses the
    whole duplicate-key machinery (REPLACE / IGNORE) without a plan tree."""

    def __init__(self, info, rows, dup_mode: str):
        self.plan = ph.PhysInsert(table=info, columns=[], source=None,
                                  on_duplicate=[],
                                  is_replace=(dup_mode == "replace"),
                                  ignore=(dup_mode == "ignore"))
        self.schema = None
        self.source = None
        self._rows = rows

    def _source_rows(self, ctx: ExecContext):
        return iter(self._rows)
