"""External (spill-to-disk) sort.

Replaces /root/reference/util/filesort/filesort.go:112,319 (worker fan-out
building sorted on-disk runs + multi-way heap merge) with a vectorized,
column-oriented design:

* full rows spill to disk in RUNS — one memory-mappable .npy per
  fixed-width column (+ bool validity); varlen (object) columns are
  dictionary-encoded at spill time, so only int64 codes hit disk and the
  (deduplicated) value dictionary stays in memory
* the evaluated SORT-KEY columns never spill: keys are a narrow slice of
  the row, and keeping them host-resident lets the "merge" be ONE global
  np.lexsort over dense ranks instead of a per-row heap loop — the same
  per-row-dispatch sin the reference's loser-tree merge commits and
  SURVEY.md §3.2 calls out
* output streams in blocks: the global order array is walked block by
  block, gathering rows from the memory-mapped runs, so peak row memory
  is O(run + block), not O(total)
"""

from __future__ import annotations

import os
import tempfile
import threading

import numpy as np

from tidb_tpu import memtrack
from tidb_tpu.chunk import Chunk, Column

__all__ = ["SpillSorter"]


def order_from_keys(key_arrays, n: int) -> np.ndarray:
    """-> int64 permutation of n rows given [(data, valid, desc)] key
    columns. Dense-rank encoding (np.unique) makes DESC a negation that
    works for numerics and object columns alike; NULLs rank below every
    value (first asc / last desc, MySQL). np.lexsort is stable."""
    lex_keys = []
    for d, v, desc in key_arrays:
        d, v = np.asarray(d), np.asarray(v, dtype=bool)
        rank = np.full(n, -1, dtype=np.int64)
        if v.any():
            _u, inv = np.unique(d[v], return_inverse=True)
            rank[v] = inv
        lex_keys.append(-rank if desc else rank)
    if not lex_keys:
        return np.arange(n, dtype=np.int64)
    return np.lexsort(lex_keys[::-1]).astype(np.int64)


class _Run:
    """One spilled run: per-column .npy paths (data may be int64 codes
    for dict-encoded varlen columns) + validity paths + row count."""

    __slots__ = ("data_paths", "valid_paths", "n")

    def __init__(self, data_paths, valid_paths, n):
        self.data_paths = data_paths
        self.valid_paths = valid_paths
        self.n = n


class SpillSorter:
    """Accumulates chunks; spills full runs to disk past `run_rows`;
    yields the globally ordered rows in `block_rows` chunks.

    Key memory stays O(total keys); row memory stays O(run + block)."""

    def __init__(self, by, run_rows: int = 1 << 20,
                 block_rows: int = 1 << 16, tmpdir: str | None = None,
                 tracker=None):
        self.by = by                      # [(Expression, desc)]
        self.run_rows = run_rows
        self.block_rows = block_rows
        self._tmp = None
        self._tmpdir = tmpdir
        self._buf: list[Chunk] = []
        self._nbuf = 0
        self._runs: list[_Run] = []
        self._keys: list[list] = []       # per run/tail: [(data, valid)]
        self._fts = None
        # shared dictionaries for object columns (per column offset)
        self._dicts: dict[int, dict] = {}
        self._dict_vals: dict[int, list] = {}
        # memory accounting: buffered full rows + resident key arrays.
        # Spilling RELEASES the buffered-row bytes (they moved to disk)
        # and keeps only the narrow keys — the tracker visibly drops, the
        # whole point of a spill OOM action. The RLock serializes add()
        # against a quota-triggered spill arriving from another thread's
        # consume (a cop worker crossing the statement quota), and is
        # re-entrant because add()'s own consume may fire the action on
        # this very thread.
        self._tracker = tracker
        self._tracked_buf = 0
        self._tracked_keys = 0
        self._mu = threading.RLock()
        self._unregister = memtrack.register_spill(self._quota_spill) \
            if tracker is not None else (lambda: None)

    # -- build phase --------------------------------------------------------

    def add(self, chunk: Chunk) -> None:
        if chunk.num_rows == 0:
            return
        with self._mu:
            if self._fts is None:
                self._fts = [c.ft for c in chunk.columns]
            self._buf.append(chunk)
            self._nbuf += chunk.num_rows
            if self._tracker is not None:
                b = memtrack.chunk_bytes(chunk)
                self._tracked_buf += b
                # lint: exempt[paired-resource] ownership transfer: buffered rows release on spill/drain/close, quota-spill re-arms
                self._tracker.consume(host=b)
            if self._nbuf >= self.run_rows:
                self._spill()

    def _quota_spill(self) -> None:
        """OOM spill action (memtrack quota chain): shed the buffered
        rows to disk early. Re-armed — fires again on later episodes."""
        with self._mu:
            if self._nbuf:
                self._spill()

    def _eval_keys(self, chunk: Chunk):
        out = []
        for e, _desc in self.by:
            d, v = e.eval(chunk)
            d = np.asarray(d)
            if e.ft.is_ci and d.dtype == np.dtype(object):
                from tidb_tpu.sqltypes import fold_column
                d = fold_column(d)           # _ci ordering
            out.append((d, np.asarray(v, dtype=bool)))
        return out

    # lint: exempt[memtrack-alloc] spill encode buffer: rows already billed to the sorter's tracker
    def _encode(self, j: int, col: Column) -> np.ndarray:
        """Dictionary-encode an object column for spilling."""
        mapping = self._dicts.setdefault(j, {})
        vals = self._dict_vals.setdefault(j, [])
        codes = np.empty(len(col.data), dtype=np.int64)
        for i, val in enumerate(col.data):
            if not col.valid[i]:
                codes[i] = 0
                continue
            code = mapping.get(val)
            if code is None:
                code = len(vals)
                mapping[val] = code
                vals.append(val)
            codes[i] = code
        return codes

    def _spill(self) -> None:
        whole = Chunk.concat_all(self._buf)
        self._buf, self._nbuf = [], 0
        if self._tracker is not None and self._tracked_buf:
            # rows move to disk: credit the buffer back so the quota sees
            # the spill actually freeing memory
            self._tracker.release(host=self._tracked_buf)
            self._tracked_buf = 0
        if whole is None or whole.num_rows == 0:
            return
        if self._tmp is None:
            self._tmp = tempfile.TemporaryDirectory(
                prefix="tidbtpu-sort-", dir=self._tmpdir)
        keys = self._eval_keys(whole)
        self._keys.append(keys)
        if self._tracker is not None:
            kb = sum((8 * len(d) if d.dtype == object else d.nbytes)
                     + v.nbytes for d, v in keys)
            self._tracked_keys += kb
            # lint: exempt[paired-resource] ownership transfer: in-memory run keys release when the merge drains or the sorter closes
            self._tracker.consume(host=kb)
        rid = len(self._runs)
        dpaths, vpaths = [], []
        for j, col in enumerate(whole.columns):
            data = self._encode(j, col) if col.data.dtype == object \
                else col.data
            dp = os.path.join(self._tmp.name, f"r{rid}c{j}.npy")
            vp = os.path.join(self._tmp.name, f"r{rid}c{j}v.npy")
            np.save(dp, data, allow_pickle=False)
            np.save(vp, col.valid, allow_pickle=False)
            dpaths.append(dp)
            vpaths.append(vp)
        self._runs.append(_Run(dpaths, vpaths, whole.num_rows))

    # -- output phase -------------------------------------------------------

    @property
    def spilled(self) -> bool:
        return bool(self._runs)

    # lint: exempt[memtrack-alloc] drains the tracker-billed run buffers; released as rows stream out
    def sorted_chunks(self):
        """Yield the accumulated rows in global sort order."""
        try:
            with self._mu:
                # drain the buffer ATOMICALLY against a quota spill from
                # another thread's consume: once _nbuf is zero the spill
                # action no-ops, so the tail can never be both spilled
                # to a run and kept in memory (double rows), and
                # _tracked_buf keeps covering the resident tail until
                # close() releases it
                tail = Chunk.concat_all(self._buf)
                self._buf, self._nbuf = [], 0
            if not self._runs:
                if tail is not None and tail.num_rows:
                    order = order_from_keys(
                        [(d, v, desc) for (d, v), (_e, desc) in
                         zip(self._eval_keys(tail), self.by)],
                        tail.num_rows)
                    yield tail.take(order)
                return
            if tail is not None and tail.num_rows:
                self._keys.append(self._eval_keys(tail))
            # global order over concatenated keys (runs in spill order,
            # then the in-memory tail)
            total = sum(r.n for r in self._runs) + \
                (tail.num_rows if tail is not None else 0)
            key_arrays = []
            for ki, (_e, desc) in enumerate(self.by):
                d = np.concatenate([ks[ki][0] for ks in self._keys])
                v = np.concatenate([ks[ki][1] for ks in self._keys])
                key_arrays.append((d, v, desc))
            self._keys = []
            order = order_from_keys(key_arrays, total)
            del key_arrays
            offs = np.cumsum([0] + [r.n for r in self._runs])
            mms = [[np.load(p, mmap_mode="r") for p in r.data_paths]
                   for r in self._runs]
            vmms = [[np.load(p, mmap_mode="r") for p in r.valid_paths]
                    for r in self._runs]
            ncols = len(self._fts)
            from tidb_tpu.sqltypes import np_dtype_for
            dtypes = [np_dtype_for(ft.tp, ft.flen) for ft in self._fts]
            is_obj = [dt == np.dtype(object) for dt in dtypes]
            nruns = len(self._runs)
            for s in range(0, total, self.block_rows):
                idx = order[s:s + self.block_rows]
                bn = len(idx)
                out_data = [np.empty(bn, dtype=dt) if not o
                            else np.full(bn, "", dtype=object)
                            for dt, o in zip(dtypes, is_obj)]
                out_valid = [np.empty(bn, dtype=bool) for _ in range(ncols)]
                src_run = np.clip(
                    np.searchsorted(offs, idx, side="right") - 1,
                    0, nruns)   # == nruns -> the in-memory tail
                for r in range(nruns + 1):
                    sel = np.flatnonzero(src_run == r)
                    if not len(sel):
                        continue
                    if r < nruns:
                        local = idx[sel] - offs[r]
                        for j in range(ncols):
                            dv = np.asarray(mms[r][j][local])
                            vv = np.asarray(vmms[r][j][local])
                            if is_obj[j]:
                                vals = self._dict_vals.get(j, [])
                                out_data[j][sel] = [
                                    vals[c] if vb and vals else ""
                                    for c, vb in zip(dv, vv)]
                            else:
                                out_data[j][sel] = dv
                            out_valid[j][sel] = vv
                    else:
                        local = idx[sel] - offs[-1]
                        for j in range(ncols):
                            c = tail.columns[j]
                            out_data[j][sel] = c.data[local]
                            out_valid[j][sel] = c.valid[local]
                cols = []
                for j, ft in enumerate(self._fts):
                    d = out_data[j]
                    if is_obj[j]:
                        d[~out_valid[j]] = ""
                    cols.append(Column(ft, d, out_valid[j]))
                yield Chunk(cols)
        finally:
            self.close()

    def close(self) -> None:
        self._unregister()
        if self._tracker is not None and \
                (self._tracked_buf or self._tracked_keys):
            self._tracker.release(
                host=self._tracked_buf + self._tracked_keys)
            self._tracked_buf = self._tracked_keys = 0
        if self._tmp is not None:
            self._tmp.cleanup()
            self._tmp = None
