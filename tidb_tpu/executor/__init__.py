"""Volcano executors over chunks.

Reference: /root/reference/executor/ — Executor iface (executor.go:172-180,
Open/NextChunk/Close), builder dispatch (builder.go:62-146). Pull model kept
(chunked iterators), but per-chunk compute is columnar numpy / XLA instead
of row loops; the distsql leaves stream partial results from the
coprocessor fan-out (distsql.go:92).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tidb_tpu import (config, kv, memtrack, profiler, runtime_stats,
                      sched, tablecodec)
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.kv import CopRequest, KVRange, ReqType
from tidb_tpu.ops import hybrid as op_hybrid
from tidb_tpu.ops import runtime as op_runtime
from tidb_tpu.ops.hashagg import (CapacityError, CollisionError,
                                  DeviceRejectError, GroupResult,
                                  HashAggregator, kernel_for)
from tidb_tpu.ops.hostagg import host_hash_agg
from tidb_tpu.ops.join import (JoinKernel, JoinKeyEncoder,
                               host_match_pairs)
from tidb_tpu.ops.streamagg import segment_kernel_for
from tidb_tpu.ops.runtime import eval_filter_host, super_batches
from tidb_tpu.plan import physical as ph
from tidb_tpu.sqltypes import EvalType, FieldType, np_dtype_for
from tidb_tpu.store.copr import exec_cop_plan
from tidb_tpu.table import Table, encode_datum_for_col, kvrows_to_chunk

__all__ = ["build_executor", "ExecError", "ExecContext"]


class ExecError(kv.KVError):
    pass


# shared shuffle-join kernels, keyed (mesh_generation, num_keys): the
# shard_map program is shape-polymorphic, so one kernel serves every
# query with the same key arity on the same mesh
_SHUFFLE_KERNELS: dict = {}
_SHUFFLE_KERNELS_LOCK = threading.Lock()


def _evict_stale_shuffle_kernels() -> None:
    from tidb_tpu import devplane as mesh_config
    gen = mesh_config.mesh_generation()
    with _SHUFFLE_KERNELS_LOCK:
        for k in [k for k in _SHUFFLE_KERNELS if k[0] != gen]:
            _SHUFFLE_KERNELS.pop(k, None)


def _register_mesh_listener() -> None:
    # release compiled shard_map executables when the topology changes
    # (incl. disable_mesh — no later join would otherwise evict them)
    from tidb_tpu import devplane as mesh_config
    mesh_config.on_topology_change(_evict_stale_shuffle_kernels)


_register_mesh_listener()


class ExecContext:
    """What executors need from the session: storage, the read ts, the
    active transaction (for writes and dirty reads), and an interrupt
    probe (KILL QUERY; ref: the Go ctx cancellation threaded through
    executors)."""

    def __init__(self, storage, read_ts: int, txn=None,
                 interrupted=None):
        self.storage = storage
        self.read_ts = read_ts
        self.txn = txn   # kv transaction or None (autocommit read)
        self.interrupted = interrupted

    def check_interrupt(self) -> None:
        if self.interrupted is not None and self.interrupted():
            raise ExecError("Query execution was interrupted")


class Executor:
    schema = None

    def open(self, ctx: ExecContext):
        pass

    def chunks(self, ctx: ExecContext):
        """Yields Chunks."""
        raise NotImplementedError

    def close(self):
        pass


def build_executor(plan: ph.PhysPlan) -> Executor:
    """Ref: executorBuilder.build (builder.go:62-146)."""
    t = type(plan)
    b = _BUILDERS.get(t)
    if b is None:
        raise ExecError(f"no executor for {t.__name__}")
    exe = b(plan)
    # per-statement runtime stats: children are built (and wrapped)
    # inside the constructor above, so every node in the tree passes
    # through here exactly once per execution
    runtime_stats.instrument(exe, plan)
    return exe


# ---------------------------------------------------------------------------
# Readers

def _txn_is_dirty(ctx: ExecContext, table_id: int) -> bool:
    if ctx.txn is None:
        return False
    lo, hi = tablecodec.table_prefix_range(table_id)
    for _k, _v in ctx.txn.us.membuf.iter_range(lo, hi):
        return True
    return False


class TableReaderExec(Executor):
    """distsql leaf (ref: executor/distsql.go:297 TableReaderExecutor).
    Streams region partial results; in a dirty transaction, falls back to
    scanning through the union store so own writes are visible
    (ref: UnionScanExec, executor/union_scan.go:90)."""

    def __init__(self, plan: ph.PhysTableReader):
        self.plan = plan
        self.schema = plan.schema

    def _ranges(self):
        cop = self.plan.cop
        if cop.ranges is not None:
            return cop.ranges
        lo = tablecodec.record_prefix(cop.table.id)
        from tidb_tpu import codec
        return [KVRange(lo, codec.prefix_next(lo))]

    def partials(self, ctx: ExecContext):
        """Agg mode: yields GroupResults."""
        cop = self.plan.cop
        if _txn_is_dirty(ctx, cop.table.id):
            for chunk in self._dirty_chunks(ctx):
                yield exec_cop_plan(cop, chunk).chunk
            return
        req = CopRequest(tp=ReqType.DAG, ranges=self._ranges(), plan=cop,
                         start_ts=ctx.read_ts)
        for resp in ctx.storage.client().send(req):
            ctx.check_interrupt()
            yield resp.chunk

    def chunks(self, ctx: ExecContext):
        cop = self.plan.cop
        assert not cop.is_agg
        if _txn_is_dirty(ctx, cop.table.id):
            for chunk in self._dirty_chunks(ctx):
                yield exec_cop_plan(cop, chunk).chunk
            return
        req = CopRequest(tp=ReqType.DAG, ranges=self._ranges(), plan=cop,
                         start_ts=ctx.read_ts,
                         keep_order=getattr(self.plan, "keep_order", False))
        if cop.feedback is not None and cop.limit is None:
            yield from self._chunks_with_feedback(ctx, req)
            return
        remaining = cop.limit
        for resp in ctx.storage.client().send(req):
            ctx.check_interrupt()
            ch = resp.chunk
            if remaining is not None:
                if remaining <= 0:
                    return
                if ch.num_rows > remaining:
                    ch = ch.slice(0, remaining)
                remaining -= ch.num_rows
            yield ch

    def _chunks_with_feedback(self, ctx, req):
        """Stream the scan while counting actual rows; report the range's
        true cardinality to the stats handle afterwards (ref:
        statistics/update.go:88 QueryFeedback collection at the reader)."""
        cop = self.plan.cop
        actual = 0
        for resp in ctx.storage.client().send(req):
            ctx.check_interrupt()
            actual += resp.chunk.num_rows
            yield resp.chunk
        col_id, dranges = cop.feedback
        try:
            from tidb_tpu.session import Domain
            Domain.get(ctx.storage).stats_handle().feedback_range(
                cop.table.id, col_id, dranges, actual)
        except Exception:   # noqa: BLE001 - feedback must never fail reads
            pass

    def _decode_rows(self, rows):
        cop = self.plan.cop
        return kvrows_to_chunk(cop.table, cop.cols, rows, cop.handle_col)

    def _dirty_chunks(self, ctx: ExecContext):
        """Union-store scan: buffered writes shadow the snapshot. The cop
        plan then runs at the root over these chunks (host compute)."""
        rows = []
        for rng in self._ranges():
            for k, v in ctx.txn.iter_range(rng.start, rng.end):
                rows.append((k, v))
                if len(rows) >= 65536:
                    yield self._decode_rows(rows)
                    rows = []
        yield self._decode_rows(rows)


class IndexReaderExec(TableReaderExec):
    """Covering-index distsql leaf (ref: executor/distsql.go:412
    IndexReaderExecutor): identical client machinery; the storage side
    decodes index entries instead of rows."""

    def __init__(self, plan: ph.PhysIndexReader):
        self.plan = plan
        self.schema = plan.schema

    def _decode_rows(self, rows):
        from tidb_tpu.table import index_kvrows_to_chunk
        cop = self.plan.cop
        return index_kvrows_to_chunk(cop.table, cop.index, cop.cols, rows,
                                     cop.handle_col)


class IndexLookUpExec(Executor):
    """Index scan -> handle batches -> parallel batched row fetch.
    Ref: executor/distsql.go:524-737 — index worker streaming handles into
    lookupTableTasks consumed by a table-worker pool; order preserved by
    yielding futures in submission order."""

    BATCH = 1024              # handles per lookup task
    LOOKUP_CONCURRENCY = 4    # ref: IndexLookupConcurrency default

    def __init__(self, plan: ph.PhysIndexLookUp):
        self.plan = plan
        self.schema = plan.schema

    def _handle_batches(self, ctx: ExecContext):
        icop = self.plan.index_cop
        req = CopRequest(tp=ReqType.DAG, ranges=icop.ranges, plan=icop,
                         start_ts=ctx.read_ts,
                         keep_order=self.plan.keep_order)
        batch: list[int] = []
        hcol = icop.handle_col
        for resp in ctx.storage.client().send(req):
            ch = resp.chunk
            handles = ch.columns[hcol].data
            for h in handles.tolist():
                batch.append(h)
                if len(batch) >= self.BATCH:
                    yield batch
                    batch = []
        if batch:
            yield batch

    def _fetch_rows(self, ctx: ExecContext, handles: list[int]):
        tcop = self.plan.table_cop
        snap = ctx.storage.snapshot(ctx.read_ts)
        keys = [tablecodec.record_key(tcop.table.id, h) for h in handles]
        got = snap.batch_get(keys)
        kvrows = [(k, got[k]) for k in keys if k in got]
        chunk = kvrows_to_chunk(tcop.table, tcop.cols, kvrows,
                                tcop.handle_col)
        return exec_cop_plan(tcop, chunk).chunk

    def chunks(self, ctx: ExecContext):
        tcop = self.plan.table_cop
        if _txn_is_dirty(ctx, tcop.table.id):
            # own writes visible: all conjuncts are retained in the
            # residual filters, so a full union-store scan is equivalent
            yield from TableReaderExec(
                ph.PhysTableReader(schema=self.schema, cop=tcop)).chunks(ctx)
            return
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(max_workers=self.LOOKUP_CONCURRENCY,
                                  thread_name_prefix="idxlookup")
        pending = deque()
        try:
            for batch in self._handle_batches(ctx):
                pending.append(pool.submit(self._fetch_rows, ctx, batch))
                while len(pending) >= self.LOOKUP_CONCURRENCY:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)


class PointGetExec(Executor):
    """Single-row read bypassing the coprocessor (ref: the point-get fast
    path detector, executor/adapter.go:381). Reads through the active
    transaction's union store so own writes are visible."""

    def __init__(self, plan: ph.PhysPointGet):
        self.plan = plan
        self.schema = plan.schema

    def chunks(self, ctx: ExecContext):
        p = self.plan
        retr = ctx.txn if ctx.txn is not None \
            else ctx.storage.snapshot(ctx.read_ts)
        handle = p.handle
        if p.index is not None:
            ik = tablecodec.index_key(p.table.id, p.index.id,
                                      list(p.index_values))
            from tidb_tpu import codec as _codec
            v = retr.get(ik)
            if v is None:
                yield kvrows_to_chunk(p.table, p.cols, [], p.handle_col)
                return
            handle, _ = _codec.decode_int(v, 0)
        rk = tablecodec.record_key(p.table.id, handle)
        raw = retr.get(rk)
        kvrows = [] if raw is None else [(rk, raw)]
        chunk = kvrows_to_chunk(p.table, p.cols, kvrows, p.handle_col)
        if p.filter is not None and chunk.num_rows:
            chunk = chunk.filter(eval_filter_host(p.filter, chunk))
        yield chunk


class ValuesExec(Executor):
    def __init__(self, plan: ph.PhysValues):
        self.plan = plan
        self.schema = plan.schema

    def chunks(self, ctx):
        fts = [c.ft for c in self.plan.schema.cols] if self.plan.schema.cols \
            else []
        rows = []
        for rexprs in self.plan.rows:
            row = []
            for e in rexprs:
                d, v = e.eval_xp(np, [], 1)
                row.append(None if not v[0] else
                           (d[0].item() if hasattr(d[0], "item") else d[0]))
            rows.append(row)
        if not fts and rows:
            fts = [e.ft for e in self.plan.rows[0]]
        cols = []
        for j, ft in enumerate(fts):
            dtype = np_dtype_for(ft.tp, ft.flen)
            valid = np.array([r[j] is not None for r in rows], dtype=bool)
            if dtype == np.dtype(object):
                from tidb_tpu.sqltypes import object_fill
                _fill = object_fill(ft)
                data = np.array([r[j] if r[j] is not None else _fill
                                 for r in rows], dtype=object)
            else:
                data = np.array([r[j] if r[j] is not None else 0
                                 for r in rows], dtype=dtype)
            cols.append(Column(ft, data, valid))
        yield Chunk(cols)


# ---------------------------------------------------------------------------
# Aggregation

# lint: exempt[memtrack-alloc] group-count-sized outputs, bounded by the tracked agg state (HashAggregator.approx_bytes)
def _agg_results_to_chunk(schema, num_group: int, aggs: list[AggDesc],
                          results) -> Chunk:
    fts = [c.ft for c in schema.cols]
    n = len(results)
    arrays = []
    for j, ft in enumerate(fts):
        dtype = np_dtype_for(ft.tp, ft.flen)
        valid = np.ones(n, dtype=bool)
        data = np.empty(n, dtype=object) if dtype == np.dtype(object) \
            else np.zeros(n, dtype=dtype)
        arrays.append((data, valid))
    for i, (key, vals) in enumerate(results):
        for j in range(num_group):
            v = key[j]
            data, valid = arrays[j]
            if v is None:
                valid[i] = False
                if data.dtype == np.dtype(object):
                    data[i] = ""
            else:
                data[i] = v
        for a_i, v in enumerate(vals):
            data, valid = arrays[num_group + a_i]
            if v is None:
                valid[i] = False
                if data.dtype == np.dtype(object):
                    data[i] = ""
            else:
                data[i] = v
    return Chunk([Column(ft, d, v) for ft, (d, v) in zip(fts, arrays)])


class FinalAggExec(Executor):
    """Merges storage-side partials (ref: final HashAgg over partial agg,
    executor/aggregate.go + aggregation.GetPartialResult protocol)."""

    def __init__(self, plan: ph.PhysFinalAgg):
        self.plan = plan
        self.schema = plan.schema
        self.reader = build_executor(plan.children[0])

    def chunks(self, ctx):
        # partials arrive pre-grouped: key fts are the schema's leading
        # num_group_cols columns
        agg = HashAggregator(
            self.plan.aggs,
            [c.ft for c in
             self.plan.schema.cols[:self.plan.num_group_cols]])
        tracked = 0
        try:
            for gr in self.reader.partials(ctx):
                agg.update(gr)
                tracked = memtrack.track_to(self.plan,
                                            agg.approx_bytes(), tracked)
            results = agg.results()
            if not self.plan.num_group_cols and not results:
                results = [((), [_empty_agg_value(a)
                                 for a in self.plan.aggs])]
            yield _agg_results_to_chunk(self.schema,
                                        self.plan.num_group_cols,
                                        self.plan.aggs, results)
        finally:
            memtrack.release(self.plan, host=tracked)


def _empty_agg_value(a: AggDesc):
    return 0 if a.fn == AggFunc.COUNT else None


class HashAggExec(Executor):
    """Root-side complete aggregation over child chunks."""

    def __init__(self, plan: ph.PhysHashAgg):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])
        # kernels live on the plan object: the plan cache shares plans
        # across executions, so the jit program (and its XLA compile)
        # outlives any one query run
        self._kernel = getattr(plan, "_root_kernel", None)

    def chunks(self, ctx):
        agg = HashAggregator(self.plan.aggs, self.plan.group_exprs)
        distinct_ok = all(not a.distinct for a in self.plan.aggs)
        sc_rows = config.superchunk_rows()
        tracked = 0
        try:
            if distinct_ok and config.device_enabled() and sc_rows:
                # fused pipeline fragment (ops/fragment.py): when the
                # child is a plain inner hash join, ONE XLA program per
                # probe superchunk executes match + gather + group +
                # partial agg — the joined intermediate never
                # materializes in HBM or on the host
                frag = self._fragment_kernel()
                source = self._fused_partials(ctx, frag) \
                    if frag is not None else \
                    self._superchunk_partials(self.child.chunks(ctx))
                # superchunk pipeline: child chunks coalesce into big
                # padded batches and flow through the dispatch-ahead
                # device queue; one partial-agg dispatch per superchunk
                for gr in source:
                    agg.update(gr)
                    tracked = memtrack.track_to(
                        self.plan, agg.approx_bytes(), tracked)
            else:
                for chunk in self.child.chunks(ctx):
                    if chunk.num_rows == 0:
                        continue
                    gr = None
                    if distinct_ok and config.device_enabled() and \
                            chunk.num_rows >= config.device_min_rows():
                        gr = self._device_partial(chunk)
                    if gr is None:
                        gr = host_hash_agg(chunk, None,
                                           self.plan.group_exprs,
                                           self.plan.aggs)
                    agg.update(gr)
                    tracked = memtrack.track_to(
                        self.plan, agg.approx_bytes(), tracked)
            results = agg.results()
            if not self.plan.group_exprs and not results:
                results = [((), [_empty_agg_value(a)
                                 for a in self.plan.aggs])]
            num_g = len(self.plan.group_exprs)
            yield _agg_results_to_chunk(self.schema, num_g,
                                        self.plan.aggs, results)
        finally:
            memtrack.release(self.plan, host=tracked)

    def _set_kernel(self, kernel) -> None:
        self._kernel = kernel
        # kernels live on the plan object: the plan cache shares plans
        # across executions, so the jit program outlives any one run
        self.plan._root_kernel = kernel

    def _fragment_kernel(self):
        """A ProbeAggKernel when this agg can fuse with its child join
        into one program per probe superchunk (ops/fragment.py), else
        None. Fusion requires a plain single-chip inner hash join (no
        other_cond — pair filtering would need the joined width) and a
        device-safe group/agg set over the joined schema; everything
        else keeps the per-operator path."""
        if not config.fuse_fragments_enabled():
            return None
        join = self.child
        if type(join) is not HashJoinExec:      # not Merge/Index subclasses
            return None
        jplan = join.plan
        if jplan.join_type != "inner" or jplan.other_cond is not None \
                or not jplan.left_keys:
            return None
        from tidb_tpu import devplane as mesh_config
        mesh = mesh_config.active_mesh()
        if mesh is not None and mesh.devices.size > 1:
            return None     # the mesh shuffle plane owns multi-chip joins
        from tidb_tpu.ops import fragment as op_fragment
        nl = len(jplan.children[0].schema)
        width = nl + len(jplan.children[1].schema)
        try:
            return op_fragment.fragment_kernel_for(
                len(jplan.left_keys), nl, width, self.plan.group_exprs,
                self.plan.aggs)
        except (DeviceRejectError, NotImplementedError, ValueError):
            return None

    def _fused_partials(self, ctx, fk):
        """Partial GroupResults from the fused probe->agg fragment: the
        build side uploads once (used columns + key lanes), probe
        superchunks stream through the dispatch-ahead pipeline, and
        each in-flight token is one whole-fragment program. A capacity
        miss escalates the fragment kernel once (later batches inherit
        it); a miss that survives — or a collision — falls back to the
        decoded per-batch path (host pair match + gather + host agg),
        counted on tidb_tpu_device_fallback_total."""
        plan = self.plan
        join = self.child
        jplan = join.plan
        nl = len(jplan.children[0].schema)
        width = nl + len(jplan.children[1].schema)
        build = Chunk.concat_all(list(join.right.chunks(ctx)))
        nb = build.num_rows if build is not None else 0
        if nb == 0:
            return      # inner join over an empty build: no input rows
        tracked = memtrack.track_to(plan, memtrack.chunk_bytes(build))
        enc = JoinKeyEncoder(len(jplan.right_keys))
        raw_bk = join._eval_keys(jplan.right_keys, build)
        bk = enc.fit_build(
            raw_bk, encoded=join._encoded_keys(jplan.right_keys, build),
            ci=[e.ft.is_ci for e in jplan.right_keys])
        engage, hot, h = join._hybrid_engage(bk, nb, raw_bk)
        if engage:
            # skew / quota pressure / over-superchunk build: the hybrid
            # join's heavy-hitter lanes and partition-spill machinery
            # own this probe — run the per-operator path (the fragment
            # would funnel a 30%-hot key through ONE ballooning pair
            # buffer with nothing sheddable under quota). The encoded
            # keys, hashes and hot set just computed ride along.
            try:
                yield from self._superchunk_partials(join._probe_join(
                    ctx, build, nb, prepared=(enc, bk, raw_bk, hot, h)))
            finally:
                memtrack.release(plan, host=tracked)
            return
        state = {"fk": fk, "build_dev": None, "build_db": 0}
        min_rows = config.device_min_rows()
        mt_node = memtrack.op_node(plan)

        def decoded_batch(pk, chunk):
            li, ri = host_match_pairs(bk, pk, nb, chunk.num_rows)
            pair = join._gather(chunk, build, li, ri)
            return host_hash_agg(pair, None, plan.group_exprs,
                                 plan.aggs)

        def dispatch(sc):
            n = sc.num_rows
            pk = join._probe_keys(enc, sc.chunk)
            if n < min_rows and nb < join._DEVICE_MIN_BUILD:
                return ("host", pk, 0)
            k = state["fk"]
            if state["build_dev"] is None:
                # build lanes stay device-resident for the whole probe
                state["build_db"] = k.build_nbytes(build, nb)
                memtrack.consume(plan, device=state["build_db"])
                state["build_dev"] = k.prepare_build(build, bk, nb)
            cap = op_runtime.bucket_size(max(n * 2, 1024))
            db = k.dispatch_nbytes(sc.chunk, cap)
            memtrack.consume(plan, device=db)
            try:
                tok = k.dispatch(state["build_dev"], nb, pk, sc.chunk, n)
            except BaseException:
                memtrack.release(plan, device=db)
                raise
            profiler.note_bytes(profiler.profile_of(k), nbytes=db)
            runtime_stats.note_superchunk(plan, n, sc.bucket, sc.sources)
            runtime_stats.note_bytes_touched(
                memtrack.chunk_bytes(sc.chunk), k.input_nbytes(sc.chunk))
            return ("dev", (k, tok, pk), db)

        def finalize(sc, tok):
            kind, payload, db = tok
            if kind == "host":
                return decoded_batch(payload, sc.chunk)
            k, pend, pk = payload
            t0 = time.perf_counter_ns()
            try:
                gr = k.finalize(sc.chunk, build, nb, pend)
                runtime_stats.note_encoding(plan, "fused:probe-agg")
                runtime_stats.note_mode(plan, "fused")
                return gr
            except CapacityError as e:
                profiler.note_escalation(profiler.profile_of(k))
                k2 = self._escalated_fragment(e, nl, width)
                if k2 is not None:
                    state["fk"] = k2    # later batches dispatch with it
                    n = sc.num_rows
                    cap = op_runtime.bucket_size(max(n * 2, 1024))
                    with sched.device_slot(), memtrack.device_scope(
                            plan, k2.dispatch_nbytes(sc.chunk, cap)):
                        try:
                            gr = k2.finalize(
                                sc.chunk, build, nb,
                                k2.dispatch(state["build_dev"], nb, pk,
                                            sc.chunk, n))
                            runtime_stats.note_encoding(
                                plan, "fused:probe-agg")
                            runtime_stats.note_mode(plan, "fused")
                            return gr
                        except (CapacityError, CollisionError):
                            pass
                runtime_stats.note_fallback(plan, "capacity")
                profiler.note_kernel_fallback(profiler.profile_of(k),
                                              "capacity")
                return decoded_batch(pk, sc.chunk)
            except CollisionError:
                runtime_stats.note_fallback(plan, "collision")
                profiler.note_kernel_fallback(profiler.profile_of(k),
                                              "collision")
                return decoded_batch(pk, sc.chunk)
            finally:
                memtrack.release(plan, device=db)
                runtime_stats.note_finalize_wait(
                    plan, time.perf_counter_ns() - t0)

        sc_iter = op_runtime.superchunk_batches(
            join.left.chunks(ctx), config.superchunk_rows(),
            tracker=mt_node)
        try:
            yield from op_runtime.pipeline_map(
                sc_iter, dispatch, finalize, config.pipeline_depth(),
                tracker=mt_node,
                cost=lambda sc: memtrack.chunk_bytes(sc.chunk),
                profile=profiler.profile_of(fk))
        finally:
            if state["build_db"]:
                memtrack.release(plan, device=state["build_db"])
            memtrack.release(plan, host=tracked)

    def _escalated_fragment(self, e: CapacityError, nl: int, width: int):
        """Fragment-kernel re-plan after a group-capacity miss; None
        when the overflow is hopeless (the per-batch decoded fallback
        then owns the batch)."""
        from tidb_tpu.ops import fragment as op_fragment
        cap = op_hybrid.escalated_capacity(getattr(e, "needed", 0))
        if cap is None:
            return None
        jplan = self.child.plan
        try:
            return op_fragment.fragment_kernel_for(
                len(jplan.left_keys), nl, width, self.plan.group_exprs,
                self.plan.aggs, capacity=cap)
        except (DeviceRejectError, NotImplementedError, ValueError):
            return None

    def _escalated_kernel(self, e: CapacityError):
        """Re-plan once with a larger device table (the re-plan the
        kernel docstring promises); None when the overflow is hopeless.
        The growth rule/ceiling live in hybrid.escalated_capacity so the
        whole-chunk retry and the per-partition chains cannot drift."""
        cap = op_hybrid.escalated_capacity(getattr(e, "needed", 0))
        if cap is None:
            return None
        try:
            k = kernel_for(None, self.plan.group_exprs, self.plan.aggs,
                           capacity=cap)
        except ValueError:
            return None
        self._set_kernel(k)
        return k

    def _device_partial(self, chunk):
        """Per-chunk device partial agg (superchunk coalescing off).
        A capacity miss re-plans once with a bigger table; a miss that
        survives (or a collision) radix-partitions the chunk and retries
        per partition (ops/hybrid.py) instead of abandoning the device.
        Returns None only for designed rejections (not device-safe) —
        the caller's host path, counted as a fallback."""
        try:
            if self._kernel is None:
                self._set_kernel(kernel_for(
                    None, self.plan.group_exprs, self.plan.aggs))
            nb = self._kernel.dispatch_nbytes(chunk)
            with sched.device_slot(), memtrack.device_scope(
                    self.plan, nb), \
                    profiler.dispatch_section(
                        profiler.profile_of(self._kernel), nbytes=nb,
                        plan=self.plan):
                gr = runtime_stats.device_call(
                    self.plan, self._kernel, chunk)
            runtime_stats.note_mode(self.plan, "hash")
            return gr
        except CapacityError as e:
            reason = "capacity"
            profiler.note_escalation(profiler.profile_of(self._kernel))
            k = self._escalated_kernel(e)
            if k is not None:
                # the retry kernel's (>=2x) scratch is the statement's
                # LARGEST device allocation — it must not dodge the quota
                nb = k.dispatch_nbytes(chunk)
                try:
                    with sched.device_slot(), \
                            memtrack.device_scope(self.plan, nb), \
                            profiler.dispatch_section(
                                profiler.profile_of(k), nbytes=nb,
                                plan=self.plan):
                        gr = runtime_stats.device_call(
                            self.plan, k, chunk)
                    runtime_stats.note_mode(self.plan, "hash")
                    return gr
                except CapacityError:
                    pass
                except CollisionError:
                    reason = "collision"
                except (DeviceRejectError, NotImplementedError):
                    runtime_stats.note_fallback(self.plan,
                                                "unsupported")
                    return None
            runtime_stats.note_mode(self.plan, "hybrid")
            return op_hybrid.partitioned_agg(
                chunk, None, self.plan.group_exprs, self.plan.aggs,
                self.plan, reason=reason)
        except CollisionError:
            runtime_stats.note_mode(self.plan, "hybrid")
            return op_hybrid.partitioned_agg(
                chunk, None, self.plan.group_exprs, self.plan.aggs,
                self.plan, reason="collision")
        except (DeviceRejectError, NotImplementedError):
            runtime_stats.note_fallback(self.plan, "unsupported")
        return None

    def _superchunk_partials(self, chunks):
        """Coalesced device partial aggregation: superchunk_batches folds
        the child's chunk stream into ~tidb_tpu_superchunk_rows batches,
        pipeline_map keeps tidb_tpu_pipeline_depth of them in flight
        (padding + H2D transfer of batch k+1 overlaps batch k's compute;
        the only sync is the finalize device_get at the output boundary),
        and the padded input buffers are donated to the kernel. Capacity
        overflow re-plans and re-runs the offending superchunk; collision
        or non-device-safe plans fall back to the host per superchunk."""
        plan = self.plan
        min_rows = config.device_min_rows()
        if self._kernel is None:
            try:
                self._set_kernel(kernel_for(None, plan.group_exprs,
                                            plan.aggs))
            except DeviceRejectError:
                # not device-safe BY DESIGN: every superchunk goes host
                runtime_stats.note_fallback(plan, "unsupported")

        mt_node = memtrack.op_node(plan)

        def dispatch(sc):
            k = self._kernel
            if k is None or sc.num_rows < min_rows:
                return None      # host path at finalize
            # device ledger: padded upload + group-table scratch, sized
            # from shapes at dispatch; credited back at finalize
            db = k.dispatch_nbytes(sc.chunk)
            memtrack.consume(plan, device=db)
            try:
                tok = (k, k.dispatch(sc.chunk, donate=True), db)
            except (DeviceRejectError, NotImplementedError):
                # trace-time rejection: this plan will never run on device
                self._kernel = None
                memtrack.release(plan, device=db)
                runtime_stats.note_fallback(plan, "unsupported")
                return None
            except BaseException:
                memtrack.release(plan, device=db)
                raise
            profiler.note_bytes(profiler.profile_of(k), nbytes=db)
            runtime_stats.note_superchunk(plan, sc.num_rows, sc.bucket,
                                          sc.sources)
            runtime_stats.note_bytes_touched(
                memtrack.chunk_bytes(sc.chunk),
                memtrack.device_put_bytes(sc.chunk))
            return tok

        def finalize(sc, tok):
            if tok is not None:
                k, fut, db = tok
                t0 = time.perf_counter_ns()
                try:
                    gr = k.finalize(sc.chunk, fut)
                    runtime_stats.note_mode(plan, "hash")
                    return gr
                except CapacityError as e:
                    reason = "capacity"
                    profiler.note_escalation(profiler.profile_of(k))
                    k2 = self._escalated_kernel(e)
                    if k2 is not None:
                        with sched.device_slot(), memtrack.device_scope(
                                plan, k2.dispatch_nbytes(sc.chunk)):
                            try:
                                gr = k2(sc.chunk)
                                runtime_stats.note_mode(plan, "hash")
                                return gr
                            except CapacityError:
                                pass
                            except CollisionError:
                                reason = "collision"
                            except (DeviceRejectError,
                                    NotImplementedError):
                                runtime_stats.note_fallback(
                                    plan, "unsupported")
                                return host_hash_agg(
                                    sc.chunk, None, plan.group_exprs,
                                    plan.aggs)
                    # a miss that survived escalation retries per
                    # radix partition instead of abandoning the device
                    runtime_stats.note_mode(plan, "hybrid")
                    return op_hybrid.partitioned_agg(
                        sc.chunk, None, plan.group_exprs, plan.aggs,
                        plan, reason=reason)
                except CollisionError:
                    runtime_stats.note_mode(plan, "hybrid")
                    return op_hybrid.partitioned_agg(
                        sc.chunk, None, plan.group_exprs, plan.aggs,
                        plan, reason="collision")
                except (DeviceRejectError, NotImplementedError):
                    runtime_stats.note_fallback(plan, "unsupported")
                finally:
                    memtrack.release(plan, device=db)
                    runtime_stats.note_finalize_wait(
                        plan, time.perf_counter_ns() - t0)
            return host_hash_agg(sc.chunk, None, plan.group_exprs,
                                 plan.aggs)

        yield from op_runtime.pipeline_map(
            op_runtime.superchunk_batches(chunks, config.superchunk_rows(),
                                          tracker=mt_node),
            dispatch, finalize, config.pipeline_depth(),
            tracker=mt_node, cost=lambda sc: memtrack.chunk_bytes(sc.chunk),
            profile=profiler.profile_of(self._kernel))


class StreamAggExec(Executor):
    """Sort-based aggregation: order rows by the group keys, then
    segment-reduce on device (ops/streamagg.py). Ref:
    executor/aggregate.go:150-170 StreamAggExec — there the sorted input
    comes from a child sort/index; here the sort itself is one vectorized
    lexsort, and the reduce has NO capacity limit (num_segments = slice
    rows), so arbitrarily many groups never overflow a device table."""

    _SLICE = 1 << 17     # rows per device dispatch

    def __init__(self, plan: ph.PhysStreamAgg):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])
        self._kernel = getattr(plan, "_root_kernel", None)

    def chunks(self, ctx):
        agg = HashAggregator(self.plan.aggs, self.plan.group_exprs)
        use_device = (config.device_enabled() and
                      all(not a.distinct for a in self.plan.aggs))
        slice_rows = config.superchunk_rows() or self._SLICE
        mt_node = memtrack.op_node(self.plan)

        def parts():
            """Ordered ~slice_rows Superchunks: key-adjacency (all the
            segment kernel needs) survives coalescing because both
            sources below yield key-ordered chunks and superchunk
            assembly preserves order. Oversize blocks are re-sliced so
            device dispatches stay bounded."""
            if self.plan.sorted_input:
                # already key-ordered (pk scan / keep_order index): pure
                # streaming, the whole input is never materialized
                yield from op_runtime.superchunk_batches(
                    self.child.chunks(ctx), slice_rows, tracker=mt_node)
                return
            # needs its own ordering pass: the spill sorter keeps row
            # memory O(run + block) however large the input
            # (executor/extsort.py), then yields globally ordered blocks.
            # The sorter bills this node and registers a quota spill
            # action — over tidb_tpu_mem_quota_query it sheds its buffer
            # to disk instead of cancelling the statement.
            from tidb_tpu.executor.extsort import SpillSorter
            by = [(g, False) for g in self.plan.group_exprs]
            sorter = SpillSorter(by, run_rows=config.sort_spill_rows(),
                                 block_rows=slice_rows, tracker=mt_node)
            try:
                for chunk in self.child.chunks(ctx):
                    sorter.add(chunk)
                yield from op_runtime.superchunk_batches(
                    sorter.sorted_chunks(), slice_rows, tracker=mt_node)
            finally:
                sorter.close()

        # batches keep host+device memory bounded; a group spanning two
        # batches merges itself in the HashAggregator
        def feed(part: Chunk) -> None:
            nonlocal use_device
            gr = None
            if use_device and part.num_rows >= config.device_min_rows():
                try:
                    if self._kernel is None:
                        self._kernel = segment_kernel_for(
                            self.plan.group_exprs, self.plan.aggs)
                        self.plan._root_kernel = self._kernel
                    nb = self._kernel.dispatch_nbytes(part)
                    with sched.device_slot(), memtrack.device_scope(
                            self.plan, nb), \
                            profiler.dispatch_section(
                                profiler.profile_of(self._kernel),
                                nbytes=nb, plan=self.plan):
                        gr = runtime_stats.device_call(
                            self.plan, self._kernel, part)
                    runtime_stats.note_mode(self.plan, "sort")
                except (DeviceRejectError, NotImplementedError):
                    runtime_stats.note_fallback(self.plan, "unsupported")
                    use_device = False
            if gr is None:
                gr = host_hash_agg(part, None, self.plan.group_exprs,
                                   self.plan.aggs)
            agg.update(gr)

        tracked = 0
        try:
            if use_device and config.superchunk_rows():
                for gr in self._pipelined_segments(parts()):
                    agg.update(gr)
                    tracked = memtrack.track_to(
                        self.plan, agg.approx_bytes(), tracked)
            else:
                for sc in parts():
                    feed(sc.chunk)
                    tracked = memtrack.track_to(
                        self.plan, agg.approx_bytes(), tracked)
            results = agg.results()
            if not self.plan.group_exprs and not results:
                results = [((), [_empty_agg_value(a)
                                 for a in self.plan.aggs])]
            yield _agg_results_to_chunk(self.schema,
                                        len(self.plan.group_exprs),
                                        self.plan.aggs, results)
        finally:
            memtrack.release(self.plan, host=tracked)

    def _pipelined_segments(self, parts):
        """Segment-reduce each superchunk through the dispatch-ahead
        queue (see HashAggExec._superchunk_partials): one whole-
        superchunk segment op per coalesced batch, inputs donated, the
        next batch padded/transferred while this one executes. Segment
        kernels have no capacity protocol; a trace failure permanently
        reverts to the host path (matching the old per-batch behavior)."""
        plan = self.plan
        min_rows = config.device_min_rows()
        if self._kernel is None:
            try:
                self._kernel = segment_kernel_for(plan.group_exprs,
                                                  plan.aggs)
                plan._root_kernel = self._kernel
            except (DeviceRejectError, NotImplementedError):
                runtime_stats.note_fallback(plan, "unsupported")
                self._kernel = None

        mt_node = memtrack.op_node(plan)

        def dispatch(sc):
            k = self._kernel
            if k is None or sc.num_rows < min_rows:
                return None
            db = k.dispatch_nbytes(sc.chunk)
            memtrack.consume(plan, device=db)
            try:
                tok = (k, k.dispatch(sc.chunk, donate=True), db)
            except (DeviceRejectError, NotImplementedError):
                self._kernel = None
                memtrack.release(plan, device=db)
                runtime_stats.note_fallback(plan, "unsupported")
                return None
            except BaseException:
                memtrack.release(plan, device=db)
                raise
            profiler.note_bytes(profiler.profile_of(k), nbytes=db)
            runtime_stats.note_superchunk(plan, sc.num_rows, sc.bucket,
                                          sc.sources)
            runtime_stats.note_bytes_touched(
                memtrack.chunk_bytes(sc.chunk),
                memtrack.device_put_bytes(sc.chunk))
            return tok

        def finalize(sc, tok):
            if tok is not None:
                k, fut, db = tok
                t0 = time.perf_counter_ns()
                try:
                    gr = k.finalize(sc.chunk, fut)
                    runtime_stats.note_mode(plan, "sort")
                    return gr
                except (DeviceRejectError, NotImplementedError):
                    self._kernel = None
                    runtime_stats.note_fallback(plan, "unsupported")
                finally:
                    memtrack.release(plan, device=db)
                    runtime_stats.note_finalize_wait(
                        plan, time.perf_counter_ns() - t0)
            return host_hash_agg(sc.chunk, None, plan.group_exprs,
                                 plan.aggs)

        yield from op_runtime.pipeline_map(
            parts, dispatch, finalize, config.pipeline_depth(),
            tracker=mt_node, cost=lambda sc: memtrack.chunk_bytes(sc.chunk),
            profile=profiler.profile_of(self._kernel))


# ---------------------------------------------------------------------------
# Row ops

class SelectionExec(Executor):
    def __init__(self, plan: ph.PhysSelection):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx):
        for chunk in self.child.chunks(ctx):
            mask = eval_filter_host(self.plan.cond, chunk)
            yield chunk.filter(mask)


class ProjectionExec(Executor):
    def __init__(self, plan: ph.PhysProjection):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx):
        fts = [c.ft for c in self.schema.cols]
        for chunk in self.child.chunks(ctx):
            cols = []
            for e, ft in zip(self.plan.exprs, fts):
                d, v = e.eval(chunk)
                if d.dtype != np.dtype(object):
                    want = np_dtype_for(ft.tp, ft.flen)
                    if d.dtype != want:
                        d = d.astype(want)
                cols.append(Column(ft, d, v.copy()))
            yield Chunk(cols)


class LimitExec(Executor):
    def __init__(self, plan: ph.PhysLimit):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx):
        skip = self.plan.offset
        left = self.plan.count
        for chunk in self.child.chunks(ctx):
            if skip >= chunk.num_rows:
                skip -= chunk.num_rows
                continue
            if skip:
                chunk = chunk.slice(skip, chunk.num_rows)
                skip = 0
            if chunk.num_rows > left:
                chunk = chunk.slice(0, left)
            left -= chunk.num_rows
            yield chunk
            if left <= 0:
                return


def _ofill(ft):
    from tidb_tpu.sqltypes import object_fill
    return object_fill(ft)


def _sort_order(by, chunk) -> np.ndarray:
    """-> int64 permutation ordering chunk rows by the sort items, fully
    vectorized (no per-row Python objects — ref SURVEY §3.2's per-row
    dispatch sin). NULLs first ascending / last descending (MySQL)."""
    from tidb_tpu.executor.extsort import order_from_keys
    keys = []
    for e, desc in by:
        d, v = e.eval(chunk)
        if e.ft.is_ci and np.asarray(d).dtype == np.dtype(object):
            from tidb_tpu.sqltypes import fold_column
            d = fold_column(np.asarray(d))   # _ci ordering
        keys.append((d, v, desc))
    return order_from_keys(keys, chunk.num_rows)


class SortExec(Executor):
    """Sort with spill-to-disk (ref: executor/sort.go:35 in-memory path +
    util/filesort/filesort.go:319 external path, unified): below the
    tidb_tpu_sort_spill_rows sysvar everything is one in-memory lexsort;
    above it, full rows spill to memory-mapped runs while the keys stay
    resident (executor/extsort.py)."""

    def __init__(self, plan: ph.PhysSort):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx):
        from tidb_tpu.executor.extsort import SpillSorter
        # the sorter bills this plan node and registers a quota spill
        # action: crossing tidb_tpu_mem_quota_query sheds the buffered
        # rows to disk (tracker drops) instead of cancelling
        sorter = SpillSorter(self.plan.by,
                             run_rows=config.sort_spill_rows(),
                             tracker=memtrack.op_node(self.plan))
        try:
            empty = None
            for chunk in self.child.chunks(ctx):
                if chunk.num_rows == 0:
                    empty = chunk
                    continue
                sorter.add(chunk)
            n = 0
            for out in sorter.sorted_chunks():
                n += out.num_rows
                yield out
            if n == 0 and empty is not None:
                yield empty
        finally:
            sorter.close()


class TopNExec(Executor):
    """Heap-free TopN: keep best (count+offset) rows per chunk
    (ref: pushDownTopNOptimizer + executor TopN)."""

    def __init__(self, plan: ph.PhysTopN):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx):
        n = self.plan.count + self.plan.offset
        best = None
        tracked = 0
        try:
            for chunk in self.child.chunks(ctx):
                cand = chunk if best is None else best.concat(chunk)
                if cand.num_rows > 0:
                    best = cand.take(_sort_order(self.plan.by, cand)[:n])
                else:
                    best = cand
                tracked = memtrack.track_to(
                    self.plan, memtrack.chunk_bytes(best), tracked)
            if best is None:
                return
            yield best.slice(min(self.plan.offset, best.num_rows),
                             best.num_rows)
        finally:
            memtrack.release(self.plan, host=tracked)


class HashJoinExec(Executor):
    """Equi-join: device sort-based pair matching (ops/join.py) for large
    inputs, python hash probe for small ones (ref: executor/join.go:37
    HashJoinExec). Build side = right child, probe streams left chunks."""

    # below these sizes the jit dispatch beats the device win
    _DEVICE_MIN_PROBE = 1024
    _DEVICE_MIN_BUILD = 4096

    def __init__(self, plan: ph.PhysHashJoin):
        self.plan = plan
        self.schema = plan.schema
        self.left = build_executor(plan.children[0])
        self.right = build_executor(plan.children[1])
        # shared via the plan object so the jit shape cache survives
        # across executions of a cached plan
        self._kernel = getattr(plan, "_join_kernel", None)
        if self._kernel is None and plan.left_keys:
            self._kernel = JoinKernel(len(plan.left_keys))
            plan._join_kernel = self._kernel

    def _eval_keys(self, exprs, chunk):
        """-> [(data, valid)] with both sides brought to one comparable
        representation: decimal-vs-decimal/int rescale to the common frac
        as exact scaled ints (falling back to double when the scaled value
        could overflow int64); anything involving a REAL side compares as
        double, matching MySQL's mixed-numeric comparison."""
        out = []
        for e, oe in zip(exprs, self._other_keys(exprs)):
            d, v = e.eval(chunk)
            d, v = np.asarray(d), np.asarray(v)
            if d.dtype == np.dtype(object) and \
                    (e.ft.is_ci or oe.ft.is_ci):
                from tidb_tpu.sqltypes import fold_column
                d = fold_column(d)           # _ci join keys
            et, ot = e.ft.eval_type, oe.ft.eval_type
            my = e.ft.frac if et == EvalType.DECIMAL else 0
            their = oe.ft.frac if ot == EvalType.DECIMAL else 0
            if EvalType.REAL in (et, ot):
                if et == EvalType.DECIMAL:
                    d = d.astype(np.float64) / (10 ** my)
                elif d.dtype != np.float64 and d.dtype != np.dtype(object):
                    d = d.astype(np.float64)
            elif EvalType.DECIMAL in (et, ot):
                common = max(my, their)
                dig = (e.ft.flen if et == EvalType.DECIMAL else 19) \
                    + common - my
                odig = (oe.ft.flen if ot == EvalType.DECIMAL else 19) \
                    + common - their
                if max(dig, odig) > 18:   # scaled int64 could overflow
                    d = d.astype(np.float64) / (10 ** my)
                elif common > my:
                    d = d * np.int64(10 ** (common - my))
            out.append((d, v))
        return out

    def _other_keys(self, exprs):
        return self.plan.right_keys if exprs is self.plan.left_keys \
            else self.plan.left_keys

    def _encoded_keys(self, exprs, chunk):
        """Pre-encoded (codes, values) key lanes for bare varlen
        ColumnRefs (ops/encoded.py, `tidb_tpu_encoded_exec`): the join
        then hashes dictionary codes directly — a probe side sharing
        the build's dictionary passes through, a mismatched one re-keys
        through a code-translation array — instead of re-building a
        per-join Python dict over every value. Engages per key only
        when BOTH sides are plain string columns with matching
        collation (mixed-type and mixed-collation keys keep the raw
        path, whose rescale/fold rules own those semantics)."""
        if not config.encoded_exec_enabled():
            return None
        from tidb_tpu.ops import encoded as op_encoded
        out = []
        any_lane = False
        for e, oe in zip(exprs, self._other_keys(exprs)):
            lane = None
            if (e.ft.eval_type == EvalType.STRING and
                    oe.ft.eval_type == EvalType.STRING and
                    bool(e.ft.is_ci) == bool(oe.ft.is_ci)):
                lane = op_encoded.encoded_lane(e, chunk)
            out.append(lane)
            any_lane = any_lane or lane is not None
        return out if any_lane else None

    def _probe_keys(self, enc, chunk):
        """One probe batch's aligned key lanes, through the encoded
        fast path when the lanes are pre-encodable."""
        return enc.transform_probe(
            self._eval_keys(self.plan.left_keys, chunk),
            encoded=self._encoded_keys(self.plan.left_keys, chunk))

    def _mesh_kernel(self, nb: int):
        """A shuffle-join kernel when a multi-chip mesh is active and the
        build side is big enough to be worth a repartition (ref: the
        scaled-out form of executor/join.go's partitioned build). Cached
        per (mesh generation, key arity) — the shard_map program costs
        seconds of XLA compile and is shape-polymorphic across queries."""
        from tidb_tpu import devplane as mesh_config
        mesh = mesh_config.active_mesh()
        if mesh is None or mesh.devices.size <= 1 or \
                nb < self._DEVICE_MIN_BUILD or not config.device_enabled():
            return None
        from tidb_tpu.ops.meshshuffle import MeshShuffleJoinKernel
        key = (mesh_config.mesh_generation(), len(self.plan.left_keys))
        with _SHUFFLE_KERNELS_LOCK:
            kernel = _SHUFFLE_KERNELS.get(key)
            if kernel is None:
                for k in [k for k in _SHUFFLE_KERNELS if k[0] != key[0]]:
                    _SHUFFLE_KERNELS.pop(k, None)
                kernel = MeshShuffleJoinKernel(mesh, len(self.plan.left_keys))
                _SHUFFLE_KERNELS[key] = kernel
        return kernel

    def chunks(self, ctx):
        plan = self.plan
        if not plan.left_keys:
            yield from self._cross_join(ctx)
            return
        build = Chunk.concat_all(list(self.right.chunks(ctx)))
        nb = build.num_rows if build is not None else 0
        # the materialized build side is the join's dominant host buffer:
        # hold it on this node's ledger for the whole probe phase
        tracked = memtrack.track_to(
            self.plan, memtrack.chunk_bytes(build) if nb else 0)
        try:
            yield from self._probe_join(ctx, build, nb)
        finally:
            memtrack.release(self.plan, host=tracked)

    def _probe_join(self, ctx, build, nb: int, prepared=None):
        """`prepared` = (enc, bk, raw_bk, hot, h) from a caller that
        already encoded the build keys and ran the hybrid-engage scan
        (the fused fragment's stand-aside path) — O(nb) key evaluation
        and heavy-hitter hashing must not run twice on exactly the
        large-build cases."""
        plan = self.plan
        if prepared is not None and nb:
            enc, bk, raw_bk, pre_hot, pre_h = prepared
        else:
            enc = JoinKeyEncoder(len(plan.right_keys))
            raw_bk = self._eval_keys(plan.right_keys, build) if nb \
                else None
            bk = enc.fit_build(
                raw_bk,
                encoded=self._encoded_keys(plan.right_keys, build),
                ci=[e.ft.is_ci for e in plan.right_keys]) if nb else None
            pre_hot = pre_h = None
        matched_build = np.zeros(nb, dtype=bool)
        probe_iter = self.left.chunks(ctx)
        mesh_kernel = self._mesh_kernel(nb)
        if mesh_kernel is not None:
            # each shuffle-join call is one all_to_all repartition of both
            # sides over the mesh, so probe chunks are re-batched into
            # large super-batches — but never the whole table: past
            # tidb_tpu_stream_rows per batch the collective is amortized
            # and host memory stays bounded (the build side's device
            # transfer is memoized across batches). A small probe doesn't
            # pay for the collective at all: fall through to the
            # per-chunk device/host paths
            buffered, total = [], 0
            for c in probe_iter:
                buffered.append(c)
                total += c.num_rows
                if total >= self._DEVICE_MIN_PROBE:
                    break
            if total >= self._DEVICE_MIN_PROBE:
                probe_iter = super_batches(
                    buffered, probe_iter,
                    max(config.stream_rows(), self._DEVICE_MIN_PROBE))
            else:
                mesh_kernel = None
                probe_iter = iter(buffered)
        device_ok = (mesh_kernel is None and nb > 0 and
                     self._kernel is not None and
                     config.device_enabled() and
                     config.superchunk_rows())
        if not device_ok:
            hyb = None
        elif pre_h is not None:
            # the caller's engage scan already said yes: construct
            # directly over its hashes/hot set
            hyb = op_hybrid.HybridJoinBuild(
                self._kernel, bk, nb, config.join_partitions(), plan,
                hot_hashes=pre_hot, h=pre_h)
        else:
            hyb = self._maybe_hybrid(bk, nb, raw_bk)
        if hyb is not None:
            # partitioned hybrid path (ops/hybrid.py): skew routed
            # through the heavy-hitter lane, cold build partitions
            # spillable to host staging under quota pressure
            try:
                yield from self._hybrid_probe(probe_iter, build, hyb,
                                              enc, matched_build)
            finally:
                hyb.close()
        elif device_ok:
            # single-chip device path: probe chunks coalesce into
            # superchunks and flow through the dispatch-ahead matcher
            # queue (build-side lanes transfer once for the whole probe)
            yield from self._pipelined_probe(probe_iter, build, bk, enc,
                                             matched_build, nb)
        else:
            for chunk in probe_iter:
                n = chunk.num_rows
                if n == 0:
                    continue
                if nb == 0:
                    if plan.join_type == "left":
                        out = self._emit(chunk, build,
                                         np.empty(0, np.int64),
                                         np.empty(0, np.int64),
                                         np.arange(n))
                        if out is not None:
                            yield out
                    elif plan.join_type == "anti":
                        yield chunk        # nothing can match: all survive
                    continue
                pk = self._probe_keys(enc, chunk)
                if mesh_kernel is not None:
                    from tidb_tpu.ops.meshshuffle import \
                        ShuffleOverflowError
                    try:
                        li, ri = runtime_stats.device_call(
                            self.plan, mesh_kernel, pk, bk, nb, n)
                    except ShuffleOverflowError:
                        # designed fallback: extreme hash skew exhausted
                        # the repartition retry budget
                        li, ri = runtime_stats.device_call(
                            self.plan, self._kernel, bk, pk, nb, n)
                elif config.device_enabled() and \
                        (n >= self._DEVICE_MIN_PROBE or
                         nb >= self._DEVICE_MIN_BUILD):
                    with sched.device_slot(), memtrack.device_scope(
                            self.plan,
                            self._kernel.build_nbytes(nb) +
                            self._kernel.dispatch_nbytes(n)):
                        li, ri = runtime_stats.device_call(
                            self.plan, self._kernel, bk, pk, nb, n)
                else:
                    # small inputs / device disabled: the same sort-join,
                    # vectorized in numpy (no jit dispatch, dynamic shapes)
                    li, ri = host_match_pairs(bk, pk, nb, n)
                yield from self._post_match(chunk, build, li, ri,
                                            matched_build)
        if plan.join_type == "right" and build is not None:
            un = np.flatnonzero(~matched_build)
            if len(un):
                yield self._emit_right_unmatched(build, un)

    def _post_match(self, chunk, build, li, ri, matched_build):
        """Shared tail after pair matching for one probe batch:
        other_cond filtering, semi/anti emission, left-unmatched fill;
        marks matched build rows for the right-join pass."""
        plan = self.plan
        n = chunk.num_rows
        # other_cond filters pairs BEFORE unmatched detection, so a
        # probe row whose every match fails the condition re-enters
        # as unmatched (outer-join ON-clause semantics)
        pair = None
        if plan.other_cond is not None and len(li):
            pair = self._gather(chunk, build, li, ri)
            keep = eval_filter_host(plan.other_cond, pair)
            li, ri = li[keep], ri[keep]
            pair = pair.filter(keep)
        if plan.join_type in ("semi", "anti"):
            # (anti-)semi join: emit probe rows by match existence,
            # never the joined width (ref: the semi-join family of
            # plan/gen_physical_plans.go; decorrelated EXISTS/IN)
            m = np.zeros(n, dtype=bool)
            m[li] = True
            yield chunk.filter(m if plan.join_type == "semi" else ~m)
            return
        matched_build[ri] = True
        unmatched = np.empty(0, np.int64)
        if plan.join_type == "left":
            m = np.zeros(n, dtype=bool)
            m[li] = True
            unmatched = np.flatnonzero(~m)
        out = self._emit(chunk, build, li, ri, unmatched, pair=pair)
        if out is not None:
            yield out

    def _hybrid_engage(self, bk, nb: int, raw_bk):
        """(engage, hot, h): should the partitioned hybrid path carry
        this build? Decision only — no HybridJoinBuild is constructed,
        so the fused-fragment eligibility check (HashAggExec) can
        consult it cheaply and stand aside when the skew/quota/spill
        machinery owns the probe."""
        parts = config.join_partitions()
        plan = self.plan
        if parts <= 1 or nb < self._DEVICE_MIN_BUILD:
            return False, None, None
        h = op_hybrid.build_hashes(bk, nb)
        raw_key = None
        if len(plan.right_keys) == 1 and raw_bk:
            rk, lk = plan.right_keys[0], plan.left_keys[0]
            ok_types = (EvalType.INT, EvalType.STRING, EvalType.DATETIME,
                        EvalType.DURATION)
            # decimal/real keys rescale in _eval_keys, so their raw
            # values no longer match the ANALYZE-time sketch encoding;
            # _ci strings fold the same way — skip sketch seeding there
            if rk.ft.eval_type in ok_types and \
                    lk.ft.eval_type in ok_types and \
                    not rk.ft.is_ci and not lk.ft.is_ci:
                raw_key = raw_bk[0]
        threshold = config.skew_threshold()
        cms = getattr(plan, "probe_cms", None)
        # the per-distinct-key sketch scan is ~1us/key: cache its result
        # on the (plan-cache-shared) plan object keyed by sketch
        # identity + threshold, so repeated executions pay it once.
        # Staleness is bounded by re-ANALYZE (new sketch object -> new
        # scan); build keys that appeared since simply miss the seed and
        # are caught by streaming promotion instead
        cached = getattr(plan, "_hot_seed", None)
        if cached is not None and cached[0] is cms and \
                cached[1] == threshold:
            sketch_hot = cached[2]
        else:
            sketch_hot = op_hybrid.sketch_hot_hashes(h, threshold,
                                                     raw_key, cms)
            plan._hot_seed = (cms, threshold, sketch_hot)
        hot = np.union1d(op_hybrid.dup_hot_hashes(h, threshold),
                         sketch_hot)
        root = memtrack.current()
        quota = root is not None and root.quota > 0
        if not hot.size and not quota and nb <= config.superchunk_rows():
            return False, hot, h
        return True, hot, h

    def _maybe_hybrid(self, bk, nb: int, raw_bk):
        """A HybridJoinBuild when the partitioned path should carry this
        probe (ops/hybrid.py). Partitioning is pure win under skew,
        memory pressure, or an over-superchunk build — and pure overhead
        otherwise, so the unskewed in-HBM case stays on the classic
        pipelined probe. Heavy hitters are seeded from exact build-side
        duplication plus the probe table's ANALYZE-time CMSketch when
        the planner traced the probe key to a base column."""
        engage, hot, h = self._hybrid_engage(bk, nb, raw_bk)
        if not engage:
            return None
        return op_hybrid.HybridJoinBuild(self._kernel, bk, nb,
                                         config.join_partitions(),
                                         self.plan, hot_hashes=hot, h=h)

    # lint: exempt[memtrack-alloc] pair-index buffers are billed at dispatch (cap*17 inside dispatch_nbytes); staged sub-chunks consume on mt_node below
    def _hybrid_probe(self, probe_iter, build, hyb, enc, matched_build):
        """Partitioned probe over a HybridJoinBuild.

        Phase 1 streams probe superchunks through the dispatch-ahead
        pipeline: rows route per partition (the heavy-hitter lane at
        index `parts`), device-resident partitions match immediately,
        and — once the memtrack quota action has spilled cold build
        partitions — rows bound for spilled partitions stage to host
        buffers instead of thrashing re-uploads. Phase 2 drains the
        staging one partition at a time, re-uploading each spilled
        build partition once and evicting it when drained.

        Every probe row reaches exactly one _post_match call (its
        matching, if any, is complete there), so outer-join unmatched
        detection and semi/anti emission stay exact per subset."""
        plan = self.plan
        kernel = self._kernel
        mt_node = memtrack.op_node(plan)
        staged: list = []      # (pid, sub_chunk, pk_lanes, host_bytes)

        def dispatch_one(p, pk_sub, hp_sub, n_sub):
            bdev = hyb.ensure(p)
            # SNAPSHOT the partition->global row map at dispatch time: a
            # later heavy-hitter promotion re-layouts the build while
            # this token is still in flight, and the pair indices must
            # resolve against the layout the matcher actually saw. The
            # pin keeps the partition's device bytes on the ledger (and
            # off the spill action's menu) while the token is pending.
            rows = hyb.build_rows(p)
            cap = hyb.hot_out_cap(hp_sub) if p == hyb.parts else None
            db = kernel.dispatch_nbytes(n_sub, cap)
            memtrack.consume(plan, device=db)
            hyb.pin(p)
            try:
                tok = kernel.dispatch(None, pk_sub, len(rows),
                                      n_sub, out_cap=cap, build_dev=bdev)
            except BaseException:
                hyb.unpin(p)
                memtrack.release(plan, device=db)
                raise
            return (p, rows, tok, db)

        def finalize_one(t):
            p, rows, tok, db = t
            t0 = time.perf_counter_ns()
            try:
                li_l, ri_l = kernel.finalize(tok)
            finally:
                hyb.unpin(p)
                memtrack.release(plan, device=db)
                runtime_stats.note_finalize_wait(
                    plan, time.perf_counter_ns() - t0)
            return li_l, rows[ri_l]

        # one superchunk fans out into one task per touched partition;
        # tasks (not whole superchunks) ride the dispatch-ahead pipeline
        # so only ~depth partitions are pinned by in-flight tokens at
        # any moment — everything else stays evictable by the quota
        # spill action. A superchunk's emission fires when its LAST
        # task finalizes (tasks of one superchunk are contiguous in the
        # stream, so that is also emission order).
        pending_promo: list = [None]
        open_states: dict = {}      # id -> state; bytes held to emission

        def task_iter(sc_iter):
            for sc in sc_iter:
                # apply the promotion observed on the PREVIOUS batch:
                # all of its tasks have dispatched by the time the
                # pipeline pulls this batch's first task, so no routed-
                # but-undispatched task can straddle the re-layout
                if pending_promo[0] is not None:
                    hyb.promote(pending_promo[0])
                    pending_promo[0] = None
                n = sc.num_rows
                pk = self._probe_keys(enc, sc.chunk)
                hp, tasks = hyb.route(pk, n)
                pending_promo[0] = hyb.observe(hp)
                staged_mask = np.zeros(n, dtype=bool)
                imm = []
                for p, idx in tasks:
                    if hyb.want_immediate(p):
                        imm.append((p, idx))
                    else:
                        sub = [(d[idx], v[idx]) for d, v in pk]
                        sub_chunk = sc.chunk.take(idx)
                        sb = memtrack.chunk_bytes(sub_chunk) + \
                            sum(d.nbytes + v.nbytes for d, v in sub)
                        if mt_node is not None:
                            # ownership transfer: staged probe bytes
                            # release in the drain loop / outer finally
                            mt_node.consume(host=sb)
                        staged.append((p, sub_chunk, sub, sb))
                        staged_mask[idx] = True
                sb = memtrack.chunk_bytes(sc.chunk)
                if mt_node is not None:
                    # held until the superchunk's emission (outer
                    # finally sweeps abandoned states)
                    mt_node.consume(host=sb)
                state = {"chunk": sc.chunk, "pk": pk, "hp": hp,
                         "mask": staged_mask, "li": [], "ri": [],
                         "left": max(len(imm), 1), "bytes": sb}
                open_states[id(state)] = state
                runtime_stats.note_superchunk(plan, n, sc.bucket,
                                              sc.sources)
                if not imm:
                    # every row staged or unmatched: one sentinel task
                    # still flows through so the emission fires
                    yield (state, None, None)
                else:
                    for p, idx in imm:
                        yield (state, p, idx)

        def dispatch(task):
            state, p, idx = task
            if p is None:
                return None
            pk = state["pk"]
            sub = [(d[idx], v[idx]) for d, v in pk]
            return dispatch_one(p, sub, state["hp"][idx], len(idx))

        def finalize(task, tok):
            state, _p, idx = task
            if tok is not None:
                li_l, ri = finalize_one(tok)
                state["li"].append(idx[li_l])
                state["ri"].append(ri)
            state["left"] -= 1
            if state["left"] > 0:
                return None
            open_states.pop(id(state), None)
            if mt_node is not None and state["bytes"]:
                mt_node.release(host=state["bytes"])
            li = np.concatenate(state["li"]) if state["li"] \
                else np.empty(0, dtype=np.int64)
            ri = np.concatenate(state["ri"]) if state["ri"] \
                else np.empty(0, dtype=np.int64)
            mask = state["mask"]
            if mask.any():
                # staged rows' matching is NOT complete: hand only the
                # immediately-matched subset to _post_match
                keep = np.flatnonzero(~mask)
                li = np.searchsorted(keep, li)
                return state["chunk"].take(keep), li, ri
            return state["chunk"], li, ri

        sc_iter = op_runtime.superchunk_batches(probe_iter,
                                                config.superchunk_rows(),
                                                tracker=mt_node)
        try:
            for out in op_runtime.pipeline_map(
                    task_iter(sc_iter), dispatch, finalize,
                    config.pipeline_depth()):
                if out is None:
                    continue
                chunk_out, li, ri = out
                yield from self._post_match(chunk_out, build, li, ri,
                                            matched_build)
            # phase 2: drain staged cold-partition rows, grouped by
            # partition so each spilled build uploads exactly once.
            # Promotions only ever MOVE keys to the always-resident hot
            # lane, so a staged batch re-routes within {its partition,
            # hot} and the grouping stays partition-local.
            staged.sort(key=lambda t: t[0])
            while staged:
                p_hint, sub_chunk, pk_sub, sb = staged[0]
                try:
                    hp, tasks = hyb.route(pk_sub, sub_chunk.num_rows)
                    li_parts, ri_parts = [], []
                    for p, idx in tasks:
                        lanes = [(d[idx], v[idx]) for d, v in pk_sub]
                        li_l, ri = finalize_one(
                            dispatch_one(p, lanes, hp[idx], len(idx)))
                        li_parts.append(idx[li_l])
                        ri_parts.append(ri)
                    li = np.concatenate(li_parts) if li_parts \
                        else np.empty(0, dtype=np.int64)
                    ri = np.concatenate(ri_parts) if ri_parts \
                        else np.empty(0, dtype=np.int64)
                finally:
                    staged.pop(0)
                    if mt_node is not None and sb:
                        mt_node.release(host=sb)
                yield from self._post_match(sub_chunk, build, li, ri,
                                            matched_build)
                if hyb.under_pressure() and \
                        (not staged or staged[0][0] != p_hint):
                    hyb.evict(p_hint)
        finally:
            if mt_node is not None:
                for _p, _c, _k, sb in staged:
                    if sb:
                        mt_node.release(host=sb)
                # superchunks abandoned before their last task finalized
                for state in open_states.values():
                    if state["bytes"]:
                        mt_node.release(host=state["bytes"])
            staged.clear()
            open_states.clear()

    def _pipelined_probe(self, probe_iter, build, bk, enc, matched_build,
                         nb: int):
        """Coalesced probe matching with dispatch-ahead: while superchunk
        k's matcher program executes, k+1's keys are encoded, padded and
        transferred (the host-side emit of k's output overlaps too). A
        probe too small to pay a dispatch matches on the host inline —
        same decision the per-chunk loop made, now per superchunk."""
        plan = self.plan
        kernel = self._kernel
        build_dev = None
        build_db = 0
        mt_node = memtrack.op_node(plan)

        def dispatch(sc):
            nonlocal build_dev, build_db
            n = sc.num_rows
            pk = self._probe_keys(enc, sc.chunk)
            if n < self._DEVICE_MIN_PROBE and nb < self._DEVICE_MIN_BUILD:
                return ("host", host_match_pairs(bk, pk, nb, n), 0)
            if build_dev is None:
                # build lanes stay device-resident for the whole probe:
                # held on the device ledger until the generator winds down
                build_db = kernel.build_nbytes(nb)
                memtrack.consume(plan, device=build_db)
                build_dev = kernel.prepare_build(bk, nb)
            db = kernel.dispatch_nbytes(n)
            memtrack.consume(plan, device=db)
            try:
                tok = kernel.dispatch(bk, pk, nb, n, build_dev=build_dev)
            except BaseException:
                memtrack.release(plan, device=db)
                raise
            runtime_stats.note_superchunk(plan, n, sc.bucket, sc.sources)
            return ("dev", tok, db)

        def finalize(sc, tok):
            kind, payload, db = tok
            if kind == "host":
                li, ri = payload
            else:
                t0 = time.perf_counter_ns()
                try:
                    li, ri = kernel.finalize(payload)
                finally:
                    memtrack.release(plan, device=db)
                    runtime_stats.note_finalize_wait(
                        plan, time.perf_counter_ns() - t0)
            return sc, li, ri

        sc_iter = op_runtime.superchunk_batches(probe_iter,
                                                config.superchunk_rows(),
                                                tracker=mt_node)
        try:
            for sc, li, ri in op_runtime.pipeline_map(
                    sc_iter, dispatch, finalize, config.pipeline_depth(),
                    tracker=mt_node,
                    cost=lambda sc: memtrack.chunk_bytes(sc.chunk)):
                yield from self._post_match(sc.chunk, build, li, ri,
                                            matched_build)
        finally:
            if build_db:
                memtrack.release(plan, device=build_db)

    def _gather(self, left_chunk, build, li, ri):
        cols = [Column(c.ft, c.data[li], c.valid[li])
                for c in left_chunk.columns]
        cols += [Column(c.ft, c.data[ri], c.valid[ri])
                 for c in build.columns]
        return Chunk(cols)

    # lint: exempt[memtrack-alloc] join-emit padding over the tracked build; pair buffers billed at dispatch
    def _emit(self, left_chunk, build, li, ri, left_unmatched, pair=None):
        plan = self.plan
        out = pair
        if out is None:
            out = self._gather(left_chunk, build, li, ri) \
                if len(li) or not len(left_unmatched) else None
        if plan.join_type == "left" and len(left_unmatched):
            ui = np.asarray(left_unmatched, dtype=np.int64)
            ucols = [Column(c.ft, c.data[ui], c.valid[ui])
                     for c in left_chunk.columns]
            for sc in self.plan.children[1].schema.cols:
                dtype = np_dtype_for(sc.ft.tp, sc.ft.flen)
                data = np.zeros(len(ui), dtype=dtype) \
                    if dtype != np.dtype(object) \
                    else np.full(len(ui), _ofill(sc.ft), dtype=object)
                ucols.append(Column(sc.ft, data,
                                    np.zeros(len(ui), dtype=bool)))
            uchunk = Chunk(ucols)
            out = uchunk if out is None else out.concat(uchunk)
        return out

    # lint: exempt[memtrack-alloc] emits over the tracked build side (right-unmatched pass)
    def _emit_right_unmatched(self, build, un):
        cols = []
        for sc in self.left.schema.cols:
            dtype = np_dtype_for(sc.ft.tp, sc.ft.flen)
            data = np.zeros(len(un), dtype=dtype) \
                if dtype != np.dtype(object) \
                else np.full(len(un), _ofill(sc.ft), dtype=object)
            cols.append(Column(sc.ft, data, np.zeros(len(un), dtype=bool)))
        for c in build.columns:
            cols.append(Column(c.ft, c.data[un], c.valid[un]))
        return Chunk(cols)

    def _cross_join(self, ctx):
        build = None
        tracked = 0
        for chunk in self.right.chunks(ctx):
            build = chunk if build is None else build.concat(chunk)
            tracked = memtrack.track_to(
                self.plan, memtrack.chunk_bytes(build), tracked)
        if build is None or build.num_rows == 0:
            memtrack.release(self.plan, host=tracked)
            return
        try:
            yield from self._cross_probe(ctx, build)
        finally:
            memtrack.release(self.plan, host=tracked)

    def _cross_probe(self, ctx, build):
        nb = build.num_rows
        for chunk in self.left.chunks(ctx):
            nl = chunk.num_rows
            if nl == 0:
                continue
            li = np.repeat(np.arange(nl), nb)
            ri = np.tile(np.arange(nb), nl)
            cols = [Column(c.ft, c.data[li], c.valid[li])
                    for c in chunk.columns]
            cols += [Column(c.ft, c.data[ri], c.valid[ri])
                     for c in build.columns]
            out = Chunk(cols)
            if self.plan.other_cond is not None:
                out = out.filter(eval_filter_host(self.plan.other_cond, out))
            yield out


class MergeJoinExec(HashJoinExec):
    """Streaming sorted-merge equi-join (ref: executor/merge_join.go:34).

    Contract (planner-enforced): both children deliver rows ascending by
    their single join key — pk-handle table scans arrive in handle order,
    keep_order index readers in index order. The executor keeps only a
    sliding window of the right side (rows whose key may still match a
    future left chunk), so neither side is fully materialized: memory is
    O(chunk + widest equal-key run). Matching is one vectorized
    searchsorted per left chunk — the same sort-join shape as the device
    kernel, minus the sort the inputs already paid."""

    def __init__(self, plan: ph.PhysMergeJoin):
        self.plan = plan
        self.schema = plan.schema
        self.left = build_executor(plan.children[0])
        self.right = build_executor(plan.children[1])
        self._kernel = None   # no device kernel: inputs are pre-sorted

    # lint: exempt[memtrack-alloc] merge window concatenation billed via track_to on the window buffer
    def chunks(self, ctx):
        plan = self.plan
        right_iter = self.right.chunks(ctx)
        window: Chunk | None = None    # right rows that may still match
        right_done = False
        # the sliding right window is this operator's only buffer; an
        # abandoned generator's residue is credited back at statement
        # detach (memtrack release-on-close)
        tracked_w = 0

        def right_key(ch):
            d, v = self._eval_keys(plan.right_keys, ch)[0]
            return d, v

        for chunk in self.left.chunks(ctx):
            n = chunk.num_rows
            if n == 0:
                continue
            lk, lv = self._eval_keys(plan.left_keys, chunk)[0]
            has_valid = bool(np.any(lv))
            lmax = lk[lv].max() if has_valid else None
            # grow the window until its tail key exceeds this chunk's max
            while not right_done and has_valid:
                wd, wv = (right_key(window) if window is not None
                          and window.num_rows else (None, None))
                if wd is not None and len(wd) and wv[-1] and wd[-1] > lmax:
                    break
                nxt = next(right_iter, None)
                if nxt is None:
                    right_done = True
                    break
                window = nxt if window is None else window.concat(nxt)
            tracked_w = memtrack.track_to(
                plan, memtrack.chunk_bytes(window) if window is not None
                else 0, tracked_w)
            if window is None or window.num_rows == 0:
                li = ri = np.empty(0, np.int64)
                unmatched = np.arange(n) if plan.join_type == "left" \
                    else np.empty(0, np.int64)
                out = self._emit(chunk, _empty_like_schema(
                    self.plan.children[1].schema), li, ri, unmatched)
                if out is not None and out.num_rows:
                    yield out
                continue
            wd, wv = right_key(window)
            val_idx = np.flatnonzero(wv)
            wdv = wd[val_idx]
            lo = np.searchsorted(wdv, lk, side="left")
            hi = np.searchsorted(wdv, lk, side="right")
            counts = np.where(lv, hi - lo, 0)
            total = int(counts.sum())
            li = np.repeat(np.arange(n), counts)
            cs = np.concatenate(([0], np.cumsum(counts)[:-1]))
            w = np.arange(total) - np.repeat(cs, counts)
            ri = val_idx[np.repeat(lo, counts) + w] if total else \
                np.empty(0, np.int64)
            pair = None
            if plan.other_cond is not None and len(li):
                pair = self._gather(chunk, window, li, ri)
                keep = eval_filter_host(plan.other_cond, pair)
                li, ri = li[keep], ri[keep]
                pair = pair.filter(keep)
            unmatched = np.empty(0, np.int64)
            if plan.join_type == "left":
                m = np.zeros(n, dtype=bool)
                m[li] = True
                unmatched = np.flatnonzero(~m)
            out = self._emit(chunk, window, li, ri, unmatched, pair=pair)
            if out is not None and out.num_rows:
                yield out
            # slide: right rows strictly below this chunk's max key can
            # never match again (left keys are non-decreasing)
            if has_valid and window.num_rows:
                keep = ~wv | (wd >= lmax)
                if not keep.all():
                    window = window.filter(keep)
                    tracked_w = memtrack.track_to(
                        plan, memtrack.chunk_bytes(window), tracked_w)
        memtrack.release(plan, host=tracked_w)


def _empty_like_schema(schema) -> Chunk:
    cols = []
    for sc in schema.cols:
        dtype = np_dtype_for(sc.ft.tp, sc.ft.flen)
        data = np.empty(0, dtype=dtype if dtype != np.dtype(object)
                        else object)
        cols.append(Column(sc.ft, data, np.empty(0, dtype=bool)))
    return Chunk(cols)


class IndexJoinExec(HashJoinExec):
    """Index nested-loop join (ref: executor/index_lookup_join.go:87).

    Streams the outer side; per outer chunk, collects the distinct valid
    join-key values and fetches ONLY the matching inner rows — via pk
    point reads (batch_get) when the key is the handle, else via
    synthesized point index ranges through the coprocessor. The fetched
    inner batch then joins against the chunk with the standard pair
    matcher. Never scans the inner table."""

    def __init__(self, plan: ph.PhysIndexJoin):
        self.plan = plan
        self.schema = plan.schema
        self.left = build_executor(plan.children[0])
        self._kernel = JoinKernel(len(plan.left_keys))

    def _fetch_inner(self, ctx, key_vals: np.ndarray) -> Chunk:
        """Inner rows whose key is in key_vals (distinct, non-null).
        Under a dirty txn, the SAME point lookups run through the union
        store (membuffer overlay) instead of the snapshot, so own writes
        are visible without ever scanning the whole inner table."""
        from tidb_tpu import ranger as rg
        icop = self.plan.children[1].cop
        dirty = _txn_is_dirty(ctx, icop.table.id)
        if self.plan.inner_index is None:
            handles = [int(v) for v in key_vals]
            if dirty:
                return self._dirty_rows_by_handles(ctx, icop, handles)
            return self._fetch_rows_by_handles(ctx, icop, handles)
        # secondary index: scan index entries for the key points to get
        # handles, then batch-fetch the rows (the per-batch form of
        # IndexLookUpExecutor, executor/distsql.go:524)
        ft = self.plan.right_keys[0].ft
        ranges = [rg.DatumRange(low=[_index_datum(v, ft)],
                                high=[_index_datum(v, ft)])
                  for v in key_vals]
        kv_ranges = rg.index_ranges_to_kv(icop.table.id,
                                          self.plan.inner_index.id, ranges)
        index_cols = [icop.table.col_by_name(c)
                      for c in self.plan.inner_index.columns]
        if dirty:
            # point index ranges through the union store: dirty index
            # entries (and tombstones) shadow the snapshot's. One range
            # scan per distinct key (bounded by the outer chunk's
            # distinct count); batching the snapshot side through the
            # coprocessor would need tombstone matching by raw index key
            # (unique-index tombstones carry no handle), so the simple
            # union scan wins until dirty index joins prove hot
            from tidb_tpu.table import index_kvrows_to_chunk
            rows = []
            for rng in kv_ranges:
                rows.extend(ctx.txn.iter_range(rng.start, rng.end))
            ich = index_kvrows_to_chunk(icop.table, self.plan.inner_index,
                                        index_cols, rows, len(index_cols))
            hc = ich.columns[len(index_cols)]
            handles = [int(h) for h in hc.data[:ich.num_rows]]
            return self._dirty_rows_by_handles(ctx, icop, handles)
        index_cop = ph.CopPlan(table=icop.table, cols=index_cols,
                               handle_col=len(index_cols),
                               index=self.plan.inner_index,
                               ranges=kv_ranges)
        req = CopRequest(tp=ReqType.DAG, ranges=kv_ranges,
                         plan=index_cop, start_ts=ctx.read_ts)
        handles: list[int] = []
        for resp in ctx.storage.client().send(req):
            hc = resp.chunk.columns[len(index_cols)]
            handles.extend(int(h) for h in hc.data[:resp.chunk.num_rows])
        return self._fetch_rows_by_handles(ctx, icop, handles)

    def _fetch_rows_by_handles(self, ctx, icop, handles) -> Chunk:
        snap = ctx.storage.snapshot(ctx.read_ts)
        keys = [tablecodec.record_key(icop.table.id, h) for h in handles]
        got = snap.batch_get(keys)
        kvrows = [(k, got[k]) for k in keys if k in got]
        chunk = kvrows_to_chunk(icop.table, icop.cols, kvrows,
                                icop.handle_col)
        return exec_cop_plan(icop, chunk).chunk

    def _dirty_rows_by_handles(self, ctx, icop, handles) -> Chunk:
        """Point reads with the membuffer overlaid on ONE batched
        snapshot read: own inserts appear, own deletes vanish, and the
        clean majority of keys costs a single batch_get instead of
        per-key round trips."""
        keys = [tablecodec.record_key(icop.table.id, h)
                for h in dict.fromkeys(int(h) for h in handles)]
        membuf = ctx.txn.us.membuf
        dirty_vals = {}
        clean = []
        for k in keys:
            v = membuf.get(k)
            if v is None:
                clean.append(k)
            else:
                dirty_vals[k] = v
        got = ctx.txn.snapshot.batch_get(clean) if clean else {}
        kvrows = []
        for k in keys:
            v = dirty_vals.get(k)
            if v is None:
                v = got.get(k)
            elif v is kv._TOMBSTONE:     # own delete shadows the snapshot
                continue
            if v is not None:
                kvrows.append((k, v))
        chunk = kvrows_to_chunk(icop.table, icop.cols, kvrows,
                                icop.handle_col)
        return exec_cop_plan(icop, chunk).chunk

    def chunks(self, ctx):
        plan = self.plan
        tracked = 0
        for chunk in self.left.chunks(ctx):
            n = chunk.num_rows
            if n == 0:
                continue
            kd, kv = plan.left_keys[0].eval(chunk)
            kd, kv = np.asarray(kd), np.asarray(kv, dtype=bool)
            vals = np.unique(kd[kv]) if kv.any() else kd[:0]
            build = self._fetch_inner(ctx, vals) if len(vals) else \
                _empty_like_schema(plan.children[1].schema)
            # per-outer-batch inner build: tracked to its replacement
            tracked = memtrack.track_to(
                plan, memtrack.chunk_bytes(build), tracked)
            nb = build.num_rows
            if nb == 0:
                if plan.join_type == "left":
                    out = self._emit(chunk, build, np.empty(0, np.int64),
                                     np.empty(0, np.int64), np.arange(n))
                    if out is not None and out.num_rows:
                        yield out
                continue
            enc = JoinKeyEncoder(len(plan.right_keys))  # fresh per batch
            bk = enc.fit_build(self._eval_keys(plan.right_keys, build))
            pk = enc.transform_probe(self._eval_keys(plan.left_keys, chunk))
            with sched.device_slot(), memtrack.device_scope(
                    self.plan, self._kernel.build_nbytes(nb) +
                    self._kernel.dispatch_nbytes(n)):
                li, ri = runtime_stats.device_call(
                    self.plan, self._kernel, bk, pk, nb, n)
            pair = None
            if plan.other_cond is not None and len(li):
                pair = self._gather(chunk, build, li, ri)
                keep = eval_filter_host(plan.other_cond, pair)
                li, ri = li[keep], ri[keep]
                pair = pair.filter(keep)
            unmatched = np.empty(0, np.int64)
            if plan.join_type == "left":
                m = np.zeros(n, dtype=bool)
                m[li] = True
                unmatched = np.flatnonzero(~m)
            out = self._emit(chunk, build, li, ri, unmatched, pair=pair)
            if out is not None and out.num_rows:
                yield out
        memtrack.release(plan, host=tracked)


def _index_datum(v, ft):
    """numpy scalar -> the datum representation codec.encode_key expects
    for an index column of FieldType ft."""
    if ft.eval_type == EvalType.DECIMAL:
        return (ft.frac, int(v))
    if isinstance(v, (np.integer, int)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        return float(v)
    return v


# ---------------------------------------------------------------------------
# Writes

def _chunk_row_to_kvdatums(chunk: Chunk, cols, row: int) -> dict[int, object]:
    """Row of a reader chunk -> {col_id: KV datum} for index maintenance."""
    out = {}
    for j, ci in enumerate(cols):
        c = chunk.columns[j]
        if not c.valid[row]:
            out[ci.id] = None
            continue
        v = c.data[row]
        if ci.ft.eval_type == EvalType.DECIMAL:
            out[ci.id] = (ci.ft.frac, int(v))
        elif c.data.dtype == np.dtype(object):
            out[ci.id] = v
        else:
            out[ci.id] = v.item()
    return out


class InsertExec(Executor):
    """Ref: executor/write.go:896 InsertExec (dup handling :1343)."""

    def __init__(self, plan: ph.PhysInsert):
        self.plan = plan
        self.schema = plan.schema
        self.source = build_executor(plan.source)

    def execute(self, ctx: ExecContext) -> int:
        from tidb_tpu.table import DupKeyError, Table
        plan = self.plan
        tbl = Table(plan.table, ctx.storage)
        txn = ctx.txn
        affected = 0
        for values in self._source_rows(ctx):
            try:
                tbl.add_record(txn, values)
                affected += 1
            except DupKeyError:
                if plan.ignore:
                    continue
                if plan.is_replace or plan.on_duplicate:
                    affected += self._handle_dup(ctx, tbl, txn, values)
                    continue
                raise
        if tbl.first_alloc_id is not None:
            # LAST_INSERT_ID(): first auto value of this statement
            ctx.last_insert_id = tbl.first_alloc_id
        return affected

    def _source_rows(self, ctx):
        """Yields {col_name: value} dicts; a key present with None is an
        explicit NULL, an absent key means 'use the default' (DEFAULT
        keyword or omitted column)."""
        plan = self.plan
        if isinstance(plan.source, ph.PhysValues) and not plan.source.schema.cols:
            # literal VALUES rows: evaluate per cell; None expr == DEFAULT
            for rexprs in plan.source.rows:
                values = {}
                for cname, e in zip(plan.columns, rexprs):
                    if e is None:      # DEFAULT keyword
                        continue
                    d, v = e.eval_xp(np, [], 1)
                    if not v[0]:
                        values[cname] = None
                    elif e.ft.eval_type == EvalType.DECIMAL:
                        values[cname] = (e.ft.frac, int(d[0]))
                    else:
                        values[cname] = d[0].item() \
                            if hasattr(d[0], "item") else d[0]
                yield values
            return
        for chunk in self.source.chunks(ctx):
            src_cols = chunk.columns
            for i in range(chunk.num_rows):
                values = {}
                for cname, col in zip(plan.columns, src_cols):
                    if not col.valid[i]:
                        values[cname] = None   # explicit NULL
                        continue
                    v = col.data[i]
                    if col.ft.eval_type == EvalType.DECIMAL:
                        # scaled at the SOURCE column's frac; target frac
                        # conversion happens in encode_datum_for_col
                        values[cname] = (col.ft.frac, int(v))
                    else:
                        values[cname] = v.item() if hasattr(v, "item") else v
                yield values

    def _handle_dup(self, ctx, tbl: "Table", txn, values) -> int:
        """REPLACE / ON DUPLICATE KEY UPDATE: find the conflicting row."""
        info = self.plan.table
        handle = self._find_conflict(tbl, txn, values)
        if handle is None:
            raise ExecError("duplicate row vanished")
        old = tbl.row_by_handle(txn, handle)
        if self.plan.is_replace:
            tbl.remove_record(txn, handle, old)
            tbl.add_record(txn, values)
            return 2
        # ON DUPLICATE KEY UPDATE over [old | candidate]: the second
        # half feeds VALUES(col) refs (planner's __values__ columns)
        cols = info.public_columns()
        from tidb_tpu.table import encode_datum_for_col, rows_to_chunk
        cand = []
        for c in cols:
            cn = c.name.lower()
            if cn in values:
                cand.append(encode_datum_for_col(values[cn], c.ft))
            elif c.has_default:
                cand.append(encode_datum_for_col(c.default, c.ft))
            else:
                cand.append(None)
        row_chunk = rows_to_chunk(
            [c.ft for c in cols] * 2,
            [[old.get(c.id) for c in cols] + cand])
        new_vals = {}
        for cname, expr in self.plan.on_duplicate:
            d, v = expr.eval(row_chunk)
            ci = info.col_by_name(cname)
            if not v[0]:
                new_vals[cname] = None
            elif ci.ft.eval_type == EvalType.DECIMAL:
                new_vals[cname] = (expr.ft.frac if
                                   expr.ft.eval_type == EvalType.DECIMAL
                                   else ci.ft.frac, int(d[0]))
            else:
                new_vals[cname] = d[0].item() if hasattr(d[0], "item") \
                    else d[0]
        tbl.update_record(txn, handle, old, new_vals)
        return 2

    def _find_conflict(self, tbl, txn, values):
        info = self.plan.table
        if info.pk_is_handle:
            pk = info.col_by_name(info.pk_col_name)
            v = values.get(info.pk_col_name.lower())
            if v is not None and tbl.row_by_handle(txn, int(v)) is not None:
                return int(v)
        for idx in info.indexes:
            if not idx.unique:
                continue
            vals = []
            for cn in idx.columns:
                ci = info.col_by_name(cn)
                v = encode_datum_for_col(values.get(cn.lower()), ci.ft)
                if ci.ft.is_ci and isinstance(v, str):
                    from tidb_tpu.sqltypes import collation_key
                    v = collation_key(v)
                vals.append(v)
            if any(v is None for v in vals):
                continue
            raw = txn.get(tablecodec.index_key(info.id, idx.id, vals))
            if raw is not None:
                from tidb_tpu import codec
                return codec.decode_int(raw)[0]
        return None


class UpdateExec(Executor):
    def __init__(self, plan: ph.PhysUpdate):
        self.plan = plan
        self.reader = build_executor(plan.reader)

    def execute(self, ctx: ExecContext) -> int:
        plan = self.plan
        tbl = Table(plan.table, ctx.storage)
        cols = plan.table.public_columns()
        affected = 0
        for chunk in self.reader.chunks(ctx):
            if chunk.num_rows == 0:
                continue
            handle_col = chunk.columns[-1]
            new_cols = {}
            for cname, expr in plan.assignments:
                new_cols[cname] = (expr, *expr.eval(chunk))
            pk_name = plan.table.pk_col_name.lower() \
                if plan.table.pk_is_handle else None
            for i in range(chunk.num_rows):
                handle = int(handle_col.data[i])
                old = _chunk_row_to_kvdatums(chunk, cols, i)
                new_vals = {}
                for cname, (expr, d, v) in new_cols.items():
                    ci = plan.table.col_by_name(cname)
                    if not v[i]:
                        new_vals[cname] = None
                    elif ci.ft.eval_type == EvalType.DECIMAL:
                        frac = expr.ft.frac if \
                            expr.ft.eval_type == EvalType.DECIMAL else ci.ft.frac
                        new_vals[cname] = (frac, int(d[i]))
                    else:
                        new_vals[cname] = d[i].item() \
                            if hasattr(d[i], "item") else d[i]
                if pk_name is not None and pk_name in new_vals and \
                        new_vals[pk_name] is not None and \
                        int(new_vals[pk_name]) != handle:
                    # handle change: move the row (delete + insert w/ dup
                    # check) instead of rewriting under the old handle
                    merged = {}
                    for c in cols:
                        merged[c.name.lower()] = old.get(c.id)
                    merged.update(new_vals)
                    tbl.remove_record(ctx.txn, handle, old)
                    tbl.add_record(ctx.txn, merged)
                else:
                    tbl.update_record(ctx.txn, handle, old, new_vals)
                affected += 1
        return affected


class DeleteExec(Executor):
    def __init__(self, plan: ph.PhysDelete):
        self.plan = plan
        self.reader = build_executor(plan.reader)

    def execute(self, ctx: ExecContext) -> int:
        tbl = Table(self.plan.table, ctx.storage)
        cols = self.plan.table.public_columns()
        affected = 0
        for chunk in self.reader.chunks(ctx):
            handle_col = chunk.columns[-1]
            for i in range(chunk.num_rows):
                handle = int(handle_col.data[i])
                old = _chunk_row_to_kvdatums(chunk, cols, i)
                tbl.remove_record(ctx.txn, handle, old)
                affected += 1
        return affected


class MultiUpdateExec(Executor):
    """UPDATE t1, t2 SET ... (ref: executor/write.go:479 multi-table
    UpdateExec): one pass over the join result; each target updates its
    matched rows, deduped per handle; assignment expressions evaluate
    over the full join row, so t1's new value may read t2's columns."""

    def __init__(self, plan: ph.PhysMultiUpdate):
        self.plan = plan
        self.reader = build_executor(plan.reader)

    def execute(self, ctx: ExecContext) -> int:
        per_target = []
        for info, col_start, handle_idx, assigns in self.plan.targets:
            per_target.append((Table(info, ctx.storage), info,
                               col_start, handle_idx, assigns, set()))
        affected = 0
        for chunk in self.reader.chunks(ctx):
            if chunk.num_rows == 0:
                continue
            for tbl, info, col_start, handle_idx, assigns, seen \
                    in per_target:
                hcol = chunk.columns[handle_idx]
                cols = info.public_columns()
                block = Chunk(chunk.columns[col_start:
                                            col_start + len(cols)])
                new_cols = {}
                for cname, expr in assigns:
                    new_cols[cname] = (expr, *expr.eval(chunk))
                pk_name = info.pk_col_name.lower() \
                    if info.pk_is_handle else None
                for i in range(chunk.num_rows):
                    if not hcol.valid[i]:
                        continue    # outer-join padding: no row there
                    handle = int(hcol.data[i])
                    if handle in seen:
                        continue
                    seen.add(handle)
                    old = _chunk_row_to_kvdatums(block, cols, i)
                    new_vals = {}
                    for cname, (expr, d, v) in new_cols.items():
                        ci = info.col_by_name(cname)
                        if not v[i]:
                            new_vals[cname] = None
                        elif ci.ft.eval_type == EvalType.DECIMAL:
                            frac = expr.ft.frac if \
                                expr.ft.eval_type == EvalType.DECIMAL \
                                else ci.ft.frac
                            new_vals[cname] = (frac, int(d[i]))
                        else:
                            new_vals[cname] = d[i].item() \
                                if hasattr(d[i], "item") else d[i]
                    if pk_name is not None and pk_name in new_vals and \
                            new_vals[pk_name] is not None and \
                            int(new_vals[pk_name]) != handle:
                        merged = {}
                        for c in cols:
                            merged[c.name.lower()] = old.get(c.id)
                        merged.update(new_vals)
                        tbl.remove_record(ctx.txn, handle, old)
                        tbl.add_record(ctx.txn, merged)
                    else:
                        tbl.update_record(ctx.txn, handle, old, new_vals)
                    affected += 1
        return affected


class MultiDeleteExec(Executor):
    """DELETE t1, t2 FROM <join> (ref: executor/write.go:194
    deleteMultiTables): one pass over the join result; each target
    deletes its matched rows, deduped per handle (a handle can match
    several join rows)."""

    def __init__(self, plan: ph.PhysMultiDelete):
        self.plan = plan
        self.reader = build_executor(plan.reader)

    def execute(self, ctx: ExecContext) -> int:
        per_target = []
        for info, col_start, handle_idx in self.plan.targets:
            per_target.append((Table(info, ctx.storage), info,
                               col_start, handle_idx, set()))
        affected = 0
        for chunk in self.reader.chunks(ctx):
            for tbl, info, col_start, handle_idx, seen in per_target:
                hcol = chunk.columns[handle_idx]
                cols = info.public_columns()
                block = Chunk(chunk.columns[col_start:
                                            col_start + len(cols)])
                for i in range(chunk.num_rows):
                    if not hcol.valid[i]:
                        continue    # outer-join padding: no row there
                    handle = int(hcol.data[i])
                    if handle in seen:
                        continue
                    seen.add(handle)
                    old = _chunk_row_to_kvdatums(block, cols, i)
                    tbl.remove_record(ctx.txn, handle, old)
                    affected += 1
        return affected


class ApplyExec(Executor):
    """Correlated-subquery apply (ref: executor/join.go:447
    NestedLoopApplyExec): per outer row, bind the correlated cells, run
    the inner plan, and evaluate the EXISTS / IN / comparison predicate
    as a filter over the outer rows. Uncorrelated inners run exactly once
    and the predicate vectorizes over the whole chunk."""

    def __init__(self, plan: ph.PhysApply):
        self.plan = plan
        self.schema = plan.schema
        self.child = build_executor(plan.children[0])

    def chunks(self, ctx: ExecContext):
        plan = self.plan
        if plan.mode == "scalar":
            yield from self._scalar_chunks(ctx)
            return
        cache = None            # uncorrelated: (vals, valid, has_rows)
        for chunk in self.child.chunks(ctx):
            n = chunk.num_rows
            if n == 0:
                continue
            left = None
            if plan.left is not None:
                ld, lv = plan.left.eval(chunk)
                left = (np.asarray(ld), np.asarray(lv))
            if not plan.corr:
                if cache is None:
                    cache = self._run_inner(ctx,
                                            first_only=plan.mode == "exists")
                keep = self._vector_predicate(left, n, *cache)
            else:
                keep = np.zeros(n, dtype=bool)
                for i in range(n):
                    self._bind_corr(chunk, i)
                    vals, valid, has = self._run_inner(
                        ctx, first_only=plan.mode == "exists")
                    row_left = None if left is None else \
                        (left[0][i:i + 1], left[1][i:i + 1])
                    keep[i] = bool(self._vector_predicate(
                        row_left, 1, vals, valid, has)[0])
            yield chunk.filter(keep)

    def _scalar_chunks(self, ctx):
        """mode="scalar": append the inner's single value as a new
        column (the planner's lifted scalar subquery)."""
        plan = self.plan
        ft = plan.schema.cols[-1].ft
        dtype = np_dtype_for(ft.tp, ft.flen)
        cache = None
        for chunk in self.child.chunks(ctx):
            n = chunk.num_rows
            if n == 0:
                continue
            if not plan.corr:
                if cache is None:
                    cache = self._scalar_value(ctx)
                val, ok = cache
                data = np.full(n, val if ok else
                               ("" if dtype == np.dtype(object) else 0),
                               dtype=dtype)
                valid = np.full(n, ok, dtype=bool)
            else:
                # lint: exempt[memtrack-alloc] one scalar column per probe chunk
                data = np.zeros(n, dtype=dtype) \
                    if dtype != np.dtype(object) else \
                    np.full(n, "", dtype=object)
                valid = np.zeros(n, dtype=bool)
                for i in range(n):
                    self._bind_corr(chunk, i)
                    val, ok = self._scalar_value(ctx)
                    if ok:
                        data[i] = val
                        valid[i] = True
            yield Chunk(chunk.columns + [Column(ft, data, valid)])

    def _scalar_value(self, ctx):
        """Run the inner plan expecting at most one row -> (value, ok);
        an empty result is SQL NULL."""
        vals, valid, has = self._run_inner(ctx, first_only=False)
        if not has or len(vals) == 0:
            return None, False
        if len(vals) > 1:
            raise ExecError("Subquery returns more than 1 row")
        return vals[0], bool(valid[0])

    def _bind_corr(self, chunk, i: int):
        """Bind outer row i into the inner plan's correlated cells."""
        for oi, cell in self.plan.corr:
            c = chunk.columns[oi]
            cell.cell[0] = c.data[i]
            cell.cell[1] = bool(c.valid[i])

    def _run_inner(self, ctx, first_only: bool):
        """-> (first-column values, valid, has_rows)."""
        exe = build_executor(self.plan.inner)
        vals = []
        valid = []
        has = False
        for ch in exe.chunks(ctx):
            if ch.num_rows == 0:
                continue
            has = True
            if first_only:
                return None, None, True
            c = ch.columns[0]
            vals.append(np.asarray(c.data))
            valid.append(np.asarray(c.valid))
        if not vals:
            return (np.empty(0), np.empty(0, dtype=bool), has)
        # lint: exempt[memtrack-alloc] subquery first-column buffer, inner-bounded
        return np.concatenate(vals), np.concatenate(valid), has

    def _vector_predicate(self, left, n: int, vals, valid, has):
        plan = self.plan
        if plan.mode == "exists":
            r = np.full(n, has, dtype=bool)
            return ~r if plan.negated else r
        if plan.mode == "cmp":
            if plan.quant:
                return self._quant_mask(left, n, vals, valid)
            if not has or len(vals) == 0:
                return np.zeros(n, dtype=bool)   # NULL -> filtered
            if len(vals) > 1:
                raise ExecError("Subquery returns more than 1 row")
            return self._cmp_mask(left, n, vals, valid)
        # IN / NOT IN with SQL three-valued logic
        ld, lv = left
        inner = vals[valid] if len(vals) else vals
        has_null = bool((~valid).any()) if len(valid) else False
        match = self._set_match(ld, inner)
        if plan.negated:
            # NOT IN: TRUE only for valid left, no match, and no NULLs
            # in the subquery result (else NULL) — except the empty set,
            # where x NOT IN () is TRUE even for NULL x
            if has_null:
                return np.zeros(n, dtype=bool)
            if len(inner) == 0:
                return np.ones(n, dtype=bool)
            return lv & ~match
        return lv & match

    def _norm_in_sides(self, ld, inner):
        """Bring both IN sides to one comparable representation (mirrors
        HashJoinExec key normalization): decimals compare at a common
        scale, mixed numeric compares as double."""
        lft = self.plan.left.ft
        ift = self.plan.inner.schema.cols[0].ft
        let, iet = lft.eval_type, ift.eval_type
        if np.dtype(object) in (getattr(ld, "dtype", None),
                                getattr(inner, "dtype", None)):
            return ld, inner
        lfrac = lft.frac if let == EvalType.DECIMAL else 0
        ifrac = ift.frac if iet == EvalType.DECIMAL else 0
        if let == iet and lfrac == ifrac:
            return ld, inner
        def to_f(d, frac):
            return np.asarray(d).astype(np.float64) / (10.0 ** frac)
        return to_f(ld, lfrac), to_f(inner, ifrac)

    def _quant_mask(self, left, n: int, vals, valid):
        """expr <cmp> ANY/ALL (subquery) with SQL three-valued logic
        (ref: expression/builtin_compare.go + plan rewrite of
        quantified comparisons): only the set's extrema decide ordering
        comparisons, so no per-element loop is needed.

        ANY:  TRUE if some valid element satisfies; else NULL if the
              set has NULLs or the left is NULL; else FALSE (empty ->
              FALSE).
        ALL:  FALSE if some valid element violates; else NULL if the
              set has NULLs or the left is NULL; else TRUE (empty ->
              TRUE)."""
        from tidb_tpu.expression.core import Op as _Op
        plan = self.plan
        ld, lv = left
        vv = vals[valid] if len(vals) else vals
        has_null_inner = bool((~valid).any()) if len(valid) else False
        is_all = plan.quant == "all"
        if len(vv) == 0:
            if has_null_inner:          # all-NULL set: always NULL
                return np.zeros(n, dtype=bool)
            base = np.full(n, is_all, dtype=bool)   # truly empty set
            return ~base if plan.negated else base
        op = plan.cmp_op

        def cmp_vs(v, o):
            return self._one_cmp(ld, lv, n, v, o)

        lo, hi = vv.min(), vv.max()
        if op in (_Op.EQ, _Op.NE):
            # = ANY is IN; = ALL: every element equal (min==v==max);
            # <> ALL is NOT IN; <> ANY: some element differs
            def all_eq():
                return cmp_vs(lo, _Op.EQ) & cmp_vs(hi, _Op.EQ)
            def in_set():
                return lv & self._set_match(ld, vv)
            if op == _Op.EQ:
                true_m = all_eq() if is_all else in_set()
            else:
                true_m = (lv & ~in_set()) if is_all else (lv & ~all_eq())
        else:
            # ordering: ANY against the friendliest element, ALL
            # against the harshest
            pick_min = (op in (_Op.GT, _Op.GE)) != is_all
            true_m = cmp_vs(lo if pick_min else hi, op)
        if is_all:
            # violation is definite FALSE even with NULLs around
            false_m = lv & ~true_m
            if has_null_inner:
                true_m = np.zeros(n, dtype=bool)
            return false_m if plan.negated else true_m
        if has_null_inner:
            false_m = np.zeros(n, dtype=bool)
        else:
            false_m = lv & ~true_m
        return false_m if plan.negated else true_m

    def _set_match(self, ld, inner):
        """Membership of each left value in the inner set, after the
        shared type normalization. Used by IN and the EQ quantifiers."""
        ld2, inner2 = self._norm_in_sides(ld, inner)
        if len(inner2) and inner2.dtype != np.dtype(object) and \
                ld2.dtype != np.dtype(object):
            return np.isin(ld2, inner2)
        pool = set(inner2.tolist())
        return np.array([v in pool for v in ld2], dtype=bool)

    def _one_cmp(self, ld, lv, n: int, v, op):
        """Vector compare of the left side against one inner value,
        through the expression layer for type-correct semantics."""
        plan = self.plan
        ift = plan.inner.schema.cols[0].ft
        dt = np.dtype(object) if isinstance(v, (str, bytes)) else None
        rhs_d = np.full(n, v, dtype=dt)
        lexpr = _ArrayExpr(plan.left.ft, ld, lv)
        rexpr = _ArrayExpr(ift, rhs_d, np.ones(n, dtype=bool))
        from tidb_tpu.expression.core import func as _f
        d, vmask = _f(op, lexpr, rexpr).eval_xp(np, [], n)
        return np.asarray(d).astype(bool) & np.asarray(vmask) & lv

    def _cmp_mask(self, left, n: int, vals, valid):
        plan = self.plan
        if not bool(valid[0]):
            return np.zeros(n, dtype=bool)       # NULL scalar
        ld, lv = left
        ift = plan.inner.schema.cols[0].ft
        v = vals[0]
        rhs_d = np.full(n, v, dtype=vals.dtype) if \
            vals.dtype != np.dtype(object) else np.full(n, v, dtype=object)
        # compare through the expression layer for type-correct semantics
        lexpr = _ArrayExpr(plan.left.ft, ld, lv)
        rexpr = _ArrayExpr(ift, rhs_d, np.ones(n, dtype=bool))
        from tidb_tpu.expression.core import func as _f
        d, vmask = _f(plan.cmp_op, lexpr, rexpr).eval_xp(np, [], n)
        out = np.asarray(d).astype(bool) & np.asarray(vmask)
        return ~out & np.asarray(vmask) if plan.negated else out


class _ArrayExpr(Expression):
    """Adapter: a precomputed (data, valid) pair as an Expression leaf."""

    def __init__(self, ft, data, valid):
        self.ft = ft
        self._d = data
        self._v = valid

    def eval_xp(self, xp, cols, n):
        return self._d, self._v

    def columns_used(self):
        return set()

    def is_device_safe(self):
        return False


def _mesh_agg_builder(plan):
    from tidb_tpu.executor.mesh import MeshAggExec
    return MeshAggExec(plan)


def _mesh_lookup_agg_builder(plan):
    from tidb_tpu.executor.mesh import MeshLookupAggExec
    return MeshLookupAggExec(plan)


from tidb_tpu.plan import mesh_route as _mr  # noqa: E402

class UnionExec(Executor):
    """UNION ALL over chunk streams: children run in order, their chunks
    pass through with columns coerced to the union's output types
    (numeric widening; names from the first branch). DISTINCT is a
    HashAgg the planner layers on top — no row-level Python dedup."""

    def __init__(self, plan: ph.PhysUnion):
        self.plan = plan
        self.schema = plan.schema
        self.children = [build_executor(c) for c in plan.children]

    @staticmethod
    def _coerce(c: Column, ft) -> Column:
        d, src = c.data, c.ft
        if ft.eval_type == EvalType.STRING and \
                src.eval_type != EvalType.STRING:
            # mixed string/numeric union: MySQL coerces to string
            from tidb_tpu.sqltypes import (format_datetime,
                                           scaled_to_decimal)
            if src.eval_type == EvalType.DECIMAL:
                vals = [str(scaled_to_decimal(int(x), src.frac))
                        for x in d]
            elif src.eval_type == EvalType.DATETIME:
                vals = [format_datetime(int(x), src.tp) for x in d]
            elif d.dtype == np.float64:
                vals = [repr(float(x)) for x in d]
            else:
                vals = [str(int(x)) for x in d]
            return Column(ft, np.array(vals, dtype=object),
                          c.valid.copy())
        if ft.eval_type == EvalType.DECIMAL:
            if src.eval_type == EvalType.DECIMAL:
                if ft.frac > src.frac:
                    d = d.astype(np.int64) * np.int64(
                        10 ** (ft.frac - src.frac))
            elif src.eval_type == EvalType.INT:
                d = d.astype(np.int64) * np.int64(10 ** ft.frac)
        elif ft.eval_type == EvalType.REAL:
            if src.eval_type == EvalType.DECIMAL:
                d = d.astype(np.float64) / (10.0 ** src.frac)
            elif d.dtype != np.float64 and d.dtype != np.dtype(object):
                d = d.astype(np.float64)
        else:
            want = np_dtype_for(ft.tp, ft.flen)
            if d.dtype != want:
                d = d.astype(want)
        return Column(ft, d, c.valid.copy())

    def chunks(self, ctx):
        fts = [c.ft for c in self.schema.cols]
        for child in self.children:
            for chunk in child.chunks(ctx):
                yield Chunk([self._coerce(c, ft)
                             for c, ft in zip(chunk.columns, fts)])


_BUILDERS = {
    _mr.PhysMeshAgg: _mesh_agg_builder,
    _mr.PhysMeshLookupAgg: _mesh_lookup_agg_builder,
    ph.PhysApply: ApplyExec,
    ph.PhysTableReader: TableReaderExec,
    ph.PhysIndexReader: IndexReaderExec,
    ph.PhysIndexLookUp: IndexLookUpExec,
    ph.PhysPointGet: PointGetExec,
    ph.PhysUnion: UnionExec,
    ph.PhysValues: ValuesExec,
    ph.PhysFinalAgg: FinalAggExec,
    ph.PhysHashAgg: HashAggExec,
    ph.PhysStreamAgg: StreamAggExec,
    ph.PhysMergeJoin: MergeJoinExec,
    ph.PhysIndexJoin: IndexJoinExec,
    ph.PhysSelection: SelectionExec,
    ph.PhysProjection: ProjectionExec,
    ph.PhysLimit: LimitExec,
    ph.PhysSort: SortExec,
    ph.PhysTopN: TopNExec,
    ph.PhysHashJoin: HashJoinExec,
    ph.PhysInsert: InsertExec,
    ph.PhysUpdate: UpdateExec,
    ph.PhysDelete: DeleteExec,
    ph.PhysMultiDelete: MultiDeleteExec,
    ph.PhysMultiUpdate: MultiUpdateExec,
}
