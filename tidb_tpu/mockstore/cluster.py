"""Mock cluster topology: stores, regions, split/merge, leader moves.

Reference: /root/reference/store/tikv/mocktikv/cluster.go:38,231-308 —
`Cluster` simulates region topology with Bootstrap/AddStore/Split so
distributed client behavior (routing, epoch retries, fan-out) is testable
on one host. Also plays the PD role: region lookup by key + TSO allocation
(ref: mocktikv/pd.go).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace

from tidb_tpu.util.sorteddict import SortedDict

from tidb_tpu import tablecodec

__all__ = ["Region", "Store", "Cluster"]


@dataclass(frozen=True)
class Region:
    id: int
    start: bytes          # inclusive; b"" = -inf
    end: bytes            # exclusive; b"" = +inf
    version: int          # bumped on split/merge (region epoch)
    conf_ver: int         # bumped on peer changes
    leader_store: int
    peer_stores: tuple[int, ...]

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (not self.end or key < self.end)


@dataclass
class Store:
    id: int
    addr: str
    labels: dict = field(default_factory=dict)
    dropped: bool = False


class Cluster:
    """Topology + TSO. Thread-safe."""

    def __init__(self):
        self._mu = threading.RLock()
        self._id = 0
        self.stores: dict[int, Store] = {}
        # regions keyed by start key for binary search routing
        self._regions: SortedDict[bytes, Region] = SortedDict()
        self._tso_physical = 0
        self._tso_logical = 0

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_mu", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._mu = threading.RLock()

    # -- ids / tso -----------------------------------------------------------

    def alloc_id(self) -> int:
        with self._mu:
            self._id += 1
            return self._id

    def tso(self) -> int:
        """Hybrid timestamp: physical ms << 18 | logical.
        Ref: oracle/oracles/pd.go; mocktikv/pd.go GetTS."""
        with self._mu:
            ms = int(time.time() * 1000)
            if ms > self._tso_physical:
                self._tso_physical = ms
                self._tso_logical = 0
            self._tso_logical += 1
            return (self._tso_physical << 18) | self._tso_logical

    # -- bootstrap / topology ------------------------------------------------

    def bootstrap(self, num_stores: int = 1) -> None:
        with self._mu:
            for _ in range(num_stores):
                sid = self.alloc_id()
                self.stores[sid] = Store(sid, f"store{sid}")
            store_ids = tuple(self.stores)
            rid = self.alloc_id()
            self._regions[b""] = Region(rid, b"", b"", 1, 1,
                                        store_ids[0], store_ids)

    def add_store(self) -> int:
        with self._mu:
            sid = self.alloc_id()
            self.stores[sid] = Store(sid, f"store{sid}")
            return sid

    # -- routing (the PD role) ----------------------------------------------

    def region_by_key(self, key: bytes) -> Region:
        with self._mu:
            idx = self._regions.bisect_right(key) - 1
            start = self._regions.keys()[idx]
            return self._regions[start]

    def region_by_id(self, rid: int) -> Region | None:
        with self._mu:
            for r in self._regions.values():
                if r.id == rid:
                    return r
            return None

    def all_regions(self) -> list[Region]:
        with self._mu:
            return list(self._regions.values())

    # -- mutation ------------------------------------------------------------

    def split(self, key: bytes) -> tuple[Region, Region]:
        """Split the region containing `key` at `key`; bumps epoch of both
        halves. Ref: cluster.go Split."""
        with self._mu:
            old = self.region_by_key(key)
            if old.start == key:
                raise ValueError("split at region start")
            left = replace(old, end=key, version=old.version + 1)
            right = Region(self.alloc_id(), key, old.end, old.version + 1,
                           old.conf_ver, old.leader_store, old.peer_stores)
            self._regions[old.start] = left
            self._regions[key] = right
            return left, right

    def split_table(self, table_id: int, count: int,
                    max_handle: int = 1 << 20) -> int:
        """Split a table's record range into `count` regions at evenly spaced
        handles in [0, max_handle); boundaries that already exist are
        skipped, so a re-run is a no-op. -> number of new splits.
        Ref: cluster.go SplitTable."""
        if count <= 1:
            return 0
        span = max(max_handle // count, 1)
        done = 0
        for i in range(1, count):
            try:
                self.split(tablecodec.record_key(table_id, span * i))
                done += 1
            except ValueError:       # already a region boundary
                pass
        return done

    def split_keys(self, keys: list[bytes]) -> None:
        for k in keys:
            self.split(k)

    def merge(self, left_start: bytes) -> None:
        """Merge the region starting at left_start with its right neighbor."""
        with self._mu:
            left = self._regions[left_start]
            if not left.end:
                raise ValueError("no right neighbor")
            right = self._regions[left.end]
            merged = replace(left, end=right.end,
                             version=max(left.version, right.version) + 1)
            del self._regions[left.end]
            self._regions[left_start] = merged

    def change_leader(self, region_id: int, store_id: int) -> None:
        """Leadership is NOT part of the region epoch (TiKV semantics):
        a transfer changes no version, clients just follow NotLeader."""
        with self._mu:
            for start, r in self._regions.items():
                if r.id == region_id:
                    peers, bump = r.peer_stores, r.conf_ver
                    if store_id not in peers:
                        peers = peers + (store_id,)
                        bump += 1    # peer membership change IS epoch
                    self._regions[start] = replace(
                        r, leader_store=store_id, peer_stores=peers,
                        conf_ver=bump)
                    return
            raise ValueError(f"no region {region_id}")

    # -- replica/partition management (the PD role; ref: region_request.go
    # store failover client-side, PD balance schedulers server-side) ---------

    def live_stores(self) -> list[int]:
        with self._mu:
            return [sid for sid, s in self.stores.items() if not s.dropped]

    def store_is_up(self, store_id: int) -> bool:
        with self._mu:
            s = self.stores.get(store_id)
            return s is not None and not s.dropped

    def drop_store(self, store_id: int) -> None:
        """Take a store down: every region it led elects a surviving
        peer, and under-replicated regions get a replacement replica on
        a live store (conf change -> conf_ver bump, exactly what a peer
        membership change means)."""
        with self._mu:
            st = self.stores.get(store_id)
            if st is None:
                raise ValueError(f"no store {store_id}")
            st.dropped = True
            live = [sid for sid, s in self.stores.items() if not s.dropped]
            if not live:
                return               # total outage: nothing to elect
            for start, r in list(self._regions.items()):
                if store_id not in r.peer_stores and \
                        r.leader_store != store_id:
                    continue
                peers = tuple(p for p in r.peer_stores if p != store_id)
                spare = [sid for sid in live if sid not in peers]
                if len(peers) < len(r.peer_stores) and spare:
                    peers = peers + (spare[0],)   # repair replication
                leader = r.leader_store
                if leader == store_id or leader not in peers:
                    leader = peers[0]
                self._regions[start] = replace(
                    r, leader_store=leader, peer_stores=peers,
                    conf_ver=r.conf_ver + 1)

    def leader_counts(self) -> dict[int, int]:
        with self._mu:
            out = {sid: 0 for sid, s in self.stores.items()
                   if not s.dropped}
            for r in self._regions.values():
                if r.leader_store in out:
                    out[r.leader_store] += 1
            return out

    def balance_leaders(self) -> int:
        """One PD balance-leader pass: move leaders from overloaded to
        underloaded live stores, leadership-only (transfers stay within
        each region's existing peer set — membership changes are
        drop_store's job, as in PD's balance-leader scheduler). Best
        effort: converges to a spread of <=1 wherever peer sets allow,
        and stops when no permitted transfer improves the balance.
        -> number of transfers."""
        moved = 0
        while True:
            with self._mu:
                counts = self.leader_counts()
                if len(counts) < 2 or \
                        max(counts.values()) - min(counts.values()) <= 1:
                    return moved
                by_load = sorted(counts, key=counts.get)
                done = False
                for hi in reversed(by_load):
                    for lo in by_load:
                        if counts[hi] - counts[lo] <= 1:
                            break
                        for start, r in self._regions.items():
                            if r.leader_store == hi and \
                                    lo in r.peer_stores:
                                self._regions[start] = replace(
                                    r, leader_store=lo)
                                done = True
                                break
                        if done:
                            break
                    if done:
                        break
                if not done:
                    return moved     # no permitted transfer remains
            moved += 1
