"""RPC shim: the "network" between client and storage nodes.

Reference: /root/reference/store/tikv/mocktikv/rpc.go:112-464 — every request
carries a region context (id, epoch); the handler re-checks it against the
cluster so the client's region-error retry paths (NotLeader, EpochNotMatch,
ServerBusy) actually execute in tests. Failpoints (ref: rpc.go:465-521
gofail sites rpcServerBusy/rpcCommitResult/rpcCommitTimeout) are the
central registry's `rpc/request` point (util/failpoint.py, the successor
of the ad-hoc `inject` attribute this shim used to carry): tests arm
`failpoint.enable("rpc/request", fn)` with a callable receiving
(cmd, ctx) — or a declarative spec — to raise errors or simulate
timeouts for specific commands; every command, including the per-frame
CopStream re-check, evaluates it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from tidb_tpu.kv import (EpochNotMatchError, IsolationLevel, KVError,
                         Mutation, NotLeaderError, RegionError,
                         ServerBusyError, StoreUnavailableError)
from tidb_tpu.mockstore.cluster import Cluster, Region
from tidb_tpu.mockstore.mvcc import MVCCStore
from tidb_tpu.util import failpoint

__all__ = ["RegionCtx", "RPCShim", "TimeoutError_"]


class TimeoutError_(KVError):
    """Simulated network timeout: the request may or may not have executed
    (drives undetermined-commit handling, ref: 2pc.go:421-431)."""


@dataclass
class RegionCtx:
    region_id: int
    version: int
    conf_ver: int
    store_id: int  # the store the client believes is leader


class RPCShim:
    """Routes commands to the MVCC engine after simulating region checks."""

    def __init__(self, cluster: Cluster, store: MVCCStore):
        self.cluster = cluster
        self.store = store
        self._mu = threading.Lock()
        # storage facade back-ref (set by MockStorage.__init__): the
        # journal-window command needs the node-local DeltaStore, which
        # lives on the facade, not the MVCC engine
        self._storage = None

    def bind_storage(self, storage) -> None:
        self._storage = storage

    # -- region checks -------------------------------------------------------

    def _check(self, cmd: str, ctx: RegionCtx) -> Region:
        failpoint.eval("rpc/request", cmd, ctx)
        if not self.cluster.store_is_up(ctx.store_id):
            # the address the client dialed is dead: connection-level
            # failure (ref: region_request.go onSendFail -> retry other
            # peers after a region reload)
            raise StoreUnavailableError(ctx.region_id, ctx.store_id)
        region = self.cluster.region_by_id(ctx.region_id)
        if region is None:
            raise EpochNotMatchError(ctx.region_id)
        if region.leader_store != ctx.store_id:
            raise NotLeaderError(ctx.region_id, region.leader_store)
        if region.version != ctx.version or region.conf_ver != ctx.conf_ver:
            raise EpochNotMatchError(ctx.region_id)
        return region

    def _check_keys_in(self, region: Region, keys) -> None:
        for k in keys:
            if not region.contains(k):
                raise EpochNotMatchError(region.id)

    # -- commands (mirror tikvrpc CmdType set, tikvrpc.go:31-53) ------------

    def kv_get(self, ctx: RegionCtx, key: bytes, ts: int,
               isolation=IsolationLevel.SI):
        r = self._check("Get", ctx)
        self._check_keys_in(r, [key])
        return self.store.get(key, ts, isolation)

    def kv_batch_get(self, ctx: RegionCtx, keys: list[bytes], ts: int,
                     isolation=IsolationLevel.SI):
        r = self._check("BatchGet", ctx)
        self._check_keys_in(r, keys)
        return self.store.batch_get(keys, ts, isolation)

    def kv_scan(self, ctx: RegionCtx, start: bytes, end: bytes, limit: int,
                ts: int, isolation=IsolationLevel.SI, desc: bool = False):
        r = self._check("Scan", ctx)
        # clamp scan to region bounds
        s = max(start, r.start)
        e = r.end if not end else (min(end, r.end) if r.end else end)
        return self.store.scan(s, e, limit, ts, isolation, desc)

    def kv_prewrite(self, ctx: RegionCtx, mutations: list[Mutation],
                    primary: bytes, start_ts: int, ttl_ms: int = 3000):
        r = self._check("Prewrite", ctx)
        self._check_keys_in(r, [m.key for m in mutations])
        self.store.prewrite(mutations, primary, start_ts, ttl_ms)

    def kv_commit(self, ctx: RegionCtx, keys: list[bytes], start_ts: int,
                  commit_ts: int):
        r = self._check("Commit", ctx)
        self._check_keys_in(r, keys)
        self.store.commit(keys, start_ts, commit_ts)

    def kv_batch_rollback(self, ctx: RegionCtx, keys: list[bytes],
                          start_ts: int):
        r = self._check("BatchRollback", ctx)
        self._check_keys_in(r, keys)
        self.store.rollback(keys, start_ts)

    def kv_cleanup(self, ctx: RegionCtx, key: bytes, start_ts: int,
                   current_ts: int = 0):
        r = self._check("Cleanup", ctx)
        self._check_keys_in(r, [key])
        return self.store.cleanup(key, start_ts, current_ts)

    def kv_scan_lock(self, ctx: RegionCtx, max_ts: int):
        r = self._check("ScanLock", ctx)
        return self.store.scan_lock(r.start, r.end, max_ts)

    def kv_resolve_lock(self, ctx: RegionCtx, start_ts: int, commit_ts: int):
        r = self._check("ResolveLock", ctx)
        self.store.resolve_lock(r.start, r.end, start_ts, commit_ts)

    def kv_delete_range(self, ctx: RegionCtx, start: bytes, end: bytes):
        r = self._check("DeleteRange", ctx)
        self.store.delete_range(max(start, r.start),
                                min(end, r.end) if r.end else end)

    def kv_gc(self, ctx: RegionCtx, safepoint: int):
        r = self._check("GC", ctx)
        return self.store.gc(safepoint, r.start, r.end)

    def split_region(self, ctx: RegionCtx, key: bytes):
        self._check("SplitRegion", ctx)
        return self.cluster.split(key)

    # -- raw KV (ref: tikvrpc.go Raw* commands; rawkv.go client) -------------

    def raw_get(self, ctx: RegionCtx, key: bytes):
        self._check("RawGet", ctx)
        return self.store.raw_get(key)

    def raw_batch_get(self, ctx: RegionCtx, keys: list[bytes]):
        r = self._check("RawBatchGet", ctx)
        self._check_keys_in(r, keys)
        return self.store.raw_batch_get(keys)

    def raw_put(self, ctx: RegionCtx, key: bytes, value: bytes):
        self._check("RawPut", ctx)
        self.store.raw_put(key, value)

    def raw_batch_put(self, ctx: RegionCtx, pairs: list[tuple]):
        r = self._check("RawBatchPut", ctx)
        self._check_keys_in(r, [k for k, _v in pairs])
        self.store.raw_batch_put(pairs)

    def raw_delete(self, ctx: RegionCtx, key: bytes):
        self._check("RawDelete", ctx)
        self.store.raw_delete(key)

    def raw_scan(self, ctx: RegionCtx, start: bytes, end: bytes,
                 limit: int):
        r = self._check("RawScan", ctx)
        end = min(end, r.end) if (end and r.end) else (end or r.end)
        return self.store.raw_scan(max(start, r.start), end, limit)

    def raw_delete_range(self, ctx: RegionCtx, start: bytes, end: bytes):
        r = self._check("RawDeleteRange", ctx)
        end = min(end, r.end) if (end and r.end) else (end or r.end)
        self.store.raw_delete_range(max(start, r.start), end)

    # -- MVCC forensics (debug API, no region ctx: ref
    # server/region_handler.go MvccGetByKey/MvccGetByStartTs) ----------------

    def mvcc_by_key(self, key: bytes):
        return self.store.mvcc_by_key(key)

    def mvcc_by_start_ts(self, start_ts: int, **kw):
        return self.store.mvcc_by_start_ts(start_ts, **kw)

    def journal_window(self, ctx: RegionCtx, table_id: int, start: bytes,
                       end: bytes, fill_ts, read_ts: int, index_id=None):
        """Fleet cache coherence: one round trip returning the engine's
        freshness meta plus the delta-journal window (fill_ts, read_ts]
        for one region range, so a remote SQL server can decide whether
        its resident chunk/HBM block is patchable in place (store/delta.py
        semantics) without re-colding. Region epoch is checked like any
        data command, so truncation races on split/merge surface as
        RegionError and the client re-resolves. The reply is wire-native
        (dicts/tuples/ndarrays only — the STALE sentinel travels as the
        string "stale")."""
        r = self._check("JournalWindow", ctx)
        s = max(start, r.start)
        e = r.end if not end else (min(end, r.end) if r.end else end)
        storage = self._storage
        dstore = getattr(storage, "delta_store", None)
        enabled = dstore is not None and dstore.enabled()
        eng = self.store
        meta = {
            "data_version": eng.data_version,
            "max_commit_ts": eng.max_commit_ts,
            "any_locks": bool(eng._locked_keys),
            "delta_enabled": enabled,
            "locked": enabled and eng.locked_in_range(s, e, read_ts),
            "index_stale": False,
            "delta": None,
        }
        if not enabled or fill_ts is None:
            return meta
        if index_id is not None:
            meta["index_stale"] = dstore.index_stale(table_id, fill_ts,
                                                     read_ts)
            return meta
        pend = dstore.pending(table_id, s, e, fill_ts, read_ts)
        from tidb_tpu.store.delta import STALE
        if pend is STALE:
            meta["delta"] = "stale"
        elif pend is not None:
            meta["delta"] = ("win", pend.watermark, pend.upsert_rows,
                             pend.upsert_handles, pend.delete_handles)
        return meta

    def coprocessor(self, ctx: RegionCtx, req):
        """Executes a pushed-down subplan against this region's data.
        Handler installed by tidb_tpu.store.copr (set at storage build time
        to avoid a module cycle)."""
        r = self._check("Cop", ctx)
        if self._cop_handler is None:
            raise KVError("no coprocessor handler installed")
        return self._cop_handler(r, req)

    def coprocessor_stream(self, ctx: RegionCtx, req, credit=None,
                           frame_bytes=None):
        """Streaming coprocessor (ref: CmdCopStream): lazy generator of
        StreamFrames. The region epoch (and the `rpc/request`
        failpoint, cmd "CopStream") is re-checked before EVERY frame
        delivery, so a
        region split/leader change mid-stream surfaces as a mid-stream
        RegionError — the client resumes from its last acked range
        boundary (store/copr.py). `credit` is unused in-process: the
        consumer pulls the generator, which is perfect backpressure.
        `frame_bytes` is the CLIENT's response-size cap (validated here
        — it also arrives off the wire)."""
        r = self._check("CopStream", ctx)
        if self._cop_stream_handler is None:
            raise KVError("no streaming coprocessor handler installed")
        if frame_bytes is not None:
            if not isinstance(frame_bytes, int) or \
                    isinstance(frame_bytes, bool) or \
                    not 1 <= frame_bytes <= (1 << 31):
                raise KVError(f"bad frame_bytes {frame_bytes!r}")
        gen = self._cop_stream_handler(r, req, frame_bytes=frame_bytes)

        def checked():
            for frame in gen:
                # per-frame failpoint + epoch re-check: an un-delivered
                # frame is never acked, so dropping it here cannot lose
                # rows on resume
                self._check("CopStream", ctx)
                yield frame

        return checked()

    _cop_handler = None
    _cop_stream_handler = None

    def install_cop_handler(self, fn) -> None:
        self._cop_handler = fn

    def install_cop_stream_handler(self, fn) -> None:
        self._cop_stream_handler = fn
