"""In-process MVCC store with Percolator transaction primitives.

Reference: /root/reference/store/tikv/mocktikv/mvcc.go:418-429 (MVCCStore
iface: Get/Scan/BatchGet/Prewrite/Commit/Rollback/Cleanup/ScanLock/
ResolveLock) and mvcc_leveldb.go (the engine). This is the spec for what a
real storage node must do; here it is one python object guarded by a lock,
so a mock cluster can host many "regions" over one engine hermetically
(SURVEY.md §4: the single highest-leverage test artifact).

Per key, state is:
    lock:   at most one {primary, start_ts, ttl, op, value}
    writes: newest-first list of (commit_ts, start_ts, WriteType)
    data:   {start_ts: value} for committed Puts
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from tidb_tpu.util.sorteddict import SortedDict

from tidb_tpu.kv import (IsolationLevel, KeyLockedError, KVError, LockInfo,
                         Mutation, MutationOp, TxnAbortedError,
                         WriteConflictError)

__all__ = ["MVCCStore", "WriteType", "physical_ms",
           "EPHEMERAL_PREFIXES"]

# Ephemeral cluster-bookkeeping namespaces: DDL owner leases
# (owner.py DDL_OWNER_KEY), schema-sync heartbeats (session Domain
# SCHEMA_SYNC_PREFIX), fleet membership heartbeats (member.py
# MEMBER_PREFIX), and auto-increment batch allocations (meta
# AutoID counters — id handout changes no committed row and no schema,
# but every 4000th INSERT refills a batch through a meta txn). A live
# server's background workers commit the leases every half-lease
# (~1/s); none of these carry table data or schema semantics, so they
# must NOT bump data_version — one heartbeat (or id-batch refill)
# would otherwise invalidate every columnar chunk-cache and HBM-cache
# entry, keeping both caches permanently cold exactly when the server
# is serving. max_commit_ts and the lock set still advance/track for
# these keys, so the MVCC fill contract is untouched.
EPHEMERAL_PREFIXES = (b"m_owner_", b"m_schema_sync_", b"m_member_",
                      b"msAutoID:")


# key classes for the delta-capture path (store/delta.py): committed
# table RECORD mutations are journaled per table instead of bumping
# data_version; index-key commits advance a per-table index watermark
# (cached index scans re-validate against it); anything else — meta /
# DDL / structure keys — keeps the wholesale version bump, because a
# schema change really does invalidate every decoded chunk.
_KIND_RECORD, _KIND_INDEX, _KIND_EPHEMERAL, _KIND_OTHER = range(4)


def _classify_key(key: bytes) -> tuple[int, int, int]:
    """-> (kind, table_id, handle). table_id/handle are 0 unless
    meaningful for the kind."""
    if key.startswith(EPHEMERAL_PREFIXES):
        return _KIND_EPHEMERAL, 0, 0
    from tidb_tpu import tablecodec
    try:
        tid, handle = tablecodec.decode_record_key(key)
        return _KIND_RECORD, tid, handle
    except ValueError:
        pass
    try:
        tid, _iid, _suffix = tablecodec.decode_index_key(key)
        return _KIND_INDEX, tid, 0
    except ValueError:
        return _KIND_OTHER, 0, 0


class WriteType(Enum):
    PUT = "put"
    DELETE = "delete"
    ROLLBACK = "rollback"
    LOCK = "lock"


@dataclass
class _Lock:
    primary: bytes
    start_ts: int
    ttl_ms: int
    op: MutationOp
    value: bytes

    def info(self, key: bytes) -> LockInfo:
        return LockInfo(self.primary, self.start_ts, key, self.ttl_ms)


@dataclass
class _Entry:
    lock: Optional[_Lock] = None
    writes: list = field(default_factory=list)   # [(commit_ts, start_ts, WriteType)] newest first
    data: dict = field(default_factory=dict)     # start_ts -> value


def physical_ms(ts: int) -> int:
    """Hybrid timestamp physical part. Ref: oracle/oracle.go:35
    (ts = physical_ms << 18 | logical)."""
    return ts >> 18


class MVCCStore:
    """Thread-safe Percolator MVCC engine over sorted keys."""

    def __init__(self):
        self._entries: SortedDict[bytes, _Entry] = SortedDict()
        self._mu = threading.RLock()
        # bumped on EVERY state change (locks included): the columnar
        # chunk cache (store/chunk_cache.py) keys its validity on it
        self.data_version = 0
        # newest commit_ts ever written: a scan snapshot at ts >= this sees
        # the full current state, so its decoded chunk is safe to cache
        # (an OLDER snapshot's scan must never populate the cache — newer
        # readers would inherit its stale view)
        self.max_commit_ts = 0
        # keys currently holding a Percolator lock: lock VISIBILITY is
        # per-reader-ts (a lock from a NEWER txn doesn't block an older
        # snapshot's scan), so a fill made while any lock is pending could
        # be served to a reader that must instead see KeyLockedError —
        # the chunk-cache filler refuses to cache while this is nonempty
        self._locked_keys: set = set()
        # delta capture (store/delta.py DeltaStore.ingest): installed by
        # the storage facade. While active, committed RECORD mutations
        # are journaled (under _mu, atomically with the commit becoming
        # readable) instead of bumping data_version — the caches then
        # serve base + delta instead of re-colding on every write.
        self._delta_sink = None

    # engines snapshot to disk for the out-of-process storage node's
    # restart path (store/remote.py); locks are recreated on load
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_mu", None)
        d.pop("_delta_sink", None)   # process-local, re-wired on load
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._mu = threading.RLock()
        self._delta_sink = None

    def set_delta_sink(self, sink) -> None:
        """Install the commit-journal sink (DeltaStore). `sink.ingest`
        is invoked under the engine lock so a commit and its journal
        entry become visible atomically; `sink.enabled()` is consulted
        per operation, so flipping tidb_tpu_delta_store reverts to the
        legacy whole-version invalidation instantly."""
        with self._mu:
            self._delta_sink = sink

    def _capture_active(self) -> bool:
        sink = self._delta_sink
        return sink is not None and sink.enabled()

    def _needs_bump(self, keys, capture: bool) -> bool:
        """Would a state change over `keys` invalidate cached chunks?
        Without delta capture: any non-ephemeral key (legacy). With it:
        only keys outside the record/index namespaces."""
        for k in keys:
            kind = _classify_key(k)[0]
            if kind == _KIND_EPHEMERAL:
                continue
            if capture and kind in (_KIND_RECORD, _KIND_INDEX):
                continue
            return True
        return False

    # -- internal ------------------------------------------------------------

    def _entry(self, key: bytes) -> _Entry:
        e = self._entries.get(key)
        if e is None:
            e = _Entry()
            self._entries[key] = e
        return e

    def _check_lock(self, key: bytes, e: _Entry, ts: int,
                    isolation: IsolationLevel) -> None:
        """A read at `ts` is blocked by a lock from an older txn (SI).
        RC reads skip locks. Ref: mvcc_leveldb.go getValue lock check."""
        if e.lock is not None and isolation == IsolationLevel.SI:
            if e.lock.start_ts <= ts and e.lock.op != MutationOp.LOCK:
                raise KeyLockedError(e.lock.info(key))

    def _read(self, key: bytes, e: _Entry, ts: int) -> Optional[bytes]:
        for commit_ts, start_ts, wt in e.writes:
            if commit_ts > ts:
                continue
            if wt == WriteType.PUT:
                return e.data[start_ts]
            if wt == WriteType.DELETE:
                return None
            # ROLLBACK/LOCK records: keep looking at older versions
        return None

    # -- reads ---------------------------------------------------------------

    def get(self, key: bytes, ts: int,
            isolation: IsolationLevel = IsolationLevel.SI) -> Optional[bytes]:
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                return None
            self._check_lock(key, e, ts, isolation)
            return self._read(key, e, ts)

    def batch_get(self, keys: list[bytes], ts: int,
                  isolation: IsolationLevel = IsolationLevel.SI) -> dict[bytes, bytes]:
        out = {}
        with self._mu:
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    continue
                self._check_lock(k, e, ts, isolation)
                v = self._read(k, e, ts)
                if v is not None:
                    out[k] = v
        return out

    def locked_in_range(self, start: bytes, end: bytes, ts: int) -> bool:
        """Is any pending Percolator lock on a key in [start, end) one a
        reader at `ts` must observe (SI: lock.start_ts <= ts; LOCK-op
        locks never block reads)? The cached read path consults this
        instead of relying on prewrite bumping data_version: while such
        a lock is pending, the range falls to the real scan path (which
        raises KeyLockedError for resolution exactly as an uncached
        read would) and the cached entries SURVIVE the write instead of
        being wholesale-invalidated.

        Lock-free fast path: with no pending locks at all (the common
        serving state) this is one attribute read — no engine-lock
        serialization on the hot analytic path. A lock being ADDED
        concurrently is safe to miss: its prewrite has not returned, so
        its txn's eventual commit_ts is strictly newer than any read_ts
        issued before this check — invisible to this reader either
        way."""
        if not self._locked_keys:
            return False
        with self._mu:
            for k in self._locked_keys:
                if k < start or (end and k >= end):
                    continue
                e = self._entries.get(k)
                if e is not None and e.lock is not None and \
                        e.lock.start_ts <= ts and \
                        e.lock.op != MutationOp.LOCK:
                    return True
        return False

    def scan(self, start: bytes, end: bytes, limit: int, ts: int,
             isolation: IsolationLevel = IsolationLevel.SI,
             desc: bool = False) -> list[tuple[bytes, bytes]]:
        """First `limit` live (key, value) pairs in [start, end).
        end=b"" means unbounded."""
        out = []
        with self._mu:
            keys = self._entries.irange(start, end or None,
                                        inclusive=(True, False), reverse=desc)
            for k in keys:
                e = self._entries[k]
                self._check_lock(k, e, ts, isolation)
                v = self._read(k, e, ts)
                if v is not None:
                    out.append((k, v))
                    if limit and len(out) >= limit:
                        break
        return out

    # -- offline ingest ------------------------------------------------------

    def bulk_import(self, pairs, start_ts: int, commit_ts: int) -> int:
        """Offline ingest of pre-encoded (key, value) pairs as committed
        PUTs at `commit_ts`, bypassing the Percolator lock protocol — the
        importer owns the target range (ref: util/kvencoder's standalone
        KV-pair encoder for offline import, and TiKV's ingest-SST flow).
        Keys already present get a new newest version; readers at a ts
        below `commit_ts` keep seeing the old state. -> pairs ingested."""
        pairs = list(pairs)
        n = 0
        with self._mu:
            # validate-then-apply so the import is all-or-nothing: a lock
            # discovered midway must not leave earlier pairs committed
            for k, _v in pairs:
                e = self._entries.get(k)
                if e is not None and e.lock is not None:
                    raise KeyLockedError(e.lock.info(k))
            self.data_version += 1
            if commit_ts > self.max_commit_ts:
                self.max_commit_ts = commit_ts
            fresh = {}
            for k, v in pairs:
                e = self._entries.get(k)
                if e is None:
                    # fresh key: construct the whole entry in one go
                    # (the common bulk-load case; avoids _entry dict probe)
                    fresh[k] = _Entry(
                        lock=None,
                        writes=[(commit_ts, start_ts, WriteType.PUT)],
                        data={start_ts: v})
                else:
                    e.data[start_ts] = v
                    e.writes.insert(0, (commit_ts, start_ts, WriteType.PUT))
                n += 1
            if fresh:
                # one bulk update: SortedDict sorts the new keys wholesale
                # instead of per-item tree inserts
                self._entries.update(fresh)
        return n

    # -- percolator write protocol ------------------------------------------

    def prewrite(self, mutations: list[Mutation], primary: bytes,
                 start_ts: int, ttl_ms: int = 3000) -> None:
        """All-or-nothing lock acquisition. Ref: mvcc_leveldb.go Prewrite."""
        with self._mu:
            # with delta capture, record/index prewrites leave
            # data_version alone: pending-lock correctness moves to the
            # serve-time locked_in_range veto, so a write in flight no
            # longer re-colds every cache
            if self._needs_bump([m.key for m in mutations],
                                self._capture_active()):
                self.data_version += 1
            for m in mutations:
                e = self._entry(m.key)
                if e.lock is not None:
                    if e.lock.start_ts != start_ts:
                        raise KeyLockedError(e.lock.info(m.key))
                    continue  # idempotent re-prewrite by the same txn
                if self._find_txn_write(e, start_ts) == WriteType.ROLLBACK:
                    raise TxnAbortedError(f"txn {start_ts} already rolled back")
                # conflict: newest real write committed at/after our start_ts
                for commit_ts, _wts, wt in e.writes:
                    if wt == WriteType.ROLLBACK:
                        continue
                    if commit_ts >= start_ts:
                        raise WriteConflictError(m.key, start_ts, commit_ts)
                    break
            for m in mutations:
                e = self._entry(m.key)
                e.lock = _Lock(primary, start_ts, ttl_ms, m.op, m.value)
                self._locked_keys.add(m.key)

    def commit(self, keys: list[bytes], start_ts: int, commit_ts: int) -> None:
        """Ref: mvcc_leveldb.go Commit — idempotent for already-committed.

        With delta capture active, committed RECORD mutations are
        journaled to the sink (under the engine lock, so the journal
        entry and the readable commit appear atomically — a reader can
        never observe the commit but miss its delta) and index-key
        commits advance the per-table index watermark; data_version
        bumps only for keys outside both namespaces."""
        with self._mu:
            capture = self._capture_active()
            if self._needs_bump(keys, capture):
                self.data_version += 1
            records: list = []
            idx_notes: list = []
            try:
                for k in keys:
                    e = self._entries.get(k)
                    if e is None or e.lock is None or \
                            e.lock.start_ts != start_ts:
                        # lock gone: committed already, or rolled back?
                        st = self._find_txn_write(e, start_ts) if e else None
                        if st == WriteType.ROLLBACK or st is None:
                            raise TxnAbortedError(
                                f"commit of {start_ts} on {k!r}: lock missing")
                        continue  # already committed: idempotent
                    if capture:
                        self._journal(k, e.lock, commit_ts, records,
                                      idx_notes)
                    self._commit_locked(k, e, start_ts, commit_ts)
            finally:
                # even a TxnAbortedError mid-loop leaves the earlier
                # keys COMMITTED — their deltas must land regardless
                if (records or idx_notes) and \
                        not self._delta_sink.ingest(records, idx_notes):
                    # sink refused (disabled mid-flight): fall back to
                    # the legacy wholesale invalidation
                    self.data_version += 1

    @staticmethod
    def _journal(key: bytes, lock: _Lock, commit_ts: int,
                 records: list, idx_notes: list) -> None:
        """Classify one about-to-commit key into the delta journal:
        record PUT/DELETE -> (table, handle, key, value|None, ts);
        index PUT/DELETE -> per-table index watermark note."""
        if lock.op == MutationOp.LOCK:
            return
        kind, tid, handle = _classify_key(key)
        if kind == _KIND_RECORD:
            records.append((tid, handle, key,
                            lock.value if lock.op == MutationOp.PUT
                            else None, commit_ts))
        elif kind == _KIND_INDEX:
            idx_notes.append((tid, commit_ts))

    def _commit_locked(self, key: bytes, e: _Entry, start_ts: int,
                       commit_ts: int) -> None:
        if commit_ts > self.max_commit_ts:
            self.max_commit_ts = commit_ts
        lock = e.lock
        if lock.op == MutationOp.PUT:
            e.data[start_ts] = lock.value
            e.writes.insert(0, (commit_ts, start_ts, WriteType.PUT))
        elif lock.op == MutationOp.DELETE:
            e.writes.insert(0, (commit_ts, start_ts, WriteType.DELETE))
        else:
            e.writes.insert(0, (commit_ts, start_ts, WriteType.LOCK))
        e.lock = None
        self._locked_keys.discard(key)

    def _find_txn_write(self, e: Optional[_Entry], start_ts: int):
        if e is None:
            return None
        for commit_ts, wts, wt in e.writes:
            if wts == start_ts:
                return wt
        return None

    def rollback(self, keys: list[bytes], start_ts: int) -> None:
        """Ref: mvcc_leveldb.go Rollback; errors if already committed."""
        with self._mu:
            # a rollback changes no committed-visible data: with delta
            # capture, record/index rollbacks leave data_version alone
            # (the lock-set veto already lifted when the lock clears)
            if self._needs_bump(keys, self._capture_active()):
                self.data_version += 1
            for k in keys:
                e = self._entry(k)
                wt = self._find_txn_write(e, start_ts)
                if wt is not None and wt != WriteType.ROLLBACK:
                    raise KVError(f"txn {start_ts} already committed on {k!r}")
                if e.lock is not None and e.lock.start_ts == start_ts:
                    e.lock = None
                    self._locked_keys.discard(k)
                if wt is None:
                    # rollback record blocks a late prewrite from this txn
                    e.writes.insert(0, (start_ts, start_ts, WriteType.ROLLBACK))

    def cleanup(self, key: bytes, start_ts: int, current_ts: int = 0) -> int:
        """Resolve a single (possibly dead) txn's lock on `key`.
        Returns commit_ts if the txn turned out committed, else 0 after
        rolling back. Raises KeyLockedError if the lock is still alive.
        Ref: mvcc_leveldb.go Cleanup + lock_resolver.go getTxnStatus."""
        with self._mu:
            if self._needs_bump([key], self._capture_active()):
                self.data_version += 1
            e = self._entry(key)
            if e.lock is not None and e.lock.start_ts == start_ts:
                if current_ts and physical_ms(current_ts) < \
                        physical_ms(start_ts) + e.lock.ttl_ms:
                    raise KeyLockedError(e.lock.info(key))
                e.lock = None
                self._locked_keys.discard(key)
                e.writes.insert(0, (start_ts, start_ts, WriteType.ROLLBACK))
                return 0
            wt = self._find_txn_write(e, start_ts)
            if wt == WriteType.ROLLBACK or wt is None:
                if wt is None:
                    e.writes.insert(0, (start_ts, start_ts, WriteType.ROLLBACK))
                return 0
            for commit_ts, wts, w in e.writes:
                if wts == start_ts and w != WriteType.ROLLBACK:
                    return commit_ts
            return 0

    def scan_lock(self, start: bytes, end: bytes, max_ts: int) -> list[LockInfo]:
        out = []
        with self._mu:
            for k in self._entries.irange(start, end or None,
                                          inclusive=(True, False)):
                e = self._entries[k]
                if e.lock is not None and e.lock.start_ts <= max_ts:
                    out.append(e.lock.info(k))
        return out

    def resolve_lock(self, start: bytes, end: bytes, start_ts: int,
                     commit_ts: int) -> None:
        """Commit (commit_ts > 0) or roll back every lock of txn start_ts in
        range. Ref: mvcc_leveldb.go ResolveLock."""
        with self._mu:
            capture = self._capture_active()
            hit = []
            for k in list(self._entries.irange(start, end or None,
                                               inclusive=(True, False))):
                e = self._entries[k]
                if e.lock is not None and e.lock.start_ts == start_ts:
                    hit.append((k, e))
            if self._needs_bump([k for k, _e in hit], capture):
                self.data_version += 1
            records: list = []
            idx_notes: list = []
            for k, e in hit:
                if commit_ts > 0:
                    if capture:
                        self._journal(k, e.lock, commit_ts, records,
                                      idx_notes)
                    self._commit_locked(k, e, start_ts, commit_ts)
                else:
                    e.lock = None
                    self._locked_keys.discard(k)
                    e.writes.insert(0, (start_ts, start_ts, WriteType.ROLLBACK))
            if (records or idx_notes) and \
                    not self._delta_sink.ingest(records, idx_notes):
                self.data_version += 1

    # -- maintenance ---------------------------------------------------------

    def delete_range(self, start: bytes, end: bytes) -> None:
        with self._mu:
            self.data_version += 1
            for k in list(self._entries.irange(start, end or None,
                                               inclusive=(True, False))):
                self._locked_keys.discard(k)
                del self._entries[k]

    def gc(self, safepoint_ts: int, start: bytes = b"",
           end: bytes = b"") -> int:
        """Drop versions no snapshot >= safepoint can see, within
        [start, end) (b"" = unbounded). Returns #pruned.
        Ref: gcworker/gc_worker.go doGC."""
        pruned = 0
        with self._mu:
            self.data_version += 1
            for k in list(self._entries.irange(start, end or None,
                                               inclusive=(True, False))):
                e = self._entries[k]
                keep = []
                seen_visible = False
                for w in e.writes:
                    commit_ts, start_ts, wt = w
                    if commit_ts > safepoint_ts or not seen_visible:
                        keep.append(w)
                        if commit_ts <= safepoint_ts and wt in (
                                WriteType.PUT, WriteType.DELETE):
                            seen_visible = True
                    else:
                        if wt == WriteType.PUT:
                            e.data.pop(start_ts, None)
                        pruned += 1
                e.writes = keep
                if not e.writes and e.lock is None:
                    del self._entries[k]
        return pruned

    def num_keys(self) -> int:
        with self._mu:
            return len(self._entries)

    # -- raw (non-transactional) namespace -----------------------------------
    # Ref: store/tikv/rawkv.go — TiKV keeps raw keys in a separate column
    # family; here a separate sorted map, invisible to MVCC readers.

    @property
    def _rawmap(self):
        raw = self.__dict__.get("_raw")
        if raw is None:          # engines unpickled from older snapshots
            raw = self.__dict__["_raw"] = SortedDict()
        return raw

    def raw_get(self, key: bytes) -> Optional[bytes]:
        with self._mu:
            return self._rawmap.get(key)

    def raw_batch_get(self, keys: list[bytes]) -> dict:
        with self._mu:
            raw = self._rawmap
            return {k: raw[k] for k in keys if k in raw}

    def raw_put(self, key: bytes, value: bytes) -> None:
        with self._mu:
            self._rawmap[key] = value

    def raw_batch_put(self, pairs: list[tuple]) -> None:
        with self._mu:
            self._rawmap.update(dict(pairs))

    def raw_delete(self, key: bytes) -> None:
        with self._mu:
            self._rawmap.pop(key, None)

    def raw_scan(self, start: bytes, end: bytes,
                 limit: int) -> list[tuple]:
        with self._mu:
            raw = self._rawmap
            out = []
            for k in raw.irange(start, end or None,
                                inclusive=(True, False)):
                out.append((k, raw[k]))
                if len(out) >= limit:
                    break
            return out

    def raw_delete_range(self, start: bytes, end: bytes) -> None:
        with self._mu:
            raw = self._rawmap
            for k in list(raw.irange(start, end or None,
                                     inclusive=(True, False))):
                del raw[k]

    # -- MVCC forensics (ref: server/region_handler.go:73-91 MvccGetByKey /
    # MvccGetByStartTs; mocktikv rpc.go MvccGetByKey) -------------------------

    def mvcc_by_key(self, key: bytes) -> dict:
        """Every version of one key: pending lock + write column entries
        with their values."""
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                return {"key": key, "lock": None, "writes": []}
            lock = None
            if e.lock is not None:
                lock = {"start_ts": e.lock.start_ts,
                        "primary": e.lock.primary,
                        "op": e.lock.op.name,
                        "ttl_ms": e.lock.ttl_ms}
            writes = [{"commit_ts": cts, "start_ts": sts, "type": wt.name,
                       "value": e.data.get(sts)}
                      for cts, sts, wt in e.writes]
            return {"key": key, "lock": lock, "writes": writes}

    def mvcc_by_start_ts(self, start_ts: int, start: bytes = b"",
                         end: bytes = b"", limit: int = 256) -> list:
        """Keys a transaction touched (committed writes, pending locks)."""
        with self._mu:
            out = []
            for k in self._entries.irange(start, end or None,
                                          inclusive=(True, False)):
                e = self._entries[k]
                hit = (e.lock is not None and
                       e.lock.start_ts == start_ts) or \
                    any(sts == start_ts for _cts, sts, _wt in e.writes)
                if hit:
                    out.append((k, self.mvcc_by_key(k)))
                    if len(out) >= limit:
                        break
            return out
