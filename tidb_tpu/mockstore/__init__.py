from tidb_tpu.mockstore.cluster import Cluster, Region, Store
from tidb_tpu.mockstore.mvcc import MVCCStore, WriteType
from tidb_tpu.mockstore.rpc import RegionCtx, RPCShim, TimeoutError_

__all__ = ["Cluster", "Region", "Store", "MVCCStore", "WriteType",
           "RegionCtx", "RPCShim", "TimeoutError_"]
