"""Global runtime configuration: the sysvar registry.

Reference: /root/reference/sessionctx/variable/sysvar.go (typed sysvar
registry), config/config.go:29-52 (TOML config tree) and the concurrency
knobs of sessionctx/variable/session.go:209-245. One flat registry serves
all three roles here: every performance knob that used to be a hard-coded
constant reads through it, `SET @@tidb_tpu_x = v` writes through it, and
the server CLI seeds it from flags.

Scope: the registry is GLOBAL (process-wide); sessions shadow it with a
thread-local overlay installed for the duration of each statement
(`session_overlay`, ref: sessionctx/variable SessionVars layering over
globals). Reads on the session's thread see the session values; the
coprocessor fan-out re-installs the overlay inside its pool workers
(store/copr.py) so per-session knobs apply uniformly there too.
"""

from __future__ import annotations

import os
import threading

__all__ = ["get_var", "set_var", "all_vars", "coerce", "session_overlay",
           "current_overlay", "device_enabled", "chunk_cache_enabled",
           "cop_concurrency", "sort_spill_rows", "device_min_rows",
           "stream_rows", "superchunk_rows", "pipeline_depth",
           "copr_stream_enabled", "copr_stream_frame_bytes",
           "copr_stream_credit", "join_partitions", "skew_threshold",
           "runtime_stats_enabled",
           "runtime_stats_device", "mem_quota_query",
           "device_cache_bytes", "fused_scan_enabled",
           "encoded_exec_enabled", "fuse_fragments_enabled",
           "direct_agg_slots",
           "server_mem_quota", "admission_timeout_ms",
           "sched_inflight", "sched_inflight_bytes",
           "delta_store_enabled", "delta_merge_rows",
           "delta_merge_ratio_pct", "delta_retain_ms",
           "fleet_local_cache",
           "dispatch_timeout_ms", "failpoints_spec", "on_change",
           "trace_sample", "slow_trace_ms",
           "kernel_profile", "kernel_profile_cap", "stmt_profile_cap",
           "metrics_history_interval_ms", "metrics_history_points",
           "member_heartbeat_ms", "member_ttl_ms",
           "cluster_fetch_timeout_ms",
           "UnknownVariableError"]


class UnknownVariableError(Exception):
    pass


_BOOL, _INT, _STR = "bool", "int", "str"

# name -> (type, default). Bool vars store 0/1 like MySQL switches;
# the rare _STR vars (failpoint arming) store their string verbatim.
_DEFS: dict[str, tuple[str, int]] = {
    # master switch for single-chip device kernels; 0 = pure host numpy
    # execution everywhere (the measured CPU baseline mode of bench.py)
    "tidb_tpu_device": (_BOOL, 1),
    # columnar region-chunk cache on the storage side (store/chunk_cache)
    "tidb_tpu_chunk_cache": (_BOOL, 1),
    # coprocessor fan-out worker count
    # (ref: DistSQLScanConcurrency, sessionctx/variable/tidb_vars.go:115)
    "tidb_tpu_cop_concurrency": (_INT, 10),
    # SortExec spill threshold in rows (executor/extsort.py run size)
    "tidb_tpu_sort_spill_rows": (_INT, 1 << 20),
    # min chunk rows before an executor pays a device dispatch
    "tidb_tpu_device_min_rows": (_INT, 2048),
    # streaming threshold for mesh/device operators: probe sides larger
    # than this never materialize whole on the host — they feed the
    # kernels in ≤stream_rows super-batches, double-buffered so the
    # host→HBM transfer of batch i+1 overlaps batch i's readback
    # (BASELINE config 5; ref: the bounded producer/consumer channels of
    # distsql/distsql.go:92-98). The default is deliberately high:
    # below it, whole tables stay memoized/resident in HBM and hot
    # re-executions transfer ZERO bytes (the analytics fast path);
    # streaming trades that residency for bounded host memory, so it
    # should engage only when tables genuinely outgrow memory. Lower it
    # per deployment (SET tidb_tpu_stream_rows = ...) to cap footprint.
    "tidb_tpu_stream_rows": (_INT, 1 << 23),
    # streaming coprocessor (store/stream.py; ref: CmdCopStream,
    # store/tikv/coprocessor.go:547-555): storage yields framed partial
    # responses per contiguous key range instead of materializing one
    # response list per region. On by default since streams consult and
    # populate the columnar chunk cache (and the HBM device cache when
    # eligible) exactly like the materialized path — the old
    # cache-bypass penalty that forced the default off is gone. 0 =
    # materialized per-region response lists.
    "tidb_tpu_copr_stream": (_BOOL, 1),
    # response-size cap: a streamed frame never carries more than this
    # many raw scanned bytes (the bound that makes SF>=1 scans run in
    # constant client memory). Cache-resident ranges ship as ONE final
    # frame only when the response respects this cap too: agg partials
    # (tiny by construction) and raw blocks that fit a single frame —
    # bigger resident blocks stream framed like a cold scan
    "tidb_tpu_copr_stream_frame_bytes": (_INT, 4 << 20),
    # credit window: max frames in flight past the consumer (client
    # grants N outstanding frames; the producer blocks past the window —
    # a slow consumer backpressures the server instead of buffering)
    "tidb_tpu_copr_stream_credit": (_INT, 4),
    # superchunk coalescing (ops/runtime.py): chunks arriving from the
    # coprocessor fan-out are re-batched into ~this-many-row fixed-shape
    # batches before a device kernel sees them, so each query compiles a
    # handful of XLA programs over big buckets instead of dispatching per
    # storage chunk (the per-batch amortization of arxiv 2505.04153 /
    # 2603.26698). Power of two keeps full superchunks on one bucket
    # shape; the tail pads to the next power of two with valid=False
    # rows. 0 disables coalescing (per-chunk dispatch, the pre-superchunk
    # behavior). Order-sensitive paths (KeepOrder streaming readers,
    # limit short-circuit scans, merge join) stay chunk-at-a-time.
    "tidb_tpu_superchunk_rows": (_INT, 1 << 18),
    # dispatch-ahead window of the device pipeline: up to this many
    # superchunks in flight, so superchunk k+1 is padded and transferred
    # while k executes (2 = classic double buffering). 1 serializes
    # dispatch against readback.
    "tidb_tpu_pipeline_depth": (_INT, 2),
    # HBM-resident columnar region-block cache (store/device_cache.py):
    # device-side budget in bytes for dict-encoded, padded region
    # columns kept resident in HBM across queries, accounted on the
    # memtrack SERVER device ledger and LRU-evicted past the budget.
    # 0 disables (every dispatch re-uploads, the pre-cache behavior).
    "tidb_tpu_device_cache_bytes": (_INT, 2 << 30),
    # fused scan->filter->partial-agg dispatch (store/copr.py): an
    # HBM-cached region block flows through predicate + partial
    # aggregation in ONE compiled call — no per-op device_put/device_get
    # round trips. 0 reverts the scan path to per-dispatch upload AND
    # stops consulting/filling the device cache entirely: a cached
    # block is only consumable by a kernel that accepts device-resident
    # columns, i.e. the fused dispatch.
    "tidb_tpu_fused_scan": (_BOOL, 1),
    # encoded execution (ops/encoded.py): operate on dictionary codes
    # end-to-end instead of decoding varlen columns at the device-cache
    # boundary — string filters compare against pre-encoded constant
    # codes on device, join build/probe sides hash codes directly
    # (re-keyed through a code-translation array when the dictionaries
    # differ), and only result columns late-materialize at the
    # operator-output finalize boundary. Any unsupported expression
    # falls back to the decoded path, counted in
    # tidb_tpu_device_fallback_total{reason="encoding"}. 0 = always
    # decode (the pre-encoded behavior).
    "tidb_tpu_encoded_exec": (_BOOL, 1),
    # fragment fusion (ops/fragment.py): one XLA program executes a
    # whole pipeline fragment (scan->filter->probe->partial-agg) per
    # probe superchunk instead of one program per operator, eliminating
    # the inter-operator HBM round trips (the joined intermediate never
    # materializes). 0 = per-operator programs.
    "tidb_tpu_fuse_fragments": (_BOOL, 1),
    # cardinality bound of the direct-indexed (code-indexed) partial-agg
    # table: group domains whose code-span product fits this many slots
    # aggregate through a fixed-size direct-indexed array (no sort, no
    # hash, no collision possibility); past it the group-by degrades to
    # the packed-sort hash table instead of ballooning the direct table
    # (arxiv 2603.26698 "Partial Partial Aggregates").
    "tidb_tpu_direct_agg_slots": (_INT, 4096),
    # radix fan-out of the partitioned hybrid hash join/agg
    # (ops/hybrid.py; arxiv 2112.02480's dynamic hybrid hash join): build
    # and probe keys split into this many hash partitions so a capacity
    # or collision miss retries ONE partition (and a memtrack quota spill
    # sheds cold build partitions to host staging) instead of dropping
    # the whole operator to the host. 0/1 disables partitioning (the
    # pre-hybrid all-or-nothing behavior). The unskewed fast path is
    # unchanged: partitioning engages only on detected skew, an
    # over-superchunk build, an active memory quota, or an agg miss.
    "tidb_tpu_join_partitions": (_INT, 8),
    # heavy-hitter threshold in rows (ops/hybrid.py; arxiv 2505.04153):
    # a join key whose build-side duplication or (CMSketch-estimated)
    # probe-side frequency reaches this many rows routes to the
    # dedicated broadcast lane, so one hot key cannot overflow its hash
    # partition. 0 disables skew routing.
    "tidb_tpu_skew_threshold": (_INT, 1 << 15),
    # statements at/above this wall time land in the slow-query log
    # (ref: config.Log.SlowThreshold, default 300ms)
    "tidb_tpu_slow_query_ms": (_INT, 300),
    # per-operator runtime statistics (runtime_stats.py; ref: the
    # RuntimeStatsColl threaded through the reference's executors). On by
    # default: the host-side cost is a clock read per chunk. Feeds
    # EXPLAIN ANALYZE, the digest summary's hot spots, the slow log and
    # the tidb_tpu_op_* metric families.
    "tidb_tpu_runtime_stats": (_BOOL, 1),
    # device-time attribution: times kernel calls around
    # block_until_ready, which SERIALIZES dispatch — off by default,
    # flip per session when profiling (EXPLAIN ANALYZE device_time)
    "tidb_tpu_runtime_stats_device": (_BOOL, 0),
    # emit every statement's span tree to the tidb_tpu.trace logger
    # (ref: the OpenTracing spans of session.go:692 / compiler.go:34)
    "tidb_tpu_trace_log": (_BOOL, 0),
    # always-on statement-trace sampling (trace.py): every N-th
    # non-internal statement retains its full span tree in the bounded
    # server trace ring (TRACE statement / statement_traces memtable /
    # GET /trace / Chrome export). Deterministic counter, not random —
    # 1 retains everything, 0 disables sampling (slow-trace capture and
    # the TRACE statement still retain).
    "tidb_tpu_trace_sample": (_INT, 64),
    # slow-trace capture threshold in milliseconds: any statement at or
    # over it retains its full span tree regardless of sampling, and
    # its trace id rides the slow log + digest summary so a digest hot
    # spot links to a concrete timeline. 0 = off.
    "tidb_tpu_slow_trace_ms": (_INT, 300),
    # per-statement memory quota in bytes over BOTH tracker ledgers
    # (host + device, memtrack.py; ref: the reference's mem-quota-query).
    # 0 = unlimited. Crossing it fires the OOM-action chain: registered
    # sort/agg spills first, then cancel with ER_MEM_EXCEED_QUOTA.
    "tidb_tpu_mem_quota_query": (_INT, 0),
    # SERVER-wide memory budget in bytes over the memtrack root's two
    # ledgers combined (tidb_tpu/sched.py AdmissionController; ref: the
    # reference's server-memory-quota). 0 = admission control off. On
    # projected overflow at statement admission the controller first
    # drives the registered shed chain (HBM cache blocks, running
    # statements' spill actions), then queues the statement up to
    # tidb_tpu_admission_timeout_ms, then rejects with the RETRYABLE
    # ER_SERVER_BUSY_ADMISSION (9008) — never a mid-query OOM cancel.
    "tidb_tpu_server_mem_quota": (_INT, 0),
    # bounded admission-queue wait before a statement is rejected with
    # the retryable 9008 (milliseconds)
    "tidb_tpu_admission_timeout_ms": (_INT, 1000),
    # global device dispatch window (tidb_tpu/sched.py DeviceScheduler):
    # at most this many kernel dispatches in flight across ALL
    # concurrent statements, granted round-robin per statement so one
    # long analytic query cannot monopolize the device while point
    # lookups starve. 0 = scheduler off (the pre-scheduler free-for-all
    # where each statement owned a private pipeline-depth window).
    "tidb_tpu_sched_inflight": (_INT, 4),
    # in-flight-bytes gate: a dispatch slot is granted only while the
    # memtrack SERVER root's DEVICE ledger sits below this many bytes
    # (0 = no bytes gate). Size it to HBM minus the device-cache budget;
    # one dispatch is always allowed through when none are in flight.
    "tidb_tpu_sched_inflight_bytes": (_INT, 0),
    # MVCC delta store (store/delta.py): committed row mutations are
    # journaled per table and cached columnar blocks serve as
    # base + delta instead of being wholesale-invalidated — the HTAP
    # write path. 0 = legacy behavior: every committed write bumps
    # data_version and re-colds both the chunk cache and the HBM cache.
    "tidb_tpu_delta_store": (_BOOL, 1),
    # staged delta rows per table that trigger a background merge
    # (fold deltas into new base blocks + truncate the journal)
    "tidb_tpu_delta_merge_rows": (_INT, 8192),
    # store-plane journal retention window in wall-clock ms: merges keep
    # at least this much journal behind now so remote fleet caches
    # (pulling (fill_ts, read_ts] windows over the journal-window RPC)
    # can patch in place instead of going STALE. 0 = truncate to the
    # local floor only (single-process behavior)
    "tidb_tpu_delta_retain_ms": (_INT, 0),
    # fleet SQL servers serve coprocessor reads from their own chunk +
    # HBM caches, kept coherent by journal-window pulls from the store
    # plane; 0 = every remote read executes on the store plane
    "tidb_tpu_fleet_local_cache": (_BOOL, 1),
    # merge when staged delta rows exceed this percent of the table's
    # observed cached base rows (0 = ratio trigger off)
    "tidb_tpu_delta_merge_ratio_pct": (_INT, 25),
    # dispatch watchdog (tidb_tpu/sched.py DispatchWatchdog): a kernel
    # finalize (or device_slot-guarded sync dispatch) that exceeds this
    # many milliseconds cancels its statement with the RETRYABLE
    # ER_DEVICE_FAULT (9009), releasing its scheduler slots and
    # device-ledger bytes on the existing finally paths — a wedged
    # device degrades to a retryable error, never a stuck server.
    # 0 = watchdog off (the default: CPU-XLA first compiles can
    # legitimately take tens of seconds).
    "tidb_tpu_dispatch_timeout_ms": (_INT, 0),
    # kernel profiling plane (tidb_tpu/profiler.py): continuous per-
    # kernel compile/dispatch/roofline accounting keyed (family, plan
    # fingerprint, mesh fingerprint), surfaced in EXPLAIN ANALYZE's
    # `kernel` column, information_schema.kernel_profile and
    # GET /profile. On by default: the armed per-dispatch cost is one
    # perf_counter pair + a dict fold under one lock, amortized over
    # superchunk-sized dispatches; disarmed cost is pinned <5us per
    # statement (tests/test_profiler.py, same discipline as trace).
    "tidb_tpu_kernel_profile": (_BOOL, 1),
    # bounded size of the kernel-profile registry (distinct
    # family/fingerprint/mesh keys; true LRU beyond). Entries bill a
    # fixed per-entry cost to the `kernel-profile` memtrack SERVER
    # node, with a registered shed action — GET /shed (and admission
    # shedding) drops the profile history before it cancels work.
    "tidb_tpu_kernel_profile_cap": (_INT, 512),
    # bounded size of the per-digest per-operator mode-history memo
    # (perfschema.py): which agg/join mode actually ran per statement
    # digest, observed group cardinality and per-mode device-ns — the
    # read side the future adaptive mode chooser (ROADMAP item 3)
    # consults. Served as information_schema.statement_profile.
    "tidb_tpu_stmt_profile_cap": (_INT, 1024),
    # metrics-history sampler cadence (tidb_tpu/metrics_history.py): a
    # supervised background sampler snapshots registered gauges plus
    # derived device-utilization / HBM occupancy / hit-rate series into
    # a bounded in-process ring (billed to a memtrack SERVER node with
    # a registered shed action) every this-many milliseconds, and rolls
    # the resource meter's per-tenant interval baselines. Served on
    # GET /metrics/history. 0 = sampler idle (manual sample_now() — the
    # bench/test door — still records).
    "tidb_tpu_metrics_history_interval_ms": (_INT, 1000),
    # metrics-history ring capacity in points (one point per sampler
    # tick); the oldest points evict past it
    "tidb_tpu_metrics_history_points": (_INT, 512),
    # fleet membership registry (tidb_tpu/member.py): every server
    # process republishes its ephemeral heartbeat record this often...
    "tidb_tpu_member_heartbeat_ms": (_INT, 1000),
    # ...and a record not rebeaten within this window is dead — peers
    # stop fanning cluster_* queries out to it and it drops from
    # information_schema.cluster_members. TTL should be >= 2-3x the
    # heartbeat so one delayed beat doesn't flap membership.
    "tidb_tpu_member_ttl_ms": (_INT, 3000),
    # per-member HTTP budget of the cluster_* / /fleet/* fan-out
    # (util/statusclient.fetch_all): an unreachable member costs at
    # most this long and degrades that member's rows to a warning,
    # never a hang or a statement error
    "tidb_tpu_cluster_fetch_timeout_ms": (_INT, 2000),
    # failpoint arming (util/failpoint.py): "name=spec;name=spec" over
    # the declared registry, e.g. 'hbm/fill=2*raise(DeviceFaultError)'.
    # The value is DECLARATIVE for the SET surface: writing it arms the
    # listed points and disarms whatever a previous SET armed (env and
    # POST /failpoint arming is unaffected). Empty = none armed via
    # SET. GLOBAL scope only — arming is a process-wide side effect.
    "tidb_tpu_failpoints": (_STR, ""),
}

_lock = threading.Lock()
_vals: dict[str, int] = {}
# name -> [fn]: set_var notifies AFTER the write, with _lock dropped
# (hooks may read the registry); util/failpoint.py uses this to make
# `SET GLOBAL tidb_tpu_failpoints = ...` arm the registry
_hooks: dict[str, list] = {}        # guarded-by: _lock


def on_change(name: str, fn) -> None:
    """Register fn(new_value) to run after every set_var(name)."""
    key = name.lower()
    if key not in _DEFS:
        raise UnknownVariableError(name)
    with _lock:
        _hooks.setdefault(key, []).append(fn)


def _coerce(name: str, tp: str, value) -> int:
    if tp == _STR:
        return "" if value is None else str(value)
    if isinstance(value, str):
        v = value.strip().lower()
        if tp == _BOOL and v in ("on", "true"):
            return 1
        if tp == _BOOL and v in ("off", "false"):
            return 0
        value = int(v)
    iv = int(value)
    if tp == _BOOL:
        iv = 1 if iv else 0
    return iv


def _init() -> None:
    """Defaults, overridable by environment (TIDB_TPU_DEVICE=0 etc.) so
    benchmarks and CI can flip modes without code. Malformed values fail
    fast with the offending variable named (not a bare int() traceback)."""
    for name, (tp, dflt) in _DEFS.items():
        env = os.environ.get(name.upper())
        if env is None:
            _vals[name] = dflt
            continue
        try:
            _vals[name] = _coerce(name, tp, env)
        except ValueError:
            raise ValueError(
                f"invalid value for environment variable "
                f"{name.upper()}={env!r} (expected "
                f"{'on/off/true/false/0/1' if tp == _BOOL else 'an integer'})"
            ) from None


_init()


_tls = threading.local()


def _read(key: str) -> int:
    ov = getattr(_tls, "overlay", None)
    if ov is not None and key in ov:
        return ov[key]
    return _vals[key]


def current_overlay() -> dict:
    """This thread's effective session overlay (for propagating into
    worker threads: wrap their work in session_overlay(...))."""
    return dict(getattr(_tls, "overlay", None) or {})


class session_overlay:
    """Shadow registry values on THIS thread for a statement's duration
    (per-session SET). Nests: inner overlays win, outers restore."""

    def __init__(self, vars: dict):
        self.vars = {k.lower(): v for k, v in vars.items()
                     if k.lower() in _DEFS}
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "overlay", None)
        merged = dict(self._prev) if self._prev else {}
        merged.update(self.vars)
        _tls.overlay = merged
        return self

    def __exit__(self, *exc):
        _tls.overlay = self._prev
        return False


# vars whose write is a process-wide side effect routed through
# on_change hooks: session-scope SET would shadow the value on one
# thread while arming nothing — reject it (ER_GLOBAL_VARIABLE)
_GLOBAL_ONLY = frozenset({"tidb_tpu_failpoints"})


def is_global_only(name: str) -> bool:
    return name.lower() in _GLOBAL_ONLY


def is_known(name: str) -> bool:
    return name.lower() in _DEFS


def coerce(name: str, value) -> int:
    """Validate + normalize a value for a known variable (raises
    UnknownVariableError / ValueError)."""
    key = name.lower()
    tp_dflt = _DEFS.get(key)
    if tp_dflt is None:
        raise UnknownVariableError(name)
    return _coerce(key, tp_dflt[0], value)


def get_var(name: str) -> int:
    key = name.lower()
    if key not in _DEFS:
        raise UnknownVariableError(name)
    return _read(key)


def set_var(name: str, value) -> None:
    key = name.lower()
    tp_dflt = _DEFS.get(key)
    if tp_dflt is None:
        raise UnknownVariableError(name)
    new = _coerce(key, tp_dflt[0], value)
    with _lock:
        prev = _vals.get(key)
        _vals[key] = new
        hooks = list(_hooks.get(key, ()))
    try:
        for fn in hooks:
            fn(new)
    except Exception:
        # a hook that rejects the value (bad failpoint spec) must not
        # leave the registry claiming a value that never took effect;
        # compare-and-restore so a CONCURRENT successful set_var that
        # interleaved before this rollback is not clobbered
        with _lock:
            if _vals.get(key) == new:
                _vals[key] = prev
        raise


def all_vars() -> dict[str, int]:
    """Effective values on this thread (session overlay applied)."""
    out = dict(_vals)
    ov = getattr(_tls, "overlay", None)
    if ov:
        out.update(ov)
    return out


# -- hot-path accessors (dict reads; no lock needed for int loads) ----------

def device_enabled() -> bool:
    return bool(_read("tidb_tpu_device"))


def chunk_cache_enabled() -> bool:
    return bool(_read("tidb_tpu_chunk_cache"))


def cop_concurrency() -> int:
    return _read("tidb_tpu_cop_concurrency")


def sort_spill_rows() -> int:
    return _read("tidb_tpu_sort_spill_rows")


def device_min_rows() -> int:
    return _read("tidb_tpu_device_min_rows")


def stream_rows() -> int:
    return _read("tidb_tpu_stream_rows")


def superchunk_rows() -> int:
    return max(0, _read("tidb_tpu_superchunk_rows"))


def pipeline_depth() -> int:
    return max(1, _read("tidb_tpu_pipeline_depth"))


def copr_stream_enabled() -> bool:
    return bool(_read("tidb_tpu_copr_stream"))


def copr_stream_frame_bytes() -> int:
    # clamp both ends: the sysvar is unbounded, the wire/shim contract
    # (mockstore/rpc.py validation) is not
    return min(max(1, _read("tidb_tpu_copr_stream_frame_bytes")), 1 << 30)


def copr_stream_credit() -> int:
    return max(1, _read("tidb_tpu_copr_stream_credit"))


def join_partitions() -> int:
    return max(0, _read("tidb_tpu_join_partitions"))


def skew_threshold() -> int:
    return max(0, _read("tidb_tpu_skew_threshold"))


def runtime_stats_enabled() -> bool:
    return bool(_read("tidb_tpu_runtime_stats"))


def runtime_stats_device() -> bool:
    return bool(_read("tidb_tpu_runtime_stats_device"))


def mem_quota_query() -> int:
    return max(0, _read("tidb_tpu_mem_quota_query"))


def device_cache_bytes() -> int:
    return max(0, _read("tidb_tpu_device_cache_bytes"))


def server_mem_quota() -> int:
    return max(0, _read("tidb_tpu_server_mem_quota"))


def admission_timeout_ms() -> int:
    return max(0, _read("tidb_tpu_admission_timeout_ms"))


def sched_inflight() -> int:
    return max(0, _read("tidb_tpu_sched_inflight"))


def sched_inflight_bytes() -> int:
    return max(0, _read("tidb_tpu_sched_inflight_bytes"))


def fused_scan_enabled() -> bool:
    return bool(_read("tidb_tpu_fused_scan"))


def encoded_exec_enabled() -> bool:
    return bool(_read("tidb_tpu_encoded_exec"))


def fuse_fragments_enabled() -> bool:
    return bool(_read("tidb_tpu_fuse_fragments"))


def direct_agg_slots() -> int:
    return max(16, _read("tidb_tpu_direct_agg_slots"))


def delta_store_enabled() -> bool:
    return bool(_read("tidb_tpu_delta_store"))


def delta_merge_rows() -> int:
    return max(1, _read("tidb_tpu_delta_merge_rows"))


def delta_merge_ratio_pct() -> int:
    return max(0, _read("tidb_tpu_delta_merge_ratio_pct"))


def delta_retain_ms() -> int:
    return max(0, _read("tidb_tpu_delta_retain_ms"))


def fleet_local_cache() -> bool:
    return bool(_read("tidb_tpu_fleet_local_cache"))


def dispatch_timeout_ms() -> int:
    return max(0, _read("tidb_tpu_dispatch_timeout_ms"))


def failpoints_spec() -> str:
    return str(_read("tidb_tpu_failpoints") or "")


def metrics_history_interval_ms() -> int:
    return max(0, _read("tidb_tpu_metrics_history_interval_ms"))


def metrics_history_points() -> int:
    return min(max(16, _read("tidb_tpu_metrics_history_points")), 1 << 16)


def member_heartbeat_ms() -> int:
    return max(100, _read("tidb_tpu_member_heartbeat_ms"))


def member_ttl_ms() -> int:
    return max(200, _read("tidb_tpu_member_ttl_ms"))


def cluster_fetch_timeout_ms() -> int:
    return max(100, _read("tidb_tpu_cluster_fetch_timeout_ms"))


def trace_sample() -> int:
    return max(0, _read("tidb_tpu_trace_sample"))


def slow_trace_ms() -> int:
    return max(0, _read("tidb_tpu_slow_trace_ms"))


def kernel_profile() -> bool:
    return bool(_read("tidb_tpu_kernel_profile"))


def kernel_profile_cap() -> int:
    return min(max(16, _read("tidb_tpu_kernel_profile_cap")), 1 << 16)


def stmt_profile_cap() -> int:
    return min(max(16, _read("tidb_tpu_stmt_profile_cap")), 1 << 16)
