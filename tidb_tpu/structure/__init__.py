"""TxStructure: Redis-like typed structures on one KV transaction.

Reference: /root/reference/structure/structure.go:49 (TxStructure),
string.go:24 (string ops), hash.go:46 (hash ops), list.go (list ops) —
the substrate the reference's meta/ package stores all schema metadata
on. Same shape here: every op reads/writes through the caller's
transaction, so structure mutations commit atomically with whatever
else the txn does (schema version bumps, DDL job state).

Key encoding under a namespace prefix (type tag keeps the three kinds
disjoint; `\\x00` separates key from field/index, so structure KEYS must
not contain NUL — metadata keys are ASCII):

    {prefix}s{key}                 string value
    {prefix}h{key}\\x00{field}      hash field value
    {prefix}l{key}                 list bounds json [left, right)
    {prefix}i{key}\\x00{index:020d} list item
"""

from __future__ import annotations

import json

from tidb_tpu import kv

__all__ = ["TxStructure"]


class TxStructure:
    def __init__(self, txn: kv.Transaction, prefix: bytes = b"m"):
        self.txn = txn
        self.prefix = prefix

    # -- key codecs ----------------------------------------------------------

    def _skey(self, key: bytes) -> bytes:
        return self.prefix + b"s" + key

    def _hkey(self, key: bytes, field: bytes) -> bytes:
        return self.prefix + b"h" + key + b"\x00" + field

    def _hrange(self, key: bytes) -> tuple[bytes, bytes]:
        base = self.prefix + b"h" + key + b"\x00"
        return base, base[:-1] + b"\x01"

    def _lmeta_key(self, key: bytes) -> bytes:
        return self.prefix + b"l" + key

    def _ikey(self, key: bytes, index: int) -> bytes:
        return self.prefix + b"i" + key + b"\x00" + b"%020d" % index

    # -- strings (ref: structure/string.go) ----------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        self.txn.set(self._skey(key), value)

    def get(self, key: bytes) -> bytes | None:
        return self.txn.get(self._skey(key))

    def inc(self, key: bytes, step: int = 1) -> int:
        """Atomic within the txn (ref: string.go Inc)."""
        raw = self.get(key)
        cur = int(raw) if raw else 0
        cur += step
        self.set(key, b"%d" % cur)
        return cur

    def get_int(self, key: bytes) -> int:
        raw = self.get(key)
        return int(raw) if raw else 0

    def clear(self, key: bytes) -> None:
        self.txn.delete(self._skey(key))

    # -- hashes (ref: structure/hash.go) -------------------------------------

    def hset(self, key: bytes, field: bytes, value: bytes) -> None:
        self.txn.set(self._hkey(key, field), value)

    def hget(self, key: bytes, field: bytes) -> bytes | None:
        return self.txn.get(self._hkey(key, field))

    def hdel(self, key: bytes, field: bytes) -> None:
        self.txn.delete(self._hkey(key, field))

    def hlen(self, key: bytes) -> int:
        return len(self.hgetall(key))

    def hgetall(self, key: bytes) -> list[tuple[bytes, bytes]]:
        """[(field, value)] in field byte order (ref: hash.go HGetAll)."""
        lo, hi = self._hrange(key)
        out = []
        for k, v in self.txn.iter_range(lo, hi):
            out.append((k[len(lo):], v))
        return out

    def hscan_prefix(self, key: bytes,
                     field_prefix: bytes) -> list[tuple[bytes, bytes]]:
        """Fields starting with field_prefix, in order."""
        return [(f, v) for f, v in self.hgetall(key)
                if f.startswith(field_prefix)]

    def hclear(self, key: bytes) -> None:
        lo, hi = self._hrange(key)
        for k, _v in list(self.txn.iter_range(lo, hi)):
            self.txn.delete(k)

    # -- lists (ref: structure/list.go) --------------------------------------

    def _bounds(self, key: bytes) -> tuple[int, int]:
        raw = self.txn.get(self._lmeta_key(key))
        if not raw:
            return 0, 0
        left, right = json.loads(raw)
        return int(left), int(right)

    def _set_bounds(self, key: bytes, left: int, right: int) -> None:
        if left == right:
            self.txn.delete(self._lmeta_key(key))
        else:
            self.txn.set(self._lmeta_key(key),
                         json.dumps([left, right]).encode())

    def rpush(self, key: bytes, *values: bytes) -> None:
        left, right = self._bounds(key)
        for v in values:
            self.txn.set(self._ikey(key, right), v)
            right += 1
        self._set_bounds(key, left, right)

    def lpush(self, key: bytes, *values: bytes) -> None:
        left, right = self._bounds(key)
        for v in values:
            left -= 1
            self.txn.set(self._ikey(key, left), v)
        self._set_bounds(key, left, right)

    def llen(self, key: bytes) -> int:
        left, right = self._bounds(key)
        return right - left

    def lindex(self, key: bytes, index: int) -> bytes | None:
        left, right = self._bounds(key)
        pos = (left + index) if index >= 0 else (right + index)
        if not (left <= pos < right):
            return None
        return self.txn.get(self._ikey(key, pos))

    def lset(self, key: bytes, index: int, value: bytes) -> None:
        left, right = self._bounds(key)
        pos = (left + index) if index >= 0 else (right + index)
        if not (left <= pos < right):
            raise IndexError("list index out of range")
        self.txn.set(self._ikey(key, pos), value)

    def lpop(self, key: bytes) -> bytes | None:
        left, right = self._bounds(key)
        if left == right:
            return None
        v = self.txn.get(self._ikey(key, left))
        self.txn.delete(self._ikey(key, left))
        self._set_bounds(key, left + 1, right)
        return v

    def rpop(self, key: bytes) -> bytes | None:
        left, right = self._bounds(key)
        if left == right:
            return None
        v = self.txn.get(self._ikey(key, right - 1))
        self.txn.delete(self._ikey(key, right - 1))
        self._set_bounds(key, left, right - 1)
        return v

    def lrem_at(self, key: bytes, index: int) -> None:
        """Remove one item by position, shifting later items left (queues
        here are short — the DDL job list; ref keeps the same O(n))."""
        left, right = self._bounds(key)
        pos = (left + index) if index >= 0 else (right + index)
        if not (left <= pos < right):
            raise IndexError("list index out of range")
        for p in range(pos, right - 1):
            nxt = self.txn.get(self._ikey(key, p + 1))
            self.txn.set(self._ikey(key, p), nxt)
        self.txn.delete(self._ikey(key, right - 1))
        self._set_bounds(key, left, right - 1)

    def litems(self, key: bytes) -> list[bytes]:
        left, right = self._bounds(key)
        return [self.txn.get(self._ikey(key, p))
                for p in range(left, right)]

    def lclear(self, key: bytes) -> None:
        left, right = self._bounds(key)
        for p in range(left, right):
            self.txn.delete(self._ikey(key, p))
        self.txn.delete(self._lmeta_key(key))
