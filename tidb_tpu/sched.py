"""Server-level device scheduler and statement admission control.

ROADMAP item 1: everything below the session layer was built one
statement at a time — each connection thread drove its own
`ops/runtime.pipeline_map` and dispatched kernels with zero
coordination, so concurrent statements interleaved arbitrarily (or
serialized on implicit XLA locks) and one long analytic scan could
monopolize the device while point lookups starved. This module owns the
two server-wide decisions:

**Scheduling** (`DeviceScheduler`): the pipeline-depth in-flight window
becomes a GLOBAL resource. Every device dispatch — pipelined superchunks
and one-shot sync kernels alike — takes a slot before it enqueues work,
and slots are granted round-robin across statements, so the depth-N
window interleaves tokens from every running statement instead of
belonging to whichever thread spun first. Two gates bound the grant:

  * `tidb_tpu_sched_inflight` concurrent dispatch slots (0 = scheduler
    off, the pre-scheduler free-for-all);
  * `tidb_tpu_sched_inflight_bytes` against the memtrack SERVER root's
    DEVICE ledger — the ledger every dispatch site already bills its
    padded upload + scratch to at dispatch and credits back at finalize,
    so it IS the in-flight HBM figure (plus deliberate residency: HBM
    cache blocks, pinned join builds). 0 = no bytes gate.

The scheduler is a throttle, not a correctness gate: a waiter that
times out proceeds WITHOUT a slot (counted in
`tidb_tpu_sched_bypass_total`) so no lost wakeup, crashed holder, or
cap misconfiguration can ever hang a statement. `pipeline_map` reacts
to contention by draining its own oldest in-flight token first —
shrinking the statement's local window to its fair share of the global
one. The one blocking resource is the slot itself, released in finally
blocks and never held across another lock acquisition, so the wait can
participate in no deadlock cycle.

**Admission** (`AdmissionController`): arms the SERVER memtrack root
with `tidb_tpu_server_mem_quota` (host+device ledgers combined). At
statement admission the projected footprint (the statement digest's
historical peak from perfschema, floor-bounded) is checked against the
quota; on projected overflow the controller first DRIVES the registered
shed chain — the hook `store/device_cache.py` registered at import and
nothing fired until now (HBM cache blocks, hybrid-join cold partitions
registered on running statements' roots) — then queues the statement
for a bounded `tidb_tpu_admission_timeout_ms` wait, and only then
rejects with the retryable `ER_SERVER_BUSY_ADMISSION` (9008) instead of
letting the statement run into a mid-query OOM cancel. One statement
always makes progress: when nothing else is admitted, the head of the
queue is admitted regardless of projection, so a pinched quota degrades
to serialized execution, never to a stuck server.

Lock discipline: each class owns ONE Condition (`_cv`) guarding its own
counters. The admission controller fires shed actions and reads the
SERVER ledgers with `_cv` dropped; the scheduler's bytes gate reads the
ledger integer lock-free (a stale read is one dispatch of slack, and
every release re-evaluates). See docs/CONCURRENCY.md.
"""

from __future__ import annotations

import contextlib
import threading
import time

from tidb_tpu import config, devplane, memtrack, meter, metrics, trace
from tidb_tpu.util import failpoint

__all__ = ["DeviceScheduler", "AdmissionController",
           "AdmissionRejectedError", "DispatchWatchdog", "DeviceHealth",
           "device_scheduler", "admission", "dispatch_watchdog",
           "device_health", "device_slot", "finalize_watch",
           "degrade_statement", "statement_degraded",
           "shed_server", "stats", "reset_for_tests"]


class AdmissionRejectedError(Exception):
    """Statement refused at admission: the server is over
    `tidb_tpu_server_mem_quota`, shedding freed too little, and the
    bounded queue wait expired. RETRYABLE — surfaced to clients as
    ER_SERVER_BUSY_ADMISSION (9008) with a retry-later message; the
    session and its transaction state are untouched."""


# scheduler wait granularity: contended acquires re-check (and
# pipeline_map gets a chance to drain its own window) on this period
_SLICE_S = 0.02
# bypass valve: a dispatch that cannot get a slot for this long stops
# waiting and proceeds unscheduled (counted, never hung)
_BYPASS_S = 2.0
# admission projection floor for digests never seen before: small enough
# to admit cold workloads, large enough that a flood of unknowns still
# queues once the ledger fills
_MIN_PROJECTION = 1 << 20


class _Slot:
    """One granted (or bypassed) dispatch slot. `chip` is the plane
    chip index the grant placed this dispatch on (0 when the plane has
    one device, or for bypass/no-op slots); `t_grant` is the grant
    timestamp, so the release can attribute the slot's hold interval —
    dispatch through finalize — to the chip's busy ledger."""

    __slots__ = ("stream", "granted", "chip", "t_grant", "_event")

    def __init__(self, stream):
        self.stream = stream
        self.granted = False          # guarded-by the scheduler's _cv
        self.chip = 0                 # guarded-by the scheduler's _cv
        self.t_grant = 0              # guarded-by the scheduler's _cv
        self._event = threading.Event()


class DeviceScheduler:
    """Round-robin dispatch-slot allocator over the device plane.

    Streams are statements (keyed by their memtrack statement root, so
    every operator and pool worker of one statement shares one fairness
    bucket; library use without a tracker falls back to the thread id).
    Grants hand off: a release picks the next stream in rotation with a
    waiting head and wakes exactly that waiter, so a statement that
    just ran yields to every other waiting statement before it runs
    again.

    Per-chip slot streams: on an N-chip ``("batch",)`` plane
    (devplane.ndev() > 1) `tidb_tpu_sched_inflight` is a PER-CHIP
    depth — total capacity scales to inflight × ndev — and every grant
    places its dispatch on the least-loaded chip (fewest slots held,
    then least RECENT busy time: a half-life-decayed EWMA of the
    attributed hold intervals, so a chip that absorbed a heavy scan an
    hour ago competes as an equal once the work drains instead of
    being penalized by its cumulative ledger forever). Releases
    attribute the slot's hold interval to both the cumulative busy
    ledger (the metrics-history sampler and serve bench derive
    utilization from its deltas — those must stay monotone) and the
    decayed one (the placement signal). On a 1-device plane every
    counter collapses to chip 0 and behavior is exactly the
    single-device scheduler."""

    # placement half-life: busy time stops mattering once it is a few
    # multiples of this old. 30s spans many statements (so placement
    # is not noise-driven) while forgetting last-minute history fast
    # enough that a drained chip rejoins the rotation promptly.
    EWMA_HALFLIFE_S = 30.0

    def __init__(self):
        self._cv = threading.Condition()
        self._granted = 0                  # guarded-by: _cv
        self._waiters: dict = {}           # guarded-by: _cv  stream -> [slot]
        self._rr: list = []                # guarded-by: _cv  rotation order
        self._stall_ns = 0                 # guarded-by: _cv
        self._bypasses = 0                 # guarded-by: _cv
        self._grants = 0                   # guarded-by: _cv
        self._chip_granted: dict = {}      # guarded-by: _cv  chip -> held
        self._chip_grants: dict = {}       # guarded-by: _cv  chip -> total
        self._chip_busy_ns: dict = {}      # guarded-by: _cv  chip -> ns
        # chip -> decayed busy ns (the placement signal); decayed in
        # place against _ewma_t whenever placement or release reads it
        self._chip_busy_ewma: dict = {}    # guarded-by: _cv
        self._ewma_t = time.monotonic()    # guarded-by: _cv

    # -- capacity ------------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        return config.sched_inflight() > 0

    def _capacity_free(self) -> bool:
        """Both gates, called under _cv. The bytes gate reads the SERVER
        device ledger without its lock (an int load; one dispatch of
        staleness, re-checked on every release). Min-progress: with
        nothing granted, one dispatch always fits — resident HBM (cache
        blocks, pinned builds) above the cap must throttle, not
        starve."""
        if self._granted >= config.sched_inflight() * devplane.ndev():
            return False
        if self._granted == 0:
            return True
        cap = config.sched_inflight_bytes()
        return cap <= 0 or memtrack.SERVER.device < cap

    def _decay_ewma_locked(self, now: float | None = None) -> None:
        """Fold elapsed time into the decayed busy ledgers (under _cv).
        Exponential decay is time-composable, so decaying lazily at
        read/update points is exact — no background timer needed."""
        if now is None:
            now = time.monotonic()
        dt = now - self._ewma_t
        if dt <= 0:
            return
        self._ewma_t = now
        f = 0.5 ** (dt / self.EWMA_HALFLIFE_S)
        for c in self._chip_busy_ewma:
            self._chip_busy_ewma[c] *= f

    def _pick_chip_locked(self) -> int:
        """Least-loaded chip of the plane: fewest held slots, then
        least RECENT busy time — the decayed EWMA, not the cumulative
        ledger (ties break to the lowest index). Called under _cv at
        grant time."""
        n = devplane.ndev()
        if n <= 1:
            return 0
        self._decay_ewma_locked()
        return min(range(n),
                   key=lambda c: (self._chip_granted.get(c, 0),
                                  self._chip_busy_ewma.get(c, 0.0), c))

    # -- acquire / release ---------------------------------------------------

    @staticmethod
    def _stream_key():
        root = memtrack.current()
        return id(root) if root is not None else threading.get_ident()

    def acquire(self, timeout: float | None = None) -> "_Slot | None":
        """A dispatch slot, or None when `timeout` expires first.
        timeout=None waits a single grant slice. Returns a no-op slot
        immediately when the scheduler is off."""
        if not self.enabled():
            return _NOOP_SLOT
        stream = self._stream_key()
        slot = _Slot(stream)
        t0 = time.perf_counter_ns()
        with self._cv:
            q = self._waiters.get(stream)
            if q is None:
                q = self._waiters[stream] = []
                if stream not in self._rr:   # may linger after a timeout
                    self._rr.append(stream)
            q.append(slot)
            self._grant_locked()
        wait_s = timeout if timeout is not None else _SLICE_S
        deadline = time.monotonic() + wait_s
        stalled = False
        granted = slot._event.wait(timeout=_SLICE_S)
        while not granted:
            stalled = True
            expired = False
            with self._cv:
                if not slot.granted:
                    self._grant_locked()   # capacity may have freed
                if not slot.granted and \
                        time.monotonic() >= deadline:
                    self._forget_locked(slot)
                    expired = True
                granted = slot.granted
            if expired:
                self._note_stall(t0, stalled=True)
                return None
            if not granted:
                granted = slot._event.wait(timeout=_SLICE_S)
        self._note_stall(t0, stalled=stalled)
        return slot

    def acquire_or_bypass(self) -> "_Slot":
        """A slot, waiting at most the bypass valve; past it, an
        ungranted slot is returned so the dispatch proceeds unscheduled
        rather than hang (`tidb_tpu_sched_bypass_total`)."""
        slot = self.acquire(timeout=_BYPASS_S)
        if slot is not None:
            return slot
        with self._cv:
            self._bypasses += 1
        metrics.counter(metrics.SCHED_BYPASSES)
        return _Slot(self._stream_key())    # never granted: release no-ops

    def release(self, slot: "_Slot | None") -> None:
        now = time.perf_counter_ns()
        if slot is None or slot is _NOOP_SLOT:
            return
        with self._cv:
            if not slot.granted:     # bypass slots / double release:
                return               # checked under _cv, so two racing
            slot.granted = False     # releasers cannot both decrement
            self._granted -= 1
            held = self._chip_granted.get(slot.chip, 0)
            self._chip_granted[slot.chip] = max(held - 1, 0)
            # the hold interval (dispatch through finalize) IS the
            # chip's attributed busy time — cumulative for the sampler
            # and serve bench (monotone deltas), decayed for placement
            held_ns = max(now - slot.t_grant, 0)
            self._chip_busy_ns[slot.chip] = \
                self._chip_busy_ns.get(slot.chip, 0) + held_ns
            self._decay_ewma_locked()
            self._chip_busy_ewma[slot.chip] = \
                self._chip_busy_ewma.get(slot.chip, 0.0) + held_ns
            self._grant_locked()

    # -- grant machinery (all under _cv) -------------------------------------

    def _grant_locked(self) -> None:
        """Hand free capacity to waiting streams, one slot per stream
        per rotation pass."""
        while self._rr and self._capacity_free():
            progressed = False
            for _ in range(len(self._rr)):
                stream = self._rr.pop(0)
                q = self._waiters.get(stream)
                if not q:
                    self._waiters.pop(stream, None)
                    continue
                slot = q.pop(0)
                if not q:
                    self._waiters.pop(stream, None)
                else:
                    self._rr.append(stream)   # stays in rotation, at back
                slot.granted = True
                slot.chip = self._pick_chip_locked()
                slot.t_grant = time.perf_counter_ns()
                self._granted += 1
                self._grants += 1
                self._chip_granted[slot.chip] = \
                    self._chip_granted.get(slot.chip, 0) + 1
                self._chip_grants[slot.chip] = \
                    self._chip_grants.get(slot.chip, 0) + 1
                slot._event.set()
                progressed = True
                break
            if not progressed:
                break
            if not self._capacity_free():
                break

    def _forget_locked(self, slot: "_Slot") -> None:
        q = self._waiters.get(slot.stream)
        if q is not None:
            try:
                q.remove(slot)
            except ValueError:
                pass
            if not q:
                self._waiters.pop(slot.stream, None)

    def _note_stall(self, t0: int, stalled: bool) -> None:
        waited = time.perf_counter_ns() - t0
        with self._cv:
            self._stall_ns += waited
        if stalled:
            metrics.histogram(metrics.SCHED_STALLS, waited / 1e9)

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            return {"inflight": self._granted,
                    "waiting": sum(len(q) for q in self._waiters.values()),
                    "grants": self._grants,
                    "bypasses": self._bypasses,
                    "stall_seconds": round(self._stall_ns / 1e9, 6),
                    "chips": self._chip_snapshot_locked()}

    def _chip_snapshot_locked(self) -> dict:
        self._decay_ewma_locked()

        def one(c: int) -> dict:
            return {"inflight": self._chip_granted.get(c, 0),
                    "grants": self._chip_grants.get(c, 0),
                    "busy_seconds": round(
                        self._chip_busy_ns.get(c, 0) / 1e9, 6),
                    "busy_ewma_seconds": round(
                        self._chip_busy_ewma.get(c, 0.0) / 1e9, 6)}

        chips = {c: one(c) for c in range(devplane.ndev())}
        # chips that held slots under a since-shrunk plane keep their
        # history visible (the busy figures still explain past samples)
        for c in self._chip_grants:
            if c not in chips:
                chips[c] = one(c)
        return chips

    def chip_busy_ns(self) -> dict:
        """{chip: cumulative attributed busy ns} — the metrics-history
        sampler derives per-chip utilization ratios from deltas of
        this, and the serve bench reads it for the mesh-balance
        aggregate (total rows over the busiest chip's time)."""
        with self._cv:
            out = {c: self._chip_busy_ns.get(c, 0)
                   for c in range(devplane.ndev())}
            for c, ns in self._chip_busy_ns.items():
                out.setdefault(c, ns)
            return out


_NOOP_SLOT = _Slot(None)


class AdmissionController:
    """Statement admission against `tidb_tpu_server_mem_quota`.

    admit() outcomes (the `tidb_tpu_admission_total{outcome}` label):
      * admitted — fit on the first check;
      * shed     — fit only after driving the SERVER shed chain;
      * queued   — fit after waiting for running statements to finish;
      * rejected — still over quota at `tidb_tpu_admission_timeout_ms`:
        AdmissionRejectedError (retryable 9008).

    Projections reserve their bytes for the statement's lifetime, so N
    racing admissions cannot all clear one remaining gap. The reserve
    double-counts once the statement's REAL consumption lands on the
    SERVER ledgers — deliberately conservative: admission exists to
    keep mid-query OOM cancels at zero, and the min-progress rule (an
    empty controller always admits its head) caps the cost at
    serialized execution, never a stuck server."""

    def __init__(self):
        self._cv = threading.Condition()
        self._reserved = 0           # guarded-by: _cv  projected bytes
        self._admitted = 0           # guarded-by: _cv  running statements
        self._waiting = 0            # guarded-by: _cv  queue depth
        self._counts = {"admitted": 0, "queued": 0, "shed": 0,
                        "rejected": 0}   # guarded-by: _cv
        self._shed_bytes = 0         # guarded-by: _cv

    @staticmethod
    def enabled() -> bool:
        return config.server_mem_quota() > 0

    def _fits_locked(self, projected: int, quota: int) -> bool:
        if self._admitted == 0:
            # min-progress: with nothing admitted, the next statement
            # runs whatever the projection says (checks serialize under
            # _cv, so exactly one waiter takes this door) — the quota
            # throttles concurrency, it must not brick the server
            return True
        return memtrack.SERVER.total() + self._reserved + projected \
            <= quota

    def admit(self, projected: int = 0, label: str = "stmt"):
        """-> ticket (pass to finish()), or None when admission is off.
        Raises AdmissionRejectedError past the bounded queue wait."""
        quota = config.server_mem_quota()
        if quota <= 0:
            return None
        projected = max(int(projected), _MIN_PROJECTION)
        t0 = time.perf_counter_ns()
        deadline = time.monotonic() + \
            max(config.admission_timeout_ms(), 1) / 1e3
        outcome = "admitted"
        shed_tried = False
        with self._cv:
            self._waiting += 1
            # published under _cv so racing enter/leave cannot publish
            # counts out of order (metrics._lock is a leaf lock)
            metrics.gauge(metrics.ADMISSION_QUEUE_DEPTH, self._waiting)
        try:
            while True:
                with self._cv:
                    if self._fits_locked(projected, quota):
                        self._reserved += projected
                        self._admitted += 1
                        self._counts[outcome] += 1
                        break
                if not shed_tried:
                    shed_tried = True
                    # drive the registered shed chain (HBM cache blocks,
                    # hybrid-join cold partitions on running statements)
                    # down to the headroom this statement needs
                    target = max(quota - projected - self._reserved, 0)
                    freed = shed_server(target)
                    if freed > 0:
                        outcome = "shed"
                        with self._cv:
                            self._shed_bytes += freed
                        continue      # re-check immediately
                if time.monotonic() >= deadline:
                    with self._cv:
                        self._counts["rejected"] += 1
                    metrics.counter(metrics.ADMISSIONS,
                                    {"outcome": "rejected"})
                    raise AdmissionRejectedError(
                        f"server is busy: admission of {label} would "
                        f"exceed tidb_tpu_server_mem_quota ({quota} "
                        f"bytes); retry later")
                if outcome == "admitted":
                    outcome = "queued"
                with self._cv:
                    # woken by finish() / shed; slices double as the
                    # re-check for ledger drains that notify nobody
                    self._cv.wait(timeout=min(
                        _SLICE_S, max(deadline - time.monotonic(), 0.001)))
        finally:
            with self._cv:
                self._waiting -= 1
                metrics.gauge(metrics.ADMISSION_QUEUE_DEPTH,
                              self._waiting)
            waited_ns = time.perf_counter_ns() - t0
            metrics.histogram(metrics.ADMISSION_WAITS, waited_ns / 1e9)
            # the per-tenant admission-wait ledger (meter.py): the
            # session thread runs admit() with its statement meter
            # installed, so the wait attributes to the right tenant
            meter.note_admission_wait(waited_ns)
        metrics.counter(metrics.ADMISSIONS, {"outcome": outcome})
        return projected

    def finish(self, ticket) -> None:
        """Release an admit() ticket (None-safe)."""
        if ticket is None:
            return
        with self._cv:
            self._reserved -= ticket
            self._admitted -= 1
            self._cv.notify_all()

    def snapshot(self) -> dict:
        with self._cv:
            out = dict(self._counts)
            out["queue_depth"] = self._waiting
            out["running"] = self._admitted
            out["reserved_bytes"] = self._reserved
            out["shed_bytes"] = self._shed_bytes
            return out


class DispatchWatchdog:
    """Bounded finalize: a dispatch/finalize section that runs past
    `tidb_tpu_dispatch_timeout_ms` cancels its statement with the
    RETRYABLE ER_DEVICE_FAULT instead of wedging the scheduler.

    Two halves cooperate. A monitor thread (started lazily on the first
    watched section, exits when idle) scans registered sections; one
    past its deadline is marked expired, counted in
    `tidb_tpu_dispatch_timeout_total`, and its statement's memtrack
    root is cancel()-latched — the cooperative-kill flag flips, so a
    statement stuck in a Python-level wait unwinds at its next
    interrupt check with the watchdog's message (classified 9009, not
    ER_QUERY_INTERRUPTED). The watched section itself re-checks on
    exit: when the blocking call eventually returns past the deadline,
    DeviceFaultError raises THERE, so the existing finally chains
    (pipeline_map's slot/ledger releases, memtrack.device_scope)
    drain every scheduler slot and device-ledger byte exactly as on
    any other error path. 0 = off (the default)."""

    _SLICE_S = 0.05         # monitor scan period while sections exist
    _IDLE_S = 5.0           # idle monitor lingers this long, then dies

    def __init__(self):
        self._cv = threading.Condition()
        self._entries: dict = {}    # guarded-by: _cv  tok -> entry
        self._seq = 0               # guarded-by: _cv
        self._thread = None         # guarded-by: _cv
        self._fired = 0             # guarded-by: _cv

    def begin(self, label: str):
        """-> opaque token (None when the watchdog is off)."""
        timeout_ms = config.dispatch_timeout_ms()
        if timeout_ms <= 0:
            return None
        # [deadline, label, statement root, expired]
        ent = [time.monotonic() + timeout_ms / 1e3, label,
               memtrack.current(), False]
        with self._cv:
            self._seq += 1
            tok = self._seq
            self._entries[tok] = ent
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._monitor, name="dispatch-watchdog",
                    daemon=True)
                self._thread.start()
            self._cv.notify()
        return (tok, ent)

    def end(self, token) -> bool:
        """Unregister; -> True when the section expired (the caller
        raises DeviceFaultError unless an error is already unwinding)."""
        if token is None:
            return False
        tok, ent = token
        with self._cv:
            self._entries.pop(tok, None)
            return ent[3]

    @contextlib.contextmanager
    def watch(self, label: str = "dispatch"):
        token = self.begin(label)
        try:
            yield
        except BaseException:
            self.end(token)     # the in-flight error wins
            raise
        if self.end(token):
            trace.event("watchdog.fired", label=label)
            raise _timeout_error(label)

    def _monitor(self) -> None:
        while True:
            fire = []
            with self._cv:
                if not self._entries:
                    self._cv.wait(timeout=self._IDLE_S)
                    if not self._entries:
                        # idle: exit. The slot clears UNDER _cv before
                        # the return, so a begin() racing our unwind
                        # cannot see a still-alive thread that will
                        # never scan its entry — it spawns a fresh one
                        self._thread = None
                        return
                now = time.monotonic()
                for ent in self._entries.values():
                    if not ent[3] and now >= ent[0]:
                        ent[3] = True
                        self._fired += 1
                        fire.append(ent)
                if not fire:
                    self._cv.wait(timeout=self._SLICE_S)
            for ent in fire:    # cancels run with _cv dropped
                metrics.counter(metrics.DISPATCH_TIMEOUTS)
                root = ent[2]
                if root is not None:
                    root.cancel(_timeout_msg(ent[1]))

    def snapshot(self) -> dict:
        with self._cv:
            return {"watching": len(self._entries),
                    "fired": self._fired}


def _timeout_msg(label: str) -> str:
    return (f"device fault: dispatch watchdog — {label} exceeded "
            f"tidb_tpu_dispatch_timeout_ms="
            f"{config.dispatch_timeout_ms()}ms; statement cancelled "
            f"(retryable)")


def _timeout_error(label: str):
    return failpoint.DispatchTimeoutError(_timeout_msg(label))


# device-fault recovery policy: consecutive faults before the device is
# quarantined, and how long quarantine lasts before ONE probe dispatch
# is let through to re-test it
_FAULT_QUARANTINE_AFTER = 3
_QUARANTINE_S = 1.0


class DeviceHealth:
    """Device-plane fault accounting + quarantine. Fault reporters:
    the copr agg dispatch sites (store/copr.py — which also run the
    full retry-once/degrade chain and gate on available()) and
    pipeline_map's dispatch wrapper (ops/runtime.py — faults feed the
    counter and propagate retryable; executor paths do not consult
    available(), so a quarantine routes the storage-side agg volume to
    the host while executor-plane dispatches surface 9009 to retrying
    clients). At `_FAULT_QUARANTINE_AFTER` consecutive faults the
    device is quarantined — HBM residency is invalidated (blocks
    uploaded through a faulting plane are not trustworthy, and nothing
    could consume them anyway) — until the quarantine window passes,
    after which exactly ONE probe dispatch is admitted: success
    readmits the device, another fault re-arms the window. Transitions
    count in `tidb_tpu_device_quarantine_total{event}`."""

    def __init__(self):
        self._mu = threading.Lock()
        self._consecutive = 0       # guarded-by: _mu
        self._quarantined = False   # guarded-by: _mu
        self._probe_at = 0.0        # guarded-by: _mu
        self._probing = False       # guarded-by: _mu
        self._probe_deadline = 0.0  # guarded-by: _mu
        self._faults = 0            # guarded-by: _mu
        self._quarantines = 0       # guarded-by: _mu

    def available(self) -> bool:
        """May this dispatch try the device? While quarantined, only
        the single re-probe past the window is admitted. A probe that
        never reports back — its dispatch exited via a designed
        rejection (capacity, unsupported) rather than success or fault
        — would otherwise pin `_probing` forever; past the probe's own
        deadline it counts as abandoned and the next caller probes."""
        with self._mu:
            if not self._quarantined:
                return True
            now = time.monotonic()
            if self._probing and now < self._probe_deadline:
                return False
            if not self._probing and now < self._probe_at:
                return False
            self._probing = True    # this caller IS the probe
            self._probe_deadline = now + _QUARANTINE_S
            return True

    def note_ok(self) -> None:
        with self._mu:
            self._consecutive = 0
            readmit = self._quarantined
            self._quarantined = False
            self._probing = False
        if readmit:
            metrics.counter(metrics.DEVICE_QUARANTINES,
                            {"event": "readmit"})
            trace.event("device.readmit")

    def note_fault(self) -> None:
        trace.event("device.fault")
        quarantined = False
        with self._mu:
            self._consecutive += 1
            self._faults += 1
            if self._quarantined:
                if self._probing:   # the probe failed: re-arm
                    self._probing = False
                    self._probe_at = time.monotonic() + _QUARANTINE_S
            elif self._consecutive >= _FAULT_QUARANTINE_AFTER:
                self._quarantined = True
                self._probing = False
                self._probe_at = time.monotonic() + _QUARANTINE_S
                self._quarantines += 1
                quarantined = True
        if quarantined:
            metrics.counter(metrics.DEVICE_QUARANTINES,
                            {"event": "quarantine"})
            trace.event("device.quarantine")
            # invalidate HBM residency with every lock dropped: the
            # shed walks the cache locks, and a re-probe refills from
            # a (possibly recovered) clean slate
            from tidb_tpu.store import device_cache
            device_cache.shed_all()

    def snapshot(self) -> dict:
        with self._mu:
            return {"quarantined": self._quarantined,
                    "consecutive_faults": self._consecutive,
                    "faults": self._faults,
                    "quarantines": self._quarantines}


def degrade_statement() -> None:
    """Latch THIS statement onto the host path after a retried device
    fault (the flag lives on the statement's memtrack root and dies
    with it): one faulting statement stops paying fault+retry per
    dispatch, while the next statement — and the quarantine re-probe —
    still exercises the device."""
    root = memtrack.current()
    if root is not None:
        root.fault_degraded = True
        trace.event("device.degrade")


def statement_degraded() -> bool:
    root = memtrack.current()
    return root is not None and root.fault_degraded


# -- process singletons ------------------------------------------------------

_SCHEDULER = DeviceScheduler()
_ADMISSION = AdmissionController()
_WATCHDOG = DispatchWatchdog()
_HEALTH = DeviceHealth()


def device_scheduler() -> DeviceScheduler:
    return _SCHEDULER


def admission() -> AdmissionController:
    return _ADMISSION


def dispatch_watchdog() -> DispatchWatchdog:
    return _WATCHDOG


def device_health() -> DeviceHealth:
    return _HEALTH


def reset_for_tests() -> None:
    """Fresh singletons (test isolation: counters and rotation state)."""
    global _SCHEDULER, _ADMISSION, _WATCHDOG, _HEALTH
    _SCHEDULER = DeviceScheduler()
    _ADMISSION = AdmissionController()
    _WATCHDOG = DispatchWatchdog()
    _HEALTH = DeviceHealth()


def finalize_watch(label: str = "finalize"):
    """Watchdog guard for a blocking finalize (ops/runtime.pipeline_map
    uses it around each pop_finalize): past
    `tidb_tpu_dispatch_timeout_ms` the statement is cancelled with the
    retryable device-fault error — see DispatchWatchdog."""
    return _WATCHDOG.watch(label)


class device_slot:
    """Hold one scheduler slot for the duration of a synchronous kernel
    call — the one-shot dispatch sites' (copr scalar aggs, escalated
    retries, mesh collectives) counterpart of pipeline_map's slot per
    in-flight token. Uses the bypass valve: a sync dispatch inside
    another statement's finalize path must throttle, never deadlock.
    The whole guarded section runs under the dispatch watchdog: a sync
    kernel call past `tidb_tpu_dispatch_timeout_ms` surfaces the
    retryable device-fault error AFTER the slot (and, one context
    inward, the memtrack.device_scope ledger bytes) released.

    With `profile` set (a profiler.KernelProfile), the guarded hold
    interval records as one dispatch on that profile row on SUCCESS —
    the device_slot seam of the kernel profiling plane, for sync sites
    that are not already inside a profiler.dispatch_section."""

    __slots__ = ("_slot", "_wtok", "_busy", "_prof", "_t0")

    def __init__(self, profile=None):
        self._slot = None
        self._wtok = None
        self._busy = None
        self._prof = profile
        self._t0 = 0

    @property
    def chip(self) -> int:
        """The plane chip the grant placed this dispatch on (0 for
        bypass slots or a 1-device plane) — dispatch sites pass it to
        devplane.chip_scope and tag their trace spans with it."""
        return self._slot.chip if self._slot is not None else 0

    def __enter__(self):
        self._wtok = _WATCHDOG.begin("sync-dispatch")
        try:
            failpoint.eval("sched/slot")
            # the slot WAIT is a statement-trace phase of its own: the
            # span covers only the acquire, not the guarded dispatch
            t0 = time.perf_counter_ns()
            with trace.span("sched.slot", sync=1):
                self._slot = _SCHEDULER.acquire_or_bypass()
            # per-tenant attribution (meter.py): the acquire is slot
            # wait; everything from here to __exit__ is the dispatch/
            # finalize interval this slot guards — device busy-time,
            # billed as a section so a nested retry's own device_slot
            # cannot double-count the same wall time
            meter.note_slot_wait(time.perf_counter_ns() - t0)
            self._busy = meter.busy_section().__enter__()
            self._t0 = time.perf_counter_ns()
        except BaseException:
            # anything that raises after a successful acquire (the
            # meter bookkeeping above is new code in this window) must
            # hand the slot back — __exit__ will never run
            _SCHEDULER.release(self._slot)
            self._slot = None
            _WATCHDOG.end(self._wtok)
            self._wtok = None
            raise
        return self

    def __exit__(self, exc_type, exc, tb):
        _SCHEDULER.release(self._slot)
        self._slot = None
        if self._prof is not None and exc_type is None:
            from tidb_tpu import profiler
            profiler.note_dispatch(
                self._prof, time.perf_counter_ns() - self._t0)
        if self._busy is not None:
            # busy even on an error path: the device (attempt) really
            # occupied this interval
            self._busy.__exit__(exc_type, exc, tb)
            self._busy = None
        expired = _WATCHDOG.end(self._wtok)
        self._wtok = None
        if expired and exc_type is None:
            # the watchdog fired while the kernel call blocked; now
            # that it returned (slot + ledger already released by the
            # finally chain), surface the cancel to the statement
            trace.event("watchdog.fired", label="sync-dispatch")
            raise _timeout_error("sync-dispatch")
        return False


def shed_server(target: int = 0) -> int:
    """Drive the SERVER root's registered shed chain (recursing into
    session/statement subtrees, so running statements' spill actions —
    hybrid-join cold partitions, sort spills — fire too) until the
    SERVER total is at/below `target` bytes. -> bytes freed. The admin
    hook behind the status port's /shed endpoint and the admission
    controller's overflow path. Registered server-scope actions today:
    the HBM region-block caches (store/device_cache.py shed) and the
    MVCC delta stores (store/delta.py — a forced early merge folds and
    truncates the staged journal, whose re-fills of lagging HBM blocks
    take device_slot like any other dispatch)."""
    failpoint.eval("admission/shed")
    return memtrack.SERVER.run_spill_actions(target, recurse=True)


def stats() -> dict:
    """Scheduler + admission snapshot (status port, bench serve block)."""
    return {"scheduler": _SCHEDULER.snapshot(),
            "admission": _ADMISSION.snapshot(),
            "watchdog": _WATCHDOG.snapshot(),
            "device_health": _HEALTH.snapshot()}
