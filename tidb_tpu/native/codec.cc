// Native memcomparable codec: the scan-path hot loops.
//
// Reference: /root/reference/util/codec/ (number.go sign-flip ints,
// bytes.go 8-byte-group stuffing, codec.go:387 DecodeOneToChunk) and
// tablecodec.go EncodeRow/DecodeRow. The reference leans on Rust TiKV for
// storage-side decode; this is the TPU build's C++ equivalent: it turns
// raw KV record pairs straight into the columnar buffers (int64/float64 +
// validity) that jax.device_put ships to HBM, replacing the per-datum
// Python loop in table.kvrows_to_chunk.
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 in the
// image). All multi-byte integers in the encoding are big-endian.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint8_t NIL_FLAG = 0x00;
constexpr uint8_t BYTES_FLAG = 0x01;
constexpr uint8_t INT_FLAG = 0x03;
constexpr uint8_t UINT_FLAG = 0x04;
constexpr uint8_t FLOAT_FLAG = 0x05;
constexpr uint8_t DECIMAL_FLAG = 0x06;
constexpr uint64_t SIGN_MASK = 0x8000000000000000ULL;

inline uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

inline int64_t decode_int_payload(const uint8_t* p) {
  return (int64_t)(load_be64(p) ^ SIGN_MASK);
}

inline double decode_float_payload(const uint8_t* p) {
  uint64_t u = load_be64(p);
  if (u & SIGN_MASK) {
    u &= ~SIGN_MASK;
  } else {
    u = ~u;
  }
  double d;
  std::memcpy(&d, &u, sizeof(d));
  // stored big-endian bit pattern; memcpy gave us host order of the
  // already-reassembled integer, so this is correct on little-endian too
  return d;
}

// Skip (or measure) one group-stuffed byte string. Returns bytes consumed,
// or -1 on malformed input.
inline int64_t skip_bytes_datum(const uint8_t* p, int64_t avail) {
  int64_t off = 0;
  while (true) {
    if (off + 9 > avail) return -1;
    uint8_t marker = p[off + 8];
    off += 9;
    int pad = 0xFF - marker;
    if (pad == 0) continue;
    if (pad > 8) return -1;
    return off;
  }
}

// Skip one datum (flag + payload). Returns bytes consumed or -1.
inline int64_t skip_datum(const uint8_t* p, int64_t avail) {
  if (avail < 1) return -1;
  switch (p[0]) {
    case NIL_FLAG:
      return 1;
    case INT_FLAG:
    case UINT_FLAG:
    case FLOAT_FLAG:
      return avail >= 9 ? 9 : -1;
    case DECIMAL_FLAG: {
      return avail >= 10 ? 10 : -1;
    }
    case BYTES_FLAG: {
      int64_t n = skip_bytes_datum(p + 1, avail - 1);
      return n < 0 ? -1 : n + 1;
    }
    default:
      return -1;
  }
}

inline int64_t pow10_i64(int n) {
  int64_t v = 1;
  while (n-- > 0) v *= 10;
  return v;
}

}  // namespace

extern "C" {

// Column kinds for decode_rows.
// 0 = int64 (INT/DATETIME eval; also accepts UINT within int64 range)
// 1 = float64
// 2 = decimal (scaled int64; rescaled to col_frac when the stored frac
//     differs)
// 3 = handle (value comes from the record key, not the row)

// Decode n encoded rows into columnar buffers.
//   values / val_offsets[n+1]: concatenated row values
//   keys / key_offsets[n+1]:   concatenated record keys (for handles)
//   ncols, col_ids[ncols], col_kind[ncols], col_frac[ncols]
//   def_valid[ncols], def_int[ncols], def_float[ncols]: per-column default
//     (applied when the row lacks the column id; def_valid 0 => NULL)
//   out_data[ncols]: int64*/double* per column; out_valid[ncols]: uint8*
// Returns 0 on success, -1 on malformed/unsupported input (caller falls
// back to the Python decoder).
int decode_rows(const uint8_t* values, const int64_t* val_offsets,
                const uint8_t* keys, const int64_t* key_offsets,
                int64_t n, int32_t ncols, const int64_t* col_ids,
                const uint8_t* col_kind, const int32_t* col_frac,
                const uint8_t* def_valid, const int64_t* def_int,
                const double* def_float, int64_t** out_data,
                uint8_t** out_valid) {
  for (int64_t r = 0; r < n; r++) {
    // handle: key = 't' + 9B(int flagged? no: raw encode_int 8B) + '_r' + 8B
    // record_key layout: 't' (1) + 8B sign-flipped table id + '_r' (2) +
    // 8B sign-flipped handle
    const uint8_t* k = keys + key_offsets[r];
    int64_t klen = key_offsets[r + 1] - key_offsets[r];
    if (klen < 1 + 8 + 2 + 8) return -1;
    int64_t handle = decode_int_payload(k + 1 + 8 + 2);

    // fill defaults first; found columns overwrite
    for (int32_t c = 0; c < ncols; c++) {
      if (col_kind[c] == 3) {
        out_data[c][r] = handle;
        out_valid[c][r] = 1;
      } else if (def_valid[c]) {
        out_valid[c][r] = 1;
        if (col_kind[c] == 1) {
          ((double*)out_data[c])[r] = def_float[c];
        } else {
          out_data[c][r] = def_int[c];
        }
      } else {
        out_valid[c][r] = 0;
        if (col_kind[c] == 1) {
          ((double*)out_data[c])[r] = 0.0;
        } else {
          out_data[c][r] = 0;
        }
      }
    }

    const uint8_t* v = values + val_offsets[r];
    int64_t avail = val_offsets[r + 1] - val_offsets[r];
    int64_t off = 0;
    while (off < avail) {
      // column id datum (always INT-flagged)
      if (v[off] != INT_FLAG || off + 9 > avail) return -1;
      int64_t cid = decode_int_payload(v + off + 1);
      off += 9;
      // find the output slot (ncols is small: linear scan)
      int32_t slot = -1;
      for (int32_t c = 0; c < ncols; c++) {
        if (col_kind[c] != 3 && col_ids[c] == cid) { slot = c; break; }
      }
      if (slot < 0) {
        int64_t used = skip_datum(v + off, avail - off);
        if (used < 0) return -1;
        off += used;
        continue;
      }
      if (off >= avail) return -1;
      uint8_t flag = v[off];
      switch (flag) {
        case NIL_FLAG:
          out_valid[slot][r] = 0;
          if (col_kind[slot] == 1) ((double*)out_data[slot])[r] = 0.0;
          else out_data[slot][r] = 0;
          off += 1;
          break;
        case INT_FLAG: {
          if (off + 9 > avail) return -1;
          int64_t x = decode_int_payload(v + off + 1);
          out_valid[slot][r] = 1;
          if (col_kind[slot] == 1) ((double*)out_data[slot])[r] = (double)x;
          else out_data[slot][r] = x;
          off += 9;
          break;
        }
        case UINT_FLAG: {
          if (off + 9 > avail) return -1;
          uint64_t x = load_be64(v + off + 1);
          out_valid[slot][r] = 1;
          if (col_kind[slot] == 1) {
            ((double*)out_data[slot])[r] = (double)x;
          } else {
            if (x > (uint64_t)INT64_MAX) return -1;  // python fallback
            out_data[slot][r] = (int64_t)x;
          }
          off += 9;
          break;
        }
        case FLOAT_FLAG: {
          if (off + 9 > avail) return -1;
          double x = decode_float_payload(v + off + 1);
          out_valid[slot][r] = 1;
          if (col_kind[slot] == 1) ((double*)out_data[slot])[r] = x;
          else return -1;  // float into int column: python handles casts
          off += 9;
          break;
        }
        case DECIMAL_FLAG: {
          if (off + 10 > avail) return -1;
          int frac = v[off + 1];
          int64_t scaled = decode_int_payload(v + off + 2);
          out_valid[slot][r] = 1;
          if (col_kind[slot] == 2) {
            int want = col_frac[slot];
            // >18-digit shifts overflow int64: python path handles those
            if (frac < want) {
              if (want - frac > 18) return -1;
              int64_t mul = pow10_i64(want - frac);
              if (scaled > INT64_MAX / mul || scaled < INT64_MIN / mul)
                return -1;
              scaled *= mul;
            } else if (frac > want) {
              if (frac - want > 18) return -1;
              // MySQL half-away-from-zero, matching _rescale_decimal
              int64_t div = pow10_i64(frac - want);
              int64_t q = scaled / div;
              int64_t rem = scaled % div;
              if (rem < 0) rem = -rem;
              if (2 * rem >= div) q += (scaled >= 0) ? 1 : -1;
              scaled = q;
            }
            out_data[slot][r] = scaled;
          } else if (col_kind[slot] == 1) {
            if (frac > 18) return -1;
            ((double*)out_data[slot])[r] =
                (double)scaled / (double)pow10_i64(frac);
          } else {
            return -1;
          }
          off += 10;
          break;
        }
        case BYTES_FLAG:
          return -1;  // varlen into fixed-width request: python fallback
        default:
          return -1;
      }
    }
  }
  return 0;
}

// Batch sign-flipped big-endian int64 encode (index/key building).
void encode_int_batch(const int64_t* vals, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t u = (uint64_t)vals[i] ^ SIGN_MASK;
    uint8_t* p = out + i * 8;
    for (int b = 7; b >= 0; b--) { p[b] = (uint8_t)u; u >>= 8; }
  }
}

// Batch decode of sign-flipped big-endian int64 (index value -> handle).
void decode_int_batch(const uint8_t* data, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = decode_int_payload(data + i * 8);
}

}  // extern "C"
