"""Native (C++) kernels, loaded via ctypes with graceful fallback.

The shared library is compiled on first use with the system g++ (cached
next to the source, keyed by source mtime) — no pybind11 or build step in
the critical path; environments without a compiler simply run the pure-
Python implementations. Ref: SURVEY.md §7 — the reference's storage-side
hot loops live in Rust TiKV; this is our C++ equivalent layer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

__all__ = ["lib", "decode_rows_native", "scan_rows_native",
           "NATIVE_KIND_INT", "NATIVE_KIND_FLOAT", "NATIVE_KIND_DECIMAL",
           "NATIVE_KIND_HANDLE"]

NATIVE_KIND_INT = 0
NATIVE_KIND_FLOAT = 1
NATIVE_KIND_DECIMAL = 2
NATIVE_KIND_HANDLE = 3

_lock = threading.Lock()
_lib = None
_tried = False


def _compile(name: str) -> ctypes.CDLL | None:
    """Build native/<name>.cc into _build/<name>.so (mtime-cached) and
    load it; None when no compiler / load failure."""
    src = Path(__file__).parent / f"{name}.cc"
    build_dir = Path(__file__).parent / "_build"
    so = build_dir / f"{name}.so"
    try:
        if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
            build_dir.mkdir(exist_ok=True)
            tmp = so.with_suffix(".so.tmp%d" % os.getpid())
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", str(tmp), str(src)],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        return ctypes.CDLL(str(so))
    except Exception:  # noqa: BLE001 - no compiler / load failure
        return None


def _build() -> ctypes.CDLL | None:
    cdll = _compile("codec")
    if cdll is None:
        return None
    cdll.decode_rows.restype = ctypes.c_int
    cdll.decode_rows.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
    ]
    return cdll


def _build_loadscan() -> ctypes.CDLL | None:
    cdll = _compile("loadscan")
    if cdll is None:
        return None
    cdll.scan_rows.restype = ctypes.c_int64
    P64 = ctypes.POINTER(ctypes.c_int64)
    P8 = ctypes.POINTER(ctypes.c_uint8)
    cdll.scan_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_uint8, ctypes.c_uint8, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int32,
        P64, P64, P8, P64, ctypes.c_int64, ctypes.c_int64, P64, P64,
    ]
    return cdll


_scan_lock = threading.Lock()
_scan_lib = None
_scan_tried = False


def _loadscan_lib() -> ctypes.CDLL | None:
    global _scan_lib, _scan_tried
    if _scan_tried:
        return _scan_lib
    with _scan_lock:
        if not _scan_tried:
            _scan_lib = _build_loadscan()
            _scan_tried = True
    return _scan_lib


def scan_rows_native(data: bytes, ft: bytes, lt: bytes, enc: bytes,
                     esc: bytes, ignore_lines: int,
                     final_chunk: bool = True):
    """Scan LOAD DATA text into field spans.

    -> (consumed_bytes, rowoff int64[nr+1], fstart, fend, fflags) or
    None when the native scanner is unavailable. consumed < len(data)
    means the caller must run the general scanner on the remainder."""
    cdll = _loadscan_lib()
    if cdll is None:
        return None
    n = len(data)
    # upper bounds: every separator byte could open a field/row
    max_fields = data.count(ft) + data.count(lt) + 2
    max_rows = data.count(lt) + 2
    fstart = np.empty(max_fields, dtype=np.int64)
    fend = np.empty(max_fields, dtype=np.int64)
    fflags = np.empty(max_fields, dtype=np.uint8)
    rowoff = np.zeros(max_rows + 1, dtype=np.int64)
    out = np.zeros(2, dtype=np.int64)
    P64 = ctypes.POINTER(ctypes.c_int64)
    P8 = ctypes.POINTER(ctypes.c_uint8)
    consumed = cdll.scan_rows(
        data, n, ft[0], lt[0],
        enc[0] if enc else -1, esc[0] if esc else -1,
        ignore_lines, 1 if final_chunk else 0,
        fstart.ctypes.data_as(P64), fend.ctypes.data_as(P64),
        fflags.ctypes.data_as(P8), rowoff.ctypes.data_as(P64),
        max_fields, max_rows,
        out[0:].ctypes.data_as(P64), out[1:].ctypes.data_as(P64))
    nr, nf = int(out[0]), int(out[1])
    return (int(consumed), rowoff[:nr + 1], fstart[:nf], fend[:nf],
            fflags[:nf])


def lib() -> ctypes.CDLL | None:
    """The native library, or None when unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if not _tried:
            _lib = _build()
            _tried = True
    return _lib


def decode_rows_native(kvrows, col_specs):
    """Batch-decode record (key, value) pairs into columnar arrays.

    col_specs: list of (col_id, kind, frac, default_valid, default_value)
    — kind NATIVE_KIND_*; for HANDLE the id/default are ignored.
    Returns (datas, valids) lists of numpy arrays, or None when the native
    path is unavailable or declined the input (caller uses the Python
    decoder).
    """
    cdll = lib()
    if cdll is None:
        return None
    n = len(kvrows)
    keys = b"".join(k for k, _v in kvrows)
    values = b"".join(v for _k, v in kvrows)
    key_offs = np.zeros(n + 1, dtype=np.int64)
    val_offs = np.zeros(n + 1, dtype=np.int64)
    ko = vo = 0
    for i, (k, v) in enumerate(kvrows):
        ko += len(k)
        vo += len(v)
        key_offs[i + 1] = ko
        val_offs[i + 1] = vo

    ncols = len(col_specs)
    col_ids = np.array([s[0] for s in col_specs], dtype=np.int64)
    col_kind = np.array([s[1] for s in col_specs], dtype=np.uint8)
    col_frac = np.array([max(0, s[2]) for s in col_specs], dtype=np.int32)
    def_valid = np.array([1 if s[3] else 0 for s in col_specs],
                         dtype=np.uint8)
    def_int = np.zeros(ncols, dtype=np.int64)
    def_float = np.zeros(ncols, dtype=np.float64)
    for i, s in enumerate(col_specs):
        if s[3] and s[4] is not None:
            if s[1] == NATIVE_KIND_FLOAT:
                def_float[i] = float(s[4])
            else:
                def_int[i] = int(s[4])
        elif s[3] and s[4] is None:
            def_valid[i] = 0   # default is NULL

    datas = []
    valids = []
    out_ptrs = (ctypes.c_void_p * ncols)()
    valid_ptrs = (ctypes.c_void_p * ncols)()
    for i, s in enumerate(col_specs):
        dt = np.float64 if s[1] == NATIVE_KIND_FLOAT else np.int64
        d = np.zeros(n, dtype=dt)
        m = np.zeros(n, dtype=np.uint8)
        datas.append(d)
        valids.append(m)
        out_ptrs[i] = d.ctypes.data_as(ctypes.c_void_p)
        valid_ptrs[i] = m.ctypes.data_as(ctypes.c_void_p)

    rc = cdll.decode_rows(
        values, val_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        keys, key_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, ncols,
        col_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        col_kind.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        col_frac.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        def_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        def_int.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        def_float.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out_ptrs, valid_ptrs)
    if rc != 0:
        return None
    return datas, [m.astype(bool) for m in valids]
