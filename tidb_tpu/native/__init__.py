"""Native (C++) kernels, loaded via ctypes with graceful fallback.

The shared library is compiled on first use with the system g++ (cached
next to the source, keyed by source mtime) — no pybind11 or build step in
the critical path; environments without a compiler simply run the pure-
Python implementations. Ref: SURVEY.md §7 — the reference's storage-side
hot loops live in Rust TiKV; this is our C++ equivalent layer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

__all__ = ["lib", "decode_rows_native", "NATIVE_KIND_INT",
           "NATIVE_KIND_FLOAT", "NATIVE_KIND_DECIMAL", "NATIVE_KIND_HANDLE"]

NATIVE_KIND_INT = 0
NATIVE_KIND_FLOAT = 1
NATIVE_KIND_DECIMAL = 2
NATIVE_KIND_HANDLE = 3

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> ctypes.CDLL | None:
    src = Path(__file__).parent / "codec.cc"
    build_dir = Path(__file__).parent / "_build"
    so = build_dir / "codec.so"
    try:
        if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
            build_dir.mkdir(exist_ok=True)
            tmp = so.with_suffix(".so.tmp%d" % os.getpid())
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", str(tmp), str(src)],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        cdll = ctypes.CDLL(str(so))
    except Exception:  # noqa: BLE001 - no compiler / load failure
        return None
    cdll.decode_rows.restype = ctypes.c_int
    cdll.decode_rows.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
    ]
    return cdll


def lib() -> ctypes.CDLL | None:
    """The native library, or None when unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if not _tried:
            _lib = _build()
            _tried = True
    return _lib


def decode_rows_native(kvrows, col_specs):
    """Batch-decode record (key, value) pairs into columnar arrays.

    col_specs: list of (col_id, kind, frac, default_valid, default_value)
    — kind NATIVE_KIND_*; for HANDLE the id/default are ignored.
    Returns (datas, valids) lists of numpy arrays, or None when the native
    path is unavailable or declined the input (caller uses the Python
    decoder).
    """
    cdll = lib()
    if cdll is None:
        return None
    n = len(kvrows)
    keys = b"".join(k for k, _v in kvrows)
    values = b"".join(v for _k, v in kvrows)
    key_offs = np.zeros(n + 1, dtype=np.int64)
    val_offs = np.zeros(n + 1, dtype=np.int64)
    ko = vo = 0
    for i, (k, v) in enumerate(kvrows):
        ko += len(k)
        vo += len(v)
        key_offs[i + 1] = ko
        val_offs[i + 1] = vo

    ncols = len(col_specs)
    col_ids = np.array([s[0] for s in col_specs], dtype=np.int64)
    col_kind = np.array([s[1] for s in col_specs], dtype=np.uint8)
    col_frac = np.array([max(0, s[2]) for s in col_specs], dtype=np.int32)
    def_valid = np.array([1 if s[3] else 0 for s in col_specs],
                         dtype=np.uint8)
    def_int = np.zeros(ncols, dtype=np.int64)
    def_float = np.zeros(ncols, dtype=np.float64)
    for i, s in enumerate(col_specs):
        if s[3] and s[4] is not None:
            if s[1] == NATIVE_KIND_FLOAT:
                def_float[i] = float(s[4])
            else:
                def_int[i] = int(s[4])
        elif s[3] and s[4] is None:
            def_valid[i] = 0   # default is NULL

    datas = []
    valids = []
    out_ptrs = (ctypes.c_void_p * ncols)()
    valid_ptrs = (ctypes.c_void_p * ncols)()
    for i, s in enumerate(col_specs):
        dt = np.float64 if s[1] == NATIVE_KIND_FLOAT else np.int64
        d = np.zeros(n, dtype=dt)
        m = np.zeros(n, dtype=np.uint8)
        datas.append(d)
        valids.append(m)
        out_ptrs[i] = d.ctypes.data_as(ctypes.c_void_p)
        valid_ptrs[i] = m.ctypes.data_as(ctypes.c_void_p)

    rc = cdll.decode_rows(
        values, val_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        keys, key_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, ncols,
        col_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        col_kind.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        col_frac.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        def_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        def_int.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        def_float.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out_ptrs, valid_ptrs)
    if rc != 0:
        return None
    return datas, [m.astype(bool) for m in valids]
