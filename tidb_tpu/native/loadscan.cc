// LOAD DATA line/field scanner — the native data-loader hot loop.
//
// Reference: the reference's LOAD DATA splitting lives in compiled Go
// (executor/load_data.go READ_INFO-style scanning); this is the C++
// equivalent for tidb_tpu/executor/loaddata.py's Python scanner. The
// contract is deliberately strict: the scanner handles REGULAR rows
// (single-byte terminators, enclosure only covering the whole field,
// escapes marked for host-side unescaping) and row-alignedly bails the
// moment anything irregular appears — the Python scanner, which matches
// MySQL semantics bit-for-bit, takes over from the reported offset.
//
// Output per field: [start,end) byte span (quotes excluded), flags:
//   1 = contains escape sequences (host runs unescape)
//   2 = contains doubled enclosure quotes (host collapses them)
//   4 = field is the \N NULL marker
//   8 = field was enclosed (an empty enclosed field is NOT an empty line)
// Row r's fields are fields[rowoff[r] : rowoff[r+1]].

#include <cstdint>

extern "C" {

// returns bytes consumed (always row-aligned; == n when fully done;
// < n when an irregular construct or output capacity stopped the scan —
// the caller finishes the remainder with the general scanner)
int64_t scan_rows(const uint8_t* t, int64_t n,
                  uint8_t ft, uint8_t lt, int32_t enc_i, int32_t esc_i,
                  int64_t ignore_lines, int32_t final_chunk,
                  int64_t* fstart, int64_t* fend, uint8_t* fflags,
                  int64_t* rowoff, int64_t max_fields, int64_t max_rows,
                  int64_t* out_nrows, int64_t* out_nfields) {
    const bool has_enc = enc_i >= 0, has_esc = esc_i >= 0;
    const uint8_t enc = (uint8_t)enc_i, esc = (uint8_t)esc_i;

    int64_t i = 0;
    // IGNORE n LINES skips PHYSICAL lines (raw terminator scan)
    for (int64_t skipped = 0; skipped < ignore_lines; skipped++) {
        while (i < n && t[i] != lt) i++;
        if (i < n) i++; else break;
    }

    int64_t nf = 0, nr = 0;
    int64_t row_begin = i;        // bail point: start of current row
    bool dangling = false;        // text ended right after a field sep
    rowoff[0] = 0;

    // every exit reports the COMPLETE rows scanned so far; fields of a
    // partial row are dropped (the caller rescans from the bail offset)
#define BAIL(ret) do { *out_nrows = nr; *out_nfields = rowoff[nr]; \
                       return (ret); } while (0)

    while (i < n) {
        // ---- one field ----
        uint8_t flags = 0;
        int64_t s, e;
        if (has_enc && t[i] == enc) {
            // enclosed field: content is everything to the closing
            // quote; doubled quotes stay (host collapses), escapes stay
            flags |= 8;
            s = ++i;
            for (;;) {
                if (i >= n) BAIL(row_begin);         // unterminated: bail
                uint8_t c = t[i];
                if (has_esc && c == esc) {
                    if (i + 1 >= n) BAIL(row_begin);
                    flags |= 1; i += 2; continue;
                }
                if (c == enc) {
                    if (i + 1 < n && t[i + 1] == enc) {
                        flags |= 2; i += 2; continue;
                    }
                    break;                            // closing quote
                }
                i++;
            }
            e = i++;                                  // skip the quote
            // only a terminator may follow a closing quote; anything
            // else is the mixed quoted+bare form -> Python handles it
            if (i < n && t[i] != ft && t[i] != lt) BAIL(row_begin);
        } else {
            s = i;
            for (;;) {
                if (i >= n) break;
                uint8_t c = t[i];
                if (has_esc && c == esc) {
                    if (i + 1 >= n) { i++; break; }   // lone esc: literal
                    flags |= 1; i += 2; continue;
                }
                if (c == ft || c == lt) break;
                if (has_enc && c == enc) BAIL(row_begin);   // stray quote
                i++;
            }
            e = i;
            // exactly \N (and nothing else) is SQL NULL
            if (has_esc && e - s == 2 && t[s] == esc && t[s + 1] == 'N')
                flags = 4;
        }
        if (nf >= max_fields) BAIL(row_begin);
        fstart[nf] = s; fend[nf] = e; fflags[nf] = flags; nf++;

        // ---- separator after the field ----
        if (i >= n) {
            // buffer ended mid-row: only a FINAL buffer may treat EOF
            // as the row terminator; otherwise the partial row carries
            // into the next chunk
            if (!final_chunk) BAIL(row_begin);
            if (nr >= max_rows) BAIL(row_begin);
            rowoff[++nr] = nf;
            row_begin = i;
        } else if (t[i] == lt) {
            i++;
            if (nr >= max_rows) BAIL(row_begin);
            rowoff[++nr] = nf;
            row_begin = i;
        } else {                                      // t[i] == ft
            i++;
            dangling = (i >= n);  // trailing sep: one empty field owed
        }
    }
    if (!final_chunk) {
        // mid-stream: an unterminated tail row stays UNCONSUMED — the
        // caller carries it into the next chunk (emitting it here would
        // split the row straddling the chunk boundary)
        BAIL(row_begin);
    }
    if (dangling) {
        if (nf >= max_fields) BAIL(row_begin);
        fstart[nf] = n; fend[nf] = n; fflags[nf] = 0; nf++;
    }
    if (nf > rowoff[nr]) {                            // unterminated tail
        if (nr >= max_rows) BAIL(row_begin);
        rowoff[++nr] = nf;
        row_begin = n;
    }
    *out_nrows = nr;
    *out_nfields = nf;
#undef BAIL
    return n;
}

}  // extern "C"
