"""DDL job model.

Reference: /root/reference/model/ddl.go:126 (Job) — a serializable record
that walks the F1 state machine one transition per meta transaction, so any
worker (and any crash) leaves the cluster in a consistent, resumable state.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum


class JobType(Enum):
    CREATE_SCHEMA = "create schema"
    DROP_SCHEMA = "drop schema"
    CREATE_TABLE = "create table"
    DROP_TABLE = "drop table"
    TRUNCATE_TABLE = "truncate table"
    RENAME_TABLE = "rename table"
    ADD_COLUMN = "add column"
    DROP_COLUMN = "drop column"
    MODIFY_COLUMN = "modify column"
    ADD_INDEX = "add index"
    DROP_INDEX = "drop index"


class JobState(Enum):
    QUEUEING = "queueing"
    RUNNING = "running"
    ROLLBACK = "rollback"      # failed mid-flight; walking states backwards
    DONE = "done"
    CANCELLED = "cancelled"


@dataclass
class Job:
    id: int = 0
    tp: JobType = JobType.CREATE_TABLE
    schema_id: int = 0
    table_id: int = 0
    state: JobState = JobState.QUEUEING
    # args: per-type payload (json-able); e.g. TableInfo dict for
    # CREATE_TABLE, index def for ADD_INDEX
    args: dict = field(default_factory=dict)
    schema_state: int = 0          # model.SchemaState of the target object
    snapshot_ver: int = 0          # read snapshot for reorg backfill
    reorg_handle: int | None = None  # backfill checkpoint (ref: reorg.go:71)
    error: str = ""
    error_count: int = 0
    seq: int = 0                   # queue position (set by meta)

    def dumps(self) -> bytes:
        return json.dumps({
            "id": self.id, "tp": self.tp.value, "schema_id": self.schema_id,
            "table_id": self.table_id, "state": self.state.value,
            "args": self.args, "schema_state": self.schema_state,
            "snapshot_ver": self.snapshot_ver,
            "reorg_handle": self.reorg_handle, "error": self.error,
            "error_count": self.error_count, "seq": self.seq,
        }).encode()

    @staticmethod
    def loads(raw: bytes) -> "Job":
        o = json.loads(raw)
        return Job(id=o["id"], tp=JobType(o["tp"]),
                   schema_id=o["schema_id"], table_id=o["table_id"],
                   state=JobState(o["state"]), args=o["args"],
                   schema_state=o["schema_state"],
                   snapshot_ver=o["snapshot_ver"],
                   reorg_handle=o["reorg_handle"], error=o["error"],
                   error_count=o["error_count"], seq=o["seq"])

    @property
    def finished(self) -> bool:
        return self.state in (JobState.DONE, JobState.CANCELLED)
