"""DDL: statement validation + job construction (the API half).

Reference: /root/reference/ddl/ddl_api.go (validation + job build),
ddl/ddl.go:406 doDDLJob (enqueue, then wait for the owner's worker to
finish the job). Statements validate against the current schema, enqueue a
`Job`, and drive the in-process worker (ddl/worker.py) until the job
reaches history — so the session API is synchronous while the metadata
walks the full F1 state machine, one schema version per transition, with
every intermediate state visible to concurrent sessions.
"""

from __future__ import annotations

from tidb_tpu import kv
from tidb_tpu.ddl.job import Job, JobType
from tidb_tpu.ddl.worker import DDLWorker, JobFailed
from tidb_tpu.meta import Meta
from tidb_tpu.parser import ast
from tidb_tpu.schema.model import (ColumnInfo, DBInfo, IndexInfo,
                                   SchemaState, TableInfo)
from tidb_tpu.sqltypes import EvalType, Flag
from tidb_tpu.table import Table  # noqa: F401  (re-export for callers)

__all__ = ["DDLError", "DDL", "DDLExecutor", "build_table_info"]


class DDLError(kv.KVError):
    pass


class DDL:
    """Validates a DDL statement, enqueues its job(s), runs the worker."""

    def __init__(self, storage, worker: DDLWorker | None = None):
        self.storage = storage
        self.worker = worker or DDLWorker(storage)

    # submitters poll the history queue this long for a remote owner to
    # finish their job (ref: ddl.go doDDLJob's wait loop)
    REMOTE_JOB_TIMEOUT = 30.0

    def execute(self, stmt: ast.StmtNode, current_db: str,
                domain=None) -> None:
        m = getattr(self, "_build_" + type(stmt).__name__, None)
        if m is None:
            raise DDLError(f"unsupported DDL {type(stmt).__name__}")
        # Build + run jobs one at a time: later specs of one ALTER validate
        # against the schema the earlier ones produced.
        builders = m(stmt, current_db)
        for build in builders:
            job = self._enqueue(build)
            if job is None:
                continue
            # any server may ACCEPT the DDL; only the lease owner RUNS it
            # (ref: ddl.go:406 doDDLJob -> owner's worker loop). With no
            # competing owner the campaign wins instantly and the job
            # runs here, preserving single-node synchronous semantics.
            # A domain with a live background schema worker never runs
            # inline — two steppers on one queue would conflict.
            if domain is not None and domain.schema_worker_running():
                self._wait_remote_job(job.id)
                continue
            if domain is None:
                try:
                    self.worker.run_job(job.id)
                except JobFailed as e:
                    raise DDLError(str(e)) from None
                continue
            owner = domain.ddl_owner()
            if not owner.campaign():
                self._wait_remote_job(job.id)
                continue

            def between_steps():
                # per-version convergence (the F1 two-version invariant
                # the background tick also enforces) + lease renewal so
                # a long backfill can't silently lose ownership
                domain.wait_schema_convergence(
                    domain.info_schema().version)
                return owner.campaign()

            from tidb_tpu import kv as _kv
            try:
                done = self.worker.run_job(job.id,
                                           between_steps=between_steps)
            except JobFailed as e:
                raise DDLError(str(e)) from None
            except _kv.RetryableError:
                # a competing stepper got the transition in first: the
                # job is still progressing — wait for it like a remote
                self._wait_remote_job(job.id)
                continue
            if not done.finished:
                # lost the lease mid-job: the new owner continues it
                self._wait_remote_job(job.id)

    def _wait_remote_job(self, job_id: int) -> None:
        """Poll history until the owning server finishes the job."""
        import time as _time
        deadline = _time.time() + self.REMOTE_JOB_TIMEOUT
        while _time.time() < deadline:
            txn = self.storage.begin()
            try:
                done = Meta(txn).history_job(job_id)
            finally:
                txn.rollback()
            if done is not None:
                if getattr(done, "error", None):
                    raise DDLError(str(done.error))
                return
            _time.sleep(0.02)
        raise DDLError(f"DDL job {job_id} timed out waiting for the "
                       "owner; is the owner alive?")

    def _enqueue(self, build) -> Job | None:
        """Run `build(meta) -> Job|None` and enqueue in one meta txn."""
        txn = self.storage.begin()
        try:
            meta = Meta(txn)
            job = build(meta)
            if job is None:
                txn.rollback()
                return None
            job.id = meta.gen_global_id()
            meta.enqueue_job(job)
            txn.commit()
            return job
        except Exception:
            if txn.valid:
                txn.rollback()
            raise

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _find_db(meta: Meta, name: str) -> DBInfo:
        for db in meta.list_databases():
            if db.name.lower() == name.lower():
                return db
        raise DDLError(f"Unknown database '{name}'")

    @staticmethod
    def _find_table(meta: Meta, db_id: int, name: str):
        for t in meta.list_tables(db_id):
            if t.name.lower() == name.lower():
                return t
        return None

    def _resolve(self, meta: Meta, ts: ast.TableSource, current_db: str):
        dbn = ts.db or current_db
        if not dbn:
            raise DDLError("No database selected")
        db = self._find_db(meta, dbn)
        return db, self._find_table(meta, db.id, ts.name)

    def _must_resolve(self, meta: Meta, ts, current_db):
        db, t = self._resolve(meta, ts, current_db)
        if t is None:
            raise DDLError(f"table '{ts.name}' doesn't exist")
        return db, t

    # -- databases -----------------------------------------------------------

    def _build_CreateDatabaseStmt(self, stmt, _db):
        def build(meta: Meta):
            for db in meta.list_databases():
                if db.name.lower() == stmt.name.lower():
                    if stmt.if_not_exists:
                        return None
                    raise DDLError(f"database '{stmt.name}' exists")
            return Job(tp=JobType.CREATE_SCHEMA,
                       schema_id=meta.gen_global_id(),
                       args={"name": stmt.name})
        return [build]

    def _build_DropDatabaseStmt(self, stmt, _db):
        def build(meta: Meta):
            for db in meta.list_databases():
                if db.name.lower() == stmt.name.lower():
                    return Job(tp=JobType.DROP_SCHEMA, schema_id=db.id)
            if stmt.if_exists:
                return None
            raise DDLError(f"database '{stmt.name}' doesn't exist")
        return [build]

    # -- tables --------------------------------------------------------------

    def _build_CreateTableStmt(self, stmt, current_db):
        def build(meta: Meta):
            db, existing = self._resolve(meta, stmt.table, current_db)
            if existing is not None:
                if stmt.if_not_exists:
                    return None
                raise DDLError(f"table '{stmt.table.name}' exists")
            if stmt.like_table is not None:
                # CREATE TABLE a LIKE b: clone b's schema with fresh ids
                # (ref: ddl_api.go CreateTableWithLike)
                _sdb, src = self._must_resolve(meta, stmt.like_table,
                                               current_db)
                info = TableInfo.from_json(src.to_json())   # deep copy
                info.id = meta.gen_global_id()
                info.name = stmt.table.name
                info.auto_inc_id = 0
            else:
                info = build_table_info(meta, stmt)
            return Job(tp=JobType.CREATE_TABLE, schema_id=db.id,
                       table_id=info.id, args={"table": info.to_json()})
        return [build]

    def _build_DropTableStmt(self, stmt, current_db):
        builders = []
        for ts in stmt.tables:
            def build(meta: Meta, ts=ts):
                db, t = self._resolve(meta, ts, current_db)
                if t is None:
                    if stmt.if_exists:
                        return None
                    raise DDLError(f"table '{ts.name}' doesn't exist")
                return Job(tp=JobType.DROP_TABLE, schema_id=db.id,
                           table_id=t.id)
            builders.append(build)
        return builders

    def _build_TruncateTableStmt(self, stmt, current_db):
        def build(meta: Meta):
            db, t = self._must_resolve(meta, stmt.table, current_db)
            return Job(tp=JobType.TRUNCATE_TABLE, schema_id=db.id,
                       table_id=t.id,
                       args={"new_table_id": meta.gen_global_id()})
        return [build]

    def _build_RenameTableStmt(self, stmt, current_db):
        builders = []
        for old_ts, new_ts in stmt.pairs:
            def build(meta: Meta, old_ts=old_ts, new_ts=new_ts):
                db, t = self._must_resolve(meta, old_ts, current_db)
                new_db = self._find_db(meta, new_ts.db or current_db)
                if self._find_table(meta, new_db.id, new_ts.name) is not None:
                    raise DDLError(f"table '{new_ts.name}' exists")
                return Job(tp=JobType.RENAME_TABLE, schema_id=db.id,
                           table_id=t.id,
                           args={"new_name": new_ts.name,
                                 "new_schema_id": new_db.id})
            builders.append(build)
        return builders

    # -- indexes -------------------------------------------------------------

    def _index_job(self, meta: Meta, db, t: TableInfo, name: str,
                   columns: list[str], unique: bool) -> Job:
        if t.index_by_name(name) is not None:
            raise DDLError(f"index '{name}' exists")
        for cn in columns:
            if t.col_by_name(cn) is None:
                raise DDLError(f"Unknown column '{cn}'")
        idx = IndexInfo(id=t.alloc_index_id(), name=name, columns=columns,
                        unique=unique)
        # persist the bumped max_index_id now so a concurrent/later job
        # can't hand out the same id
        meta.update_table(db.id, t)
        return Job(tp=JobType.ADD_INDEX, schema_id=db.id, table_id=t.id,
                   args={"index": idx.to_json()})

    def _build_CreateIndexStmt(self, stmt, current_db):
        def build(meta: Meta):
            db, t = self._must_resolve(meta, stmt.table, current_db)
            return self._index_job(meta, db, t, stmt.index_name,
                                   stmt.columns, stmt.unique)
        return [build]

    def _build_DropIndexStmt(self, stmt, current_db):
        def build(meta: Meta):
            db, t = self._must_resolve(meta, stmt.table, current_db)
            if t.index_by_name(stmt.index_name) is None:
                if stmt.if_exists:
                    return None
                raise DDLError(f"index '{stmt.index_name}' doesn't exist")
            return Job(tp=JobType.DROP_INDEX, schema_id=db.id,
                       table_id=t.id, args={"name": stmt.index_name})
        return [build]

    # -- ALTER ---------------------------------------------------------------

    def _build_AlterTableStmt(self, stmt, current_db):
        # one schema change per statement, like the reference
        # (ddl_api.go AlterTable: errRunMultiSchemaChanges) — keeps ALTER
        # atomic: a failing spec can't leave earlier specs applied.
        # Parse-level no-ops (LOCK=/ALGORITHM=/ENABLE KEYS) don't count.
        specs = [sp for sp in stmt.specs if sp.tp != "noop"]
        if not specs:
            return []
        if len(specs) != 1:
            raise DDLError("running multiple schema changes in one "
                           "statement is not supported")
        spec = specs[0]
        if spec.tp == "add_columns":
            if len(spec.columns) != 1:
                raise DDLError("running multiple schema changes in one "
                               "statement is not supported")
            spec = ast.AlterSpec(tp="add_column", column=spec.columns[0])

        def build(meta: Meta):
            db, t = self._must_resolve(meta, stmt.table, current_db)
            return self._alter_spec_job(meta, db, t, spec)
        return [build]

    def _alter_spec_job(self, meta: Meta, db, t: TableInfo, spec):
        if spec.tp == "add_column":
            cd = spec.column
            _check_column_type(cd)
            if t.col_by_name(cd.name) is not None:
                raise DDLError(f"column '{cd.name}' exists")
            default = None
            has_default = cd.has_default
            if cd.has_default and cd.default is not None:
                default = _const_default(cd)
            elif not cd.ft.not_null:
                has_default = True   # NULL default for existing rows
            col = ColumnInfo(id=t.alloc_column_id(), name=cd.name,
                             offset=len(t.columns), ft=cd.ft,
                             default=default, has_default=has_default,
                             auto_increment=cd.auto_increment)
            meta.update_table(db.id, t)   # persist max_column_id bump
            if spec.position == "after" and \
                    t.col_by_name(spec.after_col) is None:
                raise DDLError(f"Unknown column '{spec.after_col}'")
            return Job(tp=JobType.ADD_COLUMN, schema_id=db.id,
                       table_id=t.id,
                       args={"column": col.to_json(),
                             "position": spec.position,
                             "after_col": spec.after_col})
        if spec.tp == "drop_column":
            col = t.col_by_name(spec.name)
            if col is None:
                raise DDLError(f"Unknown column '{spec.name}'")
            if t.pk_is_handle and \
                    t.pk_col_name.lower() == spec.name.lower():
                raise DDLError("cannot drop the integer primary key")
            for idx in t.indexes:
                if any(c.lower() == spec.name.lower()
                       for c in idx.columns):
                    raise DDLError(f"column '{spec.name}' is indexed; "
                                   "drop index first")
            return Job(tp=JobType.DROP_COLUMN, schema_id=db.id,
                       table_id=t.id, args={"name": spec.name})
        if spec.tp == "add_index":
            idef = spec.index
            return self._index_job(meta, db, t,
                                   idef.name or "_".join(idef.columns),
                                   idef.columns, idef.unique)
        if spec.tp == "drop_index":
            if t.index_by_name(spec.name) is None:
                raise DDLError(f"index '{spec.name}' doesn't exist")
            return Job(tp=JobType.DROP_INDEX, schema_id=db.id,
                       table_id=t.id, args={"name": spec.name})
        if spec.tp in ("modify_column", "change_column"):
            old_name = spec.name if spec.tp == "change_column" \
                else spec.column.name
            old = t.col_by_name(old_name)
            if old is None:
                raise DDLError(f"Unknown column '{old_name}'")
            # MySQL MODIFY/CHANGE replaces the whole definition: the
            # default must be restated or it resets
            cd = spec.column
            default = _const_default(cd) if cd.has_default else None
            new = ColumnInfo(id=old.id, name=cd.name,
                             offset=old.offset, ft=cd.ft,
                             default=default,
                             has_default=cd.has_default or
                             not cd.ft.not_null)
            if spec.position == "after":
                # AFTER resolves against the post-change schema: the
                # column being moved (old or new name) can't anchor it
                if spec.after_col.lower() in (old_name.lower(),
                                              cd.name.lower()) or \
                        t.col_by_name(spec.after_col) is None:
                    raise DDLError(
                        f"Unknown column '{spec.after_col}'")
            return Job(tp=JobType.MODIFY_COLUMN, schema_id=db.id,
                       table_id=t.id,
                       args={"old_name": old_name,
                             "column": new.to_json(),
                             "position": spec.position,
                             "after_col": spec.after_col})
        if spec.tp in ("set_default", "drop_default"):
            old = t.col_by_name(spec.name)
            if old is None:
                raise DDLError(f"Unknown column '{spec.name}'")
            # metadata-only change, rides the MODIFY_COLUMN job
            fake = ast.ColumnDef(name=old.name, ft=old.ft,
                                 default=spec.default,
                                 has_default=spec.tp == "set_default")
            default = _const_default(fake) \
                if spec.tp == "set_default" else None
            new = ColumnInfo(id=old.id, name=old.name, offset=old.offset,
                             ft=old.ft, default=default,
                             has_default=spec.tp == "set_default" or
                             not old.ft.not_null,
                             auto_increment=old.auto_increment)
            return Job(tp=JobType.MODIFY_COLUMN, schema_id=db.id,
                       table_id=t.id,
                       args={"old_name": old.name,
                             "column": new.to_json()})
        if spec.tp == "rename":
            if spec.new_db and spec.new_db.lower() != db.name.lower():
                raise DDLError("cross-database RENAME is not supported")
            existing = self._find_table(meta, db.id, spec.name)
            if existing is not None and existing.id != t.id:
                raise DDLError(f"table '{spec.name}' exists")
            return Job(tp=JobType.RENAME_TABLE, schema_id=db.id,
                       table_id=t.id,
                       args={"new_name": spec.name,
                             "new_schema_id": db.id})
        raise DDLError(f"unsupported ALTER {spec.tp}")


# Back-compat alias: the session layer predates the job-based front-end.
DDLExecutor = DDL


# MySQL's cap (ref: types/mydecimal.go, 65 digits via 9-digit words).
# p<=18 rides the scaled-int64 device lane; wider columns use exact
# scaled python ints on the host object lane (FieldType.is_wide_decimal)
MAX_DECIMAL_DIGITS = 65


def _check_column_type(cd) -> None:
    from tidb_tpu.sqltypes import TypeCode
    if cd.ft.tp == TypeCode.NEWDECIMAL:
        if cd.ft.flen > MAX_DECIMAL_DIGITS:
            raise DDLError(
                f"column '{cd.name}': DECIMAL({cd.ft.flen},{cd.ft.frac}) "
                f"exceeds the supported precision "
                f"({MAX_DECIMAL_DIGITS} digits)")
        if cd.ft.frac > cd.ft.flen:
            raise DDLError(
                f"column '{cd.name}': scale {cd.ft.frac} > "
                f"precision {cd.ft.flen}")


def build_table_info(meta: Meta, stmt: ast.CreateTableStmt) -> TableInfo:
    info = TableInfo(id=meta.gen_global_id(), name=stmt.table.name)
    names = set()
    # table-level default collation applies to string columns without an
    # explicit COLLATE (ref: util/charset; only _bin and _general_ci are
    # implemented — docs/DEVIATIONS.md)
    table_coll = (stmt.options or {}).get("collate", "").lower()
    for i, cd in enumerate(stmt.columns):
        if cd.name.lower() in names:
            raise DDLError(f"duplicate column '{cd.name}'")
        names.add(cd.name.lower())
        _check_column_type(cd)
        ft = cd.ft
        if table_coll and ft.eval_type == EvalType.STRING and \
                not getattr(cd, "explicit_collation", False):
            import dataclasses
            ft = dataclasses.replace(ft, collation=table_coll)
        default = _const_default(cd) if cd.has_default else None
        info.columns.append(ColumnInfo(
            id=i + 1, name=cd.name, offset=i, ft=ft, default=default,
            has_default=cd.has_default or not cd.ft.not_null,
            auto_increment=cd.auto_increment, comment=cd.comment))
    info.max_column_id = len(stmt.columns)

    # primary key: inline or table-level
    pk_cols: list[str] = [cd.name for cd in stmt.columns if cd.is_primary]
    idx_id = 0
    for idef in stmt.indexes:
        if idef.primary:
            pk_cols = pk_cols or idef.columns
            if idef.columns != pk_cols:
                raise DDLError("multiple primary keys")
    if len(pk_cols) == 1:
        pkc = info.col_by_name(pk_cols[0])
        if pkc is not None and pkc.ft.eval_type == EvalType.INT:
            info.pk_is_handle = True
            info.pk_col_name = pkc.name
            pkc.ft = pkc.ft.with_flags(Flag.PRI_KEY | Flag.NOT_NULL)
    if pk_cols and not info.pk_is_handle:
        idx_id += 1
        info.indexes.append(IndexInfo(id=idx_id, name="PRIMARY",
                                      columns=pk_cols, unique=True,
                                      primary=True))
    for cd in stmt.columns:
        if cd.is_unique:
            idx_id += 1
            info.indexes.append(IndexInfo(id=idx_id, name=cd.name,
                                          columns=[cd.name], unique=True))
    for idef in stmt.indexes:
        if idef.primary:
            continue
        idx_id += 1
        info.indexes.append(IndexInfo(
            id=idx_id, name=idef.name or "_".join(idef.columns),
            columns=idef.columns, unique=idef.unique))
    info.max_index_id = idx_id
    for idx in info.indexes:
        for cn in idx.columns:
            if info.col_by_name(cn) is None:
                raise DDLError(f"Unknown column '{cn}' in index")
    return info


def _const_default(cd: ast.ColumnDef):
    d = cd.default
    if d is None:
        return None
    if isinstance(d, ast.Literal):
        v = d.value
        if v is not None and cd.ft.eval_type == EvalType.DATETIME and \
                isinstance(v, str):
            from tidb_tpu import sqltypes as st
            return st.parse_datetime(v)
        return v
    # DEFAULT CURRENT_TIMESTAMP[()] / NOW() on time columns: stored as
    # a sentinel, evaluated at each insert (ref: ddl_api.go
    # setDefaultValue + types CurrentTimestamp handling)
    name = d.name.upper() if isinstance(d, (ast.ColName,
                                            ast.FuncCall)) else ""
    if name in ("CURRENT_TIMESTAMP", "NOW", "LOCALTIME",
                "LOCALTIMESTAMP") and \
            cd.ft.eval_type == EvalType.DATETIME:
        return "CURRENT_TIMESTAMP"
    raise DDLError("only literal defaults supported")
