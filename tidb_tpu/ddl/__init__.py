"""DDL execution.

Reference: /root/reference/ddl/ — the full F1 online-schema-change worker
(state machine, owner election, backfill) arrives with the online-DDL
milestone; this module implements the synchronous single-node versions with
the same metadata effects (schema version bumps, TableInfo/DBInfo json in
meta), so upgrading to async jobs changes the driver, not the format.
"""

from __future__ import annotations

from tidb_tpu import codec, kv, tablecodec
from tidb_tpu.meta import Meta, MetaError
from tidb_tpu.parser import ast
from tidb_tpu.schema.model import (ColumnInfo, DBInfo, IndexInfo,
                                   SchemaState, TableInfo)
from tidb_tpu.sqltypes import EvalType, Flag, TypeCode
from tidb_tpu.table import Table, encode_datum_for_col

__all__ = ["DDLError", "DDLExecutor"]


class DDLError(kv.KVError):
    pass


class DDLExecutor:
    """Applies one DDL statement in its own meta transaction."""

    def __init__(self, storage):
        self.storage = storage

    def _txn(self):
        return self.storage.begin()

    def execute(self, stmt: ast.StmtNode, current_db: str) -> None:
        m = getattr(self, "_exec_" + type(stmt).__name__, None)
        if m is None:
            raise DDLError(f"unsupported DDL {type(stmt).__name__}")
        txn = self._txn()
        try:
            m(Meta(txn), stmt, current_db)
            Meta(txn).gen_schema_version()
            txn.commit()
        except Exception:
            txn.rollback()
            raise

    # -- databases -----------------------------------------------------------

    def _exec_CreateDatabaseStmt(self, meta: Meta, stmt, _db):
        for db in meta.list_databases():
            if db.name.lower() == stmt.name.lower():
                if stmt.if_not_exists:
                    return
                raise DDLError(f"database '{stmt.name}' exists")
        meta.create_database(DBInfo(id=meta.gen_global_id(), name=stmt.name))

    def _exec_DropDatabaseStmt(self, meta: Meta, stmt, _db):
        for db in meta.list_databases():
            if db.name.lower() == stmt.name.lower():
                for t in meta.list_tables(db.id):
                    self._drop_table_data(t.id)
                meta.drop_database(db.id)
                return
        if not stmt.if_exists:
            raise DDLError(f"database '{stmt.name}' doesn't exist")

    # -- tables --------------------------------------------------------------

    def _find_db(self, meta: Meta, name: str) -> DBInfo:
        for db in meta.list_databases():
            if db.name.lower() == name.lower():
                return db
        raise DDLError(f"Unknown database '{name}'")

    def _find_table(self, meta: Meta, db_id: int, name: str):
        for t in meta.list_tables(db_id):
            if t.name.lower() == name.lower():
                return t
        return None

    def _resolve_table(self, meta: Meta, ts: ast.TableSource,
                       current_db: str):
        dbn = ts.db or current_db
        if not dbn:
            raise DDLError("No database selected")
        db = self._find_db(meta, dbn)
        t = self._find_table(meta, db.id, ts.name)
        return db, t

    def _exec_CreateTableStmt(self, meta: Meta, stmt: ast.CreateTableStmt,
                              current_db: str):
        db, existing = self._resolve_table(meta, stmt.table, current_db)
        if existing is not None:
            if stmt.if_not_exists:
                return
            raise DDLError(f"table '{stmt.table.name}' exists")
        info = build_table_info(meta, stmt)
        meta.create_table(db.id, info)

    def _exec_DropTableStmt(self, meta: Meta, stmt, current_db):
        for ts in stmt.tables:
            db, t = self._resolve_table(meta, ts, current_db)
            if t is None:
                if stmt.if_exists:
                    continue
                raise DDLError(f"table '{ts.name}' doesn't exist")
            meta.drop_table(db.id, t.id)
            self._drop_table_data(t.id)

    def _exec_TruncateTableStmt(self, meta: Meta, stmt, current_db):
        db, t = self._resolve_table(meta, stmt.table, current_db)
        if t is None:
            raise DDLError(f"table '{stmt.table.name}' doesn't exist")
        # new table id, same schema (ref: ddl truncate = id swap)
        meta.drop_table(db.id, t.id)
        old_id = t.id
        t.id = meta.gen_global_id()
        meta.create_table(db.id, t)
        self._drop_table_data(old_id)

    def _exec_RenameTableStmt(self, meta: Meta, stmt, current_db):
        for old_ts, new_ts in stmt.pairs:
            db, t = self._resolve_table(meta, old_ts, current_db)
            if t is None:
                raise DDLError(f"table '{old_ts.name}' doesn't exist")
            new_db = self._find_db(meta, new_ts.db or current_db)
            if self._find_table(meta, new_db.id, new_ts.name) is not None:
                raise DDLError(f"table '{new_ts.name}' exists")
            meta.drop_table(db.id, t.id)
            t.name = new_ts.name
            meta.create_table(new_db.id, t)

    def _drop_table_data(self, table_id: int) -> None:
        """Immediate range delete (the delete-range/GC emulator arrives with
        the GC milestone; ref: ddl/delete_range.go:51)."""
        lo, hi = tablecodec.table_prefix_range(table_id)
        self.storage.engine.delete_range(lo, hi)

    # -- indexes -------------------------------------------------------------

    def _exec_CreateIndexStmt(self, meta: Meta, stmt: ast.CreateIndexStmt,
                              current_db: str):
        db, t = self._resolve_table(meta, stmt.table, current_db)
        if t is None:
            raise DDLError(f"table '{stmt.table.name}' doesn't exist")
        if t.index_by_name(stmt.index_name) is not None:
            raise DDLError(f"index '{stmt.index_name}' exists")
        for cn in stmt.columns:
            if t.col_by_name(cn) is None:
                raise DDLError(f"Unknown column '{cn}'")
        idx = IndexInfo(id=max([i.id for i in t.indexes], default=0) + 1,
                        name=stmt.index_name, columns=stmt.columns,
                        unique=stmt.unique)
        self._backfill_index(t, idx)
        t.indexes.append(idx)
        meta.update_table(db.id, t)

    def _exec_DropIndexStmt(self, meta: Meta, stmt, current_db):
        db, t = self._resolve_table(meta, stmt.table, current_db)
        if t is None:
            raise DDLError(f"table '{stmt.table.name}' doesn't exist")
        idx = t.index_by_name(stmt.index_name)
        if idx is None:
            if stmt.if_exists:
                return
            raise DDLError(f"index '{stmt.index_name}' doesn't exist")
        t.indexes.remove(idx)
        meta.update_table(db.id, t)
        prefix = tablecodec.index_prefix(t.id, idx.id)
        self.storage.engine.delete_range(prefix, codec.prefix_next(prefix))

    def _backfill_index(self, t: TableInfo, idx: IndexInfo) -> None:
        """Synchronous backfill in one txn (the reorg worker with batched
        txns + checkpoints replaces this in the online-DDL milestone;
        ref: ddl/index.go:480-676 addTableIndex)."""
        txn = self.storage.begin()
        try:
            tbl = Table(t, self.storage)
            seen = {}
            for handle, row in tbl.iter_records(txn):
                vals = []
                for cn in idx.columns:
                    ci = t.col_by_name(cn)
                    vals.append(row.get(ci.id))
                if idx.unique and all(v is not None for v in vals):
                    key = tuple(vals)
                    if key in seen:
                        raise DDLError(
                            f"duplicate entry for new unique index")
                    seen[key] = handle
                    txn.set(tablecodec.index_key(t.id, idx.id, vals),
                            codec.encode_int(handle))
                else:
                    txn.set(tablecodec.index_key(t.id, idx.id, vals,
                                                 handle=handle), b"0")
            txn.commit()
        except Exception:
            txn.rollback()
            raise

    # -- ALTER ---------------------------------------------------------------

    def _exec_AlterTableStmt(self, meta: Meta, stmt: ast.AlterTableStmt,
                             current_db: str):
        db, t = self._resolve_table(meta, stmt.table, current_db)
        if t is None:
            raise DDLError(f"table '{stmt.table.name}' doesn't exist")
        for spec in stmt.specs:
            if spec.tp == "add_column":
                self._alter_add_column(t, spec)
            elif spec.tp == "drop_column":
                self._alter_drop_column(t, spec)
            elif spec.tp == "add_index":
                idx_def = spec.index
                if t.index_by_name(idx_def.name or "") is not None:
                    raise DDLError(f"index '{idx_def.name}' exists")
                idx = IndexInfo(
                    id=max([i.id for i in t.indexes], default=0) + 1,
                    name=idx_def.name or "_".join(idx_def.columns),
                    columns=idx_def.columns, unique=idx_def.unique,
                    primary=idx_def.primary)
                self._backfill_index(t, idx)
                t.indexes.append(idx)
            elif spec.tp == "drop_index":
                idx = t.index_by_name(spec.name)
                if idx is None:
                    raise DDLError(f"index '{spec.name}' doesn't exist")
                t.indexes.remove(idx)
                prefix = tablecodec.index_prefix(t.id, idx.id)
                self.storage.engine.delete_range(prefix,
                                                 codec.prefix_next(prefix))
            elif spec.tp == "modify_column" or spec.tp == "change_column":
                old_name = spec.name if spec.tp == "change_column" \
                    else spec.column.name
                old = t.col_by_name(old_name)
                if old is None:
                    raise DDLError(f"Unknown column '{old_name}'")
                old.name = spec.column.name
                old.ft = spec.column.ft
            elif spec.tp == "rename":
                t.name = spec.name
            else:
                raise DDLError(f"unsupported ALTER {spec.tp}")
        meta.update_table(db.id, t)

    def _alter_add_column(self, t: TableInfo, spec) -> None:
        cd = spec.column
        if t.col_by_name(cd.name) is not None:
            raise DDLError(f"column '{cd.name}' exists")
        default = None
        has_default = cd.has_default
        if cd.has_default and cd.default is not None:
            default = _const_default(cd)
        elif not cd.ft.not_null:
            has_default = True  # NULL default for existing rows
        col = ColumnInfo(
            id=max([c.id for c in t.columns], default=0) + 1,
            name=cd.name, offset=len(t.columns), ft=cd.ft,
            default=default, has_default=has_default,
            auto_increment=cd.auto_increment)
        if spec.position == "first":
            t.columns.insert(0, col)
        elif spec.position == "after":
            ai = next((i for i, c in enumerate(t.columns)
                       if c.name.lower() == spec.after_col.lower()), None)
            if ai is None:
                raise DDLError(f"Unknown column '{spec.after_col}'")
            t.columns.insert(ai + 1, col)
        else:
            t.columns.append(col)
        for i, c in enumerate(t.columns):
            c.offset = i

    def _alter_drop_column(self, t: TableInfo, spec) -> None:
        col = t.col_by_name(spec.name)
        if col is None:
            raise DDLError(f"Unknown column '{spec.name}'")
        if t.pk_is_handle and t.pk_col_name.lower() == spec.name.lower():
            raise DDLError("cannot drop the integer primary key")
        for idx in t.indexes:
            if any(c.lower() == spec.name.lower() for c in idx.columns):
                raise DDLError(
                    f"column '{spec.name}' is indexed; drop index first")
        t.columns.remove(col)
        for i, c in enumerate(t.columns):
            c.offset = i


def build_table_info(meta: Meta, stmt: ast.CreateTableStmt) -> TableInfo:
    info = TableInfo(id=meta.gen_global_id(), name=stmt.table.name)
    names = set()
    for i, cd in enumerate(stmt.columns):
        if cd.name.lower() in names:
            raise DDLError(f"duplicate column '{cd.name}'")
        names.add(cd.name.lower())
        default = _const_default(cd) if cd.has_default else None
        info.columns.append(ColumnInfo(
            id=i + 1, name=cd.name, offset=i, ft=cd.ft, default=default,
            has_default=cd.has_default or not cd.ft.not_null,
            auto_increment=cd.auto_increment, comment=cd.comment))

    # primary key: inline or table-level
    pk_cols: list[str] = [cd.name for cd in stmt.columns if cd.is_primary]
    idx_id = 0
    for idef in stmt.indexes:
        if idef.primary:
            pk_cols = pk_cols or idef.columns
            if idef.columns != pk_cols:
                raise DDLError("multiple primary keys")
    if len(pk_cols) == 1:
        pkc = info.col_by_name(pk_cols[0])
        if pkc is not None and pkc.ft.eval_type == EvalType.INT:
            info.pk_is_handle = True
            info.pk_col_name = pkc.name
            pkc.ft = pkc.ft.with_flags(Flag.PRI_KEY | Flag.NOT_NULL)
    if pk_cols and not info.pk_is_handle:
        idx_id += 1
        info.indexes.append(IndexInfo(id=idx_id, name="PRIMARY",
                                      columns=pk_cols, unique=True,
                                      primary=True))
    for cd in stmt.columns:
        if cd.is_unique:
            idx_id += 1
            info.indexes.append(IndexInfo(id=idx_id, name=cd.name,
                                          columns=[cd.name], unique=True))
    for idef in stmt.indexes:
        if idef.primary:
            continue
        idx_id += 1
        info.indexes.append(IndexInfo(
            id=idx_id, name=idef.name or "_".join(idef.columns),
            columns=idef.columns, unique=idef.unique))
    for idx in info.indexes:
        for cn in idx.columns:
            if info.col_by_name(cn) is None:
                raise DDLError(f"Unknown column '{cn}' in index")
    return info


def _const_default(cd: ast.ColumnDef):
    d = cd.default
    if d is None:
        return None
    if isinstance(d, ast.Literal):
        v = d.value
        if v is not None and cd.ft.eval_type == EvalType.DATETIME and \
                isinstance(v, str):
            from tidb_tpu import sqltypes as st
            return st.parse_datetime(v)
        return v
    raise DDLError("only literal defaults supported")
