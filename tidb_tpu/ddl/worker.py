"""Online DDL worker: the F1 schema-change state machine.

Reference: /root/reference/ddl/ddl_worker.go:33-320 (job loop, one state
transition per meta transaction), ddl/index.go:280,480-676 (add-index
states + checkpointed backfill), ddl/column.go (add/drop column walk),
ddl/reorg.go:71 (resumable reorgInfo), ddl/delete_range.go:51 (deferred
range deletion), model/model.go:27-37 (schema states).

Every transition runs in its own meta transaction and bumps the global
schema version with a SchemaDiff record, so concurrent sessions reload
incrementally and the schema validator can detect conflicting commits.
A crash between any two transactions leaves a resumable state: the job
queue and the reorg checkpoint are the only progress markers.
"""

from __future__ import annotations

from typing import Callable, Optional

from tidb_tpu import codec, kv, tablecodec
from tidb_tpu.ddl.job import Job, JobState, JobType
from tidb_tpu.meta import Meta
from tidb_tpu.schema.model import (ColumnInfo, DBInfo, IndexInfo,
                                   SchemaState, TableInfo)
from tidb_tpu.table import DupKeyError, Table

__all__ = ["DDLWorker", "JobFailed"]

BACKFILL_BATCH = 256   # rows per backfill txn (ref: defaultTaskHandleCnt)


class JobFailed(kv.KVError):
    """Raised by run_job for a job that finished CANCELLED."""


class DDLWorker:
    """Single DDL owner (the reference elects one via etcd, owner/manager.go;
    in-process there is exactly one — multi-server deployments point every
    server's worker at the same job queue and the queue pop serializes)."""

    def __init__(self, storage,
                 on_state_change: Optional[Callable[[Job], None]] = None,
                 on_backfill_batch: Optional[Callable[[Job, int], None]]
                 = None):
        self.storage = storage
        self.on_state_change = on_state_change
        self.on_backfill_batch = on_backfill_batch

    # -- driving -------------------------------------------------------------

    def run_job(self, job_id: int, between_steps=None) -> Job:
        """Run queue steps until job_id finishes; raise if cancelled.
        `between_steps()` (owner-lease renewal + per-version convergence,
        tidb_tpu/session Domain) runs after every transition; returning
        False means ownership was lost — stop stepping (the new owner's
        worker continues the job) and report the job as-is."""
        while True:
            job = self.run_one_step()
            if job is not None and between_steps is not None and \
                    not between_steps():
                return job
            if job is None:
                # queue empty: the job must be in history
                txn = self.storage.begin()
                try:
                    done = Meta(txn).history_job(job_id)
                finally:
                    txn.rollback()
                if done is None:
                    raise kv.KVError(f"ddl job {job_id} vanished")
                job = done
            if job.id == job_id and job.finished:
                if job.state == JobState.CANCELLED:
                    raise JobFailed(job.error)
                return job

    def run_one_step(self) -> Job | None:
        """Apply one state transition of the queue-head job (plus, for a
        reorg state, the out-of-band backfill that precedes it)."""
        txn = self.storage.begin()
        try:
            head = Meta(txn).first_job()
        finally:
            txn.rollback()
        if head is None:
            return None
        if head.tp == JobType.ADD_INDEX and head.state == JobState.RUNNING \
                and head.schema_state == int(SchemaState.WRITE_REORG):
            try:
                self._backfill_index(head)
            except DupKeyError as e:
                # data violates the new unique index: walk the states back
                # (crash-like errors propagate instead — the checkpointed
                # reorg resumes on the next worker pass)
                self._cancel_or_rollback(head, str(e))
                return self._reload_head(head)

        txn = self.storage.begin()
        m = Meta(txn)
        job = m.first_job()
        if job is None:
            txn.rollback()
            return None
        if job.state == JobState.QUEUEING:
            job.state = JobState.RUNNING
        try:
            changed = self._dispatch(m, job)
        except Exception as e:  # noqa: BLE001 - job-level failure
            txn.rollback()
            self._cancel_or_rollback(job, str(e))
            return self._reload_head(job)
        if changed:
            ver = m.gen_schema_version()
            m.set_schema_diff(ver, [job.table_id] if job.table_id else [])
        if job.finished:
            m.finish_job(job)
        else:
            m.update_job(job)
        txn.commit()
        if job.finished and job.args.get("has_ranges"):
            self._seal_delete_ranges(job)
        if self.on_state_change is not None:
            self.on_state_change(job)
        return job

    def _seal_delete_ranges(self, job: Job) -> None:
        """Stamp the job's queued ranges with a ts acquired AFTER its final
        txn committed — an upper bound on the drop's commit ts, so GC can
        safely order the physical delete against the safepoint. Best
        effort: if this crashes, the GC worker re-seals orphaned ranges of
        finished jobs (gcworker._drain_delete_ranges)."""
        txn = self.storage.begin()
        try:
            Meta(txn).seal_delete_ranges(job.id, txn.start_ts)
            txn.commit()
        except Exception:
            if txn.valid:
                txn.rollback()

    def _reload_head(self, job: Job) -> Job:
        txn = self.storage.begin()
        try:
            head = Meta(txn).first_job()
            return head if head is not None and head.id == job.id else job
        finally:
            txn.rollback()

    def _cancel_or_rollback(self, job: Job, err: str) -> None:
        """Validation failure: cancel outright if nothing is half-built,
        else flip to ROLLBACK so the state machine walks backwards."""
        txn = self.storage.begin()
        m = Meta(txn)
        fresh = m.first_job()
        if fresh is None or fresh.id != job.id:
            txn.rollback()
            return
        fresh.error = err
        if fresh.tp == JobType.ADD_INDEX and \
                fresh.schema_state != int(SchemaState.NONE):
            fresh.state = JobState.ROLLBACK
            m.update_job(fresh)
        else:
            fresh.state = JobState.CANCELLED
            m.finish_job(fresh)
        txn.commit()

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, m: Meta, job: Job) -> bool:
        if job.state == JobState.ROLLBACK:
            return self._step_rollback_add_index(m, job)
        return {
            JobType.CREATE_SCHEMA: self._step_create_schema,
            JobType.DROP_SCHEMA: self._step_drop_schema,
            JobType.CREATE_TABLE: self._step_create_table,
            JobType.DROP_TABLE: self._step_drop_table,
            JobType.TRUNCATE_TABLE: self._step_truncate_table,
            JobType.RENAME_TABLE: self._step_rename_table,
            JobType.ADD_COLUMN: self._step_add_column,
            JobType.DROP_COLUMN: self._step_drop_column,
            JobType.MODIFY_COLUMN: self._step_modify_column,
            JobType.ADD_INDEX: self._step_add_index,
            JobType.DROP_INDEX: self._step_drop_index,
        }[job.tp](m, job)

    def _table(self, m: Meta, job: Job) -> TableInfo:
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise kv.KVError(f"table {job.table_id} doesn't exist")
        return info

    # -- schema / table jobs (single transition) -----------------------------

    def _step_create_schema(self, m: Meta, job: Job) -> bool:
        db = DBInfo(id=job.schema_id, name=job.args["name"])
        for existing in m.list_databases():
            if existing.name.lower() == db.name.lower():
                raise kv.KVError(f"database '{db.name}' exists")
        m.create_database(db)
        job.state = JobState.DONE
        return True

    def _step_drop_schema(self, m: Meta, job: Job) -> bool:
        for t in m.list_tables(job.schema_id):
            lo, hi = tablecodec.table_prefix_range(t.id)
            m.add_delete_range(job.id, lo, hi)
            job.args["has_ranges"] = True
        m.drop_database(job.schema_id)
        job.state = JobState.DONE
        return True

    def _step_create_table(self, m: Meta, job: Job) -> bool:
        info = TableInfo.from_json(job.args["table"])
        # re-validate at apply time: two sessions may have raced the enqueue
        for t in m.list_tables(job.schema_id):
            if t.name.lower() == info.name.lower():
                raise kv.KVError(f"table '{info.name}' exists")
        m.create_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    def _step_drop_table(self, m: Meta, job: Job) -> bool:
        """PUBLIC -> WRITE_ONLY -> DELETE_ONLY -> gone
        (ref: ddl/table.go onDropTable)."""
        info = self._table(m, job)
        if info.state == SchemaState.PUBLIC:
            info.state = SchemaState.WRITE_ONLY
            m.update_table(job.schema_id, info)
        elif info.state == SchemaState.WRITE_ONLY:
            info.state = SchemaState.DELETE_ONLY
            m.update_table(job.schema_id, info)
        else:
            m.drop_table(job.schema_id, info.id)
            lo, hi = tablecodec.table_prefix_range(info.id)
            m.add_delete_range(job.id, lo, hi)
            job.args["has_ranges"] = True
            job.state = JobState.DONE
        job.schema_state = int(info.state)
        return True

    def _step_truncate_table(self, m: Meta, job: Job) -> bool:
        info = self._table(m, job)
        m.drop_table(job.schema_id, info.id)
        lo, hi = tablecodec.table_prefix_range(info.id)
        m.add_delete_range(job.id, lo, hi)
        job.args["has_ranges"] = True
        info.id = job.args["new_table_id"]
        m.create_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    def _step_rename_table(self, m: Meta, job: Job) -> bool:
        info = self._table(m, job)
        new_name = job.args["new_name"]
        new_db = job.args["new_schema_id"]
        for t in m.list_tables(new_db):
            if t.id != info.id and t.name.lower() == new_name.lower():
                raise kv.KVError(f"table '{new_name}' exists")
        m.drop_table(job.schema_id, info.id)
        info.name = new_name
        m.create_table(new_db, info)
        job.state = JobState.DONE
        return True

    # -- column jobs ---------------------------------------------------------

    def _step_add_column(self, m: Meta, job: Job) -> bool:
        """NONE -> DELETE_ONLY -> WRITE_ONLY -> WRITE_REORG -> PUBLIC
        (ref: ddl/column.go onAddColumn). No physical backfill: existing
        rows materialize the default lazily at decode."""
        info = self._table(m, job)
        col = info.col_by_name(job.args["column"]["name"])
        if col is None:
            # first transition: attach in DELETE_ONLY
            col = ColumnInfo.from_json(job.args["column"])
            col.state = SchemaState.DELETE_ONLY
            col.offset = len(info.columns)
            info.columns.append(col)
        elif col.state == SchemaState.DELETE_ONLY:
            col.state = SchemaState.WRITE_ONLY
        elif col.state == SchemaState.WRITE_ONLY:
            col.state = SchemaState.WRITE_REORG
        elif col.state == SchemaState.WRITE_REORG:
            col.state = SchemaState.PUBLIC
            self._position_column(info, col, job.args.get("position"),
                                  job.args.get("after_col"))
            job.state = JobState.DONE
        job.schema_state = int(col.state)
        m.update_table(job.schema_id, info)
        return True

    @staticmethod
    def _position_column(info: TableInfo, col: ColumnInfo,
                         position: str | None, after: str | None) -> None:
        if position in ("first", "after"):
            info.columns.remove(col)
            if position == "first":
                info.columns.insert(0, col)
            else:
                ai = next(i for i, c in enumerate(info.columns)
                          if c.name.lower() == after.lower())
                info.columns.insert(ai + 1, col)
        for i, c in enumerate(info.columns):
            c.offset = i

    def _step_drop_column(self, m: Meta, job: Job) -> bool:
        """PUBLIC -> WRITE_ONLY -> DELETE_ONLY -> DELETE_REORG -> gone
        (ref: ddl/column.go onDropColumn). Row values of the dropped column
        become dead bytes in the row codec; no physical rewrite."""
        info = self._table(m, job)
        col = info.col_by_name(job.args["name"])
        if col is None:
            raise kv.KVError(f"Unknown column '{job.args['name']}'")
        if col.state == SchemaState.PUBLIC:
            col.state = SchemaState.WRITE_ONLY
        elif col.state == SchemaState.WRITE_ONLY:
            col.state = SchemaState.DELETE_ONLY
        elif col.state == SchemaState.DELETE_ONLY:
            col.state = SchemaState.DELETE_REORG
        else:
            info.columns.remove(col)
            for i, c in enumerate(info.columns):
                c.offset = i
            job.state = JobState.DONE
        job.schema_state = int(col.state)
        m.update_table(job.schema_id, info)
        return True

    def _step_modify_column(self, m: Meta, job: Job) -> bool:
        info = self._table(m, job)
        col = info.col_by_name(job.args["old_name"])
        if col is None:
            raise kv.KVError(f"Unknown column '{job.args['old_name']}'")
        new = ColumnInfo.from_json(job.args["column"])
        col.name = new.name
        col.ft = new.ft
        col.default = new.default        # SET/DROP DEFAULT ride this job
        col.has_default = new.has_default
        # CHANGE ... FIRST/AFTER x: order is metadata only (rows store
        # col-id -> value pairs)
        self._position_column(info, col, job.args.get("position"),
                              job.args.get("after_col"))
        m.update_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    # -- index jobs ----------------------------------------------------------

    def _step_add_index(self, m: Meta, job: Job) -> bool:
        """NONE -> DELETE_ONLY -> WRITE_ONLY -> WRITE_REORG(backfill) ->
        PUBLIC (ref: ddl/index.go:280 onCreateIndex)."""
        info = self._table(m, job)
        name = job.args["index"]["name"]
        idx = info.index_by_name(name)
        if idx is None:
            idx = IndexInfo.from_json(job.args["index"])
            idx.state = SchemaState.DELETE_ONLY
            info.indexes.append(idx)
        elif idx.state == SchemaState.DELETE_ONLY:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.WRITE_REORG
            # reorg reads rows as of this snapshot; later writes maintain
            # the index themselves (it has been WRITE_ONLY since)
            job.snapshot_ver = m.txn.start_ts
            job.reorg_handle = None
        elif idx.state == SchemaState.WRITE_REORG:
            # run_one_step completed the backfill before this transition
            idx.state = SchemaState.PUBLIC
            job.state = JobState.DONE
        job.schema_state = int(idx.state)
        m.update_table(job.schema_id, info)
        return True

    def _step_drop_index(self, m: Meta, job: Job) -> bool:
        info = self._table(m, job)
        idx = info.index_by_name(job.args["name"])
        if idx is None:
            raise kv.KVError(f"index '{job.args['name']}' doesn't exist")
        if idx.state == SchemaState.PUBLIC:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.DELETE_ONLY
        else:
            info.indexes.remove(idx)
            prefix = tablecodec.index_prefix(info.id, idx.id)
            m.add_delete_range(job.id, prefix, codec.prefix_next(prefix))
            job.args["has_ranges"] = True
            job.state = JobState.DONE
        job.schema_state = int(idx.state)
        m.update_table(job.schema_id, info)
        return True

    def _step_rollback_add_index(self, m: Meta, job: Job) -> bool:
        """Walk a half-built index back down and cancel the job
        (ref: ddl/index.go onDropIndex reuse for rollback)."""
        info = self._table(m, job)
        idx = info.index_by_name(job.args["index"]["name"])
        if idx is None:
            job.state = JobState.CANCELLED
            return False
        if idx.state in (SchemaState.WRITE_REORG, SchemaState.WRITE_ONLY):
            idx.state = SchemaState.DELETE_ONLY
            m.update_table(job.schema_id, info)
        else:
            info.indexes.remove(idx)
            prefix = tablecodec.index_prefix(info.id, idx.id)
            m.add_delete_range(job.id, prefix, codec.prefix_next(prefix))
            job.args["has_ranges"] = True
            m.update_table(job.schema_id, info)
            job.state = JobState.CANCELLED
        job.schema_state = int(idx.state)
        return True

    # -- backfill ------------------------------------------------------------

    def _backfill_index(self, job: Job) -> None:
        """Checkpointed backfill: batched txns, progress persisted in the
        job (ref: ddl/index.go:541-676 addTableIndex + reorg.go)."""
        while True:
            txn = self.storage.begin()
            m = Meta(txn)
            jb = m.first_job()
            if jb is None or jb.id != job.id or \
                    jb.state != JobState.RUNNING:
                txn.rollback()
                return
            info = m.get_table(jb.schema_id, jb.table_id)
            idx = info.index_by_name(jb.args["index"]["name"]) \
                if info is not None else None
            if idx is None:
                txn.rollback()
                return
            snap = self.storage.snapshot(jb.snapshot_ver)
            tbl = Table(info, self.storage)
            start = jb.reorg_handle + 1 if jb.reorg_handle is not None \
                else None
            n = 0
            last = None
            try:
                for handle, _snap_row in tbl.iter_records(
                        snap, start_handle=start):
                    # the snapshot scan only supplies handles; entry values
                    # come from the CURRENT row in this txn, so rows
                    # updated/deleted since the snapshot (whose entries the
                    # mutating txn already maintained — the index has been
                    # WRITE_ONLY throughout) are never resurrected
                    raw = txn.get(tablecodec.record_key(info.id, handle))
                    if raw is None:
                        last = handle
                        continue
                    row = tablecodec.decode_row(raw)
                    self._write_backfill_entry(txn, info, idx, row, handle)
                    last = handle
                    n += 1
                    if n >= BACKFILL_BATCH:
                        break
            except Exception:
                txn.rollback()
                raise
            if last is not None:
                jb.reorg_handle = last
            done = n < BACKFILL_BATCH
            m.update_job(jb)
            txn.commit()
            if self.on_backfill_batch is not None:
                self.on_backfill_batch(jb, n)
            if done:
                return

    @staticmethod
    def _write_backfill_entry(txn, info: TableInfo, idx: IndexInfo,
                              row: dict, handle: int) -> None:
        vals = []
        for cname in idx.columns:
            col = info.col_by_name(cname)
            vals.append(row.get(col.id))
        if idx.unique and all(v is not None for v in vals):
            ik = tablecodec.index_key(info.id, idx.id, vals)
            existing = txn.get(ik)
            if existing is not None:
                other, _ = codec.decode_int(existing)
                if other != handle:
                    raise DupKeyError(
                        f"duplicate entry {vals} for key '{idx.name}'")
            txn.set(ik, codec.encode_int(handle))
        else:
            txn.set(tablecodec.index_key(info.id, idx.id, vals,
                                         handle=handle), b"0")
