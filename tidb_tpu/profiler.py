"""Kernel profiling plane: continuous compile/dispatch/roofline accounting.

ROADMAP item 5's roofline target was unverifiable from inside the server
— achieved-GB/s math and compile-cache attribution lived only in
bench.py — and item 3's self-tuning execution needs a per-digest record
of which mode ran and what it cost (perfschema.memo_record is the write
side; this module is the per-kernel substrate).

One `KernelProfileRegistry` keyed ``(family, plan fingerprint, mesh
fingerprint)`` — the exact key discipline of the executable caches it
shadows (hashagg._KERNELS, streamagg._SEG_KERNELS, fragment._FRAGMENTS,
executor/mesh._KERNELS, devplane.plane_jit), so a 1-chip and an 8-chip
profile for the same plan shape can never alias, and a cache-key
regression shows up as compile churn on exactly one registry row.

Feeds:
  * construction sites call `note_construct(prof, reuse=...)` — a fresh
    kernel object is one compile unit, an executable-LRU hit a reuse;
  * dispatch seams (`dispatch_section` at the copr sync sites,
    `sched.device_slot(profile=...)`, `pipeline_map(profile=...)`)
    record dispatch count, busy-ns and bytes. The FIRST dispatch of a
    freshly constructed kernel is where jax actually traces+compiles,
    so its wall time lands in `compile_ns`, and diffing the persistent
    compile-cache counters (util/compile_cache.py) around it attributes
    the compile: `miss` (compiled from scratch), `hit` (loaded from the
    persistent cache) or `cached` (served from jax's in-process
    executable cache — no persistent-cache event at all).

Roofline: the platform-peak table and achieved-GB/s math hoisted out of
bench.py so `roofline_fraction` is computed ONLINE per kernel family and
per statement (bytes / busy-ns against `platform_peak_gbps()`), surfaced
in EXPLAIN ANALYZE's `kernel` column, the slow log,
`information_schema.kernel_profile` / `cluster_kernel_profile` and
`GET /profile`.

Cost discipline: entries bill a fixed per-entry cost to a
`kernel-profile` memtrack SERVER node with a registered shed action
(GET /shed and the admission chain drop profile history before they
touch real work), the registry is a bounded true-LRU
(`tidb_tpu_kernel_profile_cap`), and with `tidb_tpu_kernel_profile=0`
every entry point is one config read (pinned <5us/statement by
tests/test_profiler.py, the trace discipline).
"""

from __future__ import annotations

import threading
import time

from tidb_tpu import config

__all__ = ["KernelProfile", "KernelProfileRegistry", "enabled",
           "profile", "profile_of", "note_construct", "note_dispatch",
           "note_busy", "note_bytes", "note_escalation",
           "note_kernel_fallback", "cc_probe", "dispatch_section",
           "snapshot", "stats", "registry", "platform_peak_gbps",
           "achieved_gbps", "roofline_fraction", "FAMILIES",
           "reset_for_tests"]

# the closed family vocabulary (also the {family} metric label set and
# the plane-size-invariance contract bench.py profile pins): every
# executable-cache construction site declares exactly one of these
FAMILIES = ("hashagg", "scalaragg", "streamagg", "fragment", "mesh",
            "plane")

# fixed per-entry billing against the kernel-profile SERVER node: a
# KernelProfile is ~15 ints + 3 short strings + a small fallback dict;
# billing a round figure keeps the ledger arithmetic auditable
_ENTRY_BYTES = 1024


class KernelProfile:
    """One (family, fingerprint, mesh) row. All mutation happens under
    the owning registry's lock; readers take snapshots there too."""

    __slots__ = ("family", "fingerprint", "mesh", "generation",
                 "compiles", "compile_ns", "compile_src",
                 "pcache_hits", "pcache_misses", "reuses",
                 "dispatches", "busy_ns", "bytes_in", "bytes_out",
                 "bytes_encoded", "bytes_decoded_equiv",
                 "escalations", "fallbacks", "last_used", "_fresh",
                 "epoch")

    def __init__(self, family: str, fingerprint: str, mesh: tuple,
                 generation: int):
        self.family = family
        self.fingerprint = fingerprint
        self.mesh = mesh
        self.generation = generation
        self.compiles = 0        # kernel objects constructed (LRU misses)
        self.compile_ns = 0      # first-dispatch wall (trace+compile+load)
        self.compile_src = ""    # attribution: hit | miss | cached | reuse
        self.pcache_hits = 0     # persistent-cache loads observed
        self.pcache_misses = 0   # persistent-cache compiles observed
        self.reuses = 0          # executable-LRU hits
        self.dispatches = 0
        self.busy_ns = 0         # dispatch+finalize wall attributed here
        self.bytes_in = 0        # dispatch_nbytes: padded upload + scratch
        self.bytes_out = 0       # result bytes where cheaply known
        self.bytes_encoded = 0   # actually staged (dict codes + validity)
        self.bytes_decoded_equiv = 0
        self.escalations = 0     # capacity re-plans inherited by the key
        self.fallbacks: dict[str, int] = {}   # reason -> count
        self.last_used = time.time()
        self._fresh = False      # next dispatch is the compile dispatch
        self.epoch = 0           # registry epoch at creation (staleness)

    def to_dict(self) -> dict:
        d = {"family": self.family, "fingerprint": self.fingerprint,
             "mesh": "-".join(str(p) for p in self.mesh),
             "generation": self.generation,
             "compiles": self.compiles, "compile_ns": self.compile_ns,
             "compile_cache": self.compile_src,
             "pcache_hits": self.pcache_hits,
             "pcache_misses": self.pcache_misses,
             "reuses": self.reuses, "dispatches": self.dispatches,
             "busy_ns": self.busy_ns, "bytes_in": self.bytes_in,
             "bytes_out": self.bytes_out,
             "bytes_encoded": self.bytes_encoded,
             "bytes_decoded_equiv": self.bytes_decoded_equiv,
             "escalations": self.escalations,
             "fallbacks": sum(self.fallbacks.values()),
             "fallback_reasons": dict(self.fallbacks),
             "last_used": self.last_used}
        gbps = achieved_gbps(self.bytes_in, self.busy_ns)
        d["achieved_gbps"] = None if gbps is None else round(gbps, 3)
        frac = roofline_fraction(self.bytes_in, self.busy_ns)
        d["roofline_fraction"] = None if frac is None else round(frac, 4)
        return d


class KernelProfileRegistry:
    """Bounded true-LRU of KernelProfile entries, billed to a
    `kernel-profile` memtrack SERVER node whose registered shed action
    drops the whole history (observability data: always safe to shed).
    Keys carry `devplane.mesh_fingerprint(process=True)`, so a topology
    change starts fresh rows instead of folding 8-chip dispatches into
    1-chip compile history."""

    def __init__(self):
        self._mu = threading.Lock()
        from collections import OrderedDict
        # key -> KernelProfile, true LRU order
        self._d: "OrderedDict[tuple, KernelProfile]" = OrderedDict()  # guarded-by: _mu
        self._node = None           # lazy memtrack server node
        self._evictions = 0         # guarded-by: _mu
        # bumped by clear(): kernels cache their profile object on
        # themselves (plan._kernel outlives any one statement), so after
        # a shed the seams must detect the orphan and re-register
        # instead of recording into an invisible row forever
        self._epoch = 0             # guarded-by: _mu (racy reads ok)

    # -- memtrack billing ----------------------------------------------------

    def _billing_node(self):
        """The kernel-profile SERVER ledger node, created on first use
        (import-time creation would bill an empty registry into every
        test's hygiene sweep). The shed action clears the registry —
        profile history is the cheapest thing a loaded server owns."""
        if self._node is None:
            from tidb_tpu import memtrack
            node = memtrack.server_node("kernel-profile")
            node.add_spill_action(self._shed)
            self._node = node
        return self._node

    def _shed(self) -> None:
        self.clear()

    def clear(self) -> None:
        with self._mu:
            n = len(self._d)
            self._d.clear()
            self._epoch += 1
        if n and self._node is not None:
            self._node.release(host=n * _ENTRY_BYTES)

    # -- entry resolution ----------------------------------------------------

    def get_or_create(self, family: str, fingerprint: str | None) \
            -> KernelProfile:
        from tidb_tpu import devplane
        fp = fingerprint if fingerprint is not None else "~"
        mesh = devplane.mesh_fingerprint(process=True)
        key = (family, fp, mesh)
        with self._mu:
            hit = self._d.get(key)
            if hit is not None:
                self._d.move_to_end(key)
                hit.last_used = time.time()
                return hit
        prof = KernelProfile(family, _short_fp(fp), mesh,
                             devplane.mesh_generation())
        node = self._billing_node()
        cap = config.kernel_profile_cap()
        evicted = 0
        with self._mu:
            prof.epoch = self._epoch
            cur = self._d.setdefault(key, prof)
            if cur is prof:             # we inserted: bill + bound
                self._d.move_to_end(key)
                while len(self._d) > cap:
                    old = next(iter(self._d))
                    if old == key:
                        break
                    self._d.pop(old)
                    evicted += 1
                    self._evictions += 1
        if cur is prof:
            # lint: exempt[paired-resource] ownership transfer: entry bytes release on LRU eviction (below) / shed / clear()
            node.consume(host=_ENTRY_BYTES)
        if evicted:
            node.release(host=evicted * _ENTRY_BYTES)
        return cur

    # -- recording (all under _mu; sites hold no other locks here) -----------

    def note_construct(self, prof: KernelProfile, reuse: bool) -> None:
        with self._mu:
            if reuse:
                prof.reuses += 1
            else:
                prof.compiles += 1
                prof._fresh = True
            prof.last_used = time.time()

    def record_dispatch(self, prof: KernelProfile, busy_ns: int,
                        nbytes: int, out_nbytes: int, encoded: int,
                        decoded: int, cc_before: tuple | None) -> bool:
        """Fold one completed dispatch; -> True when it was the entry's
        compile dispatch (the caller emits the compile histogram)."""
        from tidb_tpu.util import failpoint
        failpoint.eval("profiler/record", prof.family)
        compiled = False
        with self._mu:
            prof.dispatches += 1
            prof.busy_ns += busy_ns
            prof.bytes_in += nbytes
            prof.bytes_out += out_nbytes
            prof.bytes_encoded += encoded
            prof.bytes_decoded_equiv += decoded
            prof.last_used = time.time()
            if prof._fresh:
                prof._fresh = False
                compiled = True
                prof.compile_ns += busy_ns
                if cc_before is not None:
                    hits, misses = _compile_cache_counts()
                    dh = hits - cc_before[0]
                    dm = misses - cc_before[1]
                    prof.pcache_hits += max(dh, 0)
                    prof.pcache_misses += max(dm, 0)
                    prof.compile_src = "miss" if dm > 0 else \
                        ("hit" if dh > 0 else "cached")
                else:
                    prof.compile_src = "cached"
            elif not prof.compile_src:
                # executable predates this profile row (built before the
                # registry entry existed, e.g. re-registered after a shed)
                prof.compile_src = "reuse"
        return compiled

    def note_busy(self, prof: KernelProfile, ns: int) -> None:
        with self._mu:
            prof.busy_ns += ns

    def note_bytes(self, prof: KernelProfile, nbytes: int = 0,
                   out_nbytes: int = 0, encoded: int = 0,
                   decoded: int = 0) -> None:
        with self._mu:
            prof.bytes_in += nbytes
            prof.bytes_out += out_nbytes
            prof.bytes_encoded += encoded
            prof.bytes_decoded_equiv += decoded

    def note_escalation(self, prof: KernelProfile) -> None:
        with self._mu:
            prof.escalations += 1

    def note_fallback(self, prof: KernelProfile, reason: str) -> None:
        with self._mu:
            prof.fallbacks[reason] = prof.fallbacks.get(reason, 0) + 1

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> list[dict]:
        with self._mu:
            profs = list(self._d.values())
        return [p.to_dict() for p in profs]

    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._d),
                    "cap": config.kernel_profile_cap(),
                    "evictions": self._evictions,
                    "compiles": sum(p.compiles for p in self._d.values()),
                    "dispatches": sum(p.dispatches
                                      for p in self._d.values()),
                    "busy_ns": sum(p.busy_ns for p in self._d.values())}

    def __len__(self) -> int:
        with self._mu:
            return len(self._d)


def _short_fp(fp: str) -> str:
    """Registry rows carry a bounded fingerprint: plan fingerprints are
    structural reprs that can run long; 16 hex chars is identity enough
    for a profile surface (collisions merge rows, never crash)."""
    if len(fp) <= 16:
        return fp
    import hashlib
    return hashlib.sha256(fp.encode()).hexdigest()[:16]


def _compile_cache_counts() -> tuple[int, int]:
    from tidb_tpu.util import compile_cache
    s = compile_cache.counters()
    return s["hits"], s["misses"]


_REGISTRY = KernelProfileRegistry()


def registry() -> KernelProfileRegistry:
    return _REGISTRY


def enabled() -> bool:
    return config.kernel_profile()


def profile(family: str, fingerprint: str | None) \
        -> KernelProfile | None:
    """The profile entry for a kernel being constructed or dispatched,
    None when profiling is off — every note_* below is None-tolerant,
    so call sites stay one-liners with no gating of their own."""
    if not config.kernel_profile():
        return None
    return _REGISTRY.get_or_create(family, fingerprint)


def note_construct(prof: KernelProfile | None, reuse: bool) -> None:
    if prof is not None:
        _REGISTRY.note_construct(prof, reuse)


def note_dispatch(prof: KernelProfile | None, busy_ns: int,
                  nbytes: int = 0, out_nbytes: int = 0,
                  encoded: int = 0, decoded: int = 0,
                  plan=None, cc_before: tuple | None = None) -> None:
    """Fold one completed dispatch interval (the pipeline_map /
    device_slot seam form — dispatch_section below packages the timing
    and the compile-cache diff for the sync sites)."""
    if prof is None:
        return
    from tidb_tpu import metrics
    compiled = _REGISTRY.record_dispatch(prof, busy_ns, nbytes,
                                         out_nbytes, encoded, decoded,
                                         cc_before)
    metrics.counter(metrics.KERNEL_DISPATCHES, {"family": prof.family})
    if compiled:
        metrics.histogram(metrics.KERNEL_COMPILE_SECONDS, busy_ns / 1e9,
                          {"family": prof.family})
    if plan is not None:
        from tidb_tpu import runtime_stats
        runtime_stats.note_kernel(plan, prof.family, prof.compile_src,
                                  nbytes, busy_ns)


def note_busy(prof: KernelProfile | None, ns: int) -> None:
    if prof is not None:
        _REGISTRY.note_busy(prof, ns)


def cc_probe(prof: KernelProfile | None) -> tuple | None:
    """Persistent-cache counter snapshot, taken ONLY when `prof`'s next
    dispatch is its compile dispatch (racy _fresh read: worst case one
    wasted dict copy) — pipeline_map's cheap pre-dispatch hook."""
    if prof is not None and prof._fresh:
        return _compile_cache_counts()
    return None


def note_bytes(prof: KernelProfile | None, nbytes: int = 0,
               out_nbytes: int = 0, encoded: int = 0,
               decoded: int = 0) -> None:
    if prof is not None:
        _REGISTRY.note_bytes(prof, nbytes, out_nbytes, encoded, decoded)


def note_escalation(prof: KernelProfile | None) -> None:
    if prof is not None:
        _REGISTRY.note_escalation(prof)


def note_kernel_fallback(prof: KernelProfile | None,
                         reason: str) -> None:
    if prof is not None:
        _REGISTRY.note_fallback(prof, reason)


def profile_of(kernel) -> KernelProfile | None:
    """The profile a construction site attached to a kernel object
    (dispatch seams resolve through this so they need no key math).
    Kernels outlive statements (plan-attached, executable LRUs), so a
    registry clear — shed, test reset — orphans attached profiles; an
    epoch mismatch here re-registers under the same identity and
    reattaches, so history rebuilds instead of recording into an
    invisible row forever."""
    if not config.kernel_profile():
        return None
    prof = getattr(kernel, "_profile", None)
    if prof is None:
        return None
    if prof.epoch != _REGISTRY._epoch:
        prof = _REGISTRY.get_or_create(prof.family, prof.fingerprint)
        try:
            kernel._profile = prof
        except AttributeError:   # slotted/frozen kernel: resolve anew
            pass                 # next dispatch, same merged row
    return prof


class dispatch_section:
    """Time one synchronous dispatch+finalize interval against `prof`
    (None = disarmed no-op). SUCCESS-ONLY, matching
    runtime_stats.device_section(errors=False) at the same sites: a
    capacity/collision attempt re-runs through an escalated kernel
    whose own section records — double-billing the failed wall time
    would poison exactly the per-mode cost the memo exists to compare.
    Set `.out_nbytes` inside the block once the result size is known."""

    __slots__ = ("prof", "nbytes", "encoded", "decoded", "plan",
                 "out_nbytes", "_t0", "_cc")

    def __init__(self, prof: KernelProfile | None, nbytes: int = 0,
                 encoded: int = 0, decoded: int = 0, plan=None):
        self.prof = prof
        self.nbytes = nbytes
        self.encoded = encoded
        self.decoded = decoded
        self.plan = plan
        self.out_nbytes = 0
        self._t0 = 0
        self._cc = None

    def __enter__(self):
        if self.prof is not None:
            if self.prof._fresh:    # racy read: worst case a wasted diff
                self._cc = _compile_cache_counts()
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.prof is not None and exc_type is None:
            note_dispatch(self.prof, time.perf_counter_ns() - self._t0,
                          nbytes=self.nbytes,
                          out_nbytes=self.out_nbytes,
                          encoded=self.encoded, decoded=self.decoded,
                          plan=self.plan, cc_before=self._cc)
        return False


# -- roofline (hoisted from bench.py — ONE estimator for bench and the
# continuous in-server numbers) ---------------------------------------------

# HBM peak per chip family (public figures, GB/s); the CPU fallback
# measures its own memcpy bandwidth instead
HBM_PEAK_GBPS = {"TPU v2": 700.0, "TPU v3": 900.0, "TPU v4": 1228.0,
                 "TPU v5 lite": 819.0, "TPU v5e": 819.0,
                 "TPU v5p": 2765.0, "TPU v6 lite": 1640.0,
                 "TPU v6e": 1640.0}

_peak_lock = threading.Lock()
_peak: tuple[float, str] | None = None      # guarded-by: _peak_lock


def platform_peak_gbps() -> tuple[float, str]:
    """-> (peak memory GB/s, how it was obtained). On a chip: datasheet
    lookup by device kind. On CPU: measured big-buffer memcpy bandwidth,
    once per process (~100ms), cached — EXPLAIN ANALYZE's roofline cell
    must not re-pay the probe per statement."""
    global _peak
    with _peak_lock:
        if _peak is not None:
            return _peak
        _peak = _measure_peak()
        return _peak


def _measure_peak() -> tuple[float, str]:
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - no backend: measure host anyway
        kind = "cpu"
    if kind in HBM_PEAK_GBPS:
        return HBM_PEAK_GBPS[kind], f"datasheet({kind})"
    for k, v in HBM_PEAK_GBPS.items():
        if k.lower() in kind.lower():
            return v, f"datasheet({kind})"
    import numpy as np
    buf = np.empty(1 << 27, dtype=np.uint8)   # 128 MB
    t0 = time.perf_counter()
    for _ in range(3):
        buf2 = buf.copy()
    dt = time.perf_counter() - t0
    del buf2
    # copy reads + writes: 2 bytes moved per byte copied
    return (3 * 2 * buf.nbytes / dt) / 1e9, f"measured-memcpy({kind})"


def achieved_gbps(nbytes: int, busy_ns: int) -> float | None:
    """Bytes the device touched over the wall it was busy, in GB/s;
    None when either side is zero (no dispatch yet / timing off)."""
    if nbytes <= 0 or busy_ns <= 0:
        return None
    return (nbytes / (busy_ns / 1e9)) / 1e9


def roofline_fraction(nbytes: int, busy_ns: int) -> float | None:
    g = achieved_gbps(nbytes, busy_ns)
    if g is None:
        return None
    peak, _src = platform_peak_gbps()
    if peak <= 0:
        return None
    return g / peak


def snapshot() -> list[dict]:
    """Registry rows for information_schema.kernel_profile /
    GET /profile / member.local_state's cluster fan-out payload."""
    return _REGISTRY.snapshot()


def stats() -> dict:
    """Summary block for /status and the __main__ startup line."""
    out = _REGISTRY.stats()
    out["enabled"] = config.kernel_profile()
    return out


def reset_for_tests() -> None:
    """Drop all profile entries (and their billed bytes). The memtrack
    node and its shed registration survive — they are process-scoped,
    like the HBM cache's."""
    _REGISTRY.clear()
    global _peak
    with _peak_lock:
        _peak = None
