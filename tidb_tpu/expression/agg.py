"""Aggregate function descriptors and the partial/final protocol.

Reference: /root/reference/expression/aggregation/aggregation.go:32-47 —
`Aggregation` iface with Update/GetPartialResult enabling the partial-agg
(storage-side) / final-agg (root-side) split used for pushdown.

Here the same split is expressed as data, not control flow: each AggFunc
defines its partial-state columns and a merge rule, so storage workers (and
TPU mesh shards) produce partial-state chunks that any final aggregator —
numpy or a psum across a device mesh — can combine.

Partial states (all fixed-width, device-friendly):
    COUNT   -> [count:int64]                 merge: sum
    SUM     -> [sum, has:int64]              merge: sum, or
    AVG     -> [sum, count:int64]            merge: sum, sum
    MIN     -> [val, has:int64]              merge: min-where-has, or
    MAX     -> [val, has:int64]              merge: max-where-has, or
    FIRST   -> [val, has:int64]              merge: first-where-has
    BIT_AND/OR/XOR -> [val:int64]            merge: and/or/xor
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from tidb_tpu.expression.core import Expression
from tidb_tpu.sqltypes import (EvalType, FieldType, new_decimal_field,
                               new_double_field, new_int_field)

__all__ = ["AggFunc", "AggDesc"]


class AggFunc(Enum):
    COUNT = "count"
    SUM = "sum"
    AVG = "avg"
    MIN = "min"
    MAX = "max"
    FIRST_ROW = "firstrow"
    BIT_AND = "bit_and"
    BIT_OR = "bit_or"
    BIT_XOR = "bit_xor"
    GROUP_CONCAT = "group_concat"


@dataclass
class AggDesc:
    fn: AggFunc
    arg: Expression | None  # None for COUNT(*)
    distinct: bool = False
    name: str = ""
    sep: str = ","          # GROUP_CONCAT separator

    @property
    def result_ft(self) -> FieldType:
        if self.fn == AggFunc.COUNT:
            return new_int_field()
        if self.fn in (AggFunc.BIT_AND, AggFunc.BIT_OR, AggFunc.BIT_XOR):
            return new_int_field()
        if self.fn == AggFunc.GROUP_CONCAT:
            from tidb_tpu.sqltypes import new_string_field
            return new_string_field()
        aft = self.arg.ft
        if self.fn == AggFunc.AVG:
            if aft.eval_type == EvalType.DECIMAL:
                if aft.is_wide_decimal:
                    # wide lane is exact python ints: MySQL's +4 digits
                    return new_decimal_field(
                        flen=min(aft.flen + 4, 65),
                        frac=min(aft.frac + 4, 30))
                # MySQL: avg adds 4 frac digits; we cap at 8 for int64 headroom
                return new_decimal_field(frac=min(aft.frac + 4, 8))
            return new_double_field()
        if self.fn == AggFunc.SUM:
            if aft.eval_type == EvalType.INT:
                return new_int_field()  # departure: MySQL promotes to decimal
            if aft.eval_type == EvalType.DECIMAL:
                # SUM widens precision (MySQL: DECIMAL(p+22, s)); a wide
                # arg keeps the exact object lane
                return new_decimal_field(
                    flen=min(aft.flen + 22, 65) if aft.is_wide_decimal
                    else aft.flen,
                    frac=aft.frac)
            return new_double_field()
        return aft  # MIN/MAX/FIRST keep the arg type

    @property
    def partial_width(self) -> int:
        """Number of int64/float64 lanes in this function's partial state."""
        if self.fn in (AggFunc.COUNT, AggFunc.BIT_AND, AggFunc.BIT_OR,
                       AggFunc.BIT_XOR):
            return 1
        return 2

    def __repr__(self):
        a = repr(self.arg) if self.arg is not None else "*"
        d = "distinct " if self.distinct else ""
        return f"{self.fn.value}({d}{a})"
