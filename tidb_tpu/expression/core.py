"""Scalar expression trees with columnar evaluation.

Reference: /root/reference/expression/expression.go:35-75 (Expression iface
with 8 per-row EvalXxx methods) and expression/chunk_executor.go:29-100
(column-at-a-time driver that still dispatches row-scalar inside — the single
biggest CPU sink per SURVEY.md §3.2).

TPU-first redesign: every builtin is implemented ONCE as a whole-column
function generic over the array namespace `xp` (numpy on the host path,
jax.numpy under jit on the device path). Evaluating an expression over a
Chunk is a handful of fused array ops; under jit, XLA fuses the whole tree
into one kernel. NULLs ride as a parallel boolean validity array (Kleene
logic for AND/OR, propagate-null elsewhere), replacing the reference's
per-value null tags.

Decimal columns are scaled int64 (sqltypes); this module inserts the scale
management (rescale on add/compare, scale-add on multiply, promote to double
on divide) that the reference's MyDecimal does per value.
"""

from __future__ import annotations

import math

from dataclasses import dataclass
from enum import Enum
from typing import Any, Sequence

import numpy as np

from tidb_tpu.chunk import Chunk
from tidb_tpu.sqltypes import (EvalType, FieldType, TypeCode,
                               decimal_to_scaled, new_double_field,
                               new_int_field, np_dtype_for)

__all__ = ["Expression", "ColumnRef", "Constant", "ScalarFunc", "Op",
           "col", "const", "func", "and_all"]


class Op(Enum):
    # arithmetic
    PLUS = "+"; MINUS = "-"; MUL = "*"; DIV = "/"; INTDIV = "div"; MOD = "%"
    UNARY_MINUS = "neg"
    # comparison
    EQ = "="; NE = "!="; LT = "<"; LE = "<="; GT = ">"; GE = ">="
    NULLEQ = "<=>"
    # logic
    AND = "and"; OR = "or"; NOT = "not"; XOR = "xor"
    # bit (on int64 two's complement; MySQL's BIGINT UNSIGNED domain is
    # shown signed here — same bits, doc'd in DEVIATIONS.md)
    BIT_AND = "&"; BIT_OR = "|"; BIT_XOR = "^"; SHL = "<<"; SHR = ">>"
    BIT_NEG = "~"
    # null tests
    IS_NULL = "isnull"; IS_NOT_NULL = "isnotnull"
    # membership / pattern
    IN = "in"; LIKE = "like"
    # control
    IF = "if"; IFNULL = "ifnull"; CASE = "case"; COALESCE = "coalesce"
    # math
    ABS = "abs"; CEIL = "ceil"; FLOOR = "floor"; ROUND = "round"
    POW = "pow"; SQRT = "sqrt"; EXP = "exp"; LN = "ln"; LOG2 = "log2"
    SIGN = "sign"
    # string (host-only)
    CONCAT = "concat"; LENGTH = "length"; UPPER = "upper"; LOWER = "lower"
    SUBSTRING = "substring"; TRIM = "trim"; LEFT = "left"; RIGHT = "right"
    REPLACE = "replace"; INSTR = "instr"; ASCII = "ascii"
    # date/time (on epoch-micros int64)
    YEAR = "year"; MONTH = "month"; DAY = "day"; HOUR = "hour"
    MINUTE = "minute"; SECOND = "second"
    DATE_ADD_DAYS = "date_add_days"; DATE_SUB_DAYS = "date_sub_days"
    DATEDIFF = "datediff"
    DATE_ADD_US = "date_add_us"     # fixed-width units as one micros delta
    ADD_MONTHS = "add_months"       # calendar-exact, day-clamping
    # cast
    CAST_INT = "cast_int"; CAST_REAL = "cast_real"; CAST_DECIMAL = "cast_decimal"
    CAST_STRING = "cast_string"
    # registry-dispatched long-tail builtins (expression/builtins.py);
    # extra = FnSpec
    GENERIC = "generic"


class Expression:
    """Base class. `ft` is the result FieldType."""

    ft: FieldType

    # -- evaluation ----------------------------------------------------------

    def eval(self, chunk: Chunk) -> tuple[np.ndarray, np.ndarray]:
        """Host path: returns (data, valid) numpy arrays of len(chunk)."""
        cols = [(c.data, c.valid) for c in chunk.columns]
        return self.eval_xp(np, cols, chunk.num_rows)

    def eval_xp(self, xp, cols: Sequence[tuple], n: int) -> tuple[Any, Any]:
        """Generic path: `cols[i]` is (data, valid) arrays in namespace xp.
        Under jax tracing, xp is jax.numpy and arrays are tracers."""
        raise NotImplementedError

    # -- structure -----------------------------------------------------------

    def columns_used(self) -> set[int]:
        raise NotImplementedError

    def map_columns(self, mapping: dict[int, int]) -> "Expression":
        """Rewrite column indices (for projection pushdown)."""
        raise NotImplementedError

    def is_device_safe(self) -> bool:
        """True if the whole tree can run under jax (no varlen string ops)."""
        raise NotImplementedError

    # -- sugar ---------------------------------------------------------------

    def __add__(self, o): return func(Op.PLUS, self, _wrap(o))
    def __sub__(self, o): return func(Op.MINUS, self, _wrap(o))
    def __mul__(self, o): return func(Op.MUL, self, _wrap(o))
    def __truediv__(self, o): return func(Op.DIV, self, _wrap(o))
    def __neg__(self): return func(Op.UNARY_MINUS, self)

    def eq(self, o): return func(Op.EQ, self, _wrap(o))
    def ne(self, o): return func(Op.NE, self, _wrap(o))
    def lt(self, o): return func(Op.LT, self, _wrap(o))
    def le(self, o): return func(Op.LE, self, _wrap(o))
    def gt(self, o): return func(Op.GT, self, _wrap(o))
    def ge(self, o): return func(Op.GE, self, _wrap(o))


def _wrap(v) -> "Expression":
    return v if isinstance(v, Expression) else const(v)


@dataclass
class ColumnRef(Expression):
    idx: int
    ft: FieldType
    name: str = ""

    def eval_xp(self, xp, cols, n):
        return cols[self.idx]

    def columns_used(self):
        return {self.idx}

    def map_columns(self, mapping):
        return ColumnRef(mapping[self.idx], self.ft, self.name)

    def is_device_safe(self):
        return self.ft.fixed_width

    def __repr__(self):
        return self.name or f"col#{self.idx}"

    def __hash__(self):
        return hash(("col", self.idx))


class CorrelatedCol(Expression):
    """A reference to an OUTER query's column inside a subquery plan
    (ref: expression.CorrelatedColumn — a column whose value is bound per
    outer row by the apply executor). `cell` is the shared mutable
    [value, valid] slot the ApplyExec writes before each inner run; values
    live in the chunk layer (raw int64/float64/str; decimals scaled)."""

    def __init__(self, ft: FieldType, name: str = ""):
        self.ft = ft
        self.name = name
        self.cell = [None, False]

    def eval_xp(self, xp, cols, n):
        import numpy as _np
        v, valid = self.cell
        dtype = np_dtype_for(self.ft.tp, self.ft.flen)
        if not valid:
            data = _np.zeros(n, dtype=dtype) if dtype != _np.dtype(object) \
                else _np.full(n, "", dtype=object)
            return xp.asarray(data) if dtype != _np.dtype(object) else data, \
                (xp.zeros(n, dtype=bool) if xp is not _np
                 else _np.zeros(n, dtype=bool))
        if dtype == _np.dtype(object):
            return _np.full(n, v, dtype=object), _np.ones(n, dtype=bool)
        data = _np.full(n, v, dtype=dtype)
        return xp.asarray(data), (xp.ones(n, dtype=bool) if xp is not _np
                                  else _np.ones(n, dtype=bool))

    def columns_used(self):
        return set()            # references the OUTER plan, not this one

    def map_columns(self, mapping):
        return self

    def is_device_safe(self):
        return False            # rebound per outer row: host path only

    def __repr__(self):
        return f"corr({self.name or '?'})"


@dataclass
class Constant(Expression):
    value: Any
    ft: FieldType

    def eval_xp(self, xp, cols, n):
        if self.value is None:
            return xp.zeros(n, dtype=np.int64), xp.zeros(n, dtype=bool)
        v = self.value
        if self.ft.tp == TypeCode.NEWDECIMAL:
            v = decimal_to_scaled(v, self.ft.frac,
                                  wide=self.ft.is_wide_decimal)
        dtype = np_dtype_for(self.ft.tp, self.ft.flen)
        if dtype == np.dtype(object):
            data = np.full(n, v, dtype=object)  # host-only
            return data, np.ones(n, dtype=bool)
        return xp.full(n, v, dtype=dtype), xp.ones(n, dtype=bool)

    def columns_used(self):
        return set()

    def map_columns(self, mapping):
        return self

    def is_device_safe(self):
        return self.ft.fixed_width

    def __repr__(self):
        return repr(self.value)

    def __hash__(self):
        return hash(("const", str(self.value)))


def const(v, ft: FieldType | None = None) -> Constant:
    import decimal as _d
    import datetime as _dt
    from tidb_tpu import sqltypes as st
    if ft is None:
        if v is None:
            ft = new_int_field()
        elif isinstance(v, bool):
            v, ft = int(v), new_int_field()
        elif isinstance(v, (int, np.integer)):
            if not (-(1 << 63) <= int(v) < (1 << 63)):
                # beyond BIGINT: promote to wide DECIMAL like MySQL —
                # exact against wide-decimal columns; comparisons vs
                # int columns still fold in _fold_huge_int_cmp
                import decimal as _d2
                v = _d2.Decimal(int(v))
                ft = st.new_decimal_field(
                    flen=len(v.as_tuple().digits), frac=0)
            else:
                ft = new_int_field()
        elif isinstance(v, (float, np.floating)):
            ft = new_double_field()
        elif isinstance(v, _d.Decimal):
            t = v.as_tuple()
            frac = max(0, -t.exponent)
            # magnitude digits: positive exponents (1E+30) add width
            digits = len(t.digits) + max(t.exponent, 0)
            ft = st.new_decimal_field(flen=max(digits, 15), frac=frac)
        elif isinstance(v, str):
            ft = st.new_string_field()
        elif isinstance(v, _dt.datetime):
            ft, v = st.new_datetime_field(), st.datetime_to_micros(v)
        elif isinstance(v, _dt.date):
            ft, v = st.new_date_field(), st.date_to_micros(v)
        else:
            raise TypeError(f"cannot infer type of constant {v!r}")
    return Constant(v, ft)


def col(idx: int, ft: FieldType, name: str = "") -> ColumnRef:
    return ColumnRef(idx, ft, name)


# ---------------------------------------------------------------------------
# ScalarFunc

_ARITH = {Op.PLUS, Op.MINUS, Op.MUL, Op.DIV, Op.INTDIV, Op.MOD}
_BIT = {Op.BIT_AND, Op.BIT_OR, Op.BIT_XOR, Op.SHL, Op.SHR, Op.BIT_NEG}
_CMP = {Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.NULLEQ}
_LOGIC = {Op.AND, Op.OR, Op.NOT, Op.XOR}
_STRING_OPS = {Op.CONCAT, Op.LENGTH, Op.UPPER, Op.LOWER, Op.SUBSTRING,
               Op.TRIM, Op.LEFT, Op.RIGHT, Op.REPLACE, Op.INSTR, Op.ASCII,
               Op.LIKE}
_MATH = {Op.ABS, Op.CEIL, Op.FLOOR, Op.ROUND, Op.POW, Op.SQRT, Op.EXP,
         Op.LN, Op.LOG2, Op.SIGN}
_TIME_OPS = {Op.YEAR, Op.MONTH, Op.DAY, Op.HOUR, Op.MINUTE, Op.SECOND,
             Op.DATE_ADD_DAYS, Op.DATE_SUB_DAYS, Op.DATEDIFF,
             Op.DATE_ADD_US, Op.ADD_MONTHS}
_DATE_SHIFT = {Op.DATE_ADD_DAYS, Op.DATE_SUB_DAYS, Op.DATE_ADD_US,
               Op.ADD_MONTHS}

_MAX_DEC_FRAC = 9  # cap result frac on multiply to bound int64 range


class ScalarFunc(Expression):
    def __init__(self, op: Op, args: Sequence[Expression], extra: Any = None):
        self.op = op
        self.args = list(args)
        self.extra = extra  # e.g. IN value list, LIKE pattern, cast target ft
        self.ft = self._infer_type()

    # -- typing --------------------------------------------------------------

    def _infer_type(self) -> FieldType:
        op = self.op
        if op == Op.GENERIC:
            return self.extra.result_ft(self.args)
        if op in _CMP or op in _LOGIC or op in (Op.IS_NULL, Op.IS_NOT_NULL,
                                                Op.IN, Op.LIKE):
            return new_int_field()
        if op in (Op.LENGTH, Op.INSTR, Op.ASCII) or \
                op in _TIME_OPS and op not in _DATE_SHIFT:
            return new_int_field()
        if op in _DATE_SHIFT:
            return self.args[0].ft
        if op == Op.CAST_INT:
            return new_int_field()
        if op == Op.CAST_REAL:
            return new_double_field()
        if op == Op.CAST_DECIMAL:
            return self.extra
        if op == Op.CAST_STRING:
            from tidb_tpu.sqltypes import new_string_field
            return new_string_field()
        if op in (Op.CONCAT, Op.UPPER, Op.LOWER, Op.SUBSTRING, Op.TRIM,
                  Op.LEFT, Op.RIGHT, Op.REPLACE):
            from tidb_tpu.sqltypes import new_string_field
            return new_string_field()
        if op in (Op.SQRT, Op.EXP, Op.LN, Op.LOG2, Op.POW):
            return new_double_field()
        if op == Op.UNARY_MINUS or op in (Op.ABS, Op.SIGN, Op.CEIL, Op.FLOOR,
                                          Op.ROUND):
            base = self.args[0].ft
            if op in (Op.CEIL, Op.FLOOR) and base.eval_type != EvalType.INT:
                return new_int_field() if base.eval_type == EvalType.DECIMAL else base
            return base
        if op in (Op.IF,):
            return self._merge_types(self.args[1:])
        if op in (Op.IFNULL, Op.COALESCE, Op.CASE):
            if op == Op.CASE:
                # args: [cond1, val1, cond2, val2, ..., else?]
                vals = [self.args[i] for i in range(1, len(self.args), 2)]
                if len(self.args) % 2 == 1:
                    vals.append(self.args[-1])
                return self._merge_types(vals)
            return self._merge_types(self.args)
        if op in _ARITH:
            return self._arith_type()
        if op in _BIT:
            return new_int_field()
        raise ValueError(f"cannot type op {op}")

    def _merge_types(self, exprs) -> FieldType:
        ets = [e.ft.eval_type for e in exprs]
        if EvalType.STRING in ets:
            from tidb_tpu.sqltypes import new_string_field
            return new_string_field()
        if EvalType.REAL in ets:
            return new_double_field()
        if EvalType.DECIMAL in ets:
            frac = max(e.ft.frac for e in exprs if e.ft.eval_type == EvalType.DECIMAL)
            from tidb_tpu.sqltypes import new_decimal_field
            return new_decimal_field(frac=frac)
        if EvalType.DATETIME in ets:
            return exprs[0].ft
        return new_int_field()

    def _arith_type(self) -> FieldType:
        from tidb_tpu.sqltypes import new_decimal_field
        a = self.args[0].ft
        b = self.args[1].ft if len(self.args) > 1 else a
        ea, eb = a.eval_type, b.eval_type
        if self.op == Op.DIV:
            return new_double_field()  # departure from MySQL decimal-div; doc'd
        if self.op == Op.INTDIV:
            return new_int_field()
        if EvalType.REAL in (ea, eb):
            return new_double_field()
        if EvalType.DECIMAL in (ea, eb):
            fa = a.frac if ea == EvalType.DECIMAL else 0
            fb = b.frac if eb == EvalType.DECIMAL else 0
            # a WIDE argument makes the result wide (exact bignum lane:
            # 25-digit * 28-digit literals must not squeeze into int64);
            # all-narrow chains stay on the int64 device lane
            any_wide = a.is_wide_decimal or b.is_wide_decimal
            la = a.flen if ea == EvalType.DECIMAL and a.flen > 0 else 19
            lb = b.flen if eb == EvalType.DECIMAL and b.flen > 0 else 19
            if self.op == Op.MUL:
                if any_wide:
                    return new_decimal_field(flen=min(la + lb, 65),
                                             frac=min(fa + fb, 30))
                return new_decimal_field(
                    frac=min(fa + fb, _MAX_DEC_FRAC))
            if any_wide:
                return new_decimal_field(flen=min(max(la, lb) + 1, 65),
                                         frac=max(fa, fb))
            return new_decimal_field(frac=max(fa, fb))
        if EvalType.DATETIME in (ea, eb):
            return new_int_field()
        return new_int_field()

    # -- evaluation ----------------------------------------------------------

    def eval_xp(self, xp, cols, n):
        op = self.op
        if op in _CMP:
            folded = self._fold_huge_int_cmp(xp, cols, n)
            if folded is not None:
                return folded
        argv = [a.eval_xp(xp, cols, n) for a in self.args]

        if op == Op.GENERIC:
            if xp is not np:
                raise RuntimeError(
                    f"builtin {self.extra.name} is host-only")
            return self.extra.fn(self.args, argv, n)
        if op in _LOGIC:
            return _eval_logic(xp, op, argv, n)
        if op == Op.IS_NULL:
            d, v = argv[0]
            return (~v).astype(np.int64) if xp is np else xp.asarray(~v, dtype=np.int64), _ones(xp, n)
        if op == Op.IS_NOT_NULL:
            d, v = argv[0]
            return v.astype(np.int64) if xp is np else xp.asarray(v, dtype=np.int64), _ones(xp, n)
        if op == Op.IN:
            return self._eval_in(xp, argv, n)
        if op in _STRING_OPS:
            if xp is not np:
                raise RuntimeError(f"string op {op} is host-only")
            return _eval_string(self, argv, n)
        if op in (Op.IF, Op.IFNULL, Op.COALESCE, Op.CASE):
            return self._eval_control(xp, argv, n)

        # numeric family: unify operand representation first
        datas, valids = zip(*argv) if argv else ((), ())
        valid = _and_valid(xp, valids, n)
        if op in _ARITH or op in _MATH or op in _BIT or \
                op == Op.UNARY_MINUS:
            # ENUM in numeric context evaluates as its 1-based member
            # index (MySQL: c + 0 -> ordinal)
            datas = [_enum_ordinals(a.ft, d)
                     for a, d in zip(self.args, datas)]

        if op in _CMP:
            d = _eval_cmp(xp, op, self.args, datas)
            if op == Op.NULLEQ:
                both_null = ~argv[0][1] & ~argv[1][1]
                d = xp.where(both_null, xp.ones_like(d), xp.where(
                    argv[0][1] & argv[1][1], d, xp.zeros_like(d)))
                return d, _ones(xp, n)
            return d, valid
        if op in _ARITH or op == Op.UNARY_MINUS:
            return _eval_arith(xp, op, self, datas, valid)
        if op in _BIT:
            return _eval_bit(xp, op, self, datas, valid)
        if op in _MATH:
            return _eval_math(xp, op, self, datas, valid)
        if op in _TIME_OPS:
            return _eval_time(xp, op, self, datas, valid)
        if op in (Op.CAST_INT, Op.CAST_REAL, Op.CAST_DECIMAL, Op.CAST_STRING):
            return _eval_cast(xp, op, self, argv, n)
        raise NotImplementedError(f"op {op}")

    def _fold_huge_int_cmp(self, xp, cols, n):
        """Comparing an int64-domain column with a constant beyond the
        int64 range: the truth value is known exactly (the constant is
        strictly outside every possible column value), while a numeric
        evaluation would wrap or lose precision at the boundary."""
        if len(self.args) != 2:
            return None
        i64_max, i64_min = (1 << 63) - 1, -(1 << 63)
        for c_expr, o_expr, c_on_left in ((self.args[1], self.args[0], False),
                                          (self.args[0], self.args[1], True)):
            if not (isinstance(c_expr, Constant) and
                    isinstance(c_expr.value, (int, float)) and
                    not isinstance(c_expr.value, bool)):
                continue
            v = c_expr.value
            if i64_min <= v <= i64_max:
                continue
            if o_expr.ft.eval_type not in (EvalType.INT, EvalType.DATETIME):
                continue
            op = self.op
            if c_on_left:   # const op col  ==  col flipped(op) const
                op = {Op.LT: Op.GT, Op.LE: Op.GE, Op.GT: Op.LT,
                      Op.GE: Op.LE}.get(op, op)
            above = v > i64_max        # else: below int64 min
            truth = {Op.LT: above, Op.LE: above, Op.GT: not above,
                     Op.GE: not above, Op.EQ: False, Op.NULLEQ: False,
                     Op.NE: True}[op]
            _, valid = o_expr.eval_xp(xp, cols, n)
            data = xp.full(n, 1 if truth else 0, dtype=np.int64)
            if op == Op.NULLEQ:
                return data, _ones(xp, n)
            return data, valid
        return None

    def _eval_in(self, xp, argv, n):
        d, v = argv[0]
        vals = self.extra  # list of python constants (already repr-converted)
        arg_ft = self.args[0].ft
        conv = []
        for c in vals:
            if arg_ft.tp == TypeCode.NEWDECIMAL:
                c = decimal_to_scaled(c, arg_ft.frac)
            conv.append(c)
        if arg_ft.eval_type == EvalType.STRING:
            if xp is not np:
                raise RuntimeError("string IN is host-only")
            if arg_ft.is_ci:
                from tidb_tpu.sqltypes import collation_key, fold_column
                d = fold_column(d)
                conv = [collation_key(c) for c in conv]
            if arg_ft.collation == "binary":
                # UNHEX(col) IN ('A', ...): lift bytes for np.isin
                from tidb_tpu.sqltypes import bytes_to_str
                d = _debinarize(d)
                conv = [bytes_to_str(c) if isinstance(c, (bytes, bytearray))
                        else c for c in conv]
            out = np.isin(d, np.array(conv, dtype=object))
            return out.astype(np.int64), v
        acc = xp.zeros(n, dtype=bool)
        for c in conv:
            acc = acc | (d == c)
        return acc.astype(np.int64) if xp is np else xp.asarray(acc, np.int64), v

    def _eval_control(self, xp, argv, n):
        op = self.op
        if op == Op.IF:
            (cd, cv), (ad, av), (bd, bv) = argv
            cond = cv & (cd != 0)
            ad, bd = _common_numeric(xp, self, [self.args[1], self.args[2]], [ad, bd])
            return xp.where(cond, ad, bd), xp.where(cond, av, bv)
        if op == Op.IFNULL:
            (ad, av), (bd, bv) = argv
            ad, bd = _common_numeric(xp, self, self.args, [ad, bd])
            return xp.where(av, ad, bd), av | bv
        if op == Op.COALESCE:
            datas = _common_numeric(xp, self, self.args, [a[0] for a in argv])
            out_d, out_v = datas[-1], argv[-1][1]
            for (_, av), ad in zip(reversed(argv[:-1]), reversed(datas[:-1])):
                out_d = xp.where(av, ad, out_d)
                out_v = av | out_v
            return out_d, out_v
        # CASE: [c1, v1, c2, v2, ..., else?]
        pairs = []
        i = 0
        while i + 1 < len(argv):
            pairs.append((argv[i], argv[i + 1], self.args[i + 1]))
            i += 2
        has_else = len(argv) % 2 == 1
        vexprs = [p[2] for p in pairs] + ([self.args[-1]] if has_else else [])
        vdatas = _common_numeric(xp, self, vexprs,
                                 [p[1][0] for p in pairs] +
                                 ([argv[-1][0]] if has_else else []))
        if has_else:
            out_d, out_v = vdatas[-1], argv[-1][1]
        else:
            out_d = xp.zeros(n, dtype=vdatas[0].dtype)
            out_v = xp.zeros(n, dtype=bool)
        for k in range(len(pairs) - 1, -1, -1):
            (cd, cv), (vd_, vv), _ = pairs[k]
            cond = cv & (cd != 0)
            out_d = xp.where(cond, vdatas[k], out_d)
            out_v = xp.where(cond, vv, out_v)
        return out_d, out_v

    # -- structure -----------------------------------------------------------

    def columns_used(self):
        s = set()
        for a in self.args:
            s |= a.columns_used()
        return s

    def map_columns(self, mapping):
        f = ScalarFunc.__new__(ScalarFunc)
        f.op = self.op
        f.args = [a.map_columns(mapping) for a in self.args]
        f.extra = self.extra
        f.ft = self.ft
        return f

    def is_device_safe(self):
        if self.op == Op.GENERIC:
            return False
        if self.op in _STRING_OPS or self.op == Op.CAST_STRING:
            return False
        if self.op == Op.IN and self.args[0].ft.eval_type == EvalType.STRING:
            return False
        return all(a.is_device_safe() for a in self.args)

    def __repr__(self):
        return f"{self.op.value}({', '.join(map(repr, self.args))})"

    def __hash__(self):
        return hash((self.op, tuple(hash(a) for a in self.args)))


def func(op: Op, *args, extra=None) -> ScalarFunc:
    return ScalarFunc(op, [_wrap(a) for a in args], extra=extra)


def and_all(exprs: Sequence[Expression]) -> Expression | None:
    exprs = list(exprs)
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = func(Op.AND, out, e)
    return out


# ---------------------------------------------------------------------------
# evaluation helpers (generic over xp = numpy | jax.numpy)

def _ones(xp, n):
    return xp.ones(n, dtype=bool)


def _and_valid(xp, valids, n):
    if not valids:
        return _ones(xp, n)
    out = valids[0]
    for v in valids[1:]:
        out = out & v
    return out


def _to_real(xp, ft: FieldType, d):
    """Convert a column's device repr to float64."""
    if ft.eval_type == EvalType.DECIMAL:
        return xp.asarray(d, dtype=np.float64) / (10.0 ** ft.frac)
    return xp.asarray(d, dtype=np.float64)


def _rescale(xp, d, from_frac: int, to_frac: int):
    if to_frac == from_frac:
        return d
    if to_frac > from_frac:
        return d * (10 ** (to_frac - from_frac))
    # downscale rounds half away from zero (MySQL decimal rounding)
    p = 10 ** (from_frac - to_frac)
    half = p // 2
    return xp.where(d >= 0, (d + half) // p, -((-d + half) // p))


def _common_numeric(xp, parent: "ScalarFunc", exprs, datas):
    """Bring operand arrays to the parent's result representation."""
    ft = parent.ft
    out = []
    for e, d in zip(exprs, datas):
        if d.dtype == np.dtype(object):
            out.append(d)
            continue
        if ft.eval_type == EvalType.REAL:
            out.append(_to_real(xp, e.ft, d))
        elif ft.eval_type == EvalType.DECIMAL:
            ef = e.ft.frac if e.ft.eval_type == EvalType.DECIMAL else 0
            if e.ft.eval_type == EvalType.REAL:
                out.append(xp.asarray(xp.round(d * (10 ** ft.frac)), dtype=np.int64))
            else:
                out.append(_rescale(xp, xp.asarray(d, dtype=np.int64), ef, ft.frac))
        else:
            out.append(xp.asarray(d, dtype=np.int64) if d.dtype != np.float64
                       else d)
    return out


def _eval_logic(xp, op, argv, n):
    if op == Op.NOT:
        d, v = argv[0]
        return xp.where(d != 0, 0, 1).astype(np.int64) if xp is np else \
            xp.asarray(xp.where(d != 0, 0, 1), np.int64), v
    (ad, av), (bd, bv) = argv
    at = av & (ad != 0)   # definitely true
    af = av & (ad == 0)   # definitely false
    bt = bv & (bd != 0)
    bf = bv & (bd == 0)
    if op == Op.AND:
        # Kleene: false if either false; null if any null (and none false)
        res_false = af | bf
        res_true = at & bt
        valid = res_false | (av & bv)
        d = xp.where(res_true, 1, 0)
        return xp.asarray(d, np.int64), valid
    if op == Op.OR:
        res_true = at | bt
        res_false = af & bf
        valid = res_true | (av & bv)
        d = xp.where(res_true, 1, 0)
        return xp.asarray(d, np.int64), valid
    # XOR: null if any null
    d = xp.asarray((at ^ bt), np.int64)
    return d, av & bv


def _enum_ordinals(ft: FieldType, d):
    """ENUM object column -> int64 1-based member indexes (0 for the
    empty/invalid member). Identity for everything else."""
    if ft.tp != TypeCode.ENUM or \
            getattr(d, "dtype", None) != np.dtype(object):
        return d
    elems = [str(e).lower() for e in ft.elems]
    out = np.zeros(len(d), dtype=np.int64)
    for i, x in enumerate(d):
        if x is None or x == "":
            continue
        try:
            out[i] = elems.index(str(x).lower()) + 1
        except ValueError:
            pass
    return out


def _debinarize(arr):
    """Replace bytes elements of an object array with latin-1 strings
    (identity on code points 0-255, so byte ordering is preserved)."""
    if getattr(arr, "dtype", None) != np.dtype(object):
        return arr
    out = None
    for i, v in enumerate(arr):
        if isinstance(v, (bytes, bytearray)):
            if out is None:
                out = arr.copy()
            out[i] = bytes(v).decode("latin-1")
    return out if out is not None else arr


def _cmp_operands(xp, args, datas):
    """Bring two compare operands to a common numeric/string representation."""
    a, b = args[0].ft, args[1].ft
    da, db = datas
    if da.dtype == np.dtype(object) or db.dtype == np.dtype(object):
        ea, eb = a.eval_type, b.eval_type
        # ENUM vs number compares by member index (MySQL: c = 2 matches
        # the second member)
        def _num_side(ft_n, d_n):
            if ft_n.eval_type == EvalType.DECIMAL:
                return d_n.astype(np.float64) / (10.0 ** ft_n.frac)
            return d_n
        if a.tp == TypeCode.ENUM and eb != EvalType.STRING and \
                b.tp != TypeCode.ENUM:
            return _enum_ordinals(a, da), _num_side(b, db)
        if b.tp == TypeCode.ENUM and ea != EvalType.STRING and \
                a.tp != TypeCode.ENUM:
            return _num_side(a, da), _enum_ordinals(b, db)
        if EvalType.DECIMAL in (ea, eb) and \
                EvalType.STRING not in (ea, eb):
            # wide-decimal lane: python-int math, exact at any precision
            fa = a.frac if ea == EvalType.DECIMAL else 0
            fb = b.frac if eb == EvalType.DECIMAL else 0
            if EvalType.REAL in (ea, eb):
                ca = da.astype(np.float64) / (10.0 ** fa)
                cb = db.astype(np.float64) / (10.0 ** fb)
                return ca, cb
            f = max(fa, fb)

            def widen(d, fr):
                if fr == f:
                    return d.astype(object)
                return d.astype(object) * (10 ** (f - fr))
            return widen(da, fa), widen(db, fb)
        if a.is_ci or b.is_ci:
            # _ci collation: compare casefolded keys (MySQL resolves a
            # ci column vs a literal to the column's collation)
            from tidb_tpu.sqltypes import fold_column
            if da.dtype == np.dtype(object):
                da = fold_column(da)
            if db.dtype == np.dtype(object):
                db = fold_column(db)
        # VARBINARY (e.g. UNHEX output) vs str: lift bytes to latin-1
        # str so python's '<' is total; latin-1 preserves byte order.
        # Gated on the binary collation marker so plain str columns
        # skip the per-element scan
        if a.collation == "binary" or b.collation == "binary":
            return _debinarize(da), _debinarize(db)
        return da, db
    ea, eb = a.eval_type, b.eval_type
    if EvalType.REAL in (ea, eb):
        return _to_real(xp, a, da), _to_real(xp, b, db)
    if EvalType.DECIMAL in (ea, eb):
        fa = a.frac if ea == EvalType.DECIMAL else 0
        fb = b.frac if eb == EvalType.DECIMAL else 0
        f = max(fa, fb)
        return _rescale(xp, da, fa, f), _rescale(xp, db, fb, f)
    return da, db


def _eval_cmp(xp, op, args, datas):
    da, db = _cmp_operands(xp, args, datas)
    if op in (Op.EQ, Op.NULLEQ):
        r = da == db
    elif op == Op.NE:
        r = da != db
    elif op == Op.LT:
        r = da < db
    elif op == Op.LE:
        r = da <= db
    elif op == Op.GT:
        r = da > db
    else:
        r = da >= db
    if r.dtype == np.dtype(object) or r.dtype == bool:
        return np.asarray(r, dtype=np.int64) if xp is np else xp.asarray(r, np.int64)
    return xp.asarray(r, np.int64)


def _eval_arith(xp, op, f: ScalarFunc, datas, valid):
    ft = f.ft
    if op == Op.UNARY_MINUS:
        return -datas[0], valid
    a, b = f.args[0].ft, f.args[1].ft
    da, db = datas
    if op == Op.DIV:
        da, db = _to_real(xp, a, da), _to_real(xp, b, db)
        valid = valid & (db != 0.0)   # MySQL: x/0 -> NULL
        safe = xp.where(db == 0.0, 1.0, db)
        return da / safe, valid
    if op == Op.INTDIV:
        if a.eval_type == EvalType.INT and b.eval_type == EvalType.INT:
            valid = valid & (db != 0)
            safe = xp.where(db == 0, 1, db)
            # MySQL DIV truncates toward zero; // floors. Exact int fixup.
            q = da // safe
            m = da - q * safe
            q = xp.where((m != 0) & ((da < 0) != (safe < 0)), q + 1, q)
            return q, valid
        da, db = _to_real(xp, a, da), _to_real(xp, b, db)
        valid = valid & (db != 0.0)
        safe = xp.where(db == 0.0, 1.0, db)
        return xp.asarray(xp.trunc(da / safe), np.int64), valid
    if op == Op.MOD:
        valid = valid & (db != 0)
        safe = xp.where(db == 0, 1, db)
        if ft.eval_type == EvalType.REAL:
            da, db = _to_real(xp, a, da), _to_real(xp, b, safe)
            return xp.asarray(da - db * xp.trunc(da / db)), valid
        if ft.eval_type == EvalType.DECIMAL:
            fa = a.frac if a.eval_type == EvalType.DECIMAL else 0
            fb = b.frac if b.eval_type == EvalType.DECIMAL else 0
            tf = max(fa, fb)
            da = _rescale(xp, xp.asarray(da, np.int64), fa, tf)
            safe = _rescale(xp, xp.asarray(safe, np.int64), fb, tf)
            safe = xp.where(safe == 0, 1, safe)
        # truncated (C-style) mod, exact int arithmetic: MySQL sign semantics
        m = da - (da // safe) * safe          # floored mod (sign of divisor)
        m = xp.where((m != 0) & ((m < 0) != (da < 0)), m - safe, m)
        return m, valid
    if ft.eval_type == EvalType.REAL:
        da, db = _to_real(xp, a, da), _to_real(xp, b, db)
        return (da + db if op == Op.PLUS else da - db if op == Op.MINUS else da * db), valid
    if ft.eval_type == EvalType.DECIMAL:
        fa = a.frac if a.eval_type == EvalType.DECIMAL else 0
        fb = b.frac if b.eval_type == EvalType.DECIMAL else 0

        def lane(d):
            # wide-decimal object lanes stay python ints (exact at any
            # precision); fixed lanes cast to int64 for the device path
            arr = np.asarray(d) if xp is np else d
            if xp is np and arr.dtype == np.dtype(object):
                return arr
            if ft.is_wide_decimal and xp is np:
                return arr.astype(object)   # result exceeds int64
            return xp.asarray(d, np.int64)
        if op == Op.MUL:
            r = lane(da) * lane(db)
            return _rescale(xp, r, fa + fb, ft.frac), valid
        tf = ft.frac
        da = _rescale(xp, lane(da), fa, tf)
        db = _rescale(xp, lane(db), fb, tf)
        return (da + db if op == Op.PLUS else da - db), valid
    return (da + db if op == Op.PLUS else da - db if op == Op.MINUS else da * db), valid


def _eval_math(xp, op, f: ScalarFunc, datas, valid):
    a = f.args[0].ft
    d = datas[0]
    if op == Op.ABS:
        return xp.abs(d), valid
    if op == Op.SIGN:
        return xp.asarray(xp.sign(_to_real(xp, a, d)), np.int64), valid
    if op in (Op.CEIL, Op.FLOOR):
        if a.eval_type == EvalType.INT:
            return d, valid
        r = _to_real(xp, a, d)
        r = xp.ceil(r) if op == Op.CEIL else xp.floor(r)
        return xp.asarray(r, np.int64), valid
    if op == Op.ROUND:
        nd = 0
        if len(f.args) > 1:
            if not isinstance(f.args[1], Constant):
                raise NotImplementedError("ROUND with non-constant digits")
            nd = int(f.args[1].value)
        if a.eval_type == EvalType.INT and nd >= 0:
            return d, valid
        if a.eval_type == EvalType.DECIMAL:
            # round scaled int at digit (frac - nd)
            drop = max(0, a.frac - nd)
            p = 10 ** drop
            half = p // 2
            r = xp.where(d >= 0, (d + half) // p, -((-d + half) // p)) * p
            return r, valid
        r = _to_real(xp, a, d)
        p = 10.0 ** nd
        return xp.round(r * p) / p, valid
    r = _to_real(xp, a, d)
    if op == Op.SQRT:
        valid = valid & (r >= 0)
        return xp.sqrt(xp.where(r < 0, 0.0, r)), valid
    if op == Op.EXP:
        return xp.exp(r), valid
    if op == Op.LN:
        valid = valid & (r > 0)
        return xp.log(xp.where(r <= 0, 1.0, r)), valid
    if op == Op.LOG2:
        valid = valid & (r > 0)
        return xp.log2(xp.where(r <= 0, 1.0, r)), valid
    if op == Op.POW:
        e = _to_real(xp, f.args[1].ft, datas[1])
        return xp.power(r, e), valid
    raise NotImplementedError(op)


_US_PER_DAY = 86_400_000_000


def _eval_time(xp, op, f: ScalarFunc, datas, valid):
    d = datas[0]
    if op in (Op.DATE_ADD_DAYS, Op.DATE_SUB_DAYS):
        days = xp.asarray(datas[1], np.int64)
        delta = days * _US_PER_DAY
        return (d + delta if op == Op.DATE_ADD_DAYS else d - delta), valid
    if op == Op.DATE_ADD_US:
        return xp.asarray(d, np.int64) + xp.asarray(datas[1], np.int64), \
            valid
    if op == Op.ADD_MONTHS:
        # calendar-exact month shift, day clamped into the target month
        # (Jan 31 + 1 month -> Feb 29/28), branch-free for jit
        months = xp.asarray(datas[1], np.int64)
        us = xp.asarray(d, np.int64)
        days = us // _US_PER_DAY
        rem_us = us - days * _US_PER_DAY
        y, m, dd = _civil_from_days(xp, days)
        tm = y * 12 + (m - 1) + months
        ny, nm = tm // 12, tm % 12 + 1
        one = xp.ones_like(dd)
        dim = _days_from_civil(xp, (tm + 1) // 12, (tm + 1) % 12 + 1,
                               one) - _days_from_civil(xp, ny, nm, one)
        nd = _days_from_civil(xp, ny, nm, xp.minimum(dd, dim))
        return nd * _US_PER_DAY + rem_us, valid
    if op == Op.DATEDIFF:
        a = xp.asarray(d, np.int64) // _US_PER_DAY
        b = xp.asarray(datas[1], np.int64) // _US_PER_DAY
        return a - b, valid
    # calendar field extraction: host path uses numpy datetime64; device path
    # uses the day-count algorithm (civil_from_days, Howard Hinnant) in int math
    days = xp.asarray(d, np.int64) // _US_PER_DAY
    rem_us = xp.asarray(d, np.int64) - days * _US_PER_DAY
    if op == Op.HOUR:
        return rem_us // 3_600_000_000, valid
    if op == Op.MINUTE:
        return (rem_us // 60_000_000) % 60, valid
    if op == Op.SECOND:
        return (rem_us // 1_000_000) % 60, valid
    y, m, dd = _civil_from_days(xp, days)
    if op == Op.YEAR:
        return y, valid
    if op == Op.MONTH:
        return m, valid
    return dd, valid


def _civil_from_days(xp, z):
    """days-since-epoch -> (year, month, day), branch-free int math.
    Algorithm: civil_from_days (public domain, H. Hinnant) — jit-friendly."""
    z = z + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = xp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(xp, y, m, d):
    """(year, month, day) -> days-since-epoch; inverse of
    _civil_from_days (days_from_civil, H. Hinnant), same int math."""
    y = y - (m <= 2)
    era = xp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    doy = (153 * xp.where(m > 2, m - 3, m + 9) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


_I64_MAX, _I64_MIN = (1 << 63) - 1, -(1 << 63)


def _round_half(x: float) -> int:
    """MySQL numeric->int conversion: round half away from zero, clamped
    to the int64 domain. trunc-and-compare, NOT floor(x+0.5): adding 0.5
    double-rounds at representation boundaries (0.49999999999999994+0.5
    is exactly 1.0 in IEEE double)."""
    t = math.trunc(x)
    if abs(x - t) >= 0.5:
        t += 1 if x >= 0 else -1
    return min(max(t, _I64_MIN), _I64_MAX)


_I64_MAX_F = 9223372036854774784.0   # largest double strictly below 2^63


def _round_half_xp(xp, r):
    """Vectorized _round_half over a float array, saturating at the
    int64 bounds. float(2^63) cast to int64 is invalid (wraps to
    INT64_MIN), so clip to the largest sub-2^63 double first, then
    restore exact INT64_MAX for the values that were beyond it.
    float(-2^63) is exactly representable and casts fine."""
    t = xp.trunc(r)
    t = t + xp.where(xp.abs(r - t) >= 0.5, xp.sign(r), 0.0)
    out = xp.asarray(xp.clip(t, float(_I64_MIN), _I64_MAX_F), np.int64)
    return xp.where(t > _I64_MAX_F, np.int64(_I64_MAX), out)


def _obj_to_int(d, n) -> np.ndarray:
    """Object-array (string) operands to int64 via MySQL float coercion;
    non-numeric -> 0, out-of-range clamps."""
    out = np.zeros(n, dtype=np.int64)
    for i, x in enumerate(d):
        try:
            out[i] = _round_half(float(x))
        except (ValueError, TypeError, OverflowError):
            out[i] = 0
    return out


def _bit_int(xp, ft, d):
    """Bit-op operand as plain int64; fractional operands round first
    (ref: expression/builtin_op.go bitAndSig — MySQL rounds, not
    truncates, before bit operations)."""
    if d.dtype == np.dtype(object):
        return _obj_to_int(d, len(d))
    if ft.eval_type in (EvalType.REAL, EvalType.DECIMAL) or \
            d.dtype == np.float64:
        return _round_half_xp(xp, _to_real(xp, ft, d))
    return xp.asarray(d, np.int64)


def _eval_bit(xp, op, f: ScalarFunc, datas, valid):
    ints = [_bit_int(xp, e.ft, d) for e, d in zip(f.args, datas)]
    if op == Op.BIT_NEG:
        return ~ints[0], valid
    a, b = ints
    if op == Op.BIT_AND:
        return a & b, valid
    if op == Op.BIT_OR:
        return a | b, valid
    if op == Op.BIT_XOR:
        return a ^ b, valid
    # shifts act on the 64-bit word: a count outside [0, 64) yields 0
    in_range = (b >= 0) & (b < 64)
    sb = xp.where(in_range, b, 0)
    if op == Op.SHL:
        r = a << sb
    else:
        # logical (not arithmetic) right shift in two's complement:
        # mask off the sign bits the arithmetic shift smeared in.
        # 2^(64-s)-1 for s=1 wraps through int64 min to INT64_MAX,
        # which is exactly the 0x7ff..f mask wanted.
        sb1 = xp.where(sb == 0, 1, sb)
        mask = (np.int64(1) << (np.int64(64) - sb1)) - np.int64(1)
        r = xp.where(sb == 0, a, (a >> sb1) & mask)
    zero = xp.zeros_like(r)
    return xp.where(in_range, r, zero), valid


def _eval_cast(xp, op, f: ScalarFunc, argv, n):
    (d, v) = argv[0]
    a = f.args[0].ft
    if op == Op.CAST_INT:
        if d.dtype == np.dtype(object):
            return _obj_to_int(d, n), v
        if a.eval_type == EvalType.INT:
            return d, v
        # CAST rounds half away from zero (int() would truncate)
        return _round_half_xp(xp, _to_real(xp, a, d)), v
    if op == Op.CAST_REAL:
        if d.dtype == np.dtype(object):
            out = np.zeros(n, dtype=np.float64)
            for i in range(n):
                try:
                    out[i] = float(d[i])
                except (ValueError, TypeError):
                    out[i] = 0.0
            return out, v
        return _to_real(xp, a, d), v
    if op == Op.CAST_DECIMAL:
        tft = f.ft
        if a.eval_type == EvalType.DECIMAL:
            return _rescale(xp, d, a.frac, tft.frac), v
        if a.eval_type == EvalType.REAL or d.dtype == np.float64:
            return xp.asarray(xp.round(d * (10 ** tft.frac)), np.int64), v
        return xp.asarray(d, np.int64) * (10 ** tft.frac), v
    # CAST_STRING: host only
    if xp is not np:
        raise RuntimeError("cast to string is host-only")
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = str(d[i])
    return out, v


def _eval_string(f: ScalarFunc, argv, n):
    """Host-only string builtins over object arrays."""
    import re
    op = f.op
    datas = [a[0] for a in argv]
    valid = _and_valid(np, [a[1] for a in argv], n)

    def vec(fn, *arrs, dtype=object):
        out = np.empty(n, dtype=dtype)
        for i in range(n):
            out[i] = fn(*(a[i] for a in arrs)) if valid[i] else (0 if dtype != object else "")
        return out

    from tidb_tpu.sqltypes import bytes_to_str as s

    if op == Op.CONCAT:
        return vec(lambda *xs: "".join(s(x) for x in xs), *datas), valid
    if op == Op.LENGTH:
        return vec(lambda x: len(s(x)), datas[0], dtype=np.int64), valid
    if op == Op.UPPER:
        return vec(lambda x: s(x).upper(), datas[0]), valid
    if op == Op.LOWER:
        return vec(lambda x: s(x).lower(), datas[0]), valid
    if op == Op.TRIM:
        return vec(lambda x: s(x).strip(), datas[0]), valid
    if op == Op.ASCII:
        return vec(lambda x: ord(s(x)[0]) if s(x) else 0, datas[0], dtype=np.int64), valid
    if op == Op.LEFT:
        return vec(lambda x, k: s(x)[:int(k)], datas[0], datas[1]), valid
    if op == Op.RIGHT:
        return vec(lambda x, k: s(x)[-int(k):] if int(k) > 0 else "", datas[0], datas[1]), valid
    if op == Op.SUBSTRING:
        if len(datas) == 2:
            return vec(lambda x, p: s(x)[int(p) - 1:] if int(p) > 0 else "",
                       datas[0], datas[1]), valid
        return vec(lambda x, p, l: s(x)[int(p) - 1:int(p) - 1 + int(l)] if int(p) > 0 else "",
                   datas[0], datas[1], datas[2]), valid
    if op == Op.REPLACE:
        return vec(lambda x, a, b: s(x).replace(s(a), s(b)), *datas[:3]), valid
    if op == Op.INSTR:
        return vec(lambda x, sub: s(x).find(s(sub)) + 1, datas[0], datas[1],
                   dtype=np.int64), valid
    if op == Op.LIKE:
        pat, esc = f.extra if isinstance(f.extra, tuple) \
            else (f.extra, "\\")
        # _ci collation on the matched column: case-insensitive LIKE
        flags = re.S | (re.I if f.args[0].ft.is_ci else 0)
        rx = re.compile(_like_to_regex(pat, esc), flags)
        return vec(lambda x: 1 if rx.fullmatch(s(x)) else 0, datas[0],
                   dtype=np.int64), valid
    raise NotImplementedError(op)


def _like_to_regex(pat: str, esc: str = "\\") -> str:
    """MySQL LIKE pattern -> regex (%, _ wildcards; `esc` escapes them,
    ESCAPE '' disables escaping). Ref: expression/builtin_like.go."""
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if esc and c == esc and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


import re  # noqa: E402  (used by _like_to_regex)
