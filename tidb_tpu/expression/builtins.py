"""Builtin scalar function registry — the breadth families.

Reference: /root/reference/expression/builtin_math.go, builtin_string.go,
builtin_time.go, builtin_encryption.go, builtin_compare.go (the builtin
families that make up most of the reference's 40.9k expression LoC).
The high-traffic TPC-H operators live as first-class Ops in core.py with
device (XLA) paths; everything here is the long tail: registered by name
in one table, evaluated whole-column on the host (numpy), with a handful
of pure-numeric ones marked device-safe (none yet: GENERIC builtins
always take the host path; promote hot ones to core Ops when needed).

Each FnSpec:
  * arity check at resolve time (min/max args);
  * result typing (`ret`: fixed eval kind or a callable over arg exprs);
  * `fn(args, argv, n)` whole-column evaluator -> (data, valid) where
    argv is [(data, valid)] numpy pairs;
  * NULL handling is each fn's own job: most AND their args' validity
    masks; CONCAT_WS/ELT/FIELD implement MySQL's special NULL rules.
"""

from __future__ import annotations

import calendar
import datetime as _dt
import hashlib
import math
import zlib
from dataclasses import dataclass
from typing import Callable

import numpy as np

from tidb_tpu.sqltypes import (micros_to_datetime, new_datetime_field,
                               new_double_field, new_int_field,
                               new_string_field)

__all__ = ["REGISTRY", "FnSpec", "lookup"]

_US_PER_DAY = 86_400_000_000


@dataclass(frozen=True)
class FnSpec:
    name: str
    min_args: int
    max_args: int
    ret: object                  # "int"|"real"|"string"|"datetime"|"first"|callable
    fn: Callable

    def result_ft(self, args):
        if callable(self.ret):
            return self.ret(args)
        from tidb_tpu.sqltypes import new_duration_field
        return {"int": new_int_field, "real": new_double_field,
                "string": lambda: new_string_field(),
                # VARBINARY producers (UNHEX): compare layers use the
                # binary collation marker to lift bytes for ordering
                "binary": _new_binary_field,
                "datetime": new_datetime_field,
                "duration": new_duration_field,
                "first": lambda: args[0].ft}[self.ret]()

    def __hash__(self):
        return hash(self.name)

    def __reduce__(self):
        # registry fns are closures; pickle by NAME and rehydrate from
        # the registry, so expressions holding a spec cross the storage
        # RPC (host_filter pushdown to the out-of-process coprocessor)
        return (_restore_spec, (self.name,))


def _restore_spec(name: str) -> "FnSpec":
    return REGISTRY[name]


def _new_binary_field():
    import dataclasses
    return dataclasses.replace(new_string_field(), collation="binary")


REGISTRY: dict[str, FnSpec] = {}


def _reg(name, min_args, max_args, ret, fn, **kw):
    REGISTRY[name] = FnSpec(name, min_args, max_args, ret, fn, **kw)


def lookup(name: str) -> FnSpec | None:
    return REGISTRY.get(name)


# -- helpers -----------------------------------------------------------------

def _s(x) -> str:
    from tidb_tpu.sqltypes import bytes_to_str
    return bytes_to_str(x)


def _valid_all(argv, n):
    v = np.ones(n, dtype=bool)
    for _d, av in argv:
        v = v & av
    return v


def _vec(fn, valid, n, *arrs, dtype=object):
    out = np.empty(n, dtype=dtype)
    fill = "" if dtype == object else 0
    for i in range(n):
        out[i] = fn(*(a[i] for a in arrs)) if valid[i] else fill
    return out


def _num(argv):
    return [np.asarray(d, dtype=np.float64) for d, _v in argv]


def _micros(d) -> np.ndarray:
    """Datetime arg -> epoch-micros int64; string datetime literals (and
    object columns) parse with MySQL semantics."""
    arr = np.asarray(d)
    if arr.dtype == object:
        from tidb_tpu.sqltypes import parse_datetime
        out = np.zeros(len(arr), dtype=np.int64)
        for i, x in enumerate(arr):
            if x is None or x == "":
                continue
            out[i] = int(x) if isinstance(x, (int, np.integer)) \
                else parse_datetime(_s(x))
        return out
    return arr.astype(np.int64)


def _dtarr(d):
    """epoch-micros -> numpy datetime64[us] (vectorized calendar)."""
    return _micros(d).view("datetime64[us]")


# -- math (builtin_math.go) --------------------------------------------------

def _unary_math(mfn):
    def fn(args, argv, n):
        (d,) = _num(argv)
        with np.errstate(all="ignore"):
            out = mfn(d)
        v = _valid_all(argv, n) & np.isfinite(out)
        return np.where(v, out, 0.0), v
    return fn


for _name, _m in [("SIN", np.sin), ("COS", np.cos), ("TAN", np.tan),
                  ("ASIN", np.arcsin), ("ACOS", np.arccos),
                  ("LOG10", np.log10), ("RADIANS", np.radians),
                  ("DEGREES", np.degrees)]:
    _reg(_name, 1, 1, "real", _unary_math(_m))


def _cot(args, argv, n):
    (d,) = _num(argv)
    with np.errstate(all="ignore"):
        out = 1.0 / np.tan(d)
    v = _valid_all(argv, n) & np.isfinite(out)
    return np.where(v, out, 0.0), v


_reg("COT", 1, 1, "real", _cot)


def _atan(args, argv, n):
    nums = _num(argv)
    out = np.arctan2(nums[0], nums[1]) if len(nums) == 2 \
        else np.arctan(nums[0])
    return out, _valid_all(argv, n)


_reg("ATAN", 1, 2, "real", _atan)
_reg("ATAN2", 2, 2, "real",
     lambda a, argv, n: (np.arctan2(*_num(argv)), _valid_all(argv, n)))


def _log(args, argv, n):
    nums = _num(argv)
    with np.errstate(all="ignore"):
        if len(nums) == 2:          # LOG(b, x)
            out = np.log(nums[1]) / np.log(nums[0])
        else:
            out = np.log(nums[0])
    v = _valid_all(argv, n) & np.isfinite(out)
    return np.where(v, out, 0.0), v


_reg("LOG", 1, 2, "real", _log)
_reg("PI", 0, 0, "real",
     lambda a, argv, n: (np.full(n, math.pi), np.ones(n, dtype=bool)))


def _truncate(args, argv, n):
    from tidb_tpu.sqltypes import EvalType
    (xd, xv), (dd, dv) = argv
    v = xv & dv
    if args[0].ft.eval_type == EvalType.INT:
        # negative D zeroes low digits TOWARD zero; D >= 0 is identity
        p = np.power(10, -np.minimum(np.asarray(dd, np.int64), 0)
                     ).astype(np.int64)
        x = np.asarray(xd, np.int64)
        out = np.sign(x) * ((np.abs(x) // p) * p)
        return out, v
    x = np.asarray(xd, np.float64)
    if args[0].ft.eval_type == EvalType.DECIMAL:
        x = x / (10.0 ** max(args[0].ft.frac, 0))   # unscale
    p = np.power(10.0, np.asarray(dd, np.float64))
    return np.trunc(x * p) / p, v


_reg("TRUNCATE", 2, 2,
     lambda args: args[0].ft if args[0].ft.eval_type.name == "INT"
     else new_double_field(), _truncate)


def _crc32(args, argv, n):
    d, v = argv[0]
    return _vec(lambda x: zlib.crc32(_s(x).encode()), v, n, d,
                dtype=np.int64), v


_reg("CRC32", 1, 1, "int", _crc32)


def _rand(args, argv, n):
    if argv:
        seed = int(argv[0][0][0]) if len(argv[0][0]) else 0
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
    else:
        rng = np.random
    return rng.random_sample(n), np.ones(n, dtype=bool)


_reg("RAND", 0, 1, "real", _rand)


def _conv_base(args, argv, n):
    (xd, xv), (fd, fv), (td, tv) = argv
    v = xv & fv & tv

    def one(x, f, t):
        try:
            val = int(_s(x), int(f))
        except ValueError:
            return ""
        t = int(t)
        if val == 0:
            return "0"
        digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
        neg, val = val < 0, abs(val)
        out = []
        while val:
            out.append(digits[val % t])
            val //= t
        return ("-" if neg else "") + "".join(reversed(out))

    return _vec(one, v, n, xd, fd, td), v


_reg("CONV", 3, 3, "string", _conv_base)
# negatives render as 64-bit two's complement, as MySQL does
_U64 = (1 << 64) - 1
_reg("BIN", 1, 1, "string",
     lambda a, argv, n: (_vec(lambda x: format(int(x) & _U64, "b"),
                              argv[0][1], n, argv[0][0]), argv[0][1]))
_reg("OCT", 1, 1, "string",
     lambda a, argv, n: (_vec(lambda x: format(int(x) & _U64, "o"),
                              argv[0][1], n, argv[0][0]), argv[0][1]))


def _hex(args, argv, n):
    from tidb_tpu.sqltypes import EvalType
    d, v = argv[0]
    if args[0].ft.eval_type == EvalType.STRING:
        return _vec(
            lambda x: (x if isinstance(x, bytes)
                       else _s(x).encode()).hex().upper(), v, n, d), v
    return _vec(lambda x: format(int(x) & _U64, "X"), v, n, d), v


_reg("HEX", 1, 1, "string", _hex)


def _unhex(args, argv, n):
    d, v = argv[0]

    def one(x):
        try:
            # VARBINARY result (MySQL): always bytes, never a lossy str
            # decode — keeps the column type-homogeneous for sort/compare
            return bytes.fromhex(_s(x))
        except ValueError:
            return None          # odd length / non-hex -> NULL (MySQL)

    out = _vec(one, v, n, d)
    v2 = v & np.array([out[i] is not None for i in range(n)], dtype=bool)
    out = np.where(v2, out, "")
    return out, v2


_reg("UNHEX", 1, 1, "binary", _unhex)


# -- strings (builtin_string.go) ---------------------------------------------

def _sfn(name, min_a, max_a, pyfn, ret="string", **kw):
    def fn(args, argv, n):
        v = _valid_all(argv, n)
        dtype = np.int64 if ret == "int" else object
        out = _vec(pyfn, v, n, *[d for d, _v in argv], dtype=dtype)
        return out, v
    _reg(name, min_a, max_a, ret, fn, **kw)


_sfn("CHAR_LENGTH", 1, 1, lambda x: len(_s(x)), ret="int")
_sfn("CHARACTER_LENGTH", 1, 1, lambda x: len(_s(x)), ret="int")
_sfn("BIT_LENGTH", 1, 1, lambda x: len(_s(x).encode()) * 8, ret="int")
def _pad(left: bool):
    def fn(args, argv, n):
        (xd, xv), (kd, kv), (pd_, pv) = argv
        v = xv & kv & pv
        k = np.asarray(kd, np.int64)
        v = v & (k >= 0)              # negative length is NULL in MySQL

        def one(x, k, p):
            x, p, k = _s(x), _s(p), int(k)
            if len(x) >= k:
                return x[:k]
            if not p:
                return x[:k]
            pad = (p * k)[:k - len(x)]
            return pad + x if left else x + pad

        return _vec(one, v, n, xd, kd, pd_), v
    return fn


_reg("LPAD", 3, 3, "string", _pad(True))
_reg("RPAD", 3, 3, "string", _pad(False))
_sfn("REPEAT", 2, 2, lambda x, k: _s(x) * max(int(k), 0))
_sfn("REVERSE", 1, 1, lambda x: _s(x)[::-1])
_sfn("SPACE", 1, 1, lambda k: " " * max(int(k), 0))
_sfn("STRCMP", 2, 2,
     lambda a, b: (_s(a) > _s(b)) - (_s(a) < _s(b)), ret="int")
_sfn("LOCATE", 2, 3,
     lambda sub, x, pos=1: (_s(x).find(_s(sub), max(int(pos) - 1, 0)) + 1)
     if int(pos) > 0 else 0, ret="int")
_sfn("POSITION", 2, 2,
     lambda sub, x: _s(x).find(_s(sub)) + 1, ret="int")
_sfn("LTRIM", 1, 1, lambda x: _s(x).lstrip(" "))
_sfn("RTRIM", 1, 1, lambda x: _s(x).rstrip(" "))
_sfn("QUOTE", 1, 1,
     lambda x: "'" + _s(x).replace("\\", "\\\\").replace("'", "\\'") + "'")
_sfn("SUBSTRING_INDEX", 3, 3,
     lambda x, d, k: (_s(d).join(_s(x).split(_s(d))[:int(k)])
                      if int(k) >= 0
                      else _s(d).join(_s(x).split(_s(d))[int(k):]))
     if _s(d) else "")
_sfn("FIND_IN_SET", 2, 2,
     lambda x, lst: (_s(lst).split(",").index(_s(x)) + 1
                     if _s(x) in _s(lst).split(",") else 0), ret="int")


def _concat_ws(args, argv, n):
    sep_d, sep_v = argv[0]
    out = np.empty(n, dtype=object)
    v = sep_v.copy()
    for i in range(n):
        if not sep_v[i]:
            out[i] = ""
            continue
        parts = [_s(d[i]) for d, av in argv[1:] if av[i]]
        out[i] = _s(sep_d[i]).join(parts)
    return out, v


_reg("CONCAT_WS", 2, 64, "string", _concat_ws)


def _elt(args, argv, n):
    kd, kv = argv[0]
    out = np.empty(n, dtype=object)
    v = np.zeros(n, dtype=bool)
    for i in range(n):
        out[i] = ""
        if not kv[i]:
            continue
        k = int(kd[i])
        if 1 <= k < len(argv):
            d, av = argv[k]
            if av[i]:
                out[i] = _s(d[i])
                v[i] = True
    return out, v


_reg("ELT", 2, 64, "string", _elt)


def _field(args, argv, n):
    xd, xv = argv[0]
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        if not xv[i]:
            continue
        for k in range(1, len(argv)):
            d, av = argv[k]
            if av[i] and _s(d[i]) == _s(xd[i]):
                out[i] = k
                break
    return out, np.ones(n, dtype=bool)


_reg("FIELD", 2, 64, "int", _field)


# -- greatest/least (builtin_compare.go) -------------------------------------

def _minmax(is_max):
    def fn(args, argv, n):
        from tidb_tpu.sqltypes import EvalType
        v = _valid_all(argv, n)
        if any(a.ft.eval_type == EvalType.STRING for a in args):
            pick = max if is_max else min
            out = _vec(lambda *xs: pick(_s(x) for x in xs), v, n,
                       *[d for d, _ in argv])
            return out, v
        red = np.maximum if is_max else np.minimum
        out = np.asarray(argv[0][0])
        for d, _av in argv[1:]:
            out = red(out, np.asarray(d))
        return out, v
    return fn


def _minmax_ft(args):
    from tidb_tpu.expression.core import ScalarFunc
    f = ScalarFunc.__new__(ScalarFunc)
    f.args = list(args)
    return f._merge_types(args)


_reg("GREATEST", 2, 64, _minmax_ft, _minmax(True))
_reg("LEAST", 2, 64, _minmax_ft, _minmax(False))


# -- date/time (builtin_time.go); all on epoch-micros int64 ------------------

def _days(argv):
    return _micros(argv[0][0]) // _US_PER_DAY


def _ifn(name, min_a, max_a, fn, ret="int", **kw):
    _reg(name, min_a, max_a, ret, fn, **kw)


_ifn("DAYOFWEEK", 1, 1,
     lambda a, argv, n: ((((_days(argv) + 4) % 7) + 1),
                         _valid_all(argv, n)))
_ifn("WEEKDAY", 1, 1,
     lambda a, argv, n: ((_days(argv) + 3) % 7, _valid_all(argv, n)))
_ifn("TO_DAYS", 1, 1,
     lambda a, argv, n: (_days(argv) + 719528, _valid_all(argv, n)))
_ifn("UNIX_TIMESTAMP", 0, 1,
     lambda a, argv, n: (
         (_micros(argv[0][0]) // 1_000_000,
          _valid_all(argv, n)) if argv else
         (np.full(n, int(_dt.datetime.now().timestamp()), np.int64),
          np.ones(n, dtype=bool))),
)
_ifn("MICROSECOND", 1, 1,
     lambda a, argv, n: (_micros(argv[0][0]) % 1_000_000,
                         _valid_all(argv, n)))


def _from_unixtime(args, argv, n):
    d, v = argv[0]
    return np.asarray(d, np.int64) * 1_000_000, v


_reg("FROM_UNIXTIME", 1, 1, "datetime", _from_unixtime)


def _cal_int(extract):
    def fn(args, argv, n):
        v = _valid_all(argv, n)
        dt = _dtarr(np.where(v, argv[0][0], 0))
        return extract(dt).astype(np.int64), v
    return fn


_reg("DAYOFYEAR", 1, 1, "int", _cal_int(
    lambda dt: (dt.astype("datetime64[D]") -
                dt.astype("datetime64[Y]").astype("datetime64[D]")) /
    np.timedelta64(1, "D") + 1))
_reg("QUARTER", 1, 1, "int", _cal_int(
    lambda dt: (dt.astype("datetime64[M]").astype(np.int64) % 12) // 3 + 1))
def _week0(d: _dt.date) -> int:
    """MySQL WEEK mode 0: Sunday-first, 0-53 (days before the year's
    first Sunday are week 0)."""
    jan1 = _dt.date(d.year, 1, 1)
    first_sunday = jan1 + _dt.timedelta((6 - jan1.weekday()) % 7)
    if d < first_sunday:
        return 0
    return (d - first_sunday).days // 7 + 1


def _to_us(x) -> int:
    if isinstance(x, (int, np.integer)):
        return int(x)
    from tidb_tpu.sqltypes import parse_datetime
    return parse_datetime(_s(x))


def _week(args, argv, n):
    v = _valid_all(argv, n)           # NULL date OR NULL mode -> NULL

    def one(us, m=0):
        mode = int(m)
        if mode not in (0, 1, 3):
            from tidb_tpu.executor import ExecError
            raise ExecError(f"unsupported WEEK mode {mode}")
        d = micros_to_datetime(_to_us(us)).date()
        if mode == 0:
            return _week0(d)
        iso_y, iso_w, _ = d.isocalendar()
        if mode == 3:                 # ISO 8601: 1-53
            return iso_w
        # mode 1: Monday-first, 0-53, no rollover across years
        if iso_y < d.year:
            return 0
        if iso_y > d.year:            # Dec tail of the NEXT iso year
            return (d - _dt.timedelta(7)).isocalendar()[1] + 1
        return iso_w

    arrs = [argv[0][0]] + ([argv[1][0]] if len(argv) == 2 else [])
    return _vec(one, v, n, *arrs, dtype=np.int64), v


def _yearweek(args, argv, n):
    v = _valid_all(argv, n)

    def one(us):
        d = micros_to_datetime(_to_us(us)).date()
        w = _week0(d)
        if w == 0:                    # belongs to the prior year's tail
            prev = _dt.date(d.year - 1, 12, 31)
            return (d.year - 1) * 100 + _week0(prev)
        return d.year * 100 + w

    return _vec(one, v, n, argv[0][0], dtype=np.int64), v


_reg("WEEK", 1, 2, "int", _week)
_reg("YEARWEEK", 1, 1, "int", _yearweek)

_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]
_DAYS_OF_WEEK = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                 "Saturday", "Sunday"]


def _monthname(args, argv, n):
    v = _valid_all(argv, n)
    m = _dtarr(np.where(v, argv[0][0], 0)).astype(
        "datetime64[M]").astype(np.int64) % 12
    return np.array([_MONTHS[i] for i in m], dtype=object), v


def _dayname(args, argv, n):
    v = _valid_all(argv, n)
    wd = (_days(argv) + 3) % 7
    return np.array([_DAYS_OF_WEEK[i] for i in wd], dtype=object), v


_reg("MONTHNAME", 1, 1, "string", _monthname)
_reg("DAYNAME", 1, 1, "string", _dayname)


def _last_day(args, argv, n):
    d, v = argv[0]

    def one(us):
        dt = micros_to_datetime(_to_us(us))
        last = calendar.monthrange(dt.year, dt.month)[1]
        return int(_dt.datetime(dt.year, dt.month, last)
                   .replace(tzinfo=_dt.timezone.utc).timestamp() * 1e6)

    return _vec(one, v, n, d, dtype=np.int64), v


_reg("LAST_DAY", 1, 1, "datetime", _last_day)

# MySQL DATE_FORMAT specifier -> strftime (the common subset)
_FMT_MAP = {"%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%-m", "%d": "%d",
            "%e": "%-d", "%H": "%H", "%k": "%-H", "%h": "%I", "%i": "%M",
            "%s": "%S", "%S": "%S", "%f": "%f", "%p": "%p", "%W": "%A",
            "%a": "%a", "%b": "%b", "%M": "%B", "%j": "%j", "%%": "%%",
            "%T": "%H:%M:%S"}


def _mysql_fmt_to_strftime(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "%" and i + 1 < len(fmt):
            spec = fmt[i:i + 2]
            out.append(_FMT_MAP.get(spec, spec[1]))
            i += 2
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


def _date_format(args, argv, n):
    (dd, dv), (fd, fv) = argv
    v = dv & fv

    def one(us, fmt):
        py = _mysql_fmt_to_strftime(_s(fmt))
        return micros_to_datetime(_to_us(us)).strftime(
            py.replace("%-", "%"))

    return _vec(one, v, n, dd, fd), v


_reg("DATE_FORMAT", 2, 2, "string", _date_format)


# -- crypto / checksum (builtin_encryption.go) -------------------------------

def _digest(algo):
    def fn(args, argv, n):
        d, v = argv[0]
        return _vec(lambda x: algo(_s(x).encode()).hexdigest(),
                    v, n, d), v
    return fn


_reg("MD5", 1, 1, "string", _digest(hashlib.md5))
_reg("SHA1", 1, 1, "string", _digest(hashlib.sha1))
_reg("SHA", 1, 1, "string", _digest(hashlib.sha1))


def _sha2(args, argv, n):
    (xd, xv), (bd, bv) = argv
    v = xv & bv
    algos = {0: hashlib.sha256, 224: hashlib.sha224, 256: hashlib.sha256,
             384: hashlib.sha384, 512: hashlib.sha512}

    def one(x, bits):
        a = algos.get(int(bits))
        return a(_s(x).encode()).hexdigest() if a else None

    out = _vec(one, v, n, xd, bd)
    v2 = v & np.array([out[i] is not None for i in range(n)], dtype=bool)
    return np.where(v2, out, ""), v2


_reg("SHA2", 2, 2, "string", _sha2)


# -- JSON (ref: types/json/binary.go; expression/builtin_json.go) ------------
# Documents live as canonical compact text; functions parse per row.

import json as _json


class _PathError(ValueError):
    pass


import functools


@functools.lru_cache(maxsize=1024)
def _parse_path(path: str) -> tuple:
    """'$.a.b[0]' -> ['a', 'b', 0]. Subset: member access and array
    index (no wildcards/ranges)."""
    p = path.strip()
    if not p.startswith("$"):
        raise _PathError(f"Invalid JSON path expression: {path!r}")
    out: list = []
    i = 1
    n = len(p)
    while i < n:
        c = p[i]
        if c == ".":
            i += 1
            if i < n and p[i] == '"':
                j = p.find('"', i + 1)
                if j < 0:
                    raise _PathError(f"Invalid JSON path: {path!r}")
                out.append(p[i + 1:j])
                i = j + 1
                continue
            j = i
            while j < n and (p[j].isalnum() or p[j] == "_"):
                j += 1
            if j == i:
                raise _PathError(f"Invalid JSON path: {path!r}")
            out.append(p[i:j])
            i = j
        elif c == "[":
            j = p.find("]", i)
            if j < 0:
                raise _PathError(f"Invalid JSON path: {path!r}")
            idx_s = p[i + 1:j].strip()
            if not idx_s.isdigit():      # no wildcards/negatives/last
                raise _PathError(f"Invalid JSON path: {path!r}")
            out.append(int(idx_s))
            i = j + 1
        else:
            raise _PathError(f"Invalid JSON path: {path!r}")
    return tuple(out)


def _walk(doc, steps):
    """-> (found, value)."""
    cur = doc
    for s in steps:
        if isinstance(s, int):
            if not isinstance(cur, list) or not (0 <= s < len(cur)):
                return False, None
            cur = cur[s]
        else:
            if not isinstance(cur, dict) or s not in cur:
                return False, None
            cur = cur[s]
    return True, cur


def _jload(x):
    return _json.loads(_s(x))


def _jdump(v) -> str:
    return _json.dumps(v, separators=(",", ":"))


def _json_extract(args, argv, n):
    v = _valid_all(argv, n)
    out = np.empty(n, dtype=object)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        out[i] = ""
        if not v[i]:
            continue
        doc = _jload(argv[0][0][i])
        hits = []
        for pd_, _pv in argv[1:]:
            found, val = _walk(doc, _parse_path(_s(pd_[i])))
            if found:
                hits.append(val)
        if not hits:
            continue            # no match -> NULL (MySQL)
        ok[i] = True
        # one path -> the value; several -> wrapped in an array
        out[i] = _jdump(hits[0] if len(argv) == 2 else hits)
    return out, ok


def _json_ft(args):
    from tidb_tpu.sqltypes import FieldType, TypeCode
    return FieldType(TypeCode.JSON)


def _wrap_path_errors(fn):
    """Malformed path arguments surface as clean SQL errors, never raw
    int()/parse tracebacks."""
    def wrapped(args, argv, n):
        from tidb_tpu.executor import ExecError
        try:
            return fn(args, argv, n)
        except _PathError as e:
            raise ExecError(str(e)) from None
    return wrapped


_reg("JSON_EXTRACT", 2, 16, _json_ft, _wrap_path_errors(_json_extract))


def _json_unquote(args, argv, n):
    d, v = argv[0]

    def one(x):
        s = _s(x)
        if s.startswith('"') and s.endswith('"') and len(s) >= 2:
            try:
                u = _json.loads(s)
                if isinstance(u, str):
                    return u
            except ValueError:
                pass
        return s

    return _vec(one, v, n, d), v


_reg("JSON_UNQUOTE", 1, 1, "string", _json_unquote)


def _json_type(args, argv, n):
    d, v = argv[0]
    names = {dict: "OBJECT", list: "ARRAY", str: "STRING", bool: "BOOLEAN",
             int: "INTEGER", float: "DOUBLE", type(None): "NULL"}
    return _vec(lambda x: names[type(_jload(x))], v, n, d), v


_reg("JSON_TYPE", 1, 1, "string", _json_type)


def _json_valid(args, argv, n):
    d, v = argv[0]

    def one(x):
        try:
            _jload(x)
            return 1
        except ValueError:
            return 0

    return _vec(one, v, n, d, dtype=np.int64), v


_reg("JSON_VALID", 1, 1, "int", _json_valid)


def _json_length(args, argv, n):
    v = _valid_all(argv, n)
    out = np.zeros(n, dtype=np.int64)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        if not v[i]:
            continue
        doc = _jload(argv[0][0][i])
        if len(argv) == 2:
            found, doc = _walk(doc, _parse_path(_s(argv[1][0][i])))
            if not found:
                continue
        ok[i] = True
        out[i] = len(doc) if isinstance(doc, (dict, list)) else 1
    return out, ok


_reg("JSON_LENGTH", 1, 2, "int", _wrap_path_errors(_json_length))


def _json_keys(args, argv, n):
    d, v = argv[0]
    out = np.empty(n, dtype=object)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        out[i] = ""
        if not v[i]:
            continue
        doc = _jload(d[i])
        if isinstance(doc, dict):
            out[i] = _jdump(list(doc.keys()))
            ok[i] = True
    return out, ok


_reg("JSON_KEYS", 1, 1, _json_ft, _json_keys)


def _json_contains_value(hay, needle) -> bool:
    """MySQL containment: a candidate array is contained in a target
    array iff EVERY candidate element is contained in some target
    element; a non-array candidate iff SOME element contains it; object
    containment is per-key; scalars compare with numeric coercion."""
    if isinstance(hay, list):
        if isinstance(needle, list):
            return all(_json_contains_value(hay, e) for e in needle)
        return any(_json_contains_value(e, needle) for e in hay)
    if isinstance(hay, dict):
        if isinstance(needle, dict):
            return all(k in hay and _json_contains_value(hay[k], nv)
                       for k, nv in needle.items())
        return False
    if isinstance(needle, (list, dict)):
        return False
    if isinstance(hay, bool) != isinstance(needle, bool):
        return False
    if isinstance(hay, (int, float)) and isinstance(needle, (int, float)):
        return float(hay) == float(needle)
    return hay == needle


def _json_contains(args, argv, n):
    v = _valid_all(argv, n)

    def one(doc, cand, *path):
        d = _jload(doc)
        if path:
            found, d = _walk(d, _parse_path(_s(path[0])))
            if not found:
                return 0
        return 1 if _json_contains_value(d, _jload(cand)) else 0

    return _vec(one, v, n, *[a[0] for a in argv], dtype=np.int64), v


_reg("JSON_CONTAINS", 2, 3, "int",
     _wrap_path_errors(_json_contains))


def _json_array(args, argv, n):
    out = np.empty(n, dtype=object)
    for i in range(n):
        vals = []
        for (d, av), a in zip(argv, args):
            vals.append(_arg_to_json(d[i], av[i], a))
        out[i] = _jdump(vals)
    return out, np.ones(n, dtype=bool)


def _json_object(args, argv, n):
    if len(argv) % 2:
        from tidb_tpu.executor import ExecError
        raise ExecError("JSON_OBJECT needs an even number of arguments")
    out = np.empty(n, dtype=object)
    for i in range(n):
        obj = {}
        for k in range(0, len(argv), 2):
            (kd, kv_), (vd, vv) = argv[k], argv[k + 1]
            if not kv_[i]:
                from tidb_tpu.executor import ExecError
                raise ExecError("JSON_OBJECT key cannot be NULL")
            obj[_s(kd[i])] = _arg_to_json(vd[i], vv[i], args[k + 1])
        out[i] = _jdump(obj)
    return out, np.ones(n, dtype=bool)


def _arg_to_json(x, valid, expr):
    from tidb_tpu.sqltypes import EvalType, TypeCode
    if not valid:
        return None
    if expr.ft.tp == TypeCode.JSON:
        return _jload(x)
    et = expr.ft.eval_type
    if et == EvalType.INT:
        return int(x)
    if et == EvalType.REAL:
        return float(x)
    if et == EvalType.DECIMAL:
        from tidb_tpu.sqltypes import scaled_to_decimal
        return float(scaled_to_decimal(int(x), max(expr.ft.frac, 0)))
    return _s(x)


_reg("JSON_ARRAY", 0, 32, _json_ft, _json_array)
_reg("JSON_OBJECT", 0, 32, _json_ft, _json_object)


# -- pattern matching ---------------------------------------------------------

def _regexp_like(args, argv, n):
    """a REGEXP p (ref: expression/builtin_like.go regexpSig): partial
    match, per-row pattern, case-sensitive (utf8_bin semantics)."""
    import re
    v = _valid_all(argv, n)
    out = np.zeros(n, dtype=np.int64)
    cache = {}
    for i in range(n):
        if not v[i]:
            continue
        p = _s(argv[1][0][i])
        rx = cache.get(p)
        if rx is None:
            try:
                rx = cache[p] = re.compile(p)
            except re.error as ex:
                from tidb_tpu.executor import ExecError
                raise ExecError(
                    f"Got error '{ex}' from regexp") from None
        out[i] = 1 if rx.search(_s(argv[0][0][i])) else 0
    return out, v


_reg("REGEXP_LIKE", 2, 2, "int", _regexp_like)


# -- TIMESTAMPDIFF ------------------------------------------------------------

_TSDIFF_US = {"MICROSECOND": 1, "SECOND": 1_000_000, "MINUTE": 60_000_000,
              "HOUR": 3_600_000_000, "DAY": _US_PER_DAY,
              "WEEK": 7 * _US_PER_DAY}
_TSDIFF_MONTHS = {"MONTH": 1, "QUARTER": 3, "YEAR": 12}


def _timestampdiff(args, argv, n):
    """TIMESTAMPDIFF(unit, a, b): complete units from a to b, truncated
    toward zero (ref: expression/builtin_time.go timestampDiff)."""
    v = _valid_all(argv, n)
    a = _micros(argv[1][0])
    b = _micros(argv[2][0])
    units = argv[0][0]
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        if not v[i]:
            continue
        u = _s(units[i]).upper()
        diff = int(b[i]) - int(a[i])
        if u in _TSDIFF_US:
            per = _TSDIFF_US[u]
            out[i] = abs(diff) // per * (1 if diff >= 0 else -1)
        elif u in _TSDIFF_MONTHS:
            da = micros_to_datetime(int(a[i]))
            db = micros_to_datetime(int(b[i]))
            months = (db.year - da.year) * 12 + (db.month - da.month)
            ta = (da.day, da.hour, da.minute, da.second, da.microsecond)
            tb = (db.day, db.hour, db.minute, db.second, db.microsecond)
            if months > 0 and tb < ta:
                months -= 1      # last month not complete
            elif months < 0 and tb > ta:
                months += 1
            k = _TSDIFF_MONTHS[u]
            out[i] = abs(months) // k * (1 if months >= 0 else -1)
        else:
            from tidb_tpu.executor import ExecError
            raise ExecError(f"unsupported TIMESTAMPDIFF unit {u}")
    return out, v


_reg("TIMESTAMPDIFF", 3, 3, "int", _timestampdiff)


# The long-tail extension families (time/string/info/misc/crypto/JSON)
# register themselves on import; kept in a sibling module so each family
# file stays reviewable (mirrors the reference's builtin_*.go split).
from tidb_tpu.expression import builtins_ext  # noqa: E402,F401
