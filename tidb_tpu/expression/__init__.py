from tidb_tpu.expression.core import (
    Expression, ColumnRef, Constant, ScalarFunc, Op,
    col, const, func, and_all,
)
from tidb_tpu.expression.agg import AggFunc, AggDesc

__all__ = [
    "Expression", "ColumnRef", "Constant", "ScalarFunc", "Op",
    "col", "const", "func", "and_all", "AggFunc", "AggDesc",
]
