"""Builtin registry extension — the rest of the reference's function table.

Reference: /root/reference/expression/builtin.go:270 (the `funcs` map) and
the family files builtin_time.go, builtin_string.go, builtin_info.go,
builtin_miscellaneous.go, builtin_encryption.go, builtin_json.go.
Same contract as builtins.py: whole-column host evaluators registered by
name; NULL rules per function (MySQL semantics asserted in
tests/test_builtins_ext.py).

Functions the reference itself rejects with `errFunctionNotExists`
(DECODE/ENCODE/DES_*/ENCRYPT/OLD_PASSWORD/VALIDATE_PASSWORD_STRENGTH,
builtin_encryption.go:163-199) stay unregistered here too — a loud
"unsupported function" error is exact parity.
"""

from __future__ import annotations

import base64
import calendar
import datetime as _dt
import ipaddress
import struct
import threading
import time as _time
import uuid as _uuid
import zlib

import numpy as np

from tidb_tpu.expression.builtins import (REGISTRY, _jdump, _jload, _json_ft,
                                          _micros, _mysql_fmt_to_strftime,
                                          _parse_path, _reg, _s, _to_us,
                                          _valid_all, _vec, _walk,
                                          _wrap_path_errors)
from tidb_tpu.sqltypes import (MAX_DURATION_US, clamp_duration,
                               datetime_to_micros, format_datetime,
                               format_duration, micros_to_datetime,
                               new_datetime_field, new_date_field,
                               new_duration_field, new_int_field,
                               new_string_field, parse_datetime,
                               parse_duration)

_US_PER_DAY = 86_400_000_000
_EPOCH_DAYS = 719528          # days from year 0 to 1970-01-01 (TO_DAYS)


def _dur(x) -> int:
    """Duration-ish arg (int micros / TIME string) -> signed micros."""
    if isinstance(x, (int, np.integer)):
        return int(x)
    return parse_duration(_s(x))


def _numf(x, expr) -> float:
    """Numeric arg -> float, unscaling DECIMAL's scaled-int lane."""
    from tidb_tpu.sqltypes import EvalType
    if expr.ft.eval_type == EvalType.DECIMAL:
        return float(x) / (10.0 ** max(expr.ft.frac, 0))
    return float(x)


def _const_valid(n):
    return np.ones(n, dtype=bool)


def _nullable(out, v, n, fill=""):
    """Per-row None in `out` -> NULL; keeps the rest of `v`."""
    bad = np.array([out[i] is None for i in range(n)], dtype=bool)
    v2 = v & ~bad
    for i in range(n):
        if out[i] is None:
            out[i] = fill
    return out, v2


# -- time: current-moment functions (volatile, like RAND) ---------------------
# The resolver folds NOW()/CURRENT_TIMESTAMP at plan time and marks the plan
# volatile; these are registered directly and re-evaluate per execution.

def _now_us() -> int:
    return datetime_to_micros(_dt.datetime.now())


def _utc_us() -> int:
    return datetime_to_micros(
        _dt.datetime.now(_dt.timezone.utc).replace(tzinfo=None))


def _reg_now(name, value_fn, ret_field):
    def fn(args, argv, n):
        return np.full(n, value_fn(), np.int64), _const_valid(n)
    _reg(name, 0, 1 if name in ("SYSDATE", "UTC_TIME", "UTC_TIMESTAMP",
                                "CURTIME", "CURRENT_TIME") else 0,
         lambda args: ret_field(), fn)


_reg_now("CURDATE", lambda: _now_us() // _US_PER_DAY * _US_PER_DAY,
         new_date_field)
_reg_now("CURRENT_DATE", lambda: _now_us() // _US_PER_DAY * _US_PER_DAY,
         new_date_field)
_reg_now("UTC_DATE", lambda: _utc_us() // _US_PER_DAY * _US_PER_DAY,
         new_date_field)
_reg_now("SYSDATE", _now_us, new_datetime_field)
# NOW()/CURRENT_TIMESTAMP fold at plan time in the resolver; these two
# synonyms (ref: nowFunctionClass) evaluate per execution like SYSDATE
_reg_now("LOCALTIME", _now_us, new_datetime_field)
_reg_now("LOCALTIMESTAMP", _now_us, new_datetime_field)
_reg_now("UTC_TIMESTAMP", _utc_us, new_datetime_field)
_reg_now("CURTIME", lambda: _now_us() % _US_PER_DAY, new_duration_field)
_reg_now("CURRENT_TIME", lambda: _now_us() % _US_PER_DAY, new_duration_field)
_reg_now("UTC_TIME", lambda: _utc_us() % _US_PER_DAY, new_duration_field)


# -- time: conversions --------------------------------------------------------

def _str_to_date(args, argv, n):
    """STR_TO_DATE(str, fmt): inverse DATE_FORMAT; unparseable -> NULL
    (ref: builtin_time.go strToDateFunctionClass)."""
    (sd, sv), (fd, fv) = argv
    v = sv & fv

    def one(x, fmt):
        py = _mysql_fmt_to_strftime(_s(fmt)).replace("%-", "%")
        try:
            dt = _dt.datetime.strptime(_s(x).strip(), py)
        except ValueError:
            return None
        return datetime_to_micros(dt)

    out = _vec(one, v, n, sd, fd, dtype=object)
    bad = np.array([out[i] is None for i in range(n)], dtype=bool)
    v2 = v & ~bad
    res = np.zeros(n, dtype=np.int64)
    for i in range(n):
        if v2[i]:
            res[i] = out[i]
    return res, v2


_reg("STR_TO_DATE", 2, 2, "datetime", _str_to_date)


def _time_format(args, argv, n):
    (td, tv), (fd, fv) = argv
    v = tv & fv

    def one(t, fmt):
        us = abs(_dur(t))
        sign = "-" if _dur(t) < 0 else ""
        sec = us // 1_000_000
        h, m, s = sec // 3600, (sec // 60) % 60, sec % 60
        micro = us % 1_000_000
        f = _s(fmt)
        rep = {"%H": f"{h:02d}", "%k": str(h), "%h": f"{(h % 12) or 12:02d}",
               "%I": f"{(h % 12) or 12:02d}", "%i": f"{m:02d}",
               "%s": f"{s:02d}", "%S": f"{s:02d}", "%f": f"{micro:06d}",
               "%p": "AM" if h % 24 < 12 else "PM",
               "%T": f"{h:02d}:{m:02d}:{s:02d}"}
        out = []
        i = 0
        while i < len(f):
            if f[i] == "%" and i + 1 < len(f):
                spec = f[i:i + 2]
                out.append(rep.get(spec, spec[1]))
                i += 2
            else:
                out.append(f[i])
                i += 1
        return sign + "".join(out)

    return _vec(one, v, n, td, fd), v


_reg("TIME_FORMAT", 2, 2, "string", _time_format)

_reg("FROM_DAYS", 1, 1, lambda args: new_date_field(),
     lambda a, argv, n: (
         (np.asarray(argv[0][0], np.int64) - _EPOCH_DAYS) * _US_PER_DAY,
         _valid_all(argv, n)))

_reg("TO_SECONDS", 1, 1, "int",
     lambda a, argv, n: (
         _micros(argv[0][0]) // 1_000_000 + _EPOCH_DAYS * 86400,
         _valid_all(argv, n)))


def _makedate(args, argv, n):
    (yd, yv), (dd, dv) = argv
    v = yv & dv

    def one(y, d):
        y, d = int(y), int(d)
        if d <= 0:
            return None
        if y < 70:
            y += 2000
        elif y < 100:
            y += 1900
        try:
            base = _dt.date(y, 1, 1) + _dt.timedelta(days=d - 1)
        except (ValueError, OverflowError):
            return None
        if base.year > 9999:
            return None
        return int(base.toordinal() - _dt.date(1970, 1, 1).toordinal()) \
            * _US_PER_DAY

    out = _vec(one, v, n, yd, dd, dtype=object)
    out, v2 = _nullable(out, v, n, fill=0)
    return np.array([int(x) for x in out], dtype=np.int64), v2


_reg("MAKEDATE", 2, 2, lambda args: new_date_field(), _makedate)


def _maketime(args, argv, n):
    (hd, hv), (md, mv), (sd, sv) = argv
    v = hv & mv & sv

    def one(h, m, s):
        h, m, s = int(h), int(m), _numf(s, args[2])
        if m < 0 or m > 59 or s < 0 or s >= 60:
            return None
        us = (abs(h) * 3600 + m * 60) * 1_000_000 + int(round(s * 1e6))
        return clamp_duration(-us if h < 0 else us)

    out = _vec(one, v, n, hd, md, sd, dtype=object)
    out, v2 = _nullable(out, v, n, fill=0)
    return np.array([int(x) for x in out], dtype=np.int64), v2


_reg("MAKETIME", 3, 3, lambda args: new_duration_field(frac=6), _maketime)

def _sec_to_time_ft(args):
    # fsp follows the argument: INT -> 0, DECIMAL -> its scale, REAL -> 6
    et = args[0].ft.eval_type.name
    if et == "DECIMAL":
        return new_duration_field(frac=min(max(args[0].ft.frac, 0), 6))
    return new_duration_field(frac=6 if et == "REAL" else 0)


_reg("SEC_TO_TIME", 1, 1, _sec_to_time_ft,
     lambda a, argv, n: (
         np.array([clamp_duration(int(_numf(x, a[0]) * 1e6))
                   for x in np.where(_valid_all(argv, n), argv[0][0], 0)],
                  dtype=np.int64),
         _valid_all(argv, n)))


def _time_to_sec(args, argv, n):
    d, v = argv[0]
    out = np.zeros(n, dtype=np.int64)
    ok = v.copy()
    for i in range(n):
        if not v[i]:
            continue
        try:
            out[i] = _dur(d[i]) // 1_000_000
        except ValueError:
            ok[i] = False        # unparseable time -> NULL (MySQL warns)
    return out, ok


_reg("TIME_TO_SEC", 1, 1, "int", _time_to_sec)


def _time_fn(args, argv, n):
    """TIME(expr): time part of a datetime/duration (ref: timeFunctionClass)."""
    d, v = argv[0]
    from tidb_tpu.sqltypes import EvalType
    et = args[0].ft.eval_type

    def one(x):
        if et == EvalType.DURATION:
            return int(x)
        if et == EvalType.DATETIME:
            return int(x) % _US_PER_DAY
        s = _s(x)
        if "-" in s.lstrip("-"):
            try:
                return parse_datetime(s) % _US_PER_DAY
            except ValueError:
                return None
        try:
            return parse_duration(s)   # incl. the 'D HH:MM:SS' day form
        except ValueError:
            return None

    out = _vec(one, v, n, d, dtype=object)
    out, v2 = _nullable(out, v, n, fill=0)
    return np.array([int(x) for x in out], dtype=np.int64), v2


_reg("TIME", 1, 1, lambda args: new_duration_field(frac=6), _time_fn)


def _timestamp_fn(args, argv, n):
    v = _valid_all(argv, n)
    base = _micros(argv[0][0])
    if len(argv) == 2:
        add = np.zeros(n, dtype=np.int64)
        for i in range(n):
            if not v[i]:
                continue
            try:
                add[i] = _dur(argv[1][0][i])
            except ValueError:
                v = v.copy()
                v[i] = False     # unparseable time -> NULL (MySQL warns)
        base = base + add
    return base, v


_reg("TIMESTAMP", 1, 2, "datetime", _timestamp_fn)


def _timediff(args, argv, n):
    """TIMEDIFF(a, b) -> duration; mixed datetime/time args -> NULL
    (MySQL requires same types; ref: timeDiffFunctionClass)."""
    from tidb_tpu.sqltypes import EvalType
    v = _valid_all(argv, n)
    ets = [a.ft.eval_type for a in args]

    def classify(x, et):
        if et == EvalType.DURATION:
            return ("t", int(x))
        if et == EvalType.DATETIME:
            return ("d", int(x))
        s = _s(x)
        if "-" in s.lstrip("-") and ":" in s or s.count("-") >= 2:
            try:
                return ("d", parse_datetime(s))
            except ValueError:
                return (None, 0)
        try:
            return ("t", parse_duration(s))
        except ValueError:
            return (None, 0)

    out = np.zeros(n, dtype=np.int64)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        if not v[i]:
            continue
        k1, a = classify(argv[0][0][i], ets[0])
        k2, b = classify(argv[1][0][i], ets[1])
        if k1 is None or k2 is None or k1 != k2:
            continue
        ok[i] = True
        out[i] = clamp_duration(a - b)
    return out, ok


_reg("TIMEDIFF", 2, 2, lambda args: new_duration_field(frac=6), _timediff)


def _addtime(sign):
    def fn(args, argv, n):
        from tidb_tpu.sqltypes import EvalType
        v = _valid_all(argv, n)
        et0 = args[0].ft.eval_type
        out = np.zeros(n, dtype=np.int64) if et0 != EvalType.STRING \
            else np.empty(n, dtype=object)
        ok = v.copy()
        for i in range(n):
            if not v[i]:
                if et0 == EvalType.STRING:
                    out[i] = ""
                continue
            try:
                delta = sign * _dur(argv[1][0][i])
            except ValueError:
                ok[i] = False
                if et0 == EvalType.STRING:
                    out[i] = ""
                continue
            if et0 == EvalType.DATETIME:
                out[i] = int(argv[0][0][i]) + delta
            elif et0 == EvalType.DURATION:
                out[i] = clamp_duration(int(argv[0][0][i]) + delta)
            else:
                s = _s(argv[0][0][i])
                try:
                    if s.count("-") >= 2:      # datetime-shaped string
                        us = parse_datetime(s) + delta
                        out[i] = format_datetime(us)
                    else:
                        us = clamp_duration(parse_duration(s) + delta)
                        out[i] = format_duration(us)
                except ValueError:
                    ok[i] = False
                    out[i] = ""
        return out, ok

    def ret(args):
        from tidb_tpu.sqltypes import EvalType
        et0 = args[0].ft.eval_type
        if et0 == EvalType.DATETIME:
            return new_datetime_field()
        if et0 == EvalType.DURATION:
            return new_duration_field(frac=6)
        return new_string_field()
    return fn, ret


for _name, _sgn in [("ADDTIME", 1), ("SUBTIME", -1)]:
    _f, _r = _addtime(_sgn)
    _reg(_name, 2, 2, _r, _f)


def _weekofyear(args, argv, n):
    v = _valid_all(argv, n)

    def one(us):
        return micros_to_datetime(_to_us(us)).date().isocalendar()[1]

    return _vec(one, v, n, argv[0][0], dtype=np.int64), v


_reg("WEEKOFYEAR", 1, 1, "int", _weekofyear)


def _period_to_months(p: int) -> int:
    y, m = p // 100, p % 100
    if y < 70:
        y += 2000
    elif y < 100:
        y += 1900
    return y * 12 + m - 1


def _months_to_period(months: int) -> int:
    return (months // 12) * 100 + months % 12 + 1


_reg("PERIOD_ADD", 2, 2, "int",
     lambda a, argv, n: (
         np.array([_months_to_period(
             _period_to_months(int(p)) + int(k)) if ok else 0
             for p, k, ok in zip(argv[0][0], argv[1][0],
                                 _valid_all(argv, n))], dtype=np.int64),
         _valid_all(argv, n)))
_reg("PERIOD_DIFF", 2, 2, "int",
     lambda a, argv, n: (
         np.array([_period_to_months(int(p1)) - _period_to_months(int(p2))
                   if ok else 0
                   for p1, p2, ok in zip(argv[0][0], argv[1][0],
                                         _valid_all(argv, n))],
                  dtype=np.int64),
         _valid_all(argv, n)))


def _convert_tz(args, argv, n):
    """CONVERT_TZ(dt, from, to): numeric '+HH:MM' offsets only; named
    zones -> NULL (parity: MySQL without tz tables loaded)."""
    v = _valid_all(argv, n)

    def off(s):
        s = _s(s).strip()
        if s in ("SYSTEM", "UTC"):
            return 0
        if s and s[0] in "+-" and ":" in s:
            sign = -1 if s[0] == "-" else 1
            h, m = s[1:].split(":")
            return sign * (int(h) * 3600 + int(m) * 60) * 1_000_000
        return None

    out = np.zeros(n, dtype=np.int64)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        if not v[i]:
            continue
        o1, o2 = off(argv[1][0][i]), off(argv[2][0][i])
        if o1 is None or o2 is None:
            continue
        ok[i] = True
        out[i] = _to_us(argv[0][0][i]) - o1 + o2
    return out, ok


_reg("CONVERT_TZ", 3, 3, "datetime", _convert_tz)

_GET_FORMATS = {
    ("DATE", "USA"): "%m.%d.%Y", ("DATE", "JIS"): "%Y-%m-%d",
    ("DATE", "ISO"): "%Y-%m-%d", ("DATE", "EUR"): "%d.%m.%Y",
    ("DATE", "INTERNAL"): "%Y%m%d",
    ("DATETIME", "USA"): "%Y-%m-%d %H.%i.%s",
    ("DATETIME", "JIS"): "%Y-%m-%d %H:%i:%s",
    ("DATETIME", "ISO"): "%Y-%m-%d %H:%i:%s",
    ("DATETIME", "EUR"): "%Y-%m-%d %H.%i.%s",
    ("DATETIME", "INTERNAL"): "%Y%m%d%H%i%s",
    ("TIME", "USA"): "%h:%i:%s %p", ("TIME", "JIS"): "%H:%i:%s",
    ("TIME", "ISO"): "%H:%i:%s", ("TIME", "EUR"): "%H.%i.%s",
    ("TIME", "INTERNAL"): "%H%i%s",
}
# TIMESTAMP is a synonym for DATETIME here (MySQL docs GET_FORMAT)
for _loc in ("USA", "JIS", "ISO", "EUR", "INTERNAL"):
    _GET_FORMATS[("TIMESTAMP", _loc)] = _GET_FORMATS[("DATETIME", _loc)]


def _get_format(args, argv, n):
    v = _valid_all(argv, n)
    out = _vec(lambda t, loc: _GET_FORMATS.get(
        (_s(t).upper(), _s(loc).upper())), v, n, argv[0][0], argv[1][0])
    return _nullable(out, v, n)


_reg("GET_FORMAT", 2, 2, "string", _get_format)


# -- string -------------------------------------------------------------------

def _format_number(args, argv, n):
    """FORMAT(x, d): thousands separators, rounded to d decimals."""
    from tidb_tpu.sqltypes import EvalType
    v = _valid_all(argv, n)
    et = args[0].ft.eval_type

    def one(x, d):
        d = max(int(d), 0)
        if et == EvalType.DECIMAL:
            from tidb_tpu.sqltypes import scaled_to_decimal
            val = scaled_to_decimal(int(x), max(args[0].ft.frac, 0))
        else:
            val = float(x) if et == EvalType.REAL else int(x)
        return f"{val:,.{d}f}"

    return _vec(one, v, n, argv[0][0], argv[1][0]), v


_reg("FORMAT", 2, 2, "string", _format_number)

_reg("TO_BASE64", 1, 1, "string",
     lambda a, argv, n: (
         _vec(lambda x: base64.b64encode(
             x if isinstance(x, bytes) else _s(x).encode()).decode(),
             argv[0][1], n, argv[0][0]), argv[0][1]))


def _from_base64(args, argv, n):
    d, v = argv[0]

    def one(x):
        try:
            return base64.b64decode(_s(x), validate=True).decode(
                "utf-8", "replace")
        except Exception:
            return None

    out = _vec(one, v, n, d)
    return _nullable(out, v, n)


_reg("FROM_BASE64", 1, 1, "string", _from_base64)


def _insert_str(args, argv, n):
    v = _valid_all(argv, n)

    def one(x, pos, ln, new):
        x, new, pos, ln = _s(x), _s(new), int(pos), int(ln)
        if pos < 1 or pos > len(x):
            return x
        if ln < 0 or pos + ln - 1 >= len(x):
            return x[:pos - 1] + new
        return x[:pos - 1] + new + x[pos - 1 + ln:]

    return _vec(one, v, n, *[d for d, _ in argv]), v


_reg("INSERT", 4, 4, "string", _insert_str)


def _export_set(args, argv, n):
    v = _valid_all(argv, n)

    def one(bits, on, off, sep=",", count=64):
        bits = int(bits) & ((1 << 64) - 1)
        count = min(max(int(count), 0), 64)
        return _s(sep).join(
            _s(on) if bits & (1 << i) else _s(off)
            for i in range(count))

    return _vec(one, v, n, *[d for d, _ in argv]), v


_reg("EXPORT_SET", 3, 5, "string", _export_set)


def _make_set(args, argv, n):
    bd, bv = argv[0]
    out = np.empty(n, dtype=object)
    for i in range(n):
        if not bv[i]:
            out[i] = ""
            continue
        bits = int(bd[i])
        parts = []
        for k in range(1, len(argv)):
            d, av = argv[k]
            if bits & (1 << (k - 1)) and av[i]:
                parts.append(_s(d[i]))
        out[i] = ",".join(parts)
    return out, bv


_reg("MAKE_SET", 2, 64, "string", _make_set)

# ORD: leading utf8 bytes of the first character as a base-256 number
_reg("ORD", 1, 1, "int",
     lambda a, argv, n: (
         _vec(lambda x: int.from_bytes(_s(x)[:1].encode("utf8"), "big")
              if _s(x) else 0, argv[0][1], n, argv[0][0],
              dtype=np.int64), argv[0][1]))


def _char_fn(args, argv, n):
    """CHAR(n, ...): each int contributes its bytes (base-256); NULL args
    are skipped; result interpreted as utf8 (ref: charFunctionClass)."""
    out = np.empty(n, dtype=object)
    for i in range(n):
        bs = b""
        for (d, av), arg in zip(argv, args):
            if not av[i]:
                continue
            try:
                x = int(round(_numf(d[i], arg))) & 0xFFFFFFFF
            except (ValueError, TypeError):
                x = 0            # non-numeric -> 0 (MySQL warns)
            nb = max(1, (x.bit_length() + 7) // 8)
            bs += x.to_bytes(nb, "big")
        out[i] = bs.decode("utf-8", "replace")
    return out, _const_valid(n)


_reg("CHAR", 1, 64, "string", _char_fn)

# LOAD_FILE: NULL without FILE privilege — always NULL here, like a locked-
# down MySQL (ref: loadFileFunctionClass)
_reg("LOAD_FILE", 1, 1, "string",
     lambda a, argv, n: (np.full(n, "", dtype=object),
                         np.zeros(n, dtype=bool)))


# -- information --------------------------------------------------------------

_reg("CHARSET", 1, 1, "string",
     lambda a, argv, n: (np.full(n, "utf8mb4", dtype=object),
                         _const_valid(n)))


def _collation_of(args, argv, n):
    coll = getattr(args[0].ft, "collation", None) or "utf8mb4_bin"
    return np.full(n, coll, dtype=object), _const_valid(n)


_reg("COLLATION", 1, 1, "string", _collation_of)
# constants are coercibility 4, columns 2 (ref: builtin_info.go Coercibility)
_reg("COERCIBILITY", 1, 1, "int",
     lambda a, argv, n: (
         np.full(n, 4 if not a[0].columns_used() else 2, np.int64),
         _const_valid(n)))


def _tidb_version(args, argv, n):
    from tidb_tpu.server import SERVER_VERSION
    return (np.full(n, f"tidb_tpu-{SERVER_VERSION}", dtype=object),
            _const_valid(n))


_reg("TIDB_VERSION", 0, 0, "string", _tidb_version)


# -- miscellaneous ------------------------------------------------------------

def _inet_aton(args, argv, n):
    d, v = argv[0]

    def one(x):
        # MySQL: 'a.b' == a<<24 | b ; short forms fill from the right
        parts = _s(x).split(".")
        if not 1 <= len(parts) <= 4 or not all(p.isdigit() for p in parts):
            return None
        vals = [int(p) for p in parts]
        if any(p > 255 for p in vals[:-1]) or vals[-1] >= 256 ** (
                5 - len(vals)):
            return None
        out = 0
        for p in vals[:-1]:
            out = (out << 8) | p
        return (out << (8 * (4 - len(vals) + 1))) | vals[-1]

    out = _vec(one, v, n, d, dtype=object)
    out, v2 = _nullable(out, v, n, fill=0)
    return np.array([int(x) for x in out], dtype=np.int64), v2


_reg("INET_ATON", 1, 1, "int", _inet_aton)


def _inet_ntoa(args, argv, n):
    d, v = argv[0]

    def one(x):
        x = int(x)
        if x < 0 or x > 0xFFFFFFFF:
            return None
        return ".".join(str((x >> s) & 0xFF) for s in (24, 16, 8, 0))

    out = _vec(one, v, n, d)
    return _nullable(out, v, n)


_reg("INET_NTOA", 1, 1, "string", _inet_ntoa)


def _inet6_aton(args, argv, n):
    d, v = argv[0]

    def one(x):
        try:
            return ipaddress.ip_address(_s(x)).packed
        except ValueError:
            return None

    out = _vec(one, v, n, d)
    return _nullable(out, v, n)


_reg("INET6_ATON", 1, 1, "string", _inet6_aton)


def _inet6_ntoa(args, argv, n):
    d, v = argv[0]

    def one(x):
        b = x if isinstance(x, bytes) else _s(x).encode("latin1")
        if len(b) == 4:
            return str(ipaddress.IPv4Address(b))
        if len(b) == 16:
            return str(ipaddress.IPv6Address(b))
        return None

    out = _vec(one, v, n, d)
    return _nullable(out, v, n)


_reg("INET6_NTOA", 1, 1, "string", _inet6_ntoa)


def _ip_pred(test):
    def fn(args, argv, n):
        d, v = argv[0]
        return _vec(lambda x: 1 if test(x) else 0, v, n, d,
                    dtype=np.int64), v
    return fn


def _is_ipv4(x):
    try:
        ipaddress.IPv4Address(_s(x))
        return True
    except ValueError:
        return False


def _is_ipv6(x):
    try:
        ipaddress.IPv6Address(_s(x))
        return True
    except ValueError:
        return False


def _packed16(x):
    b = x if isinstance(x, bytes) else _s(x).encode("latin1")
    return b if len(b) == 16 else None


_reg("IS_IPV4", 1, 1, "int", _ip_pred(_is_ipv4))
_reg("IS_IPV6", 1, 1, "int", _ip_pred(_is_ipv6))
_reg("IS_IPV4_COMPAT", 1, 1, "int", _ip_pred(
    lambda x: (lambda b: b is not None and b[:12] == b"\x00" * 12 and
               b[12:] != b"\x00\x00\x00\x00")(_packed16(x))))
_reg("IS_IPV4_MAPPED", 1, 1, "int", _ip_pred(
    lambda x: (lambda b: b is not None and
               b[:12] == b"\x00" * 10 + b"\xff\xff")(_packed16(x))))

_reg("UUID", 0, 0, "string",
     lambda a, argv, n: (np.array([str(_uuid.uuid1()) for _ in range(n)],
                                  dtype=object), _const_valid(n)))

_uuid_short_lock = threading.Lock()
_uuid_short_counter = [int(_time.time()) << 24]


def _uuid_short(args, argv, n):
    out = np.empty(n, dtype=np.int64)
    with _uuid_short_lock:
        for i in range(n):
            _uuid_short_counter[0] += 1
            out[i] = _uuid_short_counter[0] & 0x7FFFFFFFFFFFFFFF
    return out, _const_valid(n)


_reg("UUID_SHORT", 0, 0, "int", _uuid_short)

_reg("ANY_VALUE", 1, 1, "first",
     lambda a, argv, n: argv[0])


def _sleep(args, argv, n):
    d, v = argv[0]
    try:
        total = float(sum(_numf(d[i], args[0])
                          for i in range(n) if v[i]))
    except (TypeError, ValueError):
        from tidb_tpu.executor import ExecError
        raise ExecError(
            "Incorrect arguments to sleep") from None
    _time.sleep(min(max(total, 0.0), 10.0))   # bounded: KILL still works
    return np.zeros(n, dtype=np.int64), _const_valid(n)


_reg("SLEEP", 1, 1, "int", _sleep)

# args are evaluated once per chunk already; BENCHMARK just returns 0
_reg("BENCHMARK", 2, 2, "int",
     lambda a, argv, n: (np.zeros(n, dtype=np.int64), _const_valid(n)))

_reg("NAME_CONST", 2, 2, lambda args: args[1].ft,
     lambda a, argv, n: argv[1])


def _bit_count(args, argv, n):
    d, v = argv[0]
    return (_vec(lambda x: bin(int(x) & ((1 << 64) - 1)).count("1"),
                 v, n, d, dtype=np.int64), v)


_reg("BIT_COUNT", 1, 1, "int", _bit_count)

# advisory locks parse-and-succeed, like the reference's lockFunctionClass
# (builtin.go:470-473: "parsed but do nothing")
_reg("GET_LOCK", 2, 2, "int",
     lambda a, argv, n: (np.ones(n, dtype=np.int64), _const_valid(n)))
_reg("RELEASE_LOCK", 1, 1, "int",
     lambda a, argv, n: (np.ones(n, dtype=np.int64), _const_valid(n)))
_reg("IS_FREE_LOCK", 1, 1, "int",
     lambda a, argv, n: (np.ones(n, dtype=np.int64), _const_valid(n)))
_reg("IS_USED_LOCK", 1, 1, "int",
     lambda a, argv, n: (np.zeros(n, dtype=np.int64),
                         np.zeros(n, dtype=bool)))   # always NULL
_reg("RELEASE_ALL_LOCKS", 0, 0, "int",
     lambda a, argv, n: (np.zeros(n, dtype=np.int64), _const_valid(n)))


def _interval_fn(args, argv, n):
    """INTERVAL(n, a1, a2, ...): index of the last ai <= n (binary-search
    semantics; NULL n -> -1). Ref: intervalFunctionClass."""
    nd, nv = argv[0]
    out = np.empty(n, dtype=np.int64)
    for i in range(n):
        if not nv[i]:
            out[i] = -1
            continue
        x = _numf(nd[i], args[0])
        k = 0
        for j in range(1, len(argv)):
            d, av = argv[j]
            if av[i] and _numf(d[i], args[j]) <= x:
                k = j
            elif av[i]:
                break
        out[i] = k
    return out, _const_valid(n)


_reg("INTERVAL", 2, 64, "int", _interval_fn)


# -- compression / password (builtin_encryption.go) ---------------------------

def _compress(args, argv, n):
    d, v = argv[0]

    def one(x):
        b = x if isinstance(x, bytes) else _s(x).encode()
        if not b:
            return b""
        return struct.pack("<I", len(b)) + zlib.compress(b)

    return _vec(one, v, n, d), v


def _uncompress(args, argv, n):
    d, v = argv[0]

    def one(x):
        b = x if isinstance(x, bytes) else _s(x).encode("latin1")
        if not b:
            return ""
        if len(b) <= 4:
            return None
        try:
            out = zlib.decompress(b[4:])
        except zlib.error:
            return None
        if len(out) != struct.unpack("<I", b[:4])[0]:
            return None
        return out.decode("utf-8", "replace")

    out = _vec(one, v, n, d)
    return _nullable(out, v, n)


def _uncompressed_length(args, argv, n):
    d, v = argv[0]

    def one(x):
        b = x if isinstance(x, bytes) else _s(x).encode("latin1")
        if not b:
            return 0
        if len(b) <= 4:
            return 0
        return struct.unpack("<I", b[:4])[0]

    return _vec(one, v, n, d, dtype=np.int64), v


_reg("COMPRESS", 1, 1, "string", _compress)
_reg("UNCOMPRESS", 1, 1, "string", _uncompress)
_reg("UNCOMPRESSED_LENGTH", 1, 1, "int", _uncompressed_length)


def _password(args, argv, n):
    import hashlib
    d, v = argv[0]

    def one(x):
        s = _s(x)
        if not s:
            return ""
        return "*" + hashlib.sha1(
            hashlib.sha1(s.encode()).digest()).hexdigest().upper()

    return _vec(one, v, n, d), v


_reg("PASSWORD", 1, 1, "string", _password)


def _random_bytes(args, argv, n):
    import os
    d, v = argv[0]

    def one(x):
        k = int(x)
        if not 1 <= k <= 1024:
            raise ValueError("length argument to random_bytes "
                             "out of range (1..1024)")
        return os.urandom(k)

    try:
        return _vec(one, v, n, d), v
    except ValueError as e:
        from tidb_tpu.executor import ExecError
        raise ExecError(str(e)) from None


_reg("RANDOM_BYTES", 1, 1, "string", _random_bytes)


def _mysql_aes_key(key: bytes) -> bytes:
    """MySQL key folding: XOR the key bytes cyclically into 16 bytes."""
    out = bytearray(16)
    for i, b in enumerate(key):
        out[i % 16] ^= b
    return bytes(out)


_AES_HAVE_CRYPTOGRAPHY = None   # backend choice cached after first call


def _aes_ecb(k: bytes, data: bytes, encrypt: bool) -> bytes:
    """AES-128 ECB over full blocks: the `cryptography` package when the
    image ships it, else the pure-python fallback (util/aes128.py) —
    identical bytes either way (both FIPS-197). This runs per ROW, so
    the backend probe must happen once, not as a failed import per
    call (failed imports are never cached in sys.modules)."""
    global _AES_HAVE_CRYPTOGRAPHY
    if _AES_HAVE_CRYPTOGRAPHY is None:
        try:
            import cryptography.hazmat.primitives.ciphers  # noqa: F401
            _AES_HAVE_CRYPTOGRAPHY = True
        except ImportError:
            _AES_HAVE_CRYPTOGRAPHY = False
    if not _AES_HAVE_CRYPTOGRAPHY:
        from tidb_tpu.util.aes128 import decrypt_block, encrypt_block
        op = encrypt_block if encrypt else decrypt_block
        return b"".join(op(k, data[i:i + 16])
                        for i in range(0, len(data), 16))
    from cryptography.hazmat.primitives.ciphers import (Cipher,
                                                        algorithms,
                                                        modes)
    cipher = Cipher(algorithms.AES(k), modes.ECB())
    ctx = cipher.encryptor() if encrypt else cipher.decryptor()
    return ctx.update(data) + ctx.finalize()


def _aes(encrypt: bool):
    def fn(args, argv, n):
        v = _valid_all(argv, n)

        def one(x, key):
            k = _mysql_aes_key(
                key if isinstance(key, bytes) else _s(key).encode())
            data = x if isinstance(x, bytes) else _s(x).encode()
            if encrypt:
                pad = 16 - len(data) % 16
                data += bytes([pad]) * pad
                return _aes_ecb(k, data, encrypt=True)
            if len(data) % 16 or not data:
                return None
            out = _aes_ecb(k, data, encrypt=False)
            pad = out[-1]
            if not 1 <= pad <= 16 or out[-pad:] != bytes([pad]) * pad:
                return None
            try:
                return out[:-pad].decode("utf8")
            except UnicodeDecodeError:
                return out[:-pad]

        out = _vec(one, v, n, argv[0][0], argv[1][0])
        return _nullable(out, v, n)
    return fn


_reg("AES_ENCRYPT", 2, 2, "string", _aes(True))
_reg("AES_DECRYPT", 2, 2, "string", _aes(False))


# -- JSON modify/search (builtin_json.go) -------------------------------------

def _json_quote(args, argv, n):
    d, v = argv[0]
    return _vec(lambda x: _jdump(_s(x)), v, n, d), v


_reg("JSON_QUOTE", 1, 1, "string", _json_quote)


def _set_path(doc, steps, value, create, replace):
    """In-place path set. `create`: may add a new leaf; `replace`: may
    overwrite an existing one (JSON_SET: both; INSERT: create only;
    REPLACE: replace only)."""
    if not steps:
        return value if replace else doc
    cur = doc
    for s in steps[:-1]:
        if isinstance(s, int):
            if not isinstance(cur, list) or not (0 <= s < len(cur)):
                return doc
            cur = cur[s]
        else:
            if not isinstance(cur, dict) or s not in cur:
                return doc
            cur = cur[s]
    last = steps[-1]
    if isinstance(last, int):
        if not isinstance(cur, list):
            # MySQL: autowrap scalar -> array when appending at [N]
            return doc
        if 0 <= last < len(cur):
            if replace:
                cur[last] = value
        elif create:
            cur.append(value)
    else:
        if isinstance(cur, dict):
            if last in cur:
                if replace:
                    cur[last] = value
            elif create:
                cur[last] = value
    return doc


def _json_modify(create, replace):
    def fn(args, argv, n):
        if len(argv) % 2 == 0:
            from tidb_tpu.executor import ExecError
            raise ExecError("Incorrect parameter count")
        from tidb_tpu.expression.builtins import _arg_to_json
        dv, docv = argv[0]
        out = np.empty(n, dtype=object)
        ok = docv.copy()
        for i in range(n):
            if not docv[i]:
                out[i] = ""
                continue
            doc = _jload(dv[i])
            null_path = False
            for k in range(1, len(argv), 2):
                pd_, pv = argv[k]
                vd, vv = argv[k + 1]
                if not pv[i]:
                    null_path = True
                    break
                val = _arg_to_json(vd[i], vv[i], args[k + 1])
                doc = _set_path(doc, list(_parse_path(_s(pd_[i]))),
                                val, create, replace)
            if null_path:
                ok[i] = False
                out[i] = ""
            else:
                out[i] = _jdump(doc)
        return out, ok
    return fn


_reg("JSON_SET", 3, 32, _json_ft,
     _wrap_path_errors(_json_modify(True, True)))
_reg("JSON_INSERT", 3, 32, _json_ft,
     _wrap_path_errors(_json_modify(True, False)))
_reg("JSON_REPLACE", 3, 32, _json_ft,
     _wrap_path_errors(_json_modify(False, True)))


def _remove_path(doc, steps):
    if not steps:
        return doc
    cur = doc
    for s in steps[:-1]:
        if isinstance(s, int):
            if not isinstance(cur, list) or not (0 <= s < len(cur)):
                return doc
            cur = cur[s]
        else:
            if not isinstance(cur, dict) or s not in cur:
                return doc
            cur = cur[s]
    last = steps[-1]
    if isinstance(last, int):
        if isinstance(cur, list) and 0 <= last < len(cur):
            del cur[last]
    elif isinstance(cur, dict) and last in cur:
        del cur[last]
    return doc


def _json_remove(args, argv, n):
    dv, docv = argv[0]
    v = _valid_all(argv, n)
    out = np.empty(n, dtype=object)
    for i in range(n):
        if not v[i]:
            out[i] = ""
            continue
        doc = _jload(dv[i])
        for k in range(1, len(argv)):
            doc = _remove_path(doc, list(_parse_path(_s(argv[k][0][i]))))
        out[i] = _jdump(doc)
    return out, v


_reg("JSON_REMOVE", 2, 32, _json_ft, _wrap_path_errors(_json_remove))


def _merge_two(a, b):
    """MySQL 5.7 JSON_MERGE: arrays concat; objects merge recursively;
    scalars wrap into arrays."""
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v2 in b.items():
            out[k] = _merge_two(out[k], v2) if k in out else v2
        return out
    la = a if isinstance(a, list) else [a]
    lb = b if isinstance(b, list) else [b]
    return la + lb


def _json_merge(args, argv, n):
    v = _valid_all(argv, n)
    out = np.empty(n, dtype=object)
    for i in range(n):
        if not v[i]:
            out[i] = ""
            continue
        doc = _jload(argv[0][0][i])
        for k in range(1, len(argv)):
            doc = _merge_two(doc, _jload(argv[k][0][i]))
        out[i] = _jdump(doc)
    return out, v


_reg("JSON_MERGE", 2, 32, _json_ft, _json_merge)


def _json_array_append(args, argv, n):
    if len(argv) % 2 == 0:
        from tidb_tpu.executor import ExecError
        raise ExecError("Incorrect parameter count")
    from tidb_tpu.expression.builtins import _arg_to_json
    dv, docv = argv[0]
    v = _valid_all(argv, n)
    out = np.empty(n, dtype=object)
    for i in range(n):
        if not v[i]:
            out[i] = ""
            continue
        doc = _jload(dv[i])
        for k in range(1, len(argv), 2):
            steps = list(_parse_path(_s(argv[k][0][i])))
            val = _arg_to_json(argv[k + 1][0][i], argv[k + 1][1][i],
                               args[k + 1])
            found, target = _walk(doc, steps)
            if not found:
                continue
            wrapped = target + [val] if isinstance(target, list) \
                else [target, val]
            if steps:
                doc = _set_path(doc, steps, wrapped, False, True)
            else:
                doc = wrapped
        out[i] = _jdump(doc)
    return out, v


_reg("JSON_ARRAY_APPEND", 3, 32, _json_ft,
     _wrap_path_errors(_json_array_append))


def _json_contains_path(args, argv, n):
    v = _valid_all(argv, n)
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        if not v[i]:
            continue
        doc = _jload(argv[0][0][i])
        mode = _s(argv[1][0][i]).lower()
        if mode not in ("one", "all"):
            from tidb_tpu.executor import ExecError
            raise ExecError(
                "The oneOrAll argument to json_contains_path may take "
                "these values: 'one' or 'all'")
        hits = [
            _walk(doc, _parse_path(_s(argv[k][0][i])))[0]
            for k in range(2, len(argv))]
        out[i] = int(all(hits) if mode == "all" else any(hits))
    return out, v


_reg("JSON_CONTAINS_PATH", 3, 32, "int",
     _wrap_path_errors(_json_contains_path))


def _depth(doc) -> int:
    if isinstance(doc, dict):
        return 1 + max((_depth(v) for v in doc.values()), default=0)
    if isinstance(doc, list):
        return 1 + max((_depth(v) for v in doc), default=0)
    return 1


_reg("JSON_DEPTH", 1, 1, "int",
     lambda a, argv, n: (
         _vec(lambda x: _depth(_jload(x)), argv[0][1], n, argv[0][0],
              dtype=np.int64), argv[0][1]))


def _like_match(pat: str, s: str) -> bool:
    import re
    rx = re.escape(pat).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, s, re.S) is not None


def _search_paths(doc, pat, prefix="$"):
    hits = []
    if isinstance(doc, str):
        if _like_match(pat, doc):
            hits.append(prefix)
    elif isinstance(doc, dict):
        for k, v2 in doc.items():
            hits.extend(_search_paths(v2, pat, f'{prefix}.{k}'))
    elif isinstance(doc, list):
        for j, v2 in enumerate(doc):
            hits.extend(_search_paths(v2, pat, f"{prefix}[{j}]"))
    return hits


def _json_search(args, argv, n):
    v = _valid_all(argv, n)
    out = np.empty(n, dtype=object)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        out[i] = ""
        if not v[i]:
            continue
        doc = _jload(argv[0][0][i])
        mode = _s(argv[1][0][i]).lower()
        if mode not in ("one", "all"):
            from tidb_tpu.executor import ExecError
            raise ExecError(
                "The oneOrAll argument to json_search may take these "
                "values: 'one' or 'all'")
        hits = _search_paths(doc, _s(argv[2][0][i]))
        if not hits:
            continue
        ok[i] = True
        out[i] = _jdump(hits[0]) if mode == "one" or len(hits) == 1 \
            else _jdump(hits)
    return out, ok


_reg("JSON_SEARCH", 3, 3, _json_ft, _json_search)
