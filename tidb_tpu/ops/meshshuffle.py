"""Plane shuffle hash join: all_to_all repartition + per-chip sort join.

The reference's general hash join (/root/reference/executor/join.go:37)
builds an mvmap from the whole build side and probes it with worker
goroutines; scaled out, both sides would be repartitioned by key hash
across nodes. On the device plane that repartition is ONE collective:
each chip buckets its row shard by destination chip (hash mod n), an
``all_to_all`` over the ``"batch"`` axis exchanges the buckets over ICI,
and every chip then joins only its hash partition with the same
sort/searchsorted matcher as the single-chip kernel (ops/join.py).
Per-chip memory is O(N/ndev) for both sides — unlike the replicated-
dimension lookup join (ops/meshjoin.py), duplicate keys on either side
and build sides too large to replicate are fine. On a 1-device plane
the kernel delegates to the single-chip JoinKernel: no shuffle at all.

Static-shape handling (XLA cannot see data-dependent counts):
* send buckets have a fixed per-destination capacity; a pmax over the
  true bucket sizes detects overflow, and the host retries with larger
  buckets — heavy-hitter skew is absorbed by capacity escalation, the
  per-destination growth bounded by the shard size itself.
* the matcher emits into a fixed per-chip pair capacity with the same
  total-count overflow/retry protocol as ops/join.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tidb_tpu import devplane
from tidb_tpu.devplane import AXIS
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import _FILL, _SENTINEL_MASKED, _hash_keys
from tidb_tpu.ops.join import JoinKernel, match_pairs

__all__ = ["MeshShuffleJoinKernel", "ShuffleOverflowError"]

_DEAD_BUILD = _SENTINEL_MASKED
_DEAD_PROBE = _FILL
_HASH_SEED = 0x9E3779B97F4A7C15


class ShuffleOverflowError(Exception):
    """A shuffle bucket or the pair output exceeded its static capacity
    beyond the retry budget (extreme hash skew)."""


def _bucketize(xp, ndev, cap, dst, keep, lanes, fills):
    """Scatter each row's lanes into its destination bucket.
    -> ([ndev*cap] buffers per lane, local max bucket fill)."""
    n = dst.shape[0]
    order = xp.argsort(dst)
    sdst = dst[order]
    first = xp.searchsorted(sdst, sdst, side="left")
    rank = xp.arange(n) - first
    # dropped rows (dead/padding) and overflowing ranks park on a dump
    # slot past the buffer end
    ok = keep[order] & (rank < cap)
    slot = xp.where(ok, sdst * cap + rank, ndev * cap)
    out = []
    for lane, fill in zip(lanes, fills):
        buf = xp.full(ndev * cap + 1, fill, dtype=lane.dtype)
        out.append(buf.at[slot].set(lane[order])[:-1])
    maxfill = xp.max(xp.where(keep[order], rank + 1, 0), initial=0)
    return out, maxfill


class MeshShuffleJoinKernel:
    """Distributed equi-join pair matcher. Call signature mirrors
    ops/join.py JoinKernel: fixed-width key lanes in, (probe_idx,
    build_idx) numpy pair arrays out, so the executor's host-side payload
    gather is unchanged."""

    def __init__(self, mesh, num_keys: int):
        self.mesh = mesh
        self.ndev = devplane.ndev(mesh)
        self.num_keys = num_keys
        self._jits: dict = {}
        self._single = JoinKernel(num_keys) if self.ndev == 1 else None
        # one-slot build-side transfer memo: a streamed probe calls the
        # kernel once per super-batch against the SAME build keys object;
        # pinning it (identity compare) makes every batch after the first
        # re-send only the probe. One slot bounds pinned device memory.
        self._build_memo = None       # (build_keys_obj, shard_len, arrays)

    # -- traced program ------------------------------------------------------

    def _program(self, ls, rs, cap_l, cap_r, out_cap):
        ndev = self.ndev

        def shard_side(keys, n, shard_len, dead, is_probe):
            # lint: exempt[dtype-discipline] global row offsets are exact int64 (shard base can exceed int32)
            ci = lax.axis_index(AXIS).astype(jnp.int64)
            offs = ci * shard_len
            alive = (offs + jnp.arange(shard_len)) < n
            valid = alive
            for _d, v in keys:
                valid = valid & v
            h = _hash_keys(jnp, [(d, v & valid) for d, v in keys],
                           shard_len, seed=_HASH_SEED)
            h = jnp.where(valid, h, dead)
            # dead rows (NULL keys, shard padding) route past every real
            # bucket so they never inflate a live bucket's ranks
            dst = jnp.where(
                valid,
                # lint: exempt[dtype-discipline] route lanes ride the int64 hash dtype (dead-row sentinel = ndev)
                (h.astype(jnp.uint64) % np.uint64(ndev)).astype(jnp.int64),
                ndev)
            gidx = offs + jnp.arange(shard_len)
            cap = cap_l if is_probe else cap_r
            lanes = [h, gidx] + [d for d, _v in keys]
            fills = [dead, -1] + [np.array(0, d.dtype) for d, _v in keys]
            bufs, maxfill = _bucketize(jnp, ndev, cap, dst, valid,
                                       lanes, fills)
            exch = [lax.all_to_all(b.reshape(ndev, cap), AXIS, 0, 0)
                    .reshape(ndev * cap) for b in bufs]
            return exch[0], exch[1], exch[2:], maxfill

        def kernel(lkeys, rkeys, nl, nr):
            hp, pli, pd, ofl_l = shard_side(lkeys, nl, ls, _DEAD_PROBE,
                                            True)
            hb, bli, bd, ofl_r = shard_side(rkeys, nr, rs, _DEAD_BUILD,
                                            False)
            # per-partition sort join: the shared matcher of ops/join.py
            li_c, ri, ok, total = match_pairs(jnp, hb, hp, bd, pd, out_cap)
            gl = jnp.where(ok, pli[li_c], -1)
            gr = jnp.where(ok, bli[ri], -1)
            return (gl, gr, ok, total.reshape(1), ofl_l.reshape(1),
                    ofl_r.reshape(1))

        spec_row = devplane.batch_spec()
        nk = self.num_keys
        in_specs = (tuple((spec_row, spec_row) for _ in range(nk)),
                    tuple((spec_row, spec_row) for _ in range(nk)),
                    P(), P())
        out_specs = (spec_row, spec_row, spec_row,
                     spec_row, spec_row, spec_row)
        sm = devplane.shard_map(kernel, self.mesh, in_specs=in_specs,
                                out_specs=out_specs)
        return devplane.plane_jit(sm)

    # -- host driver ---------------------------------------------------------

    def _put_side(self, keys, shard_len):
        sh = devplane.batch_sharding(self.mesh)
        out = []
        for d, v in keys:
            pd_, pv = runtime.pad_column(np.asarray(d), np.asarray(v),
                                         shard_len * self.ndev)
            # numpy straight into the sharded device_put: one transfer,
            # no commit-then-reshard hop
            out.append((jax.device_put(pd_, sh), jax.device_put(pv, sh)))
        return tuple(out)

    def finalize(self, pending):
        """One batched device->host readback — the kernel's output
        boundary, shared by the retry loop's control read (the small
        overflow counters land first so a retry discards the cap-sized
        pair buffers without transferring them) and the success path's
        pair read (per-array reads each pay full round-trip latency
        through the tunnel)."""
        return jax.device_get(pending)

    def __call__(self, probe_keys, build_keys, nb: int, np_: int):
        """probe/build key lanes [(data, valid)] -> (li, ri) pair arrays.
        Argument order mirrors JoinKernel.__call__(bk, pk, nb, np_) users:
        here probe first for readability, sizes last."""
        if self._single is not None:
            return self._single(build_keys, probe_keys, nb, np_)
        if nb == 0 or np_ == 0:
            return (np.empty(0, np.int64), np.empty(0, np.int64))
        ndev = self.ndev
        ls = runtime.bucket_size(-(-max(np_, 1) // ndev))
        rs = runtime.bucket_size(-(-max(nb, 1) // ndev))
        # expected per-destination fill is shard/ndev; 4x slack absorbs
        # ordinary skew, the retry loop the rest
        cap_l = min(ls, runtime.bucket_size(max(-(-ls // ndev) * 4, 16)))
        cap_r = min(rs, runtime.bucket_size(max(-(-rs // ndev) * 4, 16)))
        out_cap = runtime.bucket_size(max(2 * ls, 1024))
        lk = self._put_side(probe_keys, ls)
        memo = self._build_memo
        if memo is not None and memo[0] is build_keys and memo[1] == rs:
            rk = memo[2]
        else:
            rk = self._put_side(build_keys, rs)
            self._build_memo = (build_keys, rs, rk)
        for _ in range(8):
            key = (ls, rs, cap_l, cap_r, out_cap)
            prog = self._jits.get(key)
            if prog is None:
                prog = self._program(*key)
                self._jits[key] = prog
            gl, gr, ok, totals, fl, fr = prog(lk, rk, np_, nb)
            # small control arrays first: an overflow retry then discards
            # the cap-sized pair buffers without transferring them
            totals, fl, fr = self.finalize((totals, fl, fr))
            need_l = int(np.max(fl))
            need_r = int(np.max(fr))
            max_total = int(np.max(totals))
            if need_l > cap_l:
                cap_l = min(ls, runtime.bucket_size(need_l))
                continue
            if need_r > cap_r:
                cap_r = min(rs, runtime.bucket_size(need_r))
                continue
            if max_total > out_cap:
                out_cap = runtime.bucket_size(max_total)
                continue
            gl, gr, ok = self.finalize((gl, gr, ok))
            sel = np.flatnonzero(ok)
            return (gl[sel].astype(np.int64),
                    gr[sel].astype(np.int64))
        raise ShuffleOverflowError("shuffle join retry budget exhausted")
