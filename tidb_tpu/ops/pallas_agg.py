"""Pallas TPU kernel: group-by segment-sum as a one-hot MXU matmul.

The group-by aggregation's inner op is segment-reduce: rows scatter
into C group slots. XLA lowers `jax.ops.segment_sum` to scatter-add,
which serializes on the TPU's vector unit; the MXU-native formulation
is a ONE-HOT MATMUL per row tile:

    onehot[T, C] = (ids[:, None] == iota(C)[None, :])
    out[C, K]   += onehot.T @ values[T, K]

— a [C, T] x [T, K] contraction the 128x128 systolic array eats whole
(pallas_guide.md "matmuls are where the FLOPs are"). The kernel tiles
rows over a sequential grid and accumulates into a VMEM-resident [C, K]
output block (constant index map — the standard revisiting/accumulate
pattern), so HBM traffic is one pass over the rows plus one [C, K]
writeback.

Engagement rules (auto, see `available()`):
  * TPU backend only — on CPU the scatter path is faster (measured);
  * float32 value lanes (the MXU contraction dtype); int64-exact lanes
    (decimal sums, counts) stay on the scatter path, exactness first;
  * C <= 4096 so the accumulator tile stays well inside VMEM.

Correctness is validated in interpret mode on CPU (tests/
test_pallas_agg.py) — the same kernel runs compiled on a real chip.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # noqa: BLE001 - pallas not in this jax build
    _HAS_PALLAS = False

__all__ = ["available", "segment_sum_pallas", "segment_sum"]

_TILE = 512          # rows per grid step
_MAX_C = 4096


def available(platform: str | None = None) -> bool:
    """True when the pallas path should engage (TPU + pallas present).
    TIDB_TPU_PALLAS=0 is the kill switch if a chip runtime ever rejects
    the kernel (e.g. inside an exotic shard_map nesting)."""
    import os
    if not _HAS_PALLAS or os.environ.get("TIDB_TPU_PALLAS", "1") == "0":
        return False
    if platform is None:
        try:
            platform = jax.devices()[0].platform
        except Exception:  # noqa: BLE001 - no backend
            return False
    return platform == "tpu"


def _kernel(ids_ref, vals_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ids = ids_ref[:]                       # [T, 1] int32
    c = out_ref.shape[0]
    onehot = (ids == jax.lax.broadcasted_iota(
        jnp.int32, (ids.shape[0], c), 1)).astype(vals_ref.dtype)
    # [C, T] x [T, K] on the MXU; accumulate across the sequential grid
    out_ref[:] += jax.lax.dot_general(
        onehot, vals_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype)


def _kernel_masked(ids_ref, vals_ref, valid_ref, out_ref):
    """Fused predicate + segment-sum tile: the per-lane validity mask is
    applied INSIDE the kernel on the VMEM-resident tile (jnp.where, so a
    NaN/garbage value under a dead mask can never poison the sum) before
    the one-hot contraction — the scan->filter->partial-agg fusion that
    removes the separate HBM-materialized `where(live, d, 0)` pass the
    unfused path pays per lane."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ids = ids_ref[:]                       # [T, 1] int32
    c = out_ref.shape[0]
    vals = jnp.where(valid_ref[:], vals_ref[:],
                     jnp.zeros((), vals_ref.dtype))
    onehot = (ids == jax.lax.broadcasted_iota(
        jnp.int32, (ids.shape[0], c), 1)).astype(vals_ref.dtype)
    out_ref[:] += jax.lax.dot_general(
        onehot, vals, (((0,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "interpret"))
def segment_sum_pallas(values, ids, num_segments: int,
                       interpret: bool = False, valid=None):
    """MXU segment-sum: values [n, k] float32, ids [n] int32 in
    [0, num_segments) -> [num_segments, k]. Rows are padded to the tile
    size with a dead segment that is sliced off. With `valid` ([n] or
    [n, k] bool) the mask is fused into the kernel: a row/lane
    contributes only where valid — the predicate never materializes a
    masked copy of the values in HBM."""
    if values.ndim == 1:
        values = values[:, None]
    n, k = values.shape
    c_pad = num_segments + 1               # dead slot for padding rows
    pad = (-n) % _TILE
    if valid is not None:
        valid = valid[:, None] if valid.ndim == 1 else valid
        vk = valid.shape[1]
    if pad:
        values = jnp.concatenate(
            [values, jnp.zeros((pad, k), values.dtype)])
        ids = jnp.concatenate(
            [ids, jnp.full((pad,), num_segments, jnp.int32)])
        if valid is not None:
            valid = jnp.concatenate(
                [valid, jnp.zeros((pad, vk), valid.dtype)])
    ids2 = ids.astype(jnp.int32)[:, None]
    grid = (values.shape[0] // _TILE,)
    if valid is None:
        kernel, args = _kernel, (ids2, values)
        in_specs = [
            pl.BlockSpec((_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, k), lambda i: (i, 0)),
        ]
    else:
        kernel, args = _kernel_masked, (ids2, values, valid)
        in_specs = [
            pl.BlockSpec((_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, k), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, vk), lambda i: (i, 0)),
        ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((c_pad, k), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, k), values.dtype),
        interpret=interpret,
    )(*args)
    return out[:num_segments]


def segment_sum(values, ids, num_segments: int, valid=None):
    """Dispatcher: pallas on TPU float lanes within capacity, XLA
    scatter otherwise (exactness for int lanes, speed on CPU). The
    output shape mirrors jax.ops.segment_sum exactly: 1-D in -> 1-D
    out. `valid` is the fused predicate mask: on the pallas path it is
    applied inside the kernel tile; on the scatter path it lowers to the
    classic `where(valid, v, 0)` pre-pass (XLA fuses it into the
    scatter's operand, so both paths sum exactly the masked values)."""
    v = jnp.asarray(values)
    if available() and v.dtype == jnp.float32 and \
            num_segments <= _MAX_C:
        out = segment_sum_pallas(v, ids, num_segments, valid=valid)
        return out[:, 0] if v.ndim == 1 else out
    if valid is not None:
        mask = valid if valid.ndim == v.ndim else valid[:, None]
        v = jnp.where(mask, v, jnp.zeros((), v.dtype))
    return jax.ops.segment_sum(v, ids, num_segments=num_segments)
