"""TPU operator kernels: the analytical data plane.

This package replaces the reference's Go chunk executors — SelectionExec
(executor/executor.go:689), ProjectionExec (:598), HashAggExec
(executor/aggregate.go:32), and the per-row Datum evaluation of
expression/chunk_executor.go:67-100 (the reference's biggest CPU sink,
SURVEY.md §3.2) — with jit-compiled whole-column XLA programs.

Design (SURVEY.md §7 stages 4-5):
* Chunks are padded to bucketed static shapes so XLA compiles one program
  per (plan, bucket) instead of per batch size.
* NULLs ride as bool validity arrays; the filter mask folds into validity.
* Group-by is hash-based: 64-bit mixed key hash -> sorted-unique (static
  capacity) -> segment reduce. Dynamic hash tables (the reference's mvmap)
  don't fit XLA's static shapes; sort+segment is the TPU-native recast.
* Every aggregate produces fixed-width partial states (expression/agg.py)
  so storage-side partial agg / root-side final agg — and psum-style mesh
  merges — compose exactly like the reference's partial-agg protocol
  (expression/aggregation/aggregation.go:36-41).
"""

from tidb_tpu.ops.runtime import (bucket_size, device_put_chunk,
                                  eval_filter_host)
from tidb_tpu.ops.hashagg import HashAggKernel, ScalarAggKernel, AggSpec

__all__ = ["bucket_size", "device_put_chunk", "eval_filter_host",
           "HashAggKernel", "ScalarAggKernel", "AggSpec"]
