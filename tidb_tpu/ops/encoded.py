"""Encoded execution: operate on dictionary codes end-to-end.

BENCH r05 measured roofline_fraction geomean 0.229 with most device time
spent moving bytes the query never needed: varlen columns decoded into
wide host vectors at the device-cache boundary, string predicates
evaluated over object arrays on the host (which also rewrote the chunk
and disqualified it from the fused HBM-cache dispatch), and every join
side re-building its key dictionary with a per-row Python loop. This
module keeps the data ENCODED across those boundaries:

* `translate_filter` rewrites a host-only string filter (EQ/NE/<=>/IN/
  IS [NOT] NULL over varlen columns, AND/OR combinations, device-safe
  subtrees passed through) into code space: the column rides the device
  as its int64 dict codes (exactly what `runtime.device_put_chunk`
  ships), and each string constant is pre-encoded to its code in the
  SAME dictionary — equality over codes is equality over values by
  construction (collation-folded dictionaries keep _ci semantics). The
  rewritten filter is device-safe, so the fused scan->filter->
  partial-agg dispatch keeps running from HBM-resident columns instead
  of falling back to a host filter pass + re-upload.
* `code_translation` re-keys one dictionary's codes into another's with
  a single vectorized gather — the join build/probe bridge when the two
  sides hold different dictionaries. Sides sharing one dictionary (the
  memoized `dict_encode` of a cached column) skip even that.
* `decode_codes` is THE registered full-column late-materializer: the
  only sanctioned way to decode a whole column from its dictionary
  (lint rule `decode-discipline` — everything else must decode at most
  representative rows at the operator-output finalize boundary).

Anything outside this vocabulary returns None and the caller runs the
decoded path, counted in tidb_tpu_device_fallback_total{reason=
"encoding"}. Gated by the `tidb_tpu_encoded_exec` sysvar.

Known tradeoff: a translated constant is a dictionary-specific CODE
baked into the kernel fingerprint, so distinct dictionaries (one per
region block) trace distinct programs for one plan shape. Dictionaries
are memoized per cached column — stable across executions — so warm
serving converges on one kernel per (plan, region), held by the
widened process-wide kernel cache and the persistent XLA compile
cache. Passing codes as runtime operands (one program per plan) is the
next step if region counts grow past that.
"""

from __future__ import annotations

import numpy as np

from tidb_tpu.chunk import dict_encode
from tidb_tpu.expression.core import ColumnRef, Constant, Op, ScalarFunc, func
from tidb_tpu.sqltypes import EvalType, TypeCode, new_int_field

__all__ = ["CodeColumnRef", "translate_filter", "code_translation",
           "encoded_lane", "decode_codes", "MISSING_CODE",
           "LATE_MATERIALIZE"]

# a code no live row ever carries (live codes >= 0, NULL is -1): an
# encoded constant absent from the dictionary compares equal to nothing
MISSING_CODE = -2

_CODE_FT = new_int_field()

# (repo-relative file, function name) of every sanctioned full-column
# decode site — the decode-discipline lint rule exempts decode-shaped
# gathers inside these functions and flags them everywhere else in
# ops/ + store/copr.py. finalize_group_result decodes representative
# rows only, but owns the one place agg outputs late-materialize.
LATE_MATERIALIZE = frozenset({
    ("tidb_tpu/ops/encoded.py", "decode_codes"),
    ("tidb_tpu/ops/hashagg.py", "finalize_group_result"),
})


class CodeColumnRef(ColumnRef):
    """A varlen column viewed as its int64 dictionary codes — the lane
    `runtime.device_put_chunk` (and the HBM cache block) actually holds
    on device. Device-safe by construction: the inherited eval_xp reads
    cols[idx], which on the device path IS the code lane (validity lane
    carries the column's NULLs). Never evaluated on the host — encoded
    filters exist only on the device dispatch path."""

    def __repr__(self):
        return f"codes({self.name or f'col#{self.idx}'})"

    def __hash__(self):
        return hash(("codecol", self.idx))

    def eval(self, chunk):
        # the host chunk holds VALUES in this lane, not codes: silently
        # comparing strings against an int code would drop every row.
        # Encoded filters must never reach a host evaluator — callers
        # fall back to the ORIGINAL filter on any host path.
        raise RuntimeError("encoded filter evaluated on the host path")


class _Unsupported(Exception):
    """Filter node outside the encodable vocabulary."""


def _dict_key(v, ci: bool):
    if ci:
        from tidb_tpu.sqltypes import collation_key
        return collation_key(v)
    return v


def _dict_map(values: list, ci: bool) -> dict:
    return {_dict_key(v, ci): c for c, v in enumerate(values)}


def _is_varlen_ref(e, chunk) -> bool:
    return (type(e) is ColumnRef and
            e.ft.eval_type == EvalType.STRING and
            e.ft.tp != TypeCode.JSON and
            e.idx < chunk.num_cols and
            not chunk.columns[e.idx].fixed_width)


def _code_const(values: list, ci: bool, const: Constant) -> Constant:
    """Pre-encode one string constant against the dictionary. NULL
    constants stay NULL (comparisons with them are never true, exactly
    as in value space); absent values get MISSING_CODE."""
    v = const.value
    if v is None:
        return Constant(None, _CODE_FT)
    if not isinstance(v, (str, bytes)):
        raise _Unsupported(f"non-string constant {v!r}")
    code = _dict_map_cached(values, ci).get(_dict_key(v, ci))
    return Constant(int(code) if code is not None else MISSING_CODE,
                    _CODE_FT)


# per-translation map cache: one (values -> map) pair, keyed by list
# identity. Dictionaries are memoized per column (chunk.dict_encode),
# so repeated translations over a hot cached chunk rebuild nothing; the
# one-slot shape keeps the cache O(1) without weakrefs (lists don't
# support them).
_map_cache: tuple = (None, False, None)


def _dict_map_cached(values: list, ci: bool) -> dict:
    global _map_cache
    vals, cci, m = _map_cache
    if vals is values and cci is ci and len(m) == len(values):
        return m
    m = _dict_map(values, ci)
    _map_cache = (values, ci, m)
    return m


def translate_filter(expr, chunk, dict_of=None):
    """Rewrite a host-only filter into code space. -> a device-safe
    Expression over dictionary codes, or None when any node falls
    outside the encodable vocabulary (the caller then runs the decoded
    path and counts the fallback as reason="encoding").

    `dict_of(col_idx) -> values list` overrides where dictionaries come
    from — the fused HBM path passes the resident block's (incrementally
    extended) dictionaries so constant codes match the code lanes the
    kernel actually reads; the default is the chunk's own memoized
    dict_encode, which is what `device_put_chunk` ships on the upload
    path."""
    if expr is None:
        return None
    if dict_of is None:
        def dict_of(j):
            return dict_encode(chunk.columns[j])[1]
    try:
        return _translate(expr, chunk, dict_of)
    except _Unsupported:
        return None


def _translate(e, chunk, dict_of):
    if e.is_device_safe():
        return e                    # mixed AND/OR trees pass through
    if not isinstance(e, ScalarFunc):
        raise _Unsupported(type(e).__name__)
    op = e.op
    if op in (Op.AND, Op.OR):
        return func(op, _translate(e.args[0], chunk, dict_of),
                    _translate(e.args[1], chunk, dict_of))
    if op in (Op.IS_NULL, Op.IS_NOT_NULL):
        a = e.args[0]
        if not _is_varlen_ref(a, chunk):
            raise _Unsupported(repr(a))
        return func(op, CodeColumnRef(a.idx, _CODE_FT, a.name))
    if op in (Op.EQ, Op.NE, Op.NULLEQ):
        a, b = e.args
        if _is_varlen_ref(a, chunk) and isinstance(b, Constant):
            ref, const = a, b
        elif _is_varlen_ref(b, chunk) and isinstance(a, Constant):
            ref, const = b, a
        else:
            raise _Unsupported(repr(e))
        values = dict_of(ref.idx)
        if values is None:
            raise _Unsupported(f"no dictionary for col#{ref.idx}")
        code_ref = CodeColumnRef(ref.idx, _CODE_FT, ref.name)
        ci = ref.ft.is_ci
        if ref is a:
            return func(op, code_ref, _code_const(values, ci, const))
        return func(op, _code_const(values, ci, const), code_ref)
    if op == Op.IN:
        a = e.args[0]
        if not _is_varlen_ref(a, chunk) or not isinstance(e.extra, list):
            raise _Unsupported(repr(e))
        values = dict_of(a.idx)
        if values is None:
            raise _Unsupported(f"no dictionary for col#{a.idx}")
        ci = a.ft.is_ci
        codes = []
        for v in e.extra:
            if not isinstance(v, (str, bytes)):
                raise _Unsupported(f"non-string IN item {v!r}")
            c = _dict_map_cached(values, ci).get(_dict_key(v, ci))
            codes.append(int(c) if c is not None else MISSING_CODE)
        return func(Op.IN, CodeColumnRef(a.idx, _CODE_FT, a.name),
                    extra=codes)
    raise _Unsupported(repr(e))


def encoded_lane(expr, chunk):
    """(codes, values) when `expr` is a bare varlen ColumnRef into
    `chunk` — the pre-encoded key lane a join consumes directly — else
    None. Codes/values are the column's memoized dict_encode, so two
    sides reading the same cached column share ONE dictionary object
    (identity comparison detects it)."""
    if not _is_varlen_ref(expr, chunk):
        return None
    return dict_encode(chunk.columns[expr.idx])


def code_translation(src_values: list, dst_values: list, ci: bool,
                     dst_map: dict | None = None) -> np.ndarray:
    """Re-keying bridge between two dictionaries: an int64 array T with
    T[src_code] = the matching code in `dst_values`, or a unique
    negative no-match code (<= MISSING_CODE) when the value is absent —
    rows stay live (outer-join semantics) but match nothing. The last
    slot maps the NULL code: T[codes] with codes == -1 indexes it and
    yields -1, so NULL stays NULL through the translation. `dst_map`
    lets a caller with a cached value->code map (JoinKeyEncoder, one
    map per build side vs one translation per probe batch) skip the
    O(|dst|) rebuild."""
    if dst_map is None:
        dst_map = _dict_map(dst_values, ci)
    # lint: exempt[memtrack-alloc] dictionary-sized (distinct values), not row-sized
    t = np.empty(len(src_values) + 1, dtype=np.int64)
    for c, v in enumerate(src_values):
        hit = dst_map.get(_dict_key(v, ci))
        t[c] = hit if hit is not None else MISSING_CODE - c
    t[-1] = -1
    return t


def decode_codes(values: list, codes: np.ndarray) -> np.ndarray:
    """THE registered full-column late-materializer (decode-discipline):
    gather dictionary values by code into an object array (NULL/-1 and
    no-match codes decode to None). Call this only at operator-output
    finalize boundaries — decoding a whole column anywhere else defeats
    encoded execution and the lint rule will flag it."""
    # lint: exempt[memtrack-alloc] dictionary-sized decode table; the gathered output aliases existing values
    table = np.empty(len(values) + 1, dtype=object)
    for c, v in enumerate(values):
        table[c] = v
    table[-1] = None
    safe = np.where(codes >= 0, codes, len(values))
    return table[safe]
