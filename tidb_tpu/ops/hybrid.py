"""Skew-aware, spill-capable partitioned hybrid hash join & aggregation.

Kills the device->host fallback cliff: before this module, ANY capacity
or collision miss in a device join/agg kernel dropped the whole operator
back to the host numpy path — the worst possible outcome under real data
skew, exactly when the device win matters most. Two mechanisms replace
the all-or-nothing scheme (ROADMAP item 2; arxiv 2112.02480 "Robust
Dynamic Hybrid Hash Join", 2505.04153 "Global Hash Tables Strike
Back!"):

  * **Radix partitioning.** Build and probe keys split into
    `tidb_tpu_join_partitions` hash partitions (equal keys -> equal
    hash -> same partition), so a miss retries ONE partition — each
    partition sees ~1/P of the groups/pairs, and a partition that still
    misses falls back alone while the rest stay on device.

  * **Heavy-hitter lane.** Keys whose build-side duplication or
    probe-side frequency reaches `tidb_tpu_skew_threshold` rows route
    to a dedicated broadcast lane: the hot build rows form their own
    tiny always-resident "partition", sized exactly from known per-key
    counts, so one hot key can never overflow the hash partition it
    would otherwise land in. The initial hot set is seeded from the
    probe table's ANALYZE-time `statistics.CMSketch` (when the planner
    can trace the probe key to a base column) plus exact build-side
    counts; a streaming CMSketch over OBSERVED probe keys promotes
    late-discovered hot keys mid-stream (the "dynamic" in dynamic
    hybrid hash join).

The build side is the flagship consumer of memtrack's spill machinery:
`HybridJoinBuild` registers a quota OOM action that sheds cold
device-resident build partitions (their host key lanes remain), so
under `tidb_tpu_mem_quota_query` pressure the join completes by staging
cold partitions' probe rows to the host and re-streaming them one
partition at a time — instead of cancelling with ER_MEM_EXCEED_QUOTA.

Aggregation gets the same treatment via `partitioned_agg`: group rows
radix-partition by group-key hash on the host, each partition runs the
existing device kernel with per-partition capacity escalation, and only
a partition that STILL misses aggregates on the host. Groups never span
partitions (the partition id is a function of the full key hash), so
per-partition GroupResults concatenate into one exact result.
"""

from __future__ import annotations

import threading

import numpy as np

from tidb_tpu import config, memtrack, meter, metrics, runtime_stats, \
    sched, trace
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (CapacityError, CollisionError,
                                  DeviceRejectError, GroupResult,
                                  _hash_keys, kernel_for)
from tidb_tpu.ops.join import _DEAD_BUILD, _DEAD_PROBE

__all__ = ["HybridJoinBuild", "partitioned_agg", "agg_retry",
           "concat_group_results", "group_key_hashes", "build_hashes",
           "probe_hashes", "partition_ids", "detect_hot_hashes",
           "dup_hot_hashes", "sketch_hot_hashes", "escalated_capacity"]

# one seed for BOTH join sides (matches ops/join's matcher): equal keys
# hash equal, so partition routing agrees between build and probe
_SEED = 0x9E3779B97F4A7C15

_MAX_AGG_CAPACITY = 1 << 20   # same ceiling as the executor escalation
_BASE_AGG_CAPACITY = 4096
_MAX_HOT = 1024               # hot-lane key budget (it must stay tiny)
_MAX_PROMOTIONS = 4           # re-layouts are O(nb): bound them
# max distinct build keys to probe a sketch for (one blake2b per key,
# ~1us each: a full dim-table scan stays in the low tens of ms, paid
# once per join execution and only when ANALYZE stats exist)
_CMS_SCAN_LIMIT = 1 << 16

_REMIX = np.uint64(0xFF51AFD7ED558CCD)   # murmur3 fmix64 constant


def partition_ids(h: np.ndarray, parts: int) -> np.ndarray:
    """Partition id in [0, parts) per row hash. The hash bits are
    remixed first so partition membership is independent of the raw
    hash ORDER the sort-based kernels consume — a pathological key set
    clustered in hash space still spreads across partitions."""
    u = h.astype(np.uint64)
    u = (u ^ (u >> np.uint64(33))) * _REMIX
    u = u ^ (u >> np.uint64(29))
    return (u % np.uint64(max(parts, 1))).astype(np.int64)


def build_hashes(bk, nb: int) -> np.ndarray:
    """Row hashes of encoded build key lanes; any-NULL rows get
    _DEAD_BUILD (they match nothing, exactly like the matcher)."""
    valid = np.ones(nb, dtype=bool)
    for _d, v in bk:
        valid &= np.asarray(v[:nb], dtype=bool)
    h = _hash_keys(np, [(np.asarray(d[:nb]),
                         np.asarray(v[:nb], dtype=bool) & valid)
                        for d, v in bk], nb, seed=_SEED)
    return np.where(valid, h, _DEAD_BUILD)


def probe_hashes(pk, n: int) -> np.ndarray:
    """Probe-side twin of build_hashes (_DEAD_PROBE for NULL rows)."""
    valid = np.ones(n, dtype=bool)
    for _d, v in pk:
        valid &= np.asarray(v[:n], dtype=bool)
    h = _hash_keys(np, [(np.asarray(d[:n]),
                         np.asarray(v[:n], dtype=bool) & valid)
                        for d, v in pk], n, seed=_SEED)
    return np.where(valid, h, _DEAD_PROBE)


def _hash_key_bytes(h: int) -> bytes:
    """CMSketch key for a row HASH (the streaming probe sketch counts
    hashes, not raw values — both sides already agree on them)."""
    return int(h).to_bytes(8, "little", signed=True)


def escalated_capacity(needed: int) -> int | None:
    """Next capacity for a CapacityError retry (2x the true group count,
    power of two); None when the overflow is hopeless."""
    cap = 1 << max(needed * 2 - 1, 1).bit_length()
    if not needed or cap > _MAX_AGG_CAPACITY:
        return None
    return cap


def dup_hot_hashes(h: np.ndarray, threshold: int) -> np.ndarray:
    """Build-side duplication leg of heavy-hitter detection: EXACT (the
    build is materialized) — any key with >= threshold build rows
    explodes pair counts and goes hot. Cheap (one np.unique), computed
    fresh per execution."""
    if not threshold:
        return np.empty(0, dtype=np.int64)
    live = h[h != _DEAD_BUILD]
    if not live.size:
        return np.empty(0, dtype=np.int64)
    uniq, cnt = np.unique(live, return_counts=True)
    return uniq[cnt >= threshold][:_MAX_HOT]


def sketch_hot_hashes(h: np.ndarray, threshold: int, raw_key,
                      probe_cms) -> np.ndarray:
    """Probe-side frequency leg: the probe table's ANALYZE-time
    CMSketch (`probe_cms`, per 2505.04153's global hot-key routing)
    queried per distinct build key VALUE (`raw_key` = the pre-encoding
    (data, valid) lane of the first join key) — only when the distinct
    count is small enough for per-key blake2b queries. ~1us/key: the
    result depends only on (build key set, sketch, threshold), so
    callers cache it per plan (HashJoinExec._maybe_hybrid) instead of
    re-paying the scan every execution."""
    if not threshold or probe_cms is None or raw_key is None:
        return np.empty(0, dtype=np.int64)
    live = h[h != _DEAD_BUILD]
    uniq = np.unique(live)
    if not 0 < uniq.size <= _CMS_SCAN_LIMIT:
        return np.empty(0, dtype=np.int64)
    from tidb_tpu.statistics import cm_key
    d, v = raw_key
    idx = np.flatnonzero(np.asarray(v[:len(h)], dtype=bool))
    if not idx.size:
        return np.empty(0, dtype=np.int64)
    try:
        vals, first = np.unique(np.asarray(d)[idx], return_index=True)
    except TypeError:                # mixed/unorderable values: skip
        return np.empty(0, dtype=np.int64)
    sel = [int(i) for i, val in zip(first, vals)
           if probe_cms.query(cm_key(val)) >= threshold]
    if not sel:
        return np.empty(0, dtype=np.int64)
    return np.unique(h[idx[np.asarray(sel, dtype=np.int64)]])[:_MAX_HOT]


def detect_hot_hashes(h: np.ndarray, threshold: int, raw_key=None,
                      probe_cms=None) -> np.ndarray:
    """Initial heavy-hitter hash set for a build side: exact build-side
    duplication plus sketch-estimated probe-side frequency (see the two
    legs above)."""
    hot = np.union1d(dup_hot_hashes(h, threshold),
                     sketch_hot_hashes(h, threshold, raw_key,
                                       probe_cms))
    return hot[:_MAX_HOT]


class HybridJoinBuild:
    """Radix-partitioned, device-resident build side of the hybrid hash
    join, with a heavy-hitter broadcast lane and memtrack quota spill.

    Layout: build rows sort (stably) by partition id — cold partitions
    0..parts-1 by remixed key hash, the hot lane at index `parts` — so
    every partition is one contiguous slice of the gathered key lanes.
    `ensure(p)` uploads a partition's lanes once and keeps them
    HBM-resident across probe batches; the registered quota spill
    action (`_quota_spill`) sheds every resident COLD partition except
    the one being probed, after which `want_immediate` steers newly
    arriving probe rows for spilled partitions into host staging (the
    executor drains them partition-at-a-time at end of stream).

    Threading: the probe driver (one thread) is the only mutator of the
    layout arrays; `_mu` protects the residency map and hot set against
    the quota spill action, which fires on whatever thread crossed the
    quota (memtrack fires actions with no tracker lock held)."""

    def __init__(self, kernel, bk, nb: int, parts: int, plan,
                 hot_hashes=None, threshold: int | None = None, h=None):
        self.kernel = kernel
        self.nb = nb
        self.parts = max(int(parts), 1)
        self.plan = plan
        self.threshold = config.skew_threshold() \
            if threshold is None else threshold
        self._bk = bk
        self._mu = threading.Lock()
        self._resident: dict[int, tuple] = {}   # guarded-by: _mu
        self._pins: dict[int, int] = {}         # guarded-by: _mu
        self._zombies: dict[int, list] = {}     # guarded-by: _mu
        self._active = -1                       # guarded-by: _mu
        self._spill_fired = False               # guarded-by: _mu
        self.spilled = 0                        # guarded-by: _mu
        self.hot_rows = 0          # probe rows routed through the lane
        self._promotions = 0
        self._obs = None           # streaming probe-side CMSketch
        # the tracker node is captured HERE (session thread): the spill
        # action may fire on a cop worker whose thread-local root
        # differs, and the release must hit the ledger that was charged
        self._node = memtrack.op_node(plan)
        self._host_tracked = 0                  # guarded-by: _mu
        self.h = h if h is not None else build_hashes(bk, nb)
        self._build_uniq = np.unique(self.h[self.h != _DEAD_BUILD])
        hot = np.asarray(hot_hashes if hot_hashes is not None else [],
                         dtype=np.int64)
        self.hot = np.unique(hot)[:_MAX_HOT]    # guarded-by: _mu
        with self._mu:
            delta = self._layout_locked()
        try:
            self._apply_host_delta(delta)
        except BaseException:
            # the quota cancel can fire on this very charge — and the
            # caller's try/finally (close()) does not exist yet, so the
            # gathered-copy bytes must be credited back here
            if self._node is not None and self._host_tracked:
                self._node.release(host=self._host_tracked)
                self._host_tracked = 0
            raise
        self._unregister = memtrack.register_spill(self._quota_spill)

    # -- layout --------------------------------------------------------------

    def _layout_locked(self) -> int:
        """(Re)compute the partition layout from the pristine key lanes:
        one stable argsort by partition id, one gather per lane. Caller
        holds _mu and has already drained _resident if the hot set
        changed. Returns the HOST-byte delta of the gathered copy for
        the caller to apply OUTSIDE the lock — a consume here could
        fire the quota chain, whose spill action re-enters _mu."""
        pid = partition_ids(self.h, self.parts)
        if self.hot.size:
            pid = np.where(np.isin(self.h, self.hot), self.parts, pid)
        order = np.argsort(pid, kind="stable")
        self._order = order
        self._bounds = np.searchsorted(pid[order],
                                       np.arange(self.parts + 2))
        self._lanes = [(np.asarray(d[:self.nb])[order],
                        np.asarray(v[:self.nb], dtype=bool)[order])
                       for d, v in self._bk]
        self._hs = self.h[order]
        hs, he = int(self._bounds[self.parts]), \
            int(self._bounds[self.parts + 1])
        if he > hs:
            self._hot_uniq, self._hot_cnt = np.unique(
                self._hs[hs:he], return_counts=True)
        else:
            self._hot_uniq = np.empty(0, dtype=np.int64)
            self._hot_cnt = np.empty(0, dtype=np.int64)
        if self._node is None:
            return 0
        nbytes = sum(d.nbytes + v.nbytes for d, v in self._lanes)
        delta = nbytes - self._host_tracked
        self._host_tracked = nbytes
        return delta

    def _apply_host_delta(self, delta: int) -> None:
        if self._node is None or not delta:
            return
        if delta > 0:
            # lint: exempt[paired-resource] ownership transfer: the gathered build copy releases on close()
            self._node.consume(host=delta)
        else:
            self._node.release(host=-delta)

    def part_span(self, p: int) -> tuple[int, int]:
        return int(self._bounds[p]), int(self._bounds[p + 1])

    def part_rows(self, p: int) -> int:
        s, e = self.part_span(p)
        return e - s

    def build_rows(self, p: int) -> np.ndarray:
        """Global build row index per partition-local row (maps the
        matcher's ri back onto the original build chunk)."""
        s, e = self.part_span(p)
        return self._order[s:e]

    # -- residency / spill ---------------------------------------------------

    def ensure(self, p: int):
        """Device-resident key lanes for partition `p`, uploading (and
        billing the device ledger) on first touch or after a spill.
        Marks `p` active so the quota action cannot shed the partition
        it is making room FOR."""
        with self._mu:
            self._active = p
            ent = self._resident.get(p)
            if ent is not None:
                return ent[0]
            s, e = self.part_span(p)
            lanes = [(d[s:e], v[s:e]) for d, v in self._lanes]
        nbytes = self.kernel.build_nbytes(max(e - s, 1))
        if self._node is not None:
            # may fire the quota chain — including our own spill action,
            # which skips the active partition
            # lint: exempt[paired-resource] ownership transfer: resident-partition bytes release on evict/spill/close
            self._node.consume(device=nbytes)
        try:
            # partition upload (first touch / post-spill re-upload) is
            # a partition phase on the statement timeline
            with trace.span("join.partition", partition=p, upload=1,
                            rows=e - s):
                dev = self.kernel.prepare_build(lanes, e - s)
        except BaseException:
            if self._node is not None:
                self._node.release(device=nbytes)
            raise
        with self._mu:
            self._resident[p] = (dev, nbytes)
        return dev

    def pin(self, p: int) -> None:
        """Mark one in-flight dispatch against partition `p`: until the
        matching unpin(), neither the quota spill nor a promotion may
        credit the partition's device bytes back — the pending token
        still references the buffers, so a release would under-state
        real HBM residency and let the quota admit memory that is not
        actually free."""
        with self._mu:
            self._pins[p] = self._pins.get(p, 0) + 1

    def unpin(self, p: int) -> None:
        """Drop one in-flight reference; frees any residency a
        promotion retired while the partition was pinned."""
        freed = 0
        with self._mu:
            left = self._pins.get(p, 1) - 1
            if left > 0:
                self._pins[p] = left
            else:
                self._pins.pop(p, None)
                for _dev, nbytes in self._zombies.pop(p, ()):
                    freed += nbytes
        if freed and self._node is not None:
            self._node.release(device=freed)

    def want_immediate(self, p: int) -> bool:
        """Probe partition `p` now? The hot lane and resident partitions
        always; cold partitions only until the first quota spill —
        after that their probe rows stage to the host and re-stream in
        the drain phase (re-uploading an evicted build per probe batch
        would thrash exactly the memory the spill just freed)."""
        with self._mu:
            return p == self.parts or p in self._resident or \
                not self._spill_fired

    def _quota_spill(self) -> None:
        """memtrack OOM action: shed every device-resident cold build
        partition except the active one (and the hot lane, which stays
        pinned — it is small by construction and carries the skew).
        Host key lanes remain, so spilled partitions re-stream later."""
        freed = 0
        dropped = []
        with self._mu:
            for p in list(self._resident):
                if p == self._active or p == self.parts or \
                        p in self._pins:
                    # pinned partitions have in-flight dispatches still
                    # holding the buffers: releasing their bytes now
                    # would under-state real HBM residency (and count a
                    # spill that freed nothing)
                    continue
                dev, nbytes = self._resident.pop(p)
                dropped.append(dev)
                freed += nbytes
                self.spilled += 1
            if dropped:
                self._spill_fired = True
        n = len(dropped)
        del dropped          # device refs dropped outside the lock
        if freed:
            if self._node is not None:
                self._node.release(device=freed)
            metrics.counter(metrics.JOIN_SPILL_PARTITIONS, inc=n)

    def evict(self, p: int) -> None:
        """Voluntarily drop one resident partition (drain phase: a just-
        drained cold partition makes room for the next). A pinned
        partition parks in the zombie list until its unpin()."""
        with self._mu:
            ent = self._resident.pop(p, None)
            if self._active == p:
                self._active = -1
            if ent is not None and p in self._pins:
                self._zombies.setdefault(p, []).append(ent)
                ent = None
        if ent is not None and self._node is not None:
            self._node.release(device=ent[1])

    def under_pressure(self) -> bool:
        with self._mu:
            return self._spill_fired

    def close(self) -> None:
        """Release every ledgered byte and unhook the spill action —
        the probe generator's finally."""
        self._unregister()
        with self._mu:
            freed = sum(nb for _dev, nb in self._resident.values())
            freed += sum(nb for ents in self._zombies.values()
                         for _dev, nb in ents)
            self._resident.clear()
            self._zombies.clear()
            host = self._host_tracked
            self._host_tracked = 0
        if self._node is not None:
            if freed:
                self._node.release(device=freed)
            if host:
                self._node.release(host=host)

    # -- probe routing -------------------------------------------------------

    def route(self, pk, n: int):
        """Split one probe batch by partition. -> (hp, tasks) with
        tasks = [(pid, idx)] (idx ascending within each task) covering
        every probe row whose partition holds at least one build row —
        rows routed to an empty partition can match nothing and are
        simply left for the caller's unmatched handling."""
        hp = probe_hashes(pk, n)
        with self._mu:
            hot = self.hot
        is_hot = np.isin(hp, hot) if hot.size else None
        pid = partition_ids(hp, self.parts)
        if is_hot is not None:
            pid = np.where(is_hot, self.parts, pid)
            nhot = int(is_hot.sum())
            if nhot:
                self.hot_rows += nhot
                metrics.counter(metrics.JOIN_HOT_ROWS, inc=nhot)
        order = np.argsort(pid, kind="stable")
        spid = pid[order]
        tasks = []
        for p in range(self.parts + 1):
            s, e = np.searchsorted(spid, [p, p + 1])
            if e > s and self.part_rows(p) > 0:
                tasks.append((int(p), order[s:e]))
        return hp, tasks

    def hot_out_cap(self, hp_sub: np.ndarray) -> int | None:
        """EXACT pair capacity for a hot-lane dispatch: per-key build
        counts are known, so the matcher never pays an overflow retry
        however skewed the probe batch is."""
        if not self._hot_uniq.size:
            return None
        pos = np.searchsorted(self._hot_uniq, hp_sub)
        pos = np.clip(pos, 0, self._hot_uniq.size - 1)
        cnt = np.where(self._hot_uniq[pos] == hp_sub, self._hot_cnt[pos],
                       0)
        return runtime.bucket_size(max(int(cnt.sum()), 1024))

    # -- dynamic heavy-hitter promotion --------------------------------------

    def observe(self, hp: np.ndarray):
        """Feed the streaming probe-side CMSketch with one batch's key
        hashes; -> build hashes newly crossing the skew threshold (to
        pass to promote()), or None. Only keys already frequent WITHIN
        the batch are inserted (>= threshold/8), bounding sketch work;
        a key hot overall but never locally frequent is still caught by
        its partition's own retry path."""
        if not self.threshold or self._promotions >= _MAX_PROMOTIONS:
            return None
        live = hp[hp != _DEAD_PROBE]
        if not live.size:
            return None
        from tidb_tpu.statistics import CMSketch
        if self._obs is None:
            self._obs = CMSketch(depth=4, width=4096)
        uniq, cnt = np.unique(live, return_counts=True)
        sel = cnt >= max(1, self.threshold // 8)
        cand = []
        for hv, c in zip(uniq[sel].tolist(), cnt[sel].tolist()):
            key = _hash_key_bytes(hv)
            self._obs.insert(key, int(c))
            if self._obs.query(key) >= self.threshold:
                cand.append(hv)
        if not cand:
            return None
        arr = np.asarray(cand, dtype=np.int64)
        with self._mu:
            if self.hot.size:
                arr = arr[~np.isin(arr, self.hot)]
        arr = arr[np.isin(arr, self._build_uniq)]
        return arr if arr.size else None

    def promote(self, hashes: np.ndarray) -> bool:
        """Move newly-hot keys' build rows into the broadcast lane: the
        dynamic half of heavy-hitter routing. Re-layouts the build (one
        argsort) and drops residency — partitions re-upload lazily with
        the new layout. Bounded by _MAX_PROMOTIONS/_MAX_HOT."""
        freed = 0
        with self._mu:
            if self._promotions >= _MAX_PROMOTIONS or \
                    self.hot.size + hashes.size > _MAX_HOT:
                return False
            self._promotions += 1
            self.hot = np.union1d(self.hot, hashes)
            for p in list(self._resident):
                ent = self._resident.pop(p)
                if p in self._pins:
                    # still referenced by an in-flight token: keep the
                    # bytes charged until its unpin() retires them
                    self._zombies.setdefault(p, []).append(ent)
                else:
                    freed += ent[1]
            delta = self._layout_locked()
        if freed and self._node is not None:
            self._node.release(device=freed)
        self._apply_host_delta(delta)
        return True


# -- partitioned aggregation -------------------------------------------------


# lint: exempt[memtrack-alloc] one int64 code lane over a chunk the caller already bills (the retry path's input)
def group_key_hashes(group_exprs, chunk) -> np.ndarray:
    """Host-side row hash over the group-key tuple (NULLs keyed
    distinctly, same contract as the device kernel's hash). Varlen
    lanes factorize to per-chunk int64 codes first — equal values share
    a code, so partition membership is consistent within the chunk."""
    n = chunk.num_rows
    lanes = []
    for g in group_exprs:
        d, v = g.eval(chunk)
        d = np.asarray(d)
        v = np.asarray(v, dtype=bool)
        if d.dtype == np.dtype(object):
            codes = np.zeros(n, dtype=np.int64)
            idx = np.flatnonzero(v)
            if idx.size:
                _vals, inv = np.unique(d[idx], return_inverse=True)
                codes[idx] = inv + 1
            d = codes
        lanes.append((d, v))
    return _hash_keys(np, lanes, n, seed=_SEED)


# lint: exempt[memtrack-alloc] merged partial lanes: one row per LIVE GROUP, bounded by the agg state the caller already bills via approx_bytes
def concat_group_results(results: list[GroupResult],
                         aggs) -> GroupResult:
    """Merge per-partition GroupResults whose key sets are DISJOINT
    (the partition id is a function of the full key hash, so a group
    never spans partitions) by plain concatenation."""
    results = [r for r in results if r is not None and len(r.keys)]
    if len(results) == 1:
        return results[0]
    if not results:
        return GroupResult(keys=[], partials=[[] for _ in aggs],
                           counts=np.empty(0, dtype=np.int64))
    keys = []
    for r in results:
        keys.extend(r.keys)
    partials = []
    for ai in range(len(aggs)):
        nlanes = len(results[0].partials[ai])
        partials.append([np.concatenate(
            [np.asarray(r.partials[ai][li]) for r in results])
            for li in range(nlanes)])
    counts = np.concatenate([np.asarray(r.counts) for r in results])
    return GroupResult(keys=keys, partials=partials, counts=counts)


def _one_partition_agg(sub, filter_expr, group_exprs, aggs, plan,
                       reason: str) -> GroupResult:
    """Device agg over ONE partition's rows with its own capacity-
    escalation chain; only this partition lands on the host if the
    device still cannot serve it."""
    from tidb_tpu.ops.hostagg import host_hash_agg
    cap = _BASE_AGG_CAPACITY
    # one partition = one span: the per-partition escalation chain is a
    # visible phase of the statement timeline (how long each radix
    # partition held the device, and which ones fell to the host)
    with trace.span("join.partition", rows=sub.num_rows):
        while True:
            try:
                k = kernel_for(filter_expr, group_exprs, aggs,
                               capacity=cap)
                with sched.device_slot(), \
                        memtrack.device_scope(plan,
                                              k.dispatch_nbytes(sub)):
                    return runtime_stats.device_call(plan, k, sub)
            except CapacityError as e:
                nxt = escalated_capacity(getattr(e, "needed", 0))
                if nxt is None or nxt <= cap:
                    reason = "capacity"
                    break
                cap = nxt
            except CollisionError:
                reason = "collision"
                break
            except (DeviceRejectError, NotImplementedError):
                reason = "unsupported"
                break
        runtime_stats.note_fallback(plan, reason)
        with meter.busy_section("host"), \
                trace.span("host.fallback", rows=sub.num_rows):
            return host_hash_agg(sub, filter_expr, group_exprs, aggs)


def partitioned_agg(chunk, filter_expr, group_exprs, aggs, plan,
                    parts: int | None = None,
                    reason: str = "capacity") -> GroupResult:
    """Radix-partitioned device aggregation: the retry that replaces the
    whole-operator host fallback after a capacity/collision miss.

    Rows radix-partition by group-key hash on the host; each partition
    re-runs the device kernel with its own escalation chain; a
    partition that still misses aggregates on the host ALONE (counted
    as a fallback with the surviving reason). Row order within a
    partition is preserved, so FIRST_ROW/representative-row semantics
    match the unpartitioned kernel."""
    from tidb_tpu.ops.hostagg import host_hash_agg
    parts = config.join_partitions() if parts is None else parts
    n = chunk.num_rows
    if parts <= 1 or not group_exprs or n == 0:
        runtime_stats.note_fallback(plan, reason)
        return host_hash_agg(chunk, filter_expr, group_exprs, aggs)
    try:
        h = group_key_hashes(group_exprs, chunk)
    except TypeError:
        # unorderable key values: factorization failed; the host path
        # evaluates the same exprs row-wise and still serves them
        runtime_stats.note_fallback(plan, reason)
        return host_hash_agg(chunk, filter_expr, group_exprs, aggs)
    pid = partition_ids(h, parts)
    order = np.argsort(pid, kind="stable")
    bounds = np.searchsorted(pid[order], np.arange(parts + 1))
    results = []
    for p in range(parts):
        idx = order[bounds[p]:bounds[p + 1]]
        if not idx.size:
            continue
        results.append(_one_partition_agg(chunk.take(idx), filter_expr,
                                          group_exprs, aggs, plan,
                                          reason))
    return concat_group_results(results, aggs)


def agg_retry(chunk, filter_expr, group_exprs, aggs, plan,
              err) -> GroupResult:
    """Full recovery chain after a device agg miss `err`: one whole-
    chunk escalated retry on capacity (cheap — the common medium-
    cardinality case needs exactly one bigger table), then the radix-
    partitioned per-partition path. Never raises the miss onward: the
    worst case is per-partition host aggregation."""
    reason = "collision" if isinstance(err, CollisionError) else "capacity"
    if isinstance(err, CapacityError):
        cap = escalated_capacity(getattr(err, "needed", 0))
        if cap is not None:
            try:
                k = kernel_for(filter_expr, group_exprs, aggs,
                               capacity=cap)
                with sched.device_slot(), memtrack.device_scope(
                        plan, k.dispatch_nbytes(chunk)):
                    return runtime_stats.device_call(plan, k, chunk)
            except (CapacityError, CollisionError) as e2:
                reason = "collision" if isinstance(e2, CollisionError) \
                    else "capacity"
            except (DeviceRejectError, NotImplementedError):
                from tidb_tpu.ops.hostagg import host_hash_agg
                runtime_stats.note_fallback(plan, "unsupported")
                return host_hash_agg(chunk, filter_expr, group_exprs,
                                     aggs)
    return partitioned_agg(chunk, filter_expr, group_exprs, aggs, plan,
                           reason=reason)
