"""Stream (sorted-input) aggregation on device: segment-reduce.

Replaces /root/reference/executor/aggregate.go:150-170 (StreamAggExec:
pipelined aggregation over input sorted by the group keys). On TPU this is
the *most* natural aggregation shape — no hash table, no capacity/overflow
protocol, no collision risk:

    1. the input chunk arrives sorted by the group-key expressions
       (planner guarantee: a Sort below, or an order-preserving reader)
    2. adjacent-row key comparison marks segment starts; a cumulative sum
       turns the boundary mask into dense segment ids
    3. jax.ops.segment_* reduce every aggregate into per-segment lanes
       with num_segments = chunk rows (static shape, never overflows)

Unlike HashAggKernel the result is EXACT by construction (keys compare by
value, not by hash), so there is no CollisionError path. Chunk partials
merge across chunk boundaries on the host exactly like the hash path
(a group spanning two chunks meets itself in HashAggregator).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (GroupResult, _agg_lanes, _key_bits,
                                  _validate_device_exprs,
                                  finalize_group_result)

__all__ = ["SegmentAggKernel", "segment_kernel_for"]


class SegmentAggKernel:
    """Compiled segment-reduce over one sorted-chunk schema.

    The caller owns the sorted-input contract: rows with equal group keys
    must be adjacent (full sorted order is not required, contiguity is
    enough). group_exprs must be device-safe or bare string ColumnRefs
    (dict codes compare equal iff the values are equal, which is all
    boundary detection needs)."""

    def __init__(self, group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc]):
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        _validate_device_exprs(None, self.group_exprs, self.aggs)
        self._jit = jax.jit(self._kernel)
        self._jitd = None   # donating variant, built on first dispatch

    # lint: exempt[dtype-discipline] int64 segment counts/ids: exact lane semantics shared with hashagg's agg-state stacking
    def _kernel(self, cols, nrows):
        xp = jnp
        n = cols[0][0].shape[0]
        alive = xp.arange(n) < nrows
        key_cols = [g.eval_xp(xp, cols, n) for g in self.group_exprs]
        # segment starts: row 0, plus any row whose key differs from the
        # previous row's (exact bit compare; NULLs equal NULLs)
        new = xp.zeros(n, dtype=bool).at[0].set(True)
        for d, v in key_cols:
            bits = _key_bits(xp, d)
            diff = (bits[1:] != bits[:-1]) | (v[1:] != v[:-1])
            new = new.at[1:].set(new[1:] | diff)
        new = new & alive                      # padding opens no segment
        seg = xp.cumsum(new.astype(jnp.int32)) - 1
        seg = xp.clip(seg, 0, n - 1)           # all-padding chunk guard
        nseg = xp.sum(new.astype(jnp.int64))
        counts = jax.ops.segment_sum(alive.astype(jnp.int64), seg,
                                     num_segments=n)
        rep = jax.ops.segment_min(xp.where(alive, xp.arange(n), n), seg,
                                  num_segments=n)
        lanes = [[l for l, _op in
                  _agg_lanes(xp, a, cols, n, alive, seg, n)]
                 for a in self.aggs]
        return nseg, counts, rep, lanes

    def scratch_nbytes(self, chunk: Chunk) -> int:
        """Device bytes beyond the input columns: segment-id/count/lane
        scratch (num_segments = padded rows, the no-capacity-limit
        trade) — the fused-dispatch share when the input is an
        HBM-cache-resident block."""
        n = runtime.bucket_size(max(chunk.num_rows, 1))
        return n * 8 * (3 + 2 * len(self.aggs))

    def dispatch_nbytes(self, chunk: Chunk) -> int:
        """HBM bytes one dispatch stages, from shapes at dispatch time:
        padded input columns plus the kernel scratch."""
        from tidb_tpu import memtrack
        n = runtime.bucket_size(max(chunk.num_rows, 1))
        return memtrack.device_put_bytes(chunk, n) + \
            self.scratch_nbytes(chunk)

    def dispatch(self, chunk: Chunk, donate: bool = False, dev_cols=None):
        """Async half: pad + transfer + enqueue, no sync (see
        HashAggKernel.dispatch for the donation and dev_cols
        contracts)."""
        if dev_cols is not None:
            return self._jit(dev_cols, chunk.num_rows)
        donate = donate and runtime.donation_supported()
        cols, _dicts = runtime.device_put_chunk(chunk, memo=not donate)
        if donate:
            if self._jitd is None:
                self._jitd = jax.jit(self._kernel, donate_argnums=(0,))
            return self._jitd(cols, chunk.num_rows)
        return self._jit(cols, chunk.num_rows)

    def finalize(self, chunk: Chunk, pending) -> GroupResult:
        # one batched device->host transfer (per-array reads pay full
        # round-trip latency each; see HashAggKernel.finalize)
        nseg, counts, rep, lanes = jax.device_get(pending)
        nseg = int(nseg)
        gidx = np.arange(nseg)
        lanes_at = [[l[gidx] for l in ls] for ls in lanes]
        return finalize_group_result(chunk, self.group_exprs, self.aggs,
                                     gidx, rep[gidx], lanes_at,
                                     counts[gidx])

    def __call__(self, chunk: Chunk, dev_cols=None) -> GroupResult:
        return self.finalize(chunk, self.dispatch(chunk,
                                                  dev_cols=dev_cols))


# process-wide cache like ops/hashagg.kernel_for, keyed on the group/agg
# fingerprint (segment kernels have no capacity axis); shares the same
# thread-safe true-LRU implementation
_SEG_KERNELS = runtime.FingerprintCache(64)


def segment_kernel_for(group_exprs, aggs) -> SegmentAggKernel:
    from tidb_tpu import devplane, profiler
    made = []

    def make():
        made.append(1)
        return SegmentAggKernel(group_exprs, aggs)

    fp = runtime.plan_fingerprint(None, group_exprs, aggs)
    if fp is None:
        k = make()
        prof = profiler.profile("streamagg", None)
        profiler.note_construct(prof, reuse=False)
        k._profile = prof
        return k
    key = (fp, devplane.mesh_fingerprint(process=True))
    k = _SEG_KERNELS.get_or_create(key, make)
    prof = profiler.profile("streamagg", fp)
    profiler.note_construct(prof, reuse=not made)
    k._profile = prof
    return k
