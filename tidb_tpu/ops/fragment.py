"""Fused pipeline fragments: probe + partial-agg in ONE XLA program.

The per-operator execution of a `scan -> filter -> join-probe ->
partial-agg` pipeline pays two HBM round trips the query never needed:
the matcher writes a static-capacity pair list back to the host, the
host gathers a materialized joined chunk, and the agg re-uploads that
chunk to group it. ProbeAggKernel executes the whole fragment per probe
superchunk in one compiled call (ROADMAP item 4 / arxiv 2603.26698's
partial-aggregate placement):

    1. hash both sides' key lanes and expand the sort-join candidate
       runs into a static-capacity (li, ri) pair list with exact-key
       verification — ops/join.match_pairs, unchanged semantics;
    2. gather ONLY the columns the group/agg expressions read, straight
       from the device-resident padded columns (probe superchunk cols +
       the once-uploaded build cols) at the pair indices — the joined
       intermediate never exists in HBM at full width, and varlen lanes
       stay dictionary codes end-to-end;
    3. run the shared group+partial-agg phase (ops/hashagg.group_partial:
       direct-indexed / runtime-selected / packed-sort group table, one
       batched scatter pass, dual-hash collision check) over the pairs.

Only the group tables return to the host; representative (li, ri) pairs
late-materialize exact group-key values from the two source chunks at
the finalize boundary. Pair-capacity overflow self-heals inside
finalize (regrown program over the SAME device-resident lanes, billed
to the statement's device ledger); capacity/collision misses raise to
the executor, which escalates the fragment kernel once and then falls
back to the decoded per-batch path (match on device, aggregate on
host), counted in tidb_tpu_device_fallback_total.

Gated by `tidb_tpu_fuse_fragments`; engaged by HashAggExec when its
child is a plain inner hash join (executor/__init__.py).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (DeviceRejectError, GroupResult,
                                  _validate_device_exprs,
                                  finalize_group_result, group_partial,
                                  _hash_keys)
from tidb_tpu.ops.join import _DEAD_BUILD, _DEAD_PROBE, match_pairs

__all__ = ["ProbeAggKernel", "fragment_kernel_for"]


class _PendingFragment:
    """One in-flight fused dispatch: the padded device-resident lanes
    (probe AND the shared build reference) ride along so a
    pair-capacity overflow retry re-runs WITHOUT re-padding or
    re-transferring anything. The kernel object itself stays stateless
    — it is cached process-wide across plans and sessions."""

    __slots__ = ("build_dev", "nb", "pk", "pcols", "np_", "cap", "res")

    def __init__(self, build_dev, nb, pk, pcols, np_, cap, res):
        self.build_dev = build_dev
        self.nb = nb
        self.pk, self.pcols = pk, pcols
        self.np_ = np_
        self.cap = cap
        self.res = res


class ProbeAggKernel:
    """Compiled probe->partial-agg over one (join keys, joined-schema
    group/agg) fragment signature.

    `group_exprs`/`aggs` reference the JOINED schema: probe columns at
    [0, probe_width), build columns at [probe_width, width). FIRST_ROW
    and GROUP_CONCAT reject (their late-materialize protocol needs
    row-identity lanes the pair space does not preserve) — the executor
    then runs the unfused per-operator path."""

    def __init__(self, num_keys: int, probe_width: int, width: int,
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096,
                 force_hash: bool = False, direct_limit=None):
        self.num_keys = num_keys
        self.probe_width = probe_width
        self.width = width
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        self.capacity = capacity
        self.force_hash = force_hash
        self.direct_limit = direct_limit
        for a in self.aggs:
            if a.fn in (AggFunc.FIRST_ROW, AggFunc.GROUP_CONCAT):
                raise DeviceRejectError(
                    f"{a.fn} needs row identity at finalize; the fused "
                    f"fragment carries only pair indices")
        _validate_device_exprs(None, self.group_exprs, self.aggs)
        used = set()
        for g in self.group_exprs:
            used |= g.columns_used()
        for a in self.aggs:
            if a.arg is not None:
                used |= a.arg.columns_used()
        if any(j >= width for j in used):
            raise DeviceRejectError("agg reads past the joined schema")
        self.probe_used = sorted(j for j in used if j < probe_width)
        self.build_used = sorted(j for j in used if j >= probe_width)
        self._jit = jax.jit(self._kernel,
                            static_argnames=("out_cap",))

    # -- traced program ------------------------------------------------------

    def _kernel(self, bkeys, pkeys, pcols, bcols, nb, np_, out_cap):
        xp = jnp
        b_n = bkeys[0][0].shape[0]
        p_n = pkeys[0][0].shape[0]
        b_valid = xp.arange(b_n) < nb
        for _d, v in bkeys:
            b_valid = b_valid & v
        p_valid = xp.arange(p_n) < np_
        for _d, v in pkeys:
            p_valid = p_valid & v
        hb = _hash_keys(xp, [(d, v & b_valid) for d, v in bkeys],
                        b_n, seed=0x9E3779B97F4A7C15)
        hp = _hash_keys(xp, [(d, v & p_valid) for d, v in pkeys],
                        p_n, seed=0x9E3779B97F4A7C15)
        hb = xp.where(b_valid, hb, _DEAD_BUILD)
        hp = xp.where(p_valid, hp, _DEAD_PROBE)
        li, ri, ok, total = match_pairs(
            xp, hb, hp, [d for d, _v in bkeys],
            [d for d, _v in pkeys], out_cap)
        # the joined row never materializes at full width: only the
        # lanes the group/agg expressions read are gathered, straight
        # from the device-resident padded columns
        joined = [None] * self.width
        for lane, j in enumerate(self.probe_used):
            d, v = pcols[lane]
            joined[j] = (d[li], v[li] & ok)
        for lane, j in enumerate(self.build_used):
            d, v = bcols[lane]
            joined[j] = (d[ri], v[ri] & ok)
        uniq, nuniq, collided, counts, rep, lanes = group_partial(
            xp, self.group_exprs, self.aggs, joined, out_cap, ok,
            self.capacity, force_hash=self.force_hash,
            direct_limit=self.direct_limit)
        # representative PAIRS (not pair indices) return to the host:
        # finalize gathers exact group-key values from the two source
        # chunks without ever reading the full li/ri buffers back
        repc = xp.clip(rep, 0, out_cap - 1)
        return (uniq, nuniq, collided, counts, li[repc], ri[repc],
                lanes, total)

    # -- sizing (device-ledger billing, from shapes alone) -------------------

    def _build_sub(self, build: Chunk) -> Chunk:
        return Chunk([build.columns[j - self.probe_width]
                      for j in self.build_used])

    def build_nbytes(self, build: Chunk, nb: int) -> int:
        """HBM bytes the once-per-probe build residency stages: the USED
        build columns (varlen as int64 codes + validity) plus the padded
        key lanes."""
        from tidb_tpu import memtrack
        bb = runtime.bucket_size(max(nb, 1))
        return memtrack.device_put_bytes(self._build_sub(build), bb) + \
            self.num_keys * 9 * bb

    def _probe_sub(self, chunk: Chunk) -> Chunk:
        return Chunk([chunk.columns[j] for j in self.probe_used])

    def input_nbytes(self, chunk: Chunk) -> int:
        """HBM bytes of one dispatch's INPUT lanes: only the probe
        columns the group/agg expressions read (the rest never ship),
        plus the padded key lanes — the bytes_touched figure."""
        from tidb_tpu import memtrack
        pb = runtime.bucket_size(max(chunk.num_rows, 1))
        return memtrack.device_put_bytes(self._probe_sub(chunk), pb) + \
            self.num_keys * 9 * pb

    def dispatch_nbytes(self, chunk: Chunk, out_cap: int) -> int:
        """HBM bytes one fused dispatch stages: used probe columns +
        key lanes, the pair buffers, and the group-table scratch."""
        return self.input_nbytes(chunk) + out_cap * 17 + \
            self.capacity * 8 * (5 + 2 * len(self.aggs))

    # -- async dispatch / blocking finalize ----------------------------------

    def prepare_build(self, build: Chunk, build_keys, nb: int):
        """Upload the build side once for the whole probe: padded key
        lanes + the USED build columns (dict-encoded, padded). ->
        (bkeys_dev, bcols_dev), reused by every dispatch."""
        bb = runtime.bucket_size(max(nb, 1))
        bkeys = [tuple(map(jnp.asarray, runtime.pad_column(d, v, bb)))
                 for d, v in build_keys]
        bcols, _dicts = runtime.device_put_chunk(
            self._build_sub(build), bb, memo=False) \
            if self.build_used else ([], {})
        return bkeys, bcols

    def dispatch(self, build_dev, nb: int, probe_keys, chunk: Chunk,
                 np_: int, out_cap: int | None = None) -> _PendingFragment:
        """Async half: pad + transfer the probe superchunk (used columns
        only reach the program) and enqueue the fused program — no sync,
        the pipeline's overlap point. `build_dev` is prepare_build's
        once-uploaded result, shared across every probe batch."""
        bkeys, bcols = build_dev
        pb = runtime.bucket_size(max(np_, 1))
        cap = out_cap or runtime.bucket_size(max(np_ * 2, 1024))
        pk = [tuple(map(jnp.asarray, runtime.pad_column(d, v, pb)))
              for d, v in probe_keys]
        # only the USED probe columns ship — the kernel reads nothing
        # else, and the key lanes already ride pk
        pcols, _dicts = runtime.device_put_chunk(
            self._probe_sub(chunk), pb, memo=False) \
            if self.probe_used else ([], {})
        res = self._jit(bkeys, pk, pcols, bcols, nb, np_, out_cap=cap)
        return _PendingFragment(build_dev, nb, pk, pcols, np_, cap, res)

    def finalize(self, probe_chunk: Chunk, build: Chunk, nb: int,
                 p: _PendingFragment) -> GroupResult:
        """Blocking half: read the pair total first (a scalar — an
        overflow retry then regrows the program over the SAME resident
        lanes without transferring the dead buffers), then one batched
        device->host read of the group tables, then the host
        late-materialize tail."""
        from tidb_tpu import memtrack
        from tidb_tpu.ops.hashagg import CapacityError, CollisionError
        root = memtrack.current()
        extra = 0
        try:
            while True:
                total = int(jax.device_get(p.res[7]))
                if total <= p.cap:
                    break
                new_cap = runtime.bucket_size(total)
                if root is not None:
                    grow = (new_cap - p.cap) * 17
                    extra += grow       # before consume: it may raise
                    root.consume(device=grow)
                p.cap = new_cap
                bkeys, bcols = p.build_dev
                p.res = self._jit(bkeys, p.pk, p.pcols, bcols, p.nb,
                                  p.np_, out_cap=p.cap)
            (uniq, nuniq, collided, counts, rep_li, rep_ri, lanes,
             _total) = jax.device_get(p.res)
        finally:
            if root is not None and extra:
                root.release(device=extra)
        if int(nuniq) > self.capacity:
            err = CapacityError(f"distinct groups {int(nuniq)} > "
                                f"capacity {self.capacity}")
            err.needed = int(nuniq)
            raise err
        if bool(collided):
            raise CollisionError("fused group key hash collision")
        from tidb_tpu.ops.hashagg import _FILL, _SENTINEL_MASKED
        live = (counts > 0) & (uniq != _SENTINEL_MASKED) & (uniq != _FILL)
        gidx = np.flatnonzero(live)
        lanes_at = [[lane[gidx] for lane in ls] for ls in lanes]
        # late materialization: gather ONLY the representative joined
        # rows from the two source chunks (strings decode here, at the
        # operator-output boundary, never inside the fragment)
        pli = np.clip(rep_li[gidx], 0, max(probe_chunk.num_rows - 1, 0))
        pri = np.clip(rep_ri[gidx], 0, max(nb - 1, 0))
        rep_chunk = Chunk(probe_chunk.take(pli).columns +
                          build.take(pri).columns)
        order = np.arange(len(gidx), dtype=np.int64)
        return finalize_group_result(rep_chunk, self.group_exprs,
                                     self.aggs, order, order, lanes_at,
                                     counts[gidx])

# process-wide fragment-kernel cache, keyed on the structural identity
# of the whole fragment (join-key arity, schema split, group/agg
# fingerprint, table capacity and the degrade bounds) — a re-created
# plan reuses the traced program instead of re-tracing it
_FRAGMENTS = runtime.FingerprintCache(32)


def fragment_kernel_for(num_keys: int, probe_width: int, width: int,
                        group_exprs, aggs, capacity: int = 4096):
    """ProbeAggKernel with process-wide reuse; raises DeviceRejectError
    (or ValueError) when the fragment is not device-safe — the caller
    then keeps the per-operator path."""
    from tidb_tpu import config
    from tidb_tpu.ops.hashagg import _direct_group_mode
    direct_limit = config.direct_agg_slots()
    force_hash = capacity > direct_limit and \
        _direct_group_mode(group_exprs)

    from tidb_tpu import profiler
    made = []

    def make():
        made.append(1)
        return ProbeAggKernel(num_keys, probe_width, width, group_exprs,
                              aggs, capacity=capacity,
                              force_hash=force_hash,
                              direct_limit=direct_limit)

    fp = runtime.plan_fingerprint(None, group_exprs, aggs)
    if fp is None:
        k = make()
        prof = profiler.profile("fragment", None)
        profiler.note_construct(prof, reuse=False)
        k._profile = prof
        return k
    from tidb_tpu import devplane
    key = (fp, num_keys, probe_width, width, capacity, force_hash,
           direct_limit, devplane.mesh_fingerprint(process=True))
    k = _FRAGMENTS.get_or_create(key, make)
    prof = profiler.profile(
        "fragment", f"{fp}|{num_keys}|{probe_width}|{width}|{capacity}"
                    f"|{force_hash}|{direct_limit}")
    profiler.note_construct(prof, reuse=not made)
    k._profile = prof
    return k
