"""Hash aggregation on device: filter + group-by + partial agg in one XLA
program.

Replaces /root/reference/executor/aggregate.go:32-57 (HashAggExec over an
mvmap hash table, row-at-a-time aggCtx updates) and the storage-side agg of
mocktikv/aggregate.go. The dynamic hash table becomes a TPU-friendly
sort-based group-by (SURVEY.md §7 "Device hash tables", Plan A):

    1. mix group-key lanes into a 64-bit hash per row (masked rows get a
       sentinel bucket)
    2. jnp.unique(size=capacity) -> sorted group hashes + inverse ids
       (static shapes; capacity overflow detected and surfaced)
    3. jax.ops.segment_* reduces produce fixed-width partial states
    4. a second independent hash verifies per-group key agreement, so a
       64-bit collision is *detected* (collision -> caller falls back to
       the host path) rather than silently merging groups

Partial states follow expression/agg.py's protocol, so chunk partials merge
on the host (or across a mesh with psum) exactly like the reference's
partial/final agg split.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.sqltypes import EvalType

__all__ = ["AggSpec", "HashAggKernel", "ScalarAggKernel", "HashAggregator",
           "CapacityError", "CollisionError", "GroupResult",
           "finalize_group_result"]

AggSpec = AggDesc  # the planner's descriptor doubles as the kernel spec

_SENTINEL_MASKED = np.int64(-(1 << 63))        # all filtered-out rows
_FILL = np.int64((1 << 63) - 1)                # unique() padding
_I64_MAX = np.int64((1 << 63) - 1)
_I64_MIN = np.int64(-(1 << 63))

# golden-ratio mixing constants (splitmix64, public domain)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_GOLD = np.uint64(0x9E3779B97F4A7C15)


class CapacityError(Exception):
    """More groups than the kernel's static capacity: re-plan with a larger
    capacity or fall back to the host path."""


class CollisionError(Exception):
    """Two distinct key tuples collided in 64-bit hash space (detected by
    the check hash); fall back to the host path."""


def _splitmix(xp, h):
    h = xp.asarray(h).astype(jnp.uint64) if xp is jnp else h.astype(np.uint64)
    h = (h + _GOLD)
    h = (h ^ (h >> np.uint64(30))) * _MIX1
    h = (h ^ (h >> np.uint64(27))) * _MIX2
    h = h ^ (h >> np.uint64(31))
    return h


def _key_bits(xp, d):
    """Exact uint64 bit pattern of a key lane: floats are bitcast (value
    cast would truncate 2.3 and 2.7 to the same hash under BOTH seeds,
    silently merging groups), with -0.0 normalized to +0.0 first since
    SQL treats them as equal."""
    ut = jnp.uint64 if xp is jnp else np.uint64
    d = xp.asarray(d)
    if d.dtype == (jnp.float64 if xp is jnp else np.float64):
        d = xp.where(d == 0.0, 0.0, d)
        if xp is jnp:
            return jax.lax.bitcast_convert_type(d, jnp.uint64)
        return d.view(np.uint64)
    return d.astype(ut)


def _hash_keys(xp, key_cols, n, seed: int):
    """Combine (data, valid) key lanes into one int64 hash per row.
    NULL contributes a distinct tag so NULL groups separately from 0."""
    h = xp.full(n, np.uint64(seed), dtype=jnp.uint64 if xp is jnp else np.uint64)
    for d, v in key_cols:
        u = _key_bits(xp, d)
        # validity mixes as its OWN lane: zeroing the data under NULL and
        # hashing v separately means no data value can alias the NULL key
        # (a fixed null-tag constant would collide with that literal value
        # under BOTH seeds, defeating the dual-hash collision check)
        h = _splitmix(xp, h ^ xp.where(v, u, np.uint64(0)))
        h = _splitmix(xp, h ^ v.astype(h.dtype))
    out = h.astype(jnp.int64 if xp is jnp else np.int64)
    # reserve the sentinel values for masked/fill
    out = xp.where(out == _SENTINEL_MASKED, np.int64(-(1 << 63) + 1), out)
    out = xp.where(out == _FILL, np.int64((1 << 63) - 2), out)
    return out


def _distinct_count(xp, h):
    """True number of distinct values in h (any size), static shape."""
    s = xp.sort(h)
    return 1 + xp.sum(s[1:] != s[:-1])


def _agg_lanes(xp, agg: AggDesc, cols, n, mask, inv, capacity: int,
               offs=None):
    """This aggregate's partial-state lanes as [(array[capacity],
    merge_op)] with merge_op in {'sum','min','max'} — how lanes of the
    same group combine across chunks/shards. With offs (a shard's global
    row offset) FIRST_ROW indices are globalized for cross-shard merging;
    without it they stay chunk-local (host gathers within the chunk)."""
    fn = agg.fn
    if agg.arg is not None:
        d, v = agg.arg.eval_xp(xp, cols, n)
        live = mask & v
    else:
        d, live = None, mask
    seg_sum = lambda x: jax.ops.segment_sum(x, inv, num_segments=capacity)
    seg_min = lambda x: jax.ops.segment_min(x, inv, num_segments=capacity)
    seg_max = lambda x: jax.ops.segment_max(x, inv, num_segments=capacity)
    has = seg_max(live.astype(jnp.int64))

    if fn == AggFunc.COUNT:
        return [(seg_sum(live.astype(jnp.int64)), "sum")]
    if fn == AggFunc.SUM:
        zero = 0.0 if d.dtype == jnp.float64 else 0
        return [(seg_sum(xp.where(live, d, zero)), "sum"), (has, "max")]
    if fn == AggFunc.AVG:
        zero = 0.0 if d.dtype == jnp.float64 else 0
        return [(seg_sum(xp.where(live, d, zero)), "sum"),
                (seg_sum(live.astype(jnp.int64)), "sum")]
    if fn == AggFunc.MIN:
        ident = jnp.inf if d.dtype == jnp.float64 else _I64_MAX
        return [(seg_min(xp.where(live, d, ident)), "min"), (has, "max")]
    if fn == AggFunc.MAX:
        ident = -jnp.inf if d.dtype == jnp.float64 else _I64_MIN
        return [(seg_max(xp.where(live, d, ident)), "max"), (has, "max")]
    if fn == AggFunc.FIRST_ROW:
        first = seg_min(xp.where(live, xp.arange(n), n))
        if offs is not None:
            first = xp.where(has > 0, offs + first, _I64_MAX)
        return [(first, "min"), (has, "max")]
    raise NotImplementedError(f"device agg {fn}")


def _validate_device_exprs(filter_expr, group_exprs, aggs) -> None:
    """Device kernels see dict-encoded int64 codes for varlen columns, so a
    string column may appear ONLY as a bare group-key ColumnRef (codes group
    identically to values within a chunk; exact values are recovered from
    representative rows). Any computation over strings must be pre-applied
    on the host by the planner."""
    from tidb_tpu.expression import ColumnRef
    if filter_expr is not None and not filter_expr.is_device_safe():
        raise ValueError("filter expression is not device-safe; planner "
                         "must split string predicates to the host path")
    for g in group_exprs:
        if not g.is_device_safe() and not isinstance(g, ColumnRef):
            raise ValueError(f"group expr {g!r} computes over a varlen "
                             "column; pre-project it on the host")
    for a in aggs:
        if a.fn == AggFunc.GROUP_CONCAT:
            raise ValueError("GROUP_CONCAT aggregates on the host")
        if a.arg is not None and not a.arg.is_device_safe():
            # FIRST_ROW only needs a row index on device, so a bare string
            # ColumnRef is fine (value gathered host-side); computed string
            # exprs would still trace eval_xp and explode mid-jit
            if not (a.fn == AggFunc.FIRST_ROW and
                    isinstance(a.arg, ColumnRef)):
                raise ValueError(f"agg arg {a.arg!r} is not device-safe")


@dataclass
class GroupResult:
    """Partial aggregation result of one chunk."""

    keys: list[tuple]            # group key tuples (host python values)
    partials: list[np.ndarray]   # per agg: [lanes][num_groups] arrays
    counts: np.ndarray           # rows per group


def finalize_group_result(chunk: Chunk, group_exprs, aggs, gidx: np.ndarray,
                          rep_rows: np.ndarray, lanes_per_agg,
                          counts: np.ndarray) -> GroupResult:
    """Shared host tail of the device kernels: recover exact group-key
    values from representative rows (strings included — host path),
    materialize FIRST_ROW values, and package a GroupResult.

    lanes_per_agg: per agg, the [num_live_groups]-length lane arrays
    (already gathered at gidx)."""
    sub = chunk.take(rep_rows)
    key_cols = []
    for g in group_exprs:
        d, v = g.eval(sub)
        key_cols.append([None if not v[i] else
                         (d[i].item() if hasattr(d[i], "item") else d[i])
                         for i in range(len(gidx))])
    keys = list(zip(*key_cols)) if key_cols else [()] * len(gidx)
    partials = []
    for a, ls in zip(aggs, lanes_per_agg):
        if a.fn == AggFunc.FIRST_ROW:
            # gather only the first-row rows, then evaluate the arg on
            # that tiny sub-chunk (host path handles strings)
            idx = ls[0]
            hasv = ls[1] > 0
            safe_idx = np.where(hasv, idx, 0).astype(np.int64)
            d, _v = a.arg.eval(chunk.take(safe_idx))
            vals = np.where(hasv, d, 0) if d.dtype != object else d
            ls = [vals, hasv.astype(np.int64)]
        partials.append(ls)
    return GroupResult(keys=keys, partials=partials, counts=counts)


class HashAggKernel:
    """Compiled filter+group+partial-agg over one chunk schema.

    group_exprs must be device-safe (strings dict-encoded upstream by
    runtime.device_put_chunk; their ColumnRefs then see int64 codes).
    """

    def __init__(self, filter_expr: Expression | None,
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096):
        self.filter_expr = filter_expr
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        self.capacity = capacity
        _validate_device_exprs(filter_expr, self.group_exprs, self.aggs)
        self._jit = jax.jit(self._kernel)

    def _kernel(self, cols, nrows):
        n = cols[0][0].shape[0]
        xp = jnp
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, n)
        mask = mask & (xp.arange(n) < nrows)   # padding rows are dead
        key_cols = [g.eval_xp(xp, cols, n) for g in self.group_exprs]
        h = _hash_keys(xp, key_cols, n, seed=0x517CC1B727220A95)
        h2 = _hash_keys(xp, key_cols, n, seed=0x2545F4914F6CDD1D)
        h = xp.where(mask, h, _SENTINEL_MASKED)
        uniq, inv = jnp.unique(h, size=self.capacity, fill_value=_FILL,
                               return_inverse=True)
        # true distinct count (incl. masked sentinel) for overflow detection
        nuniq = _distinct_count(xp, h)
        # collision check: within each group, the check hash must agree
        c_min = jax.ops.segment_min(xp.where(mask, h2, _I64_MAX), inv,
                                    num_segments=self.capacity)
        c_max = jax.ops.segment_max(xp.where(mask, h2, _I64_MIN), inv,
                                    num_segments=self.capacity)
        live_group = jax.ops.segment_max(mask.astype(jnp.int64), inv,
                                         num_segments=self.capacity)
        collided = jnp.any((live_group > 0) & (c_min != c_max))
        counts = jax.ops.segment_sum(mask.astype(jnp.int64), inv,
                                     num_segments=self.capacity)
        rep = jax.ops.segment_min(xp.where(mask, xp.arange(n), n), inv,
                                  num_segments=self.capacity)
        lanes = [[l for l, _op in
                  _agg_lanes(xp, a, cols, n, mask, inv, self.capacity)]
                 for a in self.aggs]
        return uniq, nuniq, collided, counts, rep, lanes

    def __call__(self, chunk: Chunk) -> GroupResult:
        cols, _dicts = runtime.device_put_chunk(chunk)
        # ONE batched device->host transfer for the whole result pytree:
        # per-array reads each pay full round-trip latency (the device may
        # sit behind a network tunnel), a single device_get amortizes it
        uniq, nuniq, collided, counts, rep, lanes = jax.device_get(
            self._jit(cols, chunk.num_rows))
        if bool(collided):
            raise CollisionError("group key hash collision")
        live = (counts > 0) & (uniq != _SENTINEL_MASKED) & (uniq != _FILL)
        if int(nuniq) > self.capacity:
            err = CapacityError(f"distinct groups {int(nuniq)} > capacity "
                                f"{self.capacity}")
            err.needed = int(nuniq)   # executors re-plan with 2x this
            raise err
        gidx = np.flatnonzero(live)
        lanes_at = [[l[gidx] for l in ls] for ls in lanes]
        return finalize_group_result(chunk, self.group_exprs, self.aggs,
                                     gidx, rep[gidx], lanes_at, counts[gidx])


class ScalarAggKernel:
    """No-group aggregation: one partial state row per chunk."""

    def __init__(self, filter_expr: Expression | None,
                 aggs: Sequence[AggDesc]):
        self.filter_expr = filter_expr
        self.aggs = list(aggs)
        _validate_device_exprs(filter_expr, [], self.aggs)
        self._jit = jax.jit(self._kernel)

    def _kernel(self, cols, nrows):
        n = cols[0][0].shape[0]
        xp = jnp
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, n)
        mask = mask & (xp.arange(n) < nrows)   # padding rows are dead
        inv = xp.zeros(n, dtype=jnp.int32)
        count = jax.ops.segment_sum(mask.astype(jnp.int64), inv,
                                    num_segments=1)
        lanes = [[l for l, _op in _agg_lanes(xp, a, cols, n, mask, inv, 1)]
                 for a in self.aggs]
        return count, lanes

    def __call__(self, chunk: Chunk) -> GroupResult:
        cols, _ = runtime.device_put_chunk(chunk)
        count, lanes = jax.device_get(self._jit(cols, chunk.num_rows))
        partials = []
        for a, ls in zip(self.aggs, lanes):
            if a.fn == AggFunc.FIRST_ROW:
                idx = ls[0]
                hasv = ls[1] > 0
                if hasv[0] and chunk.num_rows > 0:
                    d, _v = a.arg.eval(chunk.take(np.array([int(idx[0])])))
                    val = d[0]
                else:
                    val = 0
                ls = [np.array([val]), hasv.astype(np.int64)]
            partials.append(ls)
        return GroupResult(keys=[()], partials=partials, counts=count)


class HashAggregator:
    """Stateful final aggregator: merges chunk partials on the host and
    finalizes per-group values. Mirrors Aggregation.GetPartialResult
    merging (expression/aggregation/aggregation.go:32-47)."""

    def __init__(self, aggs: Sequence[AggDesc]):
        self.aggs = list(aggs)
        self._state: dict[tuple, list] = {}

    def update(self, res: GroupResult) -> None:
        for gi, key in enumerate(res.keys):
            st = self._state.get(key)
            if st is None:
                self._state[key] = [
                    [lane[gi] for lane in res.partials[ai]]
                    for ai in range(len(self.aggs))]
                continue
            for ai, agg in enumerate(self.aggs):
                lanes = res.partials[ai]
                cur = st[ai]
                fn = agg.fn
                if fn == AggFunc.COUNT:
                    cur[0] += lanes[0][gi]
                elif fn in (AggFunc.SUM, AggFunc.AVG):
                    cur[0] += lanes[0][gi]
                    cur[1] = max(cur[1], lanes[1][gi]) if fn == AggFunc.SUM \
                        else cur[1] + lanes[1][gi]
                elif fn == AggFunc.MIN:
                    if lanes[1][gi] > 0:
                        cur[0] = min(cur[0], lanes[0][gi]) if cur[1] > 0 \
                            else lanes[0][gi]
                        cur[1] = 1
                elif fn == AggFunc.MAX:
                    if lanes[1][gi] > 0:
                        cur[0] = max(cur[0], lanes[0][gi]) if cur[1] > 0 \
                            else lanes[0][gi]
                        cur[1] = 1
                elif fn == AggFunc.FIRST_ROW:
                    if cur[1] == 0 and lanes[1][gi] > 0:
                        cur[0], cur[1] = lanes[0][gi], 1
                elif fn == AggFunc.GROUP_CONCAT:
                    if lanes[1][gi] > 0:
                        if cur[1] > 0:
                            cur[0] = cur[0] + agg.sep + lanes[0][gi]
                        else:
                            cur[0], cur[1] = lanes[0][gi], 1

    def results(self) -> list[tuple[tuple, list]]:
        """-> [(key, [final agg values])] with AVG finalized; SUM/AVG of
        decimals stay scaled ints (callers format via the agg result_ft)."""
        out = []
        for key, st in sorted(self._state.items(),
                              key=lambda kv: tuple(
                                  (x is None, x) for x in kv[0])):
            vals = []
            for agg, cur in zip(self.aggs, st):
                fn = agg.fn
                if fn == AggFunc.COUNT:
                    vals.append(int(cur[0]))
                elif fn == AggFunc.SUM:
                    vals.append(None if cur[1] == 0 else cur[0])
                elif fn == AggFunc.AVG:
                    if cur[1] == 0:
                        vals.append(None)
                    elif agg.result_ft.eval_type == EvalType.DECIMAL:
                        # scaled-int avg: rescale sum by extra frac then div
                        extra = agg.result_ft.frac - agg.arg.ft.frac
                        vals.append(int(round(
                            int(cur[0]) * (10 ** extra) / int(cur[1]))))
                    else:
                        vals.append(float(cur[0]) / float(cur[1]))
                elif fn in (AggFunc.MIN, AggFunc.MAX, AggFunc.FIRST_ROW,
                            AggFunc.GROUP_CONCAT):
                    vals.append(None if cur[1] == 0 else cur[0])
                else:
                    raise NotImplementedError(fn)
            out.append((key, vals))
        return out
