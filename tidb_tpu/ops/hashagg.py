"""Hash aggregation on device: filter + group-by + partial agg in one XLA
program.

Replaces /root/reference/executor/aggregate.go:32-57 (HashAggExec over an
mvmap hash table, row-at-a-time aggCtx updates) and the storage-side agg of
mocktikv/aggregate.go. The dynamic hash table becomes a TPU-friendly
sort-based group-by (SURVEY.md §7 "Device hash tables", Plan A):

    1. mix group-key lanes into a 64-bit hash per row (masked rows get a
       sentinel bucket)
    2. jnp.unique(size=capacity) -> sorted group hashes + inverse ids
       (static shapes; capacity overflow detected and surfaced)
    3. jax.ops.segment_* reduces produce fixed-width partial states
    4. a second independent hash verifies per-group key agreement, so a
       64-bit collision is *detected* (collision -> caller falls back to
       the host path) rather than silently merging groups

Partial states follow expression/agg.py's protocol, so chunk partials merge
on the host (or across a mesh with psum) exactly like the reference's
partial/final agg split.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.sqltypes import EvalType

__all__ = ["AggSpec", "HashAggKernel", "ScalarAggKernel", "HashAggregator",
           "CapacityError", "CollisionError", "DeviceRejectError",
           "GroupResult", "finalize_group_result", "kernel_for",
           "group_partial"]

AggSpec = AggDesc  # the planner's descriptor doubles as the kernel spec

_SENTINEL_MASKED = np.int64(-(1 << 63))        # all filtered-out rows
_FILL = np.int64((1 << 63) - 1)                # unique() padding
_I64_MAX = np.int64((1 << 63) - 1)
_I64_MIN = np.int64(-(1 << 63))

# golden-ratio mixing constants (splitmix64, public domain)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_GOLD = np.uint64(0x9E3779B97F4A7C15)


class CapacityError(Exception):
    """More groups than the kernel's static capacity: re-plan with a larger
    capacity or fall back to the host path."""


class CollisionError(Exception):
    """Two distinct key tuples collided in 64-bit hash space (detected by
    the check hash); fall back to the host path."""


class DeviceRejectError(ValueError):
    """The plan is not device-safe BY DESIGN (string computation, host-
    only aggregate): the designed device->host fallback signal. A
    ValueError subclass so legacy `except ValueError` handlers keep
    working — but fallback nets should catch THIS, so a genuine kernel
    bug raising a bare ValueError surfaces instead of masquerading as a
    capacity miss."""


def _splitmix(xp, h):
    h = xp.asarray(h).astype(jnp.uint64) if xp is jnp else h.astype(np.uint64)
    h = (h + _GOLD)
    h = (h ^ (h >> np.uint64(30))) * _MIX1
    h = (h ^ (h >> np.uint64(27))) * _MIX2
    h = h ^ (h >> np.uint64(31))
    return h


def _key_bits(xp, d):
    """Exact uint64 bit pattern of a key lane: floats are bitcast (value
    cast would truncate 2.3 and 2.7 to the same hash under BOTH seeds,
    silently merging groups), with -0.0 normalized to +0.0 first since
    SQL treats them as equal."""
    ut = jnp.uint64 if xp is jnp else np.uint64
    d = xp.asarray(d)
    if d.dtype == (jnp.float64 if xp is jnp else np.float64):
        d = xp.where(d == 0.0, 0.0, d)
        if xp is jnp:
            return jax.lax.bitcast_convert_type(d, jnp.uint64)
        return d.view(np.uint64)
    return d.astype(ut)


# lint: exempt[dtype-discipline] row hashes are int64 by contract: splitmix64 bit patterns, sentinel headroom at both int64 extremes
def _hash_keys(xp, key_cols, n, seed: int):
    """Combine (data, valid) key lanes into one int64 hash per row.
    NULL contributes a distinct tag so NULL groups separately from 0."""
    h = xp.full(n, np.uint64(seed), dtype=jnp.uint64 if xp is jnp else np.uint64)
    for d, v in key_cols:
        u = _key_bits(xp, d)
        # validity mixes as its OWN lane: zeroing the data under NULL and
        # hashing v separately means no data value can alias the NULL key
        # (a fixed null-tag constant would collide with that literal value
        # under BOTH seeds, defeating the dual-hash collision check)
        h = _splitmix(xp, h ^ xp.where(v, u, np.uint64(0)))
        h = _splitmix(xp, h ^ v.astype(h.dtype))
    out = h.astype(jnp.int64 if xp is jnp else np.int64)
    # reserve the sentinel values for masked/fill
    out = xp.where(out == _SENTINEL_MASKED, np.int64(-(1 << 63) + 1), out)
    out = xp.where(out == _FILL, np.int64((1 << 63) - 2), out)
    return out


def _distinct_count(xp, h):
    """True number of distinct values in h (any size), static shape."""
    s = xp.sort(h)
    return 1 + xp.sum(s[1:] != s[:-1])


def _direct_group_mode(group_exprs) -> bool:
    """True when every group key is a dict-encoded string ColumnRef: the
    device sees small dense int64 codes, so group slots can be indexed
    DIRECTLY (code0 * m1 + code1 ...) — no sort, no hash, no collision
    possibility, and cross-shard tables merge elementwise because every
    shard shares one slot space. This is the TPC-H Q1/Q5 shape (group by
    returnflag+linestatus / n_name)."""
    from tidb_tpu.expression.core import ColumnRef
    from tidb_tpu.sqltypes import EvalType, TypeCode
    if not group_exprs:
        return False
    return all(isinstance(g, ColumnRef) and
               g.ft.eval_type == EvalType.STRING and
               g.ft.tp != TypeCode.JSON
               for g in group_exprs)


# lint: exempt[dtype-discipline] group codes carry exact int64 key values (scaled decimals / epoch-micros exceed float range)
def _direct_group_table(xp, group_exprs, cols, n, mask, C, pmax_axes=None):
    """Direct-indexed group table -> (uniq[C], inv[n] i32, tot).
    Strides come from data maxima (pmax over the mesh axes so every
    shard agrees on the slot space). Slot C-1 is the masked-rows slot;
    combined codes clamp to C-2 and `tot` overshoots _C when clamping
    occurred, so the capacity-escalation path re-plans exactly as in
    the hash mode. uniq holds the combined code per live slot."""
    combined = None
    for g in group_exprs:
        d, v = g.eval_xp(xp, cols, n)
        code = xp.where(v, xp.asarray(d, dtype=jnp.int64) + 1, 0)
        code = xp.where(mask, code, 0)
        if combined is None:
            combined = code
        else:
            m = xp.max(code) if n else jnp.int64(0)
            if pmax_axes is not None:
                m = lax.pmax(m, pmax_axes)
            combined = combined * (m + 1) + code
    tot = xp.max(xp.where(mask, combined, -1)) + 2
    slot = xp.minimum(combined, C - 2).astype(jnp.int32)
    inv = xp.where(mask, slot, C - 1).astype(jnp.int32)
    uniq = xp.full(C, _FILL, dtype=jnp.int64).at[inv].set(
        xp.where(mask, xp.minimum(combined, C - 2), _SENTINEL_MASKED))
    return uniq, inv, tot


def _cond_direct_mode(group_exprs) -> bool:
    """True when every group key is a bare ColumnRef of INT or
    dict-string kind — the shape where a RUNTIME range check can pick
    direct code-indexed slots (no sort, no hash, exact) over the packed
    sort, via lax.cond. Covers low-cardinality int keys (status codes,
    dates-as-days, small dimension ids) that the static string-only
    check misses; wide-range keys take the hash branch at runtime."""
    from tidb_tpu.expression.core import ColumnRef
    from tidb_tpu.sqltypes import EvalType, TypeCode
    if not group_exprs:
        return False
    for g in group_exprs:
        if not isinstance(g, ColumnRef) or g.ft.tp == TypeCode.JSON:
            return False
        if g.ft.eval_type not in (EvalType.INT, EvalType.STRING,
                                  EvalType.DATETIME,
                                  EvalType.DURATION):
            return False
    return True


# lint: exempt[dtype-discipline] exact int64 key codes + float64 span product (span overflow check must not round at 2^53)
def _cond_group_table(xp, group_exprs, cols, n, mask, h, C,
                      pmax_axes=None, direct_limit=None):
    """Runtime-selected group table: if the keys' (min..max) span
    product fits the capacity, index slots DIRECTLY by normalized
    codes; otherwise fall back to the packed-sort table over the
    precomputed hash `h`. Mins/spans are global over the mesh axes so
    every shard agrees on the code space (the value-based re-unique
    merge then stays correct). `direct_limit` caps the direct branch
    below the table capacity (tidb_tpu_direct_agg_slots): a
    capacity-escalated retry keeps a bounded direct domain and degrades
    wide spans to the hash branch instead of ballooning the
    direct-indexed table."""
    codes = []
    spans = []
    span_fs = []
    for g in group_exprs:
        d, v = g.eval_xp(xp, cols, n)
        d = xp.asarray(d, jnp.int64)
        live = mask & v
        lo = xp.min(xp.where(live, d, _I64_MAX))
        hi_raw = xp.max(xp.where(live, d, _I64_MIN))
        if pmax_axes is not None:
            lo = -lax.pmax(-lo, pmax_axes)
            hi_raw = lax.pmax(hi_raw, pmax_axes)
        # NULL -> 0; live values -> 1.. (saturate when no live rows)
        code = xp.where(live, xp.maximum(d - lo, 0) + 1, 0)
        hi = xp.max(code)
        if pmax_axes is not None:
            hi = lax.pmax(hi, pmax_axes)
        codes.append(code)
        spans.append(hi + 1)
        # the SMALLNESS decision uses raw min/max in float64: the int64
        # code math (d - lo) wraps when the raw span exceeds 2^63 and
        # would make a huge span look tiny, forcing the direct branch
        # onto colliding codes
        span_fs.append(jnp.maximum(
            hi_raw.astype(jnp.float64) - lo.astype(jnp.float64) + 2.0,
            1.0))      # no live rows: empty span counts as 1

    span_prod = jnp.prod(jnp.stack(span_fs))
    bound = C - 2 if direct_limit is None else min(C - 2, direct_limit)
    small = span_prod <= jnp.float64(bound)

    def direct(_):
        combined = codes[0]
        for c, s in zip(codes[1:], spans[1:]):
            combined = combined * s + c
        tot = xp.max(xp.where(mask, combined, -1)) + 2
        slot = xp.minimum(combined, C - 2).astype(jnp.int32)
        inv = xp.where(mask, slot, C - 1).astype(jnp.int32)
        # slot IDENTITY is the key-tuple hash, not the dense code:
        # the cross-shard re-unique merge quantizes top bits, which
        # would collapse small codes into one group (hash values keep
        # the hash mode's merge contract exactly)
        uniq = xp.full(C, _FILL, dtype=jnp.int64).at[inv].set(
            xp.where(mask, h, _SENTINEL_MASKED))
        return uniq, inv, tot.astype(jnp.int64)

    def hashed(_):
        uniq, inv, tot = _group_table(xp, h, n, C, mask=mask)
        return uniq, inv, jnp.asarray(tot, jnp.int64)

    return lax.cond(small, direct, hashed, None)


# lint: exempt[dtype-discipline] packed sort rides the int64 hash lanes (row index bit-packed into the low hash bits)
def _group_table(xp, x, m, C, mask=None):
    """Dense group-id table from one PACKED sort — the jnp.unique
    replacement. jnp.unique(size=C, return_inverse) costs a sort plus an
    argsort-shaped pair sort (~5x a plain sort on CPU XLA, measured), and
    the separate _distinct_count costs another; here the hash is
    quantized to (64 - ceil_log2(m)) bits, the element index rides the
    freed low bits, and ONE sort yields uniq, inverse, and the true
    distinct count via boundary flags + cumsum + two cheap scatters.

    Quantization can merge two distinct hashes into one group; like a
    full 64-bit collision that is caught by the caller's dual-hash
    (h2 min != max) check, which triggers the host fallback. The
    bottom and top quanta are reserved so real hashes never alias
    _SENTINEL_MASKED (masked rows, with `mask`) or _FILL (padding in
    gathered tables).

    -> (uniq[C] ascending with _FILL padding, inv[m] int32, tot)."""
    bits = max(1, int(m - 1).bit_length()) if m > 1 else 1  # lint: exempt[retrace-hazard] m is the padded length (shape-derived, static at trace time), not a traced value
    B = np.int64(bits)
    Q = np.int64(1) << B
    low = Q - np.int64(1)
    qfill = (_FILL >> B) << B
    hq = (x >> B) << B
    hq = xp.where(hq == _SENTINEL_MASKED, _SENTINEL_MASKED + Q, hq)
    hq = xp.where(hq == qfill, qfill - Q, hq)
    hq = xp.where(x == _FILL, qfill, hq)
    hq = xp.where(x == _SENTINEL_MASKED, _SENTINEL_MASKED, hq)
    if mask is not None:
        hq = xp.where(mask, hq, _SENTINEL_MASKED)
    packed = hq | xp.arange(m, dtype=jnp.int64)
    s = xp.sort(packed)
    sh = (s >> B) << B
    row = (s & low).astype(jnp.int32)
    newg = xp.concatenate([xp.ones((1,), dtype=bool), sh[1:] != sh[:-1]])
    sid = xp.cumsum(newg.astype(jnp.int32)) - 1
    tot = sid[-1] + 1
    sidc = xp.minimum(sid, C - 1)
    inv = xp.zeros(m, dtype=jnp.int32).at[row].set(sidc)
    uniq = xp.full(C, _FILL, dtype=jnp.int64).at[sidc].set(sh)
    uniq = xp.where(uniq == qfill, _FILL, uniq)
    return uniq, inv, tot


_SEG_FNS = {"sum": jax.ops.segment_sum,
            "min": jax.ops.segment_min,
            "max": jax.ops.segment_max}


class _SegBatch:
    """Batches segment reductions: every requested lane with the same
    (merge-op, dtype) reduces in ONE wide segment op over stacked [n, k]
    data instead of k separate scatters. Scatter passes dominate the
    group-by program (CPU XLA scatters are serial; on TPU each scatter
    is a full HBM pass), so Q1's ~16 per-lane scatters collapse to ~4.
    dtype-separated stacking keeps int64 lanes exact (decimal sums can
    exceed 2^53 — promoting through float64 would corrupt them).

    Sum lanes may carry a `valid` mask instead of a pre-masked array:
    on the TPU pallas path the mask fuses INTO the one-hot MXU kernel
    (ops/pallas_agg._kernel_masked) so the predicate never materializes
    a masked value copy in HBM; everywhere else run() lowers the mask to
    the classic `where(valid, x, 0)` pre-pass, preserving the exact
    pre-fusion program (and its stacking) bit for bit."""

    def __init__(self, inv, capacity: int):
        self.inv = inv
        self.capacity = capacity
        self._reqs: list = []     # (op, array[n], valid[n] | None)
        self._out: list | None = None

    def add(self, x, op: str, valid=None) -> int:
        self._reqs.append((op, x, valid))
        return len(self._reqs) - 1

    def run(self) -> None:
        from tidb_tpu.ops import pallas_agg
        fuse = pallas_agg.available()
        plain: list = []          # (i, op, x) after mask lowering
        fused: list = []          # (i, x, valid) f32 sums for the MXU
        for i, (op, x, valid) in enumerate(self._reqs):
            if valid is not None and op == "sum" and fuse and \
                    x.dtype == jnp.float32:
                fused.append((i, x, valid))
                continue
            if valid is not None:
                x = jnp.where(valid, x, jnp.zeros((), x.dtype))
            plain.append((i, op, x))
        out: list = [None] * len(self._reqs)
        groups: dict = {}
        for i, op, x in plain:
            groups.setdefault((op, x.dtype), []).append((i, x))
        for (op, _dt), reqs in groups.items():
            if op == "sum":
                # MXU one-hot matmul on TPU float lanes; XLA scatter
                # elsewhere (pallas_agg dispatches)
                def fn(x, ids, num_segments):
                    return pallas_agg.segment_sum(x, ids, num_segments)
            else:
                fn = _SEG_FNS[op]
            if len(reqs) == 1:
                i, x = reqs[0]
                out[i] = fn(x, self.inv, num_segments=self.capacity)
            else:
                stk = jnp.stack([x for _i, x in reqs], axis=1)
                r = fn(stk, self.inv, num_segments=self.capacity)
                for j, (i, _x) in enumerate(reqs):
                    out[i] = r[:, j]
        if fused:
            if len(fused) == 1:
                i, x, valid = fused[0]
                out[i] = pallas_agg.segment_sum(
                    x, self.inv, num_segments=self.capacity, valid=valid)
            else:
                stk = jnp.stack([x for _i, x, _v in fused], axis=1)
                mstk = jnp.stack([v for _i, _x, v in fused], axis=1)
                r = pallas_agg.segment_sum(
                    stk, self.inv, num_segments=self.capacity, valid=mstk)
                for j, (i, _x, _v) in enumerate(fused):
                    out[i] = r[:, j]
        self._out = out

    def get(self, i: int):
        return self._out[i]


# lint: exempt[dtype-discipline] int64 sum lanes: decimal sums exceed 2^53, float64 promotion would corrupt them
def _agg_requests(xp, agg: AggDesc, cols, n, mask, batch: _SegBatch,
                  offs=None, row_ids=None):
    """Phase 1 of an aggregate's partial-state lanes: enqueue the per-row
    inputs on `batch`, return assemble(get) -> [(array[capacity],
    merge_op)] for after batch.run(). merge_op in {'sum','min','max'} is
    how lanes of the same group combine across chunks/shards. With offs
    (a shard's global row offset) FIRST_ROW indices are globalized for
    cross-shard merging; without it they stay chunk-local. row_ids
    overrides the per-row identity entirely (compacted stages carry the
    ORIGINAL probe row index as a column — ops/meshjoin two-phase path)."""
    fn = agg.fn
    if agg.arg is not None:
        d, v = agg.arg.eval_xp(xp, cols, n)
        live = mask & v
    else:
        d, live = None, mask
    live_i = live.astype(jnp.int64)

    if fn == AggFunc.COUNT:
        i0 = batch.add(live_i, "sum")
        return lambda g: [(g(i0), "sum")]
    if fn == AggFunc.SUM:
        # the mask rides the request: fused into the MXU kernel on the
        # pallas path, lowered to where(live, d, 0) everywhere else
        i0 = batch.add(d, "sum", valid=live)
        i1 = batch.add(live_i, "max")
        return lambda g: [(g(i0), "sum"), (g(i1), "max")]
    if fn == AggFunc.AVG:
        i0 = batch.add(d, "sum", valid=live)
        i1 = batch.add(live_i, "sum")
        return lambda g: [(g(i0), "sum"), (g(i1), "sum")]
    if fn == AggFunc.MIN:
        ident = jnp.inf if d.dtype == jnp.float64 else _I64_MAX
        i0 = batch.add(xp.where(live, d, ident), "min")
        i1 = batch.add(live_i, "max")
        return lambda g: [(g(i0), "min"), (g(i1), "max")]
    if fn == AggFunc.MAX:
        ident = -jnp.inf if d.dtype == jnp.float64 else _I64_MIN
        i0 = batch.add(xp.where(live, d, ident), "max")
        i1 = batch.add(live_i, "max")
        return lambda g: [(g(i0), "max"), (g(i1), "max")]
    if fn == AggFunc.FIRST_ROW:
        if row_ids is not None:
            i0 = batch.add(xp.where(live, row_ids, _I64_MAX), "min")
            i1 = batch.add(live_i, "max")
            return lambda g: [(g(i0), "min"), (g(i1), "max")]
        i0 = batch.add(xp.where(live, xp.arange(n), n), "min")
        i1 = batch.add(live_i, "max")

        def assemble(g):
            first = g(i0)
            if offs is not None:
                first = xp.where(g(i1) > 0, offs + first, _I64_MAX)
            return [(first, "min"), (g(i1), "max")]
        return assemble
    raise NotImplementedError(f"device agg {fn}")


def _agg_lanes(xp, agg: AggDesc, cols, n, mask, inv, capacity: int,
               offs=None):
    """Single-aggregate convenience wrapper over _agg_requests."""
    b = _SegBatch(inv, capacity)
    assemble = _agg_requests(xp, agg, cols, n, mask, b, offs=offs)
    b.run()
    return assemble(b.get)


def _validate_device_exprs(filter_expr, group_exprs, aggs) -> None:
    """Device kernels see dict-encoded int64 codes for varlen columns, so a
    string column may appear ONLY as a bare group-key ColumnRef (codes group
    identically to values within a chunk; exact values are recovered from
    representative rows). Any computation over strings must be pre-applied
    on the host by the planner."""
    from tidb_tpu.expression import ColumnRef
    if filter_expr is not None and not filter_expr.is_device_safe():
        raise DeviceRejectError("filter expression is not device-safe; planner "
                         "must split string predicates to the host path")
    for g in group_exprs:
        if not g.is_device_safe() and not isinstance(g, ColumnRef):
            raise DeviceRejectError(f"group expr {g!r} computes over a varlen "
                             "column; pre-project it on the host")
    for a in aggs:
        if a.fn == AggFunc.GROUP_CONCAT:
            raise DeviceRejectError("GROUP_CONCAT aggregates on the host")
        if a.arg is not None and not a.arg.is_device_safe():
            # FIRST_ROW only needs a row index on device, so a bare string
            # ColumnRef is fine (value gathered host-side); computed string
            # exprs would still trace eval_xp and explode mid-jit
            if not (a.fn == AggFunc.FIRST_ROW and
                    isinstance(a.arg, ColumnRef)):
                raise DeviceRejectError(f"agg arg {a.arg!r} is not device-safe")


@dataclass
class GroupResult:
    """Partial aggregation result of one chunk."""

    keys: list[tuple]            # group key tuples (host python values)
    partials: list[np.ndarray]   # per agg: [lanes][num_groups] arrays
    counts: np.ndarray           # rows per group


def finalize_group_result(chunk: Chunk, group_exprs, aggs, gidx: np.ndarray,
                          rep_rows: np.ndarray, lanes_per_agg,
                          counts: np.ndarray) -> GroupResult:
    """Shared host tail of the device kernels: recover exact group-key
    values from representative rows (strings included — host path),
    materialize FIRST_ROW values, and package a GroupResult.

    lanes_per_agg: per agg, the [num_live_groups]-length lane arrays
    (already gathered at gidx)."""
    sub = chunk.take(rep_rows)
    key_cols = []
    for g in group_exprs:
        d, v = g.eval(sub)
        key_cols.append([None if not v[i] else
                         (d[i].item() if hasattr(d[i], "item") else d[i])
                         for i in range(len(gidx))])
    keys = list(zip(*key_cols)) if key_cols else [()] * len(gidx)
    partials = []
    for a, ls in zip(aggs, lanes_per_agg):
        if a.fn == AggFunc.FIRST_ROW:
            # gather only the first-row rows, then evaluate the arg on
            # that tiny sub-chunk (host path handles strings)
            idx = ls[0]
            hasv = ls[1] > 0
            safe_idx = np.where(hasv, idx, 0).astype(np.int64)
            d, _v = a.arg.eval(chunk.take(safe_idx))
            vals = np.where(hasv, d, 0) if d.dtype != object else d
            ls = [vals, hasv.astype(np.int64)]
        partials.append(ls)
    return GroupResult(keys=keys, partials=partials, counts=counts)


# lint: exempt[dtype-discipline] int64 slot init: group slots hold exact key codes and decimal sums
def group_partial(xp, group_exprs, aggs, cols, n, mask, capacity,
                  force_hash: bool = False, direct_limit=None):
    """The traced group+partial-agg phase shared by HashAggKernel and
    the fused pipeline-fragment kernel (ops/fragment.py): group table
    (direct-indexed / runtime-selected / packed-sort per the group-key
    shape), one batched scatter pass per (merge-op, dtype), dual-hash
    collision check. `cols` entries may be None for columns no
    group/agg expression reads (the fragment kernel gathers only used
    lanes). -> (uniq, nuniq, collided, counts, rep, lanes)."""
    if not force_hash and _direct_group_mode(group_exprs):
        uniq, inv, nuniq = _direct_group_table(
            xp, group_exprs, cols, n, mask, capacity)
        h2 = xp.zeros(n, dtype=jnp.int64)
    elif not force_hash and _cond_direct_mode(group_exprs):
        key_cols = [g.eval_xp(xp, cols, n) for g in group_exprs]
        h = _hash_keys(xp, key_cols, n, seed=0x517CC1B727220A95)
        h2 = _hash_keys(xp, key_cols, n, seed=0x2545F4914F6CDD1D)
        uniq, inv, nuniq = _cond_group_table(
            xp, group_exprs, cols, n, mask, h, capacity,
            direct_limit=direct_limit)
    else:
        key_cols = [g.eval_xp(xp, cols, n) for g in group_exprs]
        h = _hash_keys(xp, key_cols, n, seed=0x517CC1B727220A95)
        h2 = _hash_keys(xp, key_cols, n, seed=0x2545F4914F6CDD1D)
        # one packed sort -> group table + inverse + true distinct
        # count (incl. masked sentinel) for overflow detection
        uniq, inv, nuniq = _group_table(xp, h, n, capacity, mask=mask)
    # one batched scatter pass per (merge-op, dtype) for the header
    # lanes + every aggregate (see _SegBatch)
    mask_i = mask.astype(jnp.int64)
    b = _SegBatch(inv, capacity)
    i_cmin = b.add(xp.where(mask, h2, _I64_MAX), "min")
    i_cmax = b.add(xp.where(mask, h2, _I64_MIN), "max")
    i_live = b.add(mask_i, "max")
    i_cnt = b.add(mask_i, "sum")
    i_rep = b.add(xp.where(mask, xp.arange(n), n), "min")
    assembles = [_agg_requests(xp, a, cols, n, mask, b) for a in aggs]
    b.run()
    # collision check: within each group, the check hash must agree
    collided = jnp.any((b.get(i_live) > 0) &
                       (b.get(i_cmin) != b.get(i_cmax)))
    counts = b.get(i_cnt)
    rep = b.get(i_rep)
    lanes = [[l for l, _op in assemble(b.get)] for assemble in assembles]
    return uniq, nuniq, collided, counts, rep, lanes


class HashAggKernel:
    """Compiled filter+group+partial-agg over one chunk schema.

    group_exprs must be device-safe (strings dict-encoded upstream by
    runtime.device_put_chunk; their ColumnRefs then see int64 codes).
    """

    def __init__(self, filter_expr: Expression | None,
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096,
                 force_hash: bool = False, direct_limit: int | None = None):
        """`force_hash` degrades the direct-indexed (code-indexed) group
        table to the packed-sort hash path — set by kernel_for when a
        capacity escalation crosses `tidb_tpu_direct_agg_slots`, so the
        fixed-size direct table never balloons past its bound.
        `direct_limit` caps the runtime-selected direct branch the same
        way (both are construction-time values; kernel_for keys its
        cache on them)."""
        self.filter_expr = filter_expr
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        self.capacity = capacity
        self.force_hash = force_hash
        self.direct_limit = direct_limit
        _validate_device_exprs(filter_expr, self.group_exprs, self.aggs)
        self._jit = jax.jit(self._kernel)
        self._jitd = None   # donating variant, built on first dispatch

    def _kernel(self, cols, nrows):
        n = cols[0][0].shape[0]
        xp = jnp
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, n)
        mask = mask & (xp.arange(n) < nrows)   # padding rows are dead
        return group_partial(xp, self.group_exprs, self.aggs, cols, n,
                             mask, self.capacity,
                             force_hash=self.force_hash,
                             direct_limit=self.direct_limit)

    def scratch_nbytes(self, chunk: Chunk) -> int:
        """Device bytes a dispatch stages BEYOND the input columns: the
        group-table and lane scratch at the kernel's static capacity —
        the share a fused dispatch over an HBM-cache-resident block
        still pays (the input bytes stay on the cache's own ledger)."""
        return self.capacity * 8 * (5 + 2 * len(self.aggs))

    def dispatch_nbytes(self, chunk: Chunk) -> int:
        """HBM bytes one dispatch stages, sized purely from shapes at
        dispatch time: the padded input columns (varlen ships as int64
        dict codes, every lane carries bool validity) plus the
        group-table and lane scratch at the kernel's static capacity.
        Executors charge this to the plan node's device ledger before
        dispatch and credit it back at finalize."""
        from tidb_tpu import memtrack
        n = runtime.bucket_size(max(chunk.num_rows, 1))
        return memtrack.device_put_bytes(chunk, n) + \
            self.scratch_nbytes(chunk)

    def dispatch(self, chunk: Chunk, donate: bool = False, dev_cols=None):
        """Pad + transfer + enqueue the program WITHOUT forcing a sync
        (jax dispatch is async): the pipeline's overlap point. With
        donate=True (and a backend that honors it) the padded input
        buffers are donated to the program, so a transient superchunk's
        HBM is reused for the group tables instead of living alongside
        them; donated transfers skip the chunk memo (a memoized donated
        buffer would be read after free). With dev_cols (device-resident
        padded columns, e.g. an HBM cache block — store/device_cache.py)
        the upload is skipped entirely and the fused program runs
        straight from HBM; cached blocks are shared, so donation never
        applies to them. -> opaque pending token."""
        if dev_cols is not None:
            return self._jit(dev_cols, chunk.num_rows)
        donate = donate and runtime.donation_supported()
        cols, _dicts = runtime.device_put_chunk(chunk, memo=not donate)
        if donate:
            if self._jitd is None:
                self._jitd = jax.jit(self._kernel, donate_argnums=(0,))
            return self._jitd(cols, chunk.num_rows)
        return self._jit(cols, chunk.num_rows)

    def finalize(self, chunk: Chunk, pending) -> GroupResult:
        """Blocking half: one batched device->host transfer for the whole
        result pytree (per-array reads each pay full round-trip latency —
        the device may sit behind a network tunnel), then the host tail."""
        uniq, nuniq, collided, counts, rep, lanes = jax.device_get(pending)
        # capacity before collision: overflow groups clamp into the last
        # slot, which then trips the collision check spuriously
        if int(nuniq) > self.capacity:
            err = CapacityError(f"distinct groups {int(nuniq)} > capacity "
                                f"{self.capacity}")
            err.needed = int(nuniq)   # executors re-plan with 2x this
            raise err
        if bool(collided):
            raise CollisionError("group key hash collision")
        live = (counts > 0) & (uniq != _SENTINEL_MASKED) & (uniq != _FILL)
        gidx = np.flatnonzero(live)
        lanes_at = [[l[gidx] for l in ls] for ls in lanes]
        return finalize_group_result(chunk, self.group_exprs, self.aggs,
                                     gidx, rep[gidx], lanes_at, counts[gidx])

    def __call__(self, chunk: Chunk, dev_cols=None) -> GroupResult:
        return self.finalize(chunk, self.dispatch(chunk,
                                                  dev_cols=dev_cols))


class ScalarAggKernel:
    """No-group aggregation: one partial state row per chunk."""

    def __init__(self, filter_expr: Expression | None,
                 aggs: Sequence[AggDesc]):
        self.filter_expr = filter_expr
        self.aggs = list(aggs)
        _validate_device_exprs(filter_expr, [], self.aggs)
        self._jit = jax.jit(self._kernel)
        self._jitd = None

    # lint: exempt[dtype-discipline] int64 COUNT lane: exact even past 2^53 rows, matches the agg-state stacking dtype
    def _kernel(self, cols, nrows):
        n = cols[0][0].shape[0]
        xp = jnp
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, n)
        mask = mask & (xp.arange(n) < nrows)   # padding rows are dead
        inv = xp.zeros(n, dtype=jnp.int32)
        count = jax.ops.segment_sum(mask.astype(jnp.int64), inv,
                                    num_segments=1)
        lanes = [[l for l, _op in _agg_lanes(xp, a, cols, n, mask, inv, 1)]
                 for a in self.aggs]
        return count, lanes

    def scratch_nbytes(self, chunk: Chunk) -> int:
        """See HashAggKernel.scratch_nbytes (one state row, no table)."""
        return 16 * len(self.aggs)

    def dispatch_nbytes(self, chunk: Chunk) -> int:
        """See HashAggKernel.dispatch_nbytes (one state row, no table)."""
        from tidb_tpu import memtrack
        n = runtime.bucket_size(max(chunk.num_rows, 1))
        return memtrack.device_put_bytes(chunk, n) + \
            self.scratch_nbytes(chunk)

    def dispatch(self, chunk: Chunk, donate: bool = False, dev_cols=None):
        """Async half; see HashAggKernel.dispatch."""
        if dev_cols is not None:
            return self._jit(dev_cols, chunk.num_rows)
        donate = donate and runtime.donation_supported()
        cols, _ = runtime.device_put_chunk(chunk, memo=not donate)
        if donate:
            if self._jitd is None:
                self._jitd = jax.jit(self._kernel, donate_argnums=(0,))
            return self._jitd(cols, chunk.num_rows)
        return self._jit(cols, chunk.num_rows)

    def finalize(self, chunk: Chunk, pending) -> GroupResult:
        count, lanes = jax.device_get(pending)
        partials = []
        for a, ls in zip(self.aggs, lanes):
            if a.fn == AggFunc.FIRST_ROW:
                idx = ls[0]
                hasv = ls[1] > 0
                if hasv[0] and chunk.num_rows > 0:
                    d, _v = a.arg.eval(chunk.take(np.array([int(idx[0])])))
                    val = d[0]
                else:
                    val = 0
                ls = [np.array([val]), hasv.astype(np.int64)]
            partials.append(ls)
        return GroupResult(keys=[()], partials=partials, counts=count)

    def __call__(self, chunk: Chunk, dev_cols=None) -> GroupResult:
        return self.finalize(chunk, self.dispatch(chunk,
                                                  dev_cols=dev_cols))


# -- process-wide kernel cache (executable reuse across plan objects) --------

# keyed on (plan fingerprint, capacity): a plan-cache miss, a new session,
# or a re-parsed statement re-creates plan OBJECTS, but the device program
# is identical — re-tracing and re-compiling it per plan instance is pure
# waste (and through a chip tunnel, seconds of it). jit's own executable
# cache inside each kernel then handles the bucket-shape axis: one traced
# kernel serves every padded superchunk size. Sized for encoded filters
# too (ops/encoded.py): a translated constant is a dictionary-specific
# CODE baked into the fingerprint, so a query over R regions can occupy
# R keys for one plan shape — the capacity keeps that from thrashing
# genuinely-hot kernels, and the dictionaries themselves are stable
# (memoized per cached column), so warm serving converges on a fixed
# key set whose compiles the persistent XLA cache absorbs.
_KERNELS = runtime.FingerprintCache(256)


def kernel_for(filter_expr, group_exprs, aggs, capacity: int = 4096):
    """HashAggKernel/ScalarAggKernel with process-wide reuse keyed on the
    structural plan fingerprint + capacity. Falls back to a fresh
    (uncached) kernel when the plan cannot be fingerprinted. Raises
    ValueError exactly like the constructors when the exprs are not
    device-safe.

    Degrade-to-hash boundary (tidb_tpu_direct_agg_slots): a direct-mode
    group-by whose capacity escalation crosses the bound is rebuilt on
    the packed-sort hash path — the direct-indexed partial table stays
    a FIXED-SIZE array (arxiv 2603.26698) instead of doubling with the
    group domain; wide-span int keys clamp the runtime-selected direct
    branch the same way."""
    from tidb_tpu import config
    direct_limit = config.direct_agg_slots()
    force_hash = bool(group_exprs) and capacity > direct_limit and \
        _direct_group_mode(group_exprs)

    from tidb_tpu import profiler
    family = "hashagg" if group_exprs else "scalaragg"
    made = []

    def make():
        made.append(1)
        if group_exprs:
            return HashAggKernel(filter_expr, group_exprs, aggs,
                                 capacity=capacity,
                                 force_hash=force_hash,
                                 direct_limit=direct_limit)
        return ScalarAggKernel(filter_expr, aggs)

    fp = runtime.plan_fingerprint(filter_expr, group_exprs, aggs)
    if fp is None:
        k = make()
        prof = profiler.profile(family, None)
        profiler.note_construct(prof, reuse=False)
        k._profile = prof
        return k
    from tidb_tpu import devplane
    key = (fp, capacity if group_exprs else 0, force_hash,
           direct_limit if group_exprs else 0,
           # plane identity: a 1-chip and an 8-chip mesh executable for
           # the same plan shape must never alias one cache slot
           devplane.mesh_fingerprint(process=True))
    k = _KERNELS.get_or_create(key, make)
    # profile rows key on the same (family, fingerprint, mesh) identity
    # as the cache slot; an LRU miss (`made` fired) is one compile unit
    prof = profiler.profile(family, f"{fp}|{key[1]}|{key[2]}|{key[3]}")
    profiler.note_construct(prof, reuse=not made)
    k._profile = prof
    return k


class HashAggregator:
    """Stateful final aggregator: merges chunk partials on the host and
    finalizes per-group values. Mirrors Aggregation.GetPartialResult
    merging (expression/aggregation/aggregation.go:32-47)."""

    def __init__(self, aggs: Sequence[AggDesc], group_meta=None):
        """group_meta: the group-key expressions OR FieldTypes, in key
        order (anything with an .ft, or an ft itself)."""
        self.aggs = list(aggs)
        self._state: dict[tuple, list] = {}
        self._orig: dict[tuple, tuple] = {}
        # _ci group keys must merge across CHUNK partials too (per-chunk
        # grouping already folds): fold the dict identity, surface the
        # first-seen variant
        self._ci = [getattr(g, "ft", g).is_ci for g in group_meta] \
            if group_meta else None

    def _group_key(self, key: tuple) -> tuple:
        if not self._ci or not any(self._ci):
            return key
        from tidb_tpu.sqltypes import collation_key
        return tuple(collation_key(x) if c and x is not None else x
                     for x, c in zip(key, self._ci))

    def approx_bytes(self) -> int:
        """Rough host footprint of the merged state — dict slots, key
        tuples and per-agg lane scalars at CPython object costs. This is
        the number memtrack bounds under tidb_tpu_mem_quota_query: it
        scales with the live GROUP COUNT (the quantity that actually
        grows without bound on a runaway aggregation), not the input."""
        n = len(self._state)
        if n == 0:
            return 0
        st = next(iter(self._state.values()))
        lanes = sum(len(ls) for ls in st)
        key = next(iter(self._orig.values()))
        return n * (96 + 56 * len(key) + 48 * lanes)

    def update(self, res: GroupResult) -> None:
        for gi, key in enumerate(res.keys):
            gkey = self._group_key(key)
            st = self._state.get(gkey)
            if st is None:
                self._state[gkey] = [
                    [lane[gi] for lane in res.partials[ai]]
                    for ai in range(len(self.aggs))]
                self._orig[gkey] = key
                continue
            for ai, agg in enumerate(self.aggs):
                lanes = res.partials[ai]
                cur = st[ai]
                fn = agg.fn
                if fn == AggFunc.COUNT:
                    cur[0] += lanes[0][gi]
                elif fn in (AggFunc.SUM, AggFunc.AVG):
                    cur[0] += lanes[0][gi]
                    cur[1] = max(cur[1], lanes[1][gi]) if fn == AggFunc.SUM \
                        else cur[1] + lanes[1][gi]
                elif fn == AggFunc.MIN:
                    if lanes[1][gi] > 0:
                        cur[0] = min(cur[0], lanes[0][gi]) if cur[1] > 0 \
                            else lanes[0][gi]
                        cur[1] = 1
                elif fn == AggFunc.MAX:
                    if lanes[1][gi] > 0:
                        cur[0] = max(cur[0], lanes[0][gi]) if cur[1] > 0 \
                            else lanes[0][gi]
                        cur[1] = 1
                elif fn == AggFunc.FIRST_ROW:
                    if cur[1] == 0 and lanes[1][gi] > 0:
                        cur[0], cur[1] = lanes[0][gi], 1
                elif fn == AggFunc.GROUP_CONCAT:
                    if lanes[1][gi] > 0:
                        if cur[1] > 0:
                            cur[0] = cur[0] + agg.sep + lanes[0][gi]
                        else:
                            cur[0], cur[1] = lanes[0][gi], 1

    def results(self) -> list[tuple[tuple, list]]:
        """-> [(key, [final agg values])] with AVG finalized; SUM/AVG of
        decimals stay scaled ints (callers format via the agg result_ft)."""
        out = []
        for key, st in sorted(self._state.items(),
                              key=lambda kv: tuple(
                                  (x is None, x) for x in kv[0])):
            key = self._orig.get(key, key)
            vals = []
            for agg, cur in zip(self.aggs, st):
                fn = agg.fn
                if fn == AggFunc.COUNT:
                    vals.append(int(cur[0]))
                elif fn == AggFunc.SUM:
                    vals.append(None if cur[1] == 0 else cur[0])
                elif fn == AggFunc.AVG:
                    if cur[1] == 0:
                        vals.append(None)
                    elif agg.result_ft.eval_type == EvalType.DECIMAL:
                        # scaled-int avg: rescale sum by extra frac then
                        # divide in EXACT integer arithmetic (half-up;
                        # float division corrupts wide decimals)
                        extra = agg.result_ft.frac - agg.arg.ft.frac
                        num = int(cur[0]) * (10 ** extra)
                        den = int(cur[1])
                        q, r = divmod(abs(num), den)
                        if 2 * r >= den:
                            q += 1
                        vals.append(q if num >= 0 else -q)
                    else:
                        vals.append(float(cur[0]) / float(cur[1]))
                elif fn in (AggFunc.MIN, AggFunc.MAX, AggFunc.FIRST_ROW,
                            AggFunc.GROUP_CONCAT):
                    vals.append(None if cur[1] == 0 else cur[0])
                else:
                    raise NotImplementedError(fn)
            out.append((key, vals))
        return out
