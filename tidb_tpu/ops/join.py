"""Equi-join pair matching on device.

Replaces the matching loop of /root/reference/executor/join.go:37
(HashJoinExec: mvmap build + per-row probe goroutines). A dynamic hash
table fights XLA's static shapes, so the device program is sort-based
(SURVEY.md §7 "Device hash tables", Plan A):

    1. hash both sides' key tuples to int64 (NULL keys -> per-side
       sentinels so they never match anything, SQL semantics)
    2. sort the build hashes once; searchsorted gives every probe row its
       contiguous candidate run [left,right)
    3. a prefix sum over run lengths + one searchsorted turns the dynamic
       fan-out into a static-capacity (li, ri) pair list with an overflow
       flag (caller doubles capacity and retries)
    4. candidate pairs are verified by EXACT key equality on device, so
       hash collisions only cost a discarded candidate — never a wrong row

Keys are evaluated to fixed-width arrays on the host first (strings get a
dictionary shared across both sides), so the kernel only ever sees int64 /
float64 lanes; payload gather happens on the host from the returned pair
indices.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import _FILL, _SENTINEL_MASKED, _hash_keys

__all__ = ["JoinKernel", "JoinOverflowError", "JoinKeyEncoder",
           "match_pairs"]

# build-side dead rows hash to _SENTINEL_MASKED, probe-side to _FILL:
# distinct values, and _hash_keys never produces either for live rows
_DEAD_BUILD = _SENTINEL_MASKED
_DEAD_PROBE = _FILL


class JoinOverflowError(Exception):
    """More output pairs than the kernel's static capacity."""

    def __init__(self, needed: int):
        super().__init__(f"join output needs {needed} pairs")
        self.needed = needed


class JoinKeyEncoder:
    """Aligns varlen key columns across both sides of a join.

    Fitted once on the (materialized) build side; probe chunks stream
    through transform(). String values get int64 codes from one shared
    dictionary; probe values absent from it get unique negative codes so
    they match nothing yet remain live rows (outer-join semantics).

    Encoded fast path (ops/encoded.py, `tidb_tpu_encoded_exec`): when a
    side arrives PRE-ENCODED — the memoized dict_encode of a bare varlen
    ColumnRef — the per-row Python dict loop disappears. A probe side
    sharing the build's dictionary OBJECT passes its codes straight
    through; a mismatched dictionary re-keys with one vectorized gather
    through a code-translation array (O(|dict|) to build, O(rows) to
    apply)."""

    def __init__(self, num_keys: int):
        self._dicts: list[dict | None] = [None] * num_keys
        self._bvalues: list[list | None] = [None] * num_keys
        self._ci = [False] * num_keys

    # lint: exempt[memtrack-alloc] build-side key lanes: covered by the tracked build (prepare_build device billing)
    def fit_build(self, cols, encoded=None, ci=None):
        out = []
        for j, (d, v) in enumerate(cols):
            enc = encoded[j] if encoded is not None else None
            if enc is not None:
                # pre-encoded lane: the column's memoized dictionary IS
                # the join dictionary (value map built lazily only if a
                # raw probe side ever needs it)
                codes, values = enc
                self._bvalues[j] = values
                if ci is not None:
                    self._ci[j] = bool(ci[j])
                out.append((codes, v))
                continue
            if d.dtype != object:
                out.append((d, v))
                continue
            mapping: dict = {}
            codes = np.empty(len(d), dtype=np.int64)
            for i, val in enumerate(d):
                codes[i] = mapping.setdefault(val, len(mapping)) if v[i] \
                    else -1
            self._dicts[j] = mapping
            out.append((codes, v))
        return out

    def _mapping(self, j: int) -> dict | None:
        """The build-side value->code map, built lazily from an encoded
        build dictionary when a raw probe side needs per-value lookup."""
        mapping = self._dicts[j]
        if mapping is None and self._bvalues[j] is not None:
            from tidb_tpu.ops import encoded as op_encoded
            mapping = op_encoded._dict_map(self._bvalues[j], self._ci[j])
            self._dicts[j] = mapping
        return mapping

    # lint: exempt[memtrack-alloc] probe key lanes bounded by the probe chunk already billed upstream
    def transform_probe(self, cols, encoded=None):
        out = []
        for j, (d, v) in enumerate(cols):
            enc = encoded[j] if encoded is not None else None
            bvals = self._bvalues[j]
            if enc is not None and bvals is not None:
                codes, values = enc
                if values is bvals:
                    # shared dictionary: codes are directly comparable
                    out.append((codes, v))
                else:
                    from tidb_tpu.ops import encoded as op_encoded
                    # the cached build map amortizes across probe
                    # batches; only the O(|probe dict|) walk is per batch
                    t = op_encoded.code_translation(
                        values, bvals, self._ci[j],
                        dst_map=self._mapping(j))
                    out.append((t[codes], v))
                continue
            mapping = self._mapping(j)
            if mapping is None:
                if d.dtype == object:
                    # build side had no string values at all: nothing can
                    # match, but rows stay live for outer joins
                    codes = np.arange(-2, -2 - len(d), -1, dtype=np.int64)
                    out.append((codes, v))
                else:
                    out.append((d, v))
                continue
            codes = np.empty(len(d), dtype=np.int64)
            for i, val in enumerate(d):
                codes[i] = mapping.get(val, -2 - i) if v[i] else -1
            out.append((codes, v))
        return out


def match_pairs(xp, hb, hp, bd_lanes, pd_lanes, out_cap):
    """Sort-join matcher steps 2-4 (module docstring): build hashes `hb`
    (dead rows = _DEAD_BUILD) vs probe hashes `hp` (dead = _DEAD_PROBE),
    expanded into a static-capacity pair list with exact-key verification
    over the raw data lanes. Shared by the single-chip kernel and the
    per-partition stage of the mesh shuffle join
    (ops/meshshuffle.py). -> (li, ri, ok, total)."""
    b_n = hb.shape[0]
    p_n = hp.shape[0]
    perm = xp.argsort(hb)
    sb = hb[perm]
    left = xp.searchsorted(sb, hp, side="left")
    right = xp.searchsorted(sb, hp, side="right")
    counts = xp.where(hp != _DEAD_PROBE, right - left, 0)
    cum = xp.cumsum(counts)
    total = cum[p_n - 1] if p_n else 0

    k = xp.arange(out_cap)
    li = xp.searchsorted(cum, k, side="right")
    li_c = xp.clip(li, 0, p_n - 1)
    start = cum[li_c] - counts[li_c]
    pos = left[li_c] + (k - start)
    ri = perm[xp.clip(pos, 0, b_n - 1)]
    ok = k < xp.minimum(total, out_cap)
    # exact key verification: candidates from colliding hashes are
    # discarded here, making the join exact
    for bd, pd in zip(bd_lanes, pd_lanes):
        ok = ok & (bd[ri] == pd[li_c])
    return li_c, ri, ok, total


def host_match_pairs(build_keys, probe_keys, nb: int, np_: int):
    """Vectorized numpy pair matcher — the same sort-join algorithm as the
    device kernel, with dynamic shapes (free on the host). This is the
    measured-baseline equivalent of the reference's compiled Go hash join
    (executor/join.go:37): columnar and vectorized, no accelerator.
    -> (li, ri) numpy index arrays of matching (probe, build) pairs."""
    if nb == 0 or np_ == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    b_valid = np.ones(nb, dtype=bool)
    for _d, v in build_keys:
        b_valid &= v[:nb]
    p_valid = np.ones(np_, dtype=bool)
    for _d, v in probe_keys:
        p_valid &= v[:np_]
    hb = _hash_keys(np, [(d[:nb], v[:nb] & b_valid)
                         for d, v in build_keys], nb,
                    seed=0x9E3779B97F4A7C15)
    hp = _hash_keys(np, [(d[:np_], v[:np_] & p_valid)
                         for d, v in probe_keys], np_,
                    seed=0x9E3779B97F4A7C15)
    hb = np.where(b_valid, hb, _DEAD_BUILD)
    hp = np.where(p_valid, hp, _DEAD_PROBE)
    perm = np.argsort(hb, kind="stable")
    sb = hb[perm]
    left = np.searchsorted(sb, hp, side="left")
    right = np.searchsorted(sb, hp, side="right")
    counts = np.where(hp != _DEAD_PROBE, right - left, 0)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    li = np.repeat(np.arange(np_, dtype=np.int64), counts)
    # position within each probe row's candidate run
    run_start = np.cumsum(counts) - counts
    pos = left[li] + (np.arange(total, dtype=np.int64) - run_start[li])
    ri = perm[pos]
    # exact key verification discards hash-collision candidates
    ok = np.ones(total, dtype=bool)
    for (bd, _bv), (pd_, _pv) in zip(build_keys, probe_keys):
        ok &= bd[:nb][ri] == pd_[:np_][li]
    return li[ok], ri[ok]


# Module-level program memo: the traced matcher depends only on out_cap
# (shapes, dtypes and key arity are jit's own cache key). Executors build
# a fresh JoinKernel per query execution — a per-instance cache would
# re-trace and re-compile the identical program on EVERY query (~300ms
# per join). Capacities are power-of-two buckets, so this stays small.
_PROGRAMS: dict[int, object] = {}


def _matcher_program(out_cap: int):
    prog = _PROGRAMS.get(out_cap)
    if prog is not None:
        return prog

    def kernel(bkeys, pkeys, nb, np_):
        xp = jnp
        b_n = bkeys[0][0].shape[0]
        p_n = pkeys[0][0].shape[0]
        b_alive = (xp.arange(b_n) < nb)
        p_alive = (xp.arange(p_n) < np_)
        b_valid = b_alive
        for _d, v in bkeys:
            b_valid = b_valid & v
        p_valid = p_alive
        for _d, v in pkeys:
            p_valid = p_valid & v
        hb = _hash_keys(xp, [(d, v & b_valid) for d, v in bkeys],
                        b_n, seed=0x9E3779B97F4A7C15)
        hp = _hash_keys(xp, [(d, v & p_valid) for d, v in pkeys],
                        p_n, seed=0x9E3779B97F4A7C15)
        hb = xp.where(b_valid, hb, _DEAD_BUILD)
        hp = xp.where(p_valid, hp, _DEAD_PROBE)

        return match_pairs(xp, hb, hp, [d for d, _v in bkeys],
                           [d for d, _v in pkeys], out_cap)

    prog = jax.jit(kernel)
    _PROGRAMS[out_cap] = prog
    return prog


class _PendingJoin:
    """In-flight matcher dispatch: the padded device-resident key lanes
    ride along so an overflow retry re-runs WITHOUT re-padding or
    re-transferring either side."""

    __slots__ = ("bk", "pk", "nb", "np_", "cap", "res")

    def __init__(self, bk, pk, nb, np_, cap, res):
        self.bk, self.pk = bk, pk
        self.nb, self.np_ = nb, np_
        self.cap = cap
        self.res = res


class JoinKernel:
    """Pair matcher for one key-lane signature; compiled programs are
    shared process-wide (see _matcher_program)."""

    def __init__(self, num_keys: int):
        self.num_keys = num_keys

    def build_nbytes(self, nb: int) -> int:
        """HBM bytes prepare_build stages: one padded int64/float64 data
        lane + bool validity per key — the device-resident build side a
        pipelined probe keeps for its whole lifetime."""
        return self.num_keys * 9 * runtime.bucket_size(max(nb, 1))

    def dispatch_nbytes(self, np_: int, out_cap: int | None = None) -> int:
        """HBM bytes one probe dispatch stages, from shapes alone: the
        padded probe key lanes plus the static-capacity pair buffers
        (li/ri int64 + ok bool). Charged to the plan node's device
        ledger at dispatch, credited back at finalize."""
        cap = out_cap or runtime.bucket_size(max(np_ * 2, 1024))
        return self.num_keys * 9 * runtime.bucket_size(max(np_, 1)) \
            + cap * 17

    def prepare_build(self, build_keys, nb: int):
        """Pad + transfer the build-side key lanes once; the returned
        device lanes feed every probe superchunk's dispatch (per-probe
        build re-uploads were pure waste)."""
        bb = runtime.bucket_size(max(nb, 1))
        return [tuple(map(jnp.asarray, runtime.pad_column(d, v, bb)))
                for d, v in build_keys]

    def dispatch(self, build_keys, probe_keys, nb: int, np_: int,
                 out_cap: int | None = None, build_dev=None) -> _PendingJoin:
        """Async half: enqueue the matcher program for one probe batch
        (no sync — the pipeline's overlap point). build_dev, when given,
        is the prepare_build() result reused across batches."""
        bk = build_dev if build_dev is not None \
            else self.prepare_build(build_keys, nb)
        pb = runtime.bucket_size(max(np_, 1))
        cap = out_cap or runtime.bucket_size(max(np_ * 2, 1024))
        pk = [tuple(map(jnp.asarray, runtime.pad_column(d, v, pb)))
              for d, v in probe_keys]
        prog = _matcher_program(cap)
        return _PendingJoin(bk, pk, nb, np_, cap,
                            prog(bk, pk, nb, np_))

    def finalize(self, p: _PendingJoin):
        """Blocking half: read back the pair list, growing the output
        capacity (device lanes reused) until it fits. Capacity growth is
        billed to the ACTIVE statement's memory root (device ledger):
        the regrown li/ri/ok buffers on a many-to-many join are the
        join's largest HBM allocation, and the quota must see them even
        though no plan handle reaches this layer."""
        from tidb_tpu import memtrack
        root = memtrack.current()
        extra = 0
        try:
            while True:
                li, ri, ok, total = p.res
                # scalar first: an overflow retry then discards the
                # cap-sized index buffers without ever transferring them;
                # the success path batches the three arrays into one
                # device_get (per-array reads each pay full round-trip
                # latency through the tunnel)
                total = int(jax.device_get(total))
                if total <= p.cap:
                    break
                new_cap = runtime.bucket_size(total)
                if root is not None:
                    grow = (new_cap - p.cap) * 17    # li+ri int64, ok bool
                    extra += grow    # before consume: it may raise
                    root.consume(device=grow)
                p.cap = new_cap
                p.res = _matcher_program(p.cap)(p.bk, p.pk, p.nb, p.np_)
            li, ri, ok = jax.device_get((li, ri, ok))
        finally:
            if root is not None and extra:
                root.release(device=extra)
        sel = np.flatnonzero(ok)
        return li[sel], ri[sel]

    def __call__(self, build_keys, probe_keys, nb: int, np_: int,
                 out_cap: int | None = None):
        """build_keys/probe_keys: [(np data, np valid)] aligned fixed-width
        lanes (see encode_join_keys). Returns (li, ri) numpy index arrays
        of matching (probe, build) row pairs."""
        return self.finalize(self.dispatch(build_keys, probe_keys, nb, np_,
                                           out_cap=out_cap))
