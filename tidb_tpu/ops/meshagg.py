"""Distributed group-by aggregation on the one device plane.

The reference merges per-region partial aggregates on one Go root
(/root/reference/executor/aggregate.go + distsql fan-in, distsql.go:92).
Here the merge itself is distributed: every chip aggregates its row shard
locally (sort-based groups, exactly like ops/hashagg.py), the per-chip
group tables ride an ``all_gather`` over ICI, and each chip re-reduces
the gathered tables — the aggregation-state analogue of ring attention
(SURVEY.md §5.7). Rows arrive as ``NamedSharding(mesh, P("batch"))``
(devplane.batch_sharding); the merged bucket table is replicated, so the
host reads one copy and downstream operators are free to re-shard it.
On a 1-device mesh the collectives are elided at trace time and the
program lowers to the plain single-chip kernel — one code path, 1..N
chips (the "global table strikes back" replicate-the-merge placement:
arxiv 2505.04153 measures gather+re-reduce beating partitioned group
exchange until group counts far exceed ours).

Collision/overflow semantics match the single-chip kernel: a dual 64-bit
hash detects key collisions, a true-distinct count detects capacity
overflow; both raise so the caller can fall back or re-plan.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tidb_tpu import devplane
from tidb_tpu.chunk import Chunk
from tidb_tpu.devplane import AXIS
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (CapacityError, CollisionError, GroupResult,
                                  _FILL, _SENTINEL_MASKED, _I64_MAX, _I64_MIN,
                                  _SegBatch, _agg_requests,
                                  _cond_direct_mode, _cond_group_table,
                                  _direct_group_mode, _direct_group_table,
                                  _group_table, _hash_keys,
                                  _validate_device_exprs,
                                  finalize_group_result)

__all__ = ["MeshAggKernel", "MeshKernelBase", "group_merge_program"]

_BIG = _I64_MAX


_MERGE = {"sum": jax.ops.segment_sum,
          "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}


def group_merge_program(xp, cols, mask, ln, offs, group_exprs, aggs,
                        C, ndev, row_ids=None):
    """The shared traced body: local sort-based group tables, all_gather
    merge over the ``"batch"`` axis, replicated output. `cols` is any
    virtual column list (probe columns, or probe + gathered join
    payloads — ops/meshjoin.py); expressions index into it. row_ids
    (global original probe row index per row) replaces offs+arange for
    the representative/FIRST_ROW lanes when rows were compacted."""
    direct = _direct_group_mode(group_exprs)
    axes = (AXIS,) if ndev > 1 else None
    if direct:
        # dense dict codes index slots directly: no sort, no hash, no
        # collisions (h2 lanes are zeros so the check trivially passes)
        uniq, inv, local_tot = _direct_group_table(
            xp, group_exprs, cols, ln, mask, C, pmax_axes=axes)
        # lint: exempt[dtype-discipline] h2 lanes ride the int64 hash dtype (splitmix64 bit patterns)
        h2 = xp.zeros(ln, dtype=jnp.int64)
    elif _cond_direct_mode(group_exprs):
        # bare int/dict keys: RUNTIME range check picks direct slots
        # when the span fits capacity, packed-sort hash table otherwise
        key_cols = [g.eval_xp(xp, cols, ln) for g in group_exprs]
        h = _hash_keys(xp, key_cols, ln, seed=0x517CC1B727220A95)
        h2 = _hash_keys(xp, key_cols, ln, seed=0x2545F4914F6CDD1D)
        uniq, inv, local_tot = _cond_group_table(
            xp, group_exprs, cols, ln, mask, h, C, pmax_axes=axes)
    else:
        key_cols = [g.eval_xp(xp, cols, ln) for g in group_exprs]
        h = _hash_keys(xp, key_cols, ln, seed=0x517CC1B727220A95)
        h2 = _hash_keys(xp, key_cols, ln, seed=0x2545F4914F6CDD1D)
        uniq, inv, local_tot = _group_table(xp, h, ln, C, mask=mask)

    # one _SegBatch for the header lanes + every aggregate: all lanes
    # with the same (merge-op, dtype) reduce in one wide scatter pass
    # lint: exempt[dtype-discipline] int64 COUNT lane: exact past 2^53 rows, matches the agg-state stacking dtype
    mask_i = mask.astype(jnp.int64)
    b = _SegBatch(inv, C)
    i_cnt = b.add(mask_i, "sum")
    i_h2min = b.add(xp.where(mask, h2, _I64_MAX), "min")
    i_h2max = b.add(xp.where(mask, h2, _I64_MIN), "max")
    if row_ids is not None:
        i_grep = b.add(xp.where(mask, row_ids, _BIG), "min")
    else:
        i_grep = b.add(xp.where(mask, xp.arange(ln), ln), "min")
    i_ghas = b.add(mask_i, "max")
    assembles = [_agg_requests(xp, a, cols, ln, mask, b, offs=offs,
                               row_ids=row_ids)
                 for a in aggs]
    b.run()

    lanes: list[tuple] = []  # (array[C], merge_op)
    lanes.append((b.get(i_cnt), "sum"))                            # cnt
    lanes.append((b.get(i_h2min), "min"))
    lanes.append((b.get(i_h2max), "max"))
    if row_ids is not None:
        lanes.append((b.get(i_grep), "min"))                       # rep
    else:
        lanes.append((xp.where(b.get(i_ghas) > 0,
                               offs + b.get(i_grep), _BIG), "min"))
    agg_lane_slices = []
    for assemble in assembles:
        ls = assemble(b.get)
        agg_lane_slices.append((len(lanes) - 4, len(ls)))
        lanes.extend(ls)

    # -- cross-chip merge: gather every shard's table, re-reduce -----------
    # (single-device meshes skip the collectives entirely: some
    # single-chip runtimes can't lower pmax/all_gather, and the local
    # table already is the global table)
    if ndev == 1:
        return (uniq, *(l for l, _op in lanes[:4]),
                tuple(tuple(lanes[4 + s + i][0] for i in range(w))
                      for s, w in agg_lane_slices),
                local_tot)
    ax = (AXIS,)
    if direct:
        # every shard shares one slot space: merge is an elementwise
        # reduce over the gathered [ndev, C] tables — no re-unique
        gu = lax.all_gather(uniq, ax)                        # [ndev, C]
        muniq = xp.min(gu, axis=0)     # FILL > real code > SENTINEL;
        # a slot live anywhere must not surface as masked-sentinel
        any_real = xp.max(xp.where(gu == _SENTINEL_MASKED,
                                   _I64_MIN, gu), axis=0)
        muniq = xp.where((muniq == _SENTINEL_MASKED) &
                         (any_real != _I64_MIN) & (any_real != _FILL),
                         any_real, muniq)
        tot = lax.pmax(local_tot, ax)
        merged = []
        _RED = {"sum": xp.sum, "min": xp.min, "max": xp.max}
        for lane, op in lanes:
            g = lax.all_gather(lane, ax)                     # [ndev, C]
            merged.append(_RED[op](g, axis=0))
        cnt, h2min, h2max, rep = merged[:4]
        agg_out = tuple(
            tuple(merged[4 + start + i] for i in range(width))
            for start, width in agg_lane_slices)
        return (muniq, cnt, h2min, h2max, rep, agg_out, tot)
    all_uniq = lax.all_gather(uniq, ax, tiled=True)          # [ndev*C]
    muniq, minv, gtot = _group_table(xp, all_uniq, ndev * C, C)
    # gathered fill/sentinel slots can add up to 2 phantom values to
    # gtot relative to a single table; they are excluded on the host
    # via the live mask, and capacity is checked with slack for them
    tot = xp.maximum(gtot, lax.pmax(local_tot, ax))
    # batched re-reduce: stack same-(op,dtype) lanes, one all_gather +
    # one segment op per kind instead of one per lane
    groups: dict = {}
    for i, (lane, op) in enumerate(lanes):
        groups.setdefault((op, lane.dtype), []).append(i)
    merged: list = [None] * len(lanes)
    for (op, _dt), idxs in groups.items():
        if len(idxs) == 1:
            g = lax.all_gather(lanes[idxs[0]][0], ax, tiled=True)
            merged[idxs[0]] = _MERGE[op](g, minv, num_segments=C)
        else:
            stk = jnp.stack([lanes[i][0] for i in idxs], axis=1)
            g = lax.all_gather(stk, ax, tiled=True)
            r = _MERGE[op](g, minv, num_segments=C)
            for j, i in enumerate(idxs):
                merged[i] = r[:, j]

    # -- replicated outputs: every chip holds the full merged table --------
    cnt, h2min, h2max, rep = merged[:4]
    agg_out = tuple(
        tuple(merged[4 + start + i] for i in range(width))
        for start, width in agg_lane_slices)
    return (muniq, cnt, h2min, h2max, rep, agg_out, tot)


class MeshKernelBase:
    """Shared plane plumbing: capacity sizing, shard_map wrapper, probe
    sharding, and the merged-table finalize (capacity / collision
    checks + live-group extraction)."""

    def _setup_sizes(self, mesh: Mesh, capacity: int):
        self.mesh = mesh
        self.ndev = devplane.ndev(mesh)
        # internal table size = requested capacity + 2 headroom slots for
        # the masked-sentinel and fill phantoms (which count as "distinct"
        # but are never live groups)
        self.capacity = max(capacity, 1)
        self._C = self.capacity + 2
        self._row_spec = devplane.batch_spec()

    def _setup_mesh(self, mesh: Mesh, capacity: int, n_extra_args: int = 0):
        self._setup_sizes(mesh, capacity)
        in_specs = (self._row_spec, P()) + (P(),) * n_extra_args
        shard = devplane.shard_map(
            self._kernel, mesh, in_specs=in_specs,
            out_specs=(P(), P(), P(), P(), P(), P(), P()))
        self._jit = devplane.plane_jit(shard)

    def _shard_probe(self, chunk: Chunk, bucket: bool = False):
        """-> (sharded device cols, padded shard length). The sharded
        transfer is memoized on the chunk (keyed by mesh + padded size):
        cached storage chunks stay resident across re-executions.
        bucket=True pads the shard length to a power-of-two bucket so a
        stream of similar-sized super-batches reuses one compiled shape."""
        n = chunk.num_rows
        ln = -(-max(n, 1) // self.ndev)
        ln += (-ln) % 8
        if bucket:
            ln = runtime.bucket_size(ln)
        # generation (not id(mesh)) keys the memo: a torn-down mesh's id
        # can be recycled by a new Mesh object at the same address
        key = ("shard", devplane.mesh_generation(), ln * self.ndev)
        hit = runtime.dev_cache_get(chunk, key)
        if hit is not None:
            return hit, ln
        cols, _dicts = runtime.device_put_chunk(chunk, size=ln * self.ndev,
                                                to_device=False)
        sh = devplane.batch_sharding(self.mesh)
        cols = jax.device_put(cols, sh)   # one batched sharded transfer
        runtime.dev_cache_put(chunk, key, cols)
        return cols, ln

    def finalize(self, outs):
        """-> (gidx, rep_rows, lanes_at, counts) from the kernel outputs,
        raising on capacity overflow or group-key hash collision. The
        kernel's one output boundary: ONE batched device->host transfer
        for the whole output pytree (per-array reads each pay full
        round-trip latency; see ops/hashagg.py HashAggKernel.__call__)."""
        uniq, cnt, h2min, h2max, rep, agg_out, tot = jax.device_get(outs)
        # tot counts the masked sentinel / fill phantoms; _C holds >= 2
        # headroom slots for them, so tot > _C means possible truncation
        if int(tot) > self._C:
            err = CapacityError(
                f"distinct groups {int(tot)} > capacity {self.capacity}")
            err.needed = int(tot)   # executors re-plan with 2x this
            raise err
        live = (cnt > 0) & (uniq != _SENTINEL_MASKED) & (uniq != _FILL)
        if bool(np.any(live & (h2min != h2max))):
            raise CollisionError("group key hash collision")
        gidx = np.flatnonzero(live)
        rep_rows = rep[gidx]
        lanes_at = [[l[gidx] for l in ls] for ls in agg_out]
        return gidx, rep_rows, lanes_at, cnt[gidx]


class MeshAggKernel(MeshKernelBase):
    """Filter + group-by + aggregation, distributed over the ("batch",)
    device plane.

    One compiled XLA program: per-shard local aggregation, all_gather of
    the group tables across the batch axis, re-reduction to a replicated
    merged table. Rows are sharded as NamedSharding(mesh, P("batch"));
    columns stay separate arrays so int64 keys keep exact bits.
    """

    def __init__(self, mesh: Mesh, filter_expr: Expression | None,
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096):
        self.filter_expr = filter_expr
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        _validate_device_exprs(filter_expr, self.group_exprs, self.aggs)
        self._setup_mesh(mesh, capacity)

    # -- traced program ------------------------------------------------------

    def _kernel(self, cols, nrows):
        ln = cols[0][0].shape[0]
        xp = jnp
        bi = lax.axis_index(AXIS)
        # lint: exempt[dtype-discipline] global row offsets are exact int64 (shard base can exceed int32 on big superchunks)
        offs = bi.astype(jnp.int64) * ln
        alive = (offs + xp.arange(ln)) < nrows
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, ln) & alive
        return group_merge_program(xp, cols, mask, ln, offs,
                                   self.group_exprs, self.aggs, self._C,
                                   self.ndev)

    # -- host driver ---------------------------------------------------------

    def launch(self, chunk: Chunk, bucket: bool = False):
        """Asynchronous half: host→HBM transfer + kernel dispatch. Returns
        an opaque in-flight handle; nothing blocks, so the caller can
        overlap the next batch's transfer with this batch's readback
        (the pipeline_map streaming of executor/mesh.py)."""
        cols, _ln = self._shard_probe(chunk, bucket=bucket)
        return self._jit(cols, jnp.int64(chunk.num_rows))

    def finish(self, outs, chunk: Chunk) -> GroupResult:
        """Blocking half: one batched device→host readback + host tail."""
        gidx, rep_rows, lanes_at, counts = self.finalize(outs)
        return finalize_group_result(chunk, self.group_exprs, self.aggs,
                                     gidx, rep_rows, lanes_at, counts)

    def __call__(self, chunk: Chunk) -> GroupResult:
        return self.finish(self.launch(chunk), chunk)
