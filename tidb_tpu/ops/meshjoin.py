"""Distributed star-join + aggregation pipeline on the one device plane.

The reference executes Q3/Q5-shaped plans as a chain of HashJoinExecs
(executor/join.go:37: build a hash table per join, probe row-at-a-time in
goroutines) feeding a HashAggExec. On the device plane the idiomatic
program is one fused XLA computation per probe shard:

    probe rows sharded over ("batch",)    [the fact table: lineitem]
    build tables replicated on every chip [the dimension tables]
    filter -> lookup chain -> group-by aggregate -> all_gather merge

Each lookup is a bounded open-addressing probe against the dimension
table's packed hash slots plus an exact-bits verify — the join never
materializes: matched rows flow straight into the aggregation, so HBM
traffic is one pass over the probe shard. Build keys must be unique
(dimension tables: customer, orders, nation, ...); the executor layer
falls back to the host hash join otherwise. Replicating the small build
side and sharding the large probe side is the skew-free co-location
placement (JSPIM, arxiv 2508.08503): no probe row ever leaves its chip.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tidb_tpu import devplane
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.devplane import AXIS
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (_hash_keys, _key_bits, _splitmix,
                                  _validate_device_exprs,
                                  finalize_group_result)
from tidb_tpu.ops.meshagg import MeshKernelBase, group_merge_program

__all__ = ["LookupSpec", "MeshLookupAggKernel", "BuildError",
           "host_lookup_agg"]

_KEY_SEED = 0x9E6D55A3C1B70F27


def _lookup_hash(xp, key_cols, n):
    """Join-key hash WITHOUT the NULL-validity lane of _hash_keys: build
    keys are NULL-free by construction and NULL probe rows are masked
    out by `hit & v`, so mixing validity would only double the hash
    cost. Half the splitmix rounds of the group-key hash."""
    import jax.numpy as jnp
    ut = jnp.uint64 if xp is not np else np.uint64
    h = xp.full(n, np.uint64(_KEY_SEED), dtype=ut)
    for d, _v in key_cols:
        h = _splitmix(xp, h ^ _key_bits(xp, d))
    # lint: exempt[dtype-discipline] row hashes are int64 by contract (splitmix64 bit patterns, sentinel headroom)
    return h.astype(jnp.int64 if xp is not np else np.int64)


class BuildError(Exception):
    """Build side unusable for the lookup kernel (dup/NULL keys, strings
    in key columns, hash collision) — caller falls back to the host join."""


@dataclass
class LookupSpec:
    """One dimension-table lookup in the chain.

    key_exprs index the CURRENT virtual schema (probe columns, then the
    payloads of earlier lookups, in order). build_key_offsets/payload
    offsets index build_chunk's columns; payload columns are appended to
    the virtual schema for later key_exprs / group_exprs / aggs."""

    key_exprs: list
    build_chunk: Chunk
    build_key_offsets: list[int]
    payload_offsets: list[int] = field(default_factory=list)


_EMPTY_SLOT = np.int64((1 << 63) - 1)   # _hash_keys never emits it


class _BuildTable:
    """Host-prepared replicated lookup table: an open-addressing hash
    table over the key hashes (load factor <= 0.25, linear probing with
    a KNOWN max displacement so the device probe is a statically
    unrolled gather chain — no sort, no searchsorted), exact key bit
    lanes, payload lanes (strings dict-encoded for the device; original
    values kept for host finalize)."""

    def __init__(self, spec: LookupSpec):
        ch = spec.build_chunk
        keys = [ch.columns[o] for o in spec.build_key_offsets]
        n = ch.num_rows
        valid = np.ones(n, dtype=bool)
        for k in keys:
            valid &= np.asarray(k.valid)
        if not valid.all():
            # NULL join keys never match anything: drop them here
            ch = ch.filter(valid)
            keys = [ch.columns[o] for o in spec.build_key_offsets]
            n = ch.num_rows
        key_lanes = []
        for k in keys:
            if k.data.dtype == np.dtype(object):
                raise BuildError("string build keys need the host join")
            key_lanes.append((np.asarray(k.data),
                              np.ones(n, dtype=bool)))
        h = _lookup_hash(np, key_lanes, n)
        if n > 1:
            hs = np.sort(h)
            if (hs[1:] == hs[:-1]).any():
                # duplicate hash: either duplicate keys (not a dimension
                # table) or a 2^-64 collision — both go to the host join
                raise BuildError("duplicate build keys / hash collision")
        self.chunk = ch                         # NULL-free build rows
        self.n = n
        self._insert(h)
        self.key_bits = [np.asarray(_key_bits(np, d))
                         for d, _v in key_lanes]
        self.pay_data = []
        self.pay_valid = []
        for o in spec.payload_offsets:
            c = ch.columns[o]
            d = np.asarray(c.data)
            if d.dtype == np.dtype(object):
                # lint: exempt[memtrack-alloc] build-side encode scratch bounded by the build rows the executor bills via device_scope at launch
                codes = np.empty(n, dtype=np.int64)
                seen: dict = {}
                for i, v in enumerate(d):
                    codes[i] = seen.setdefault(v, len(seen))
                d = codes
            self.pay_data.append(d)
            self.pay_valid.append(np.asarray(c.valid))
        self._key_lanes = key_lanes
        self._row_by_key = None
        self._dev = None

    def _insert(self, h: np.ndarray) -> None:
        """Vectorized round-based insertion: round d places every pending
        key whose slot (base+d) is free, first writer per slot wins. The
        final round count bounds every key's displacement, so lookups
        probe exactly `probe_depth` slots.

        Slots PACK (quantized hash | row index) into one int64 — one
        gather per probe step instead of two (random gathers dominate the
        probe cost). Probe hits compare the quantized top bits; the
        existing exact key-bits verify makes quantization merges
        harmless (they can only produce false candidates, which the
        verify rejects)."""
        n = len(h)
        M = 1 << max(int(2 * max(n, 1) - 1).bit_length(), 4)
        bits = max(1, int(max(n, 1) - 1).bit_length()) if n > 1 else 1
        B = np.int64(bits)
        hq = (h >> B) << B
        slot_pack = np.full(M, _EMPTY_SLOT, dtype=np.int64)
        # reserve the empty-marker's quantum so no packed value can
        # alias it (quantized _EMPTY_SLOT has the row bits free)
        eq = (_EMPTY_SLOT >> B) << B
        hq = np.where(hq == eq, eq - (np.int64(1) << B), hq)
        if n > 1:
            sq = np.sort(hq)
            if (sq[1:] == sq[:-1]).any():
                # two build keys share a quantized hash: the probe's
                # first-match-wins walk could stop at the wrong slot
                raise BuildError("quantized hash collision")
        base = h & np.int64(M - 1)
        pending = np.arange(n)
        d = 0
        while pending.size:
            if d > 64:
                raise BuildError("pathological hash clustering")
            cand = (base[pending] + d) & (M - 1)
            empty = slot_pack[cand] == _EMPTY_SLOT
            marked = np.where(empty, cand, -1)
            uniq, first = np.unique(marked, return_index=True)
            win = np.zeros(len(pending), dtype=bool)
            win[first[uniq >= 0]] = True
            win &= empty
            wi = pending[win]
            slot_pack[cand[win]] = hq[wi] | wi
            pending = pending[~win]
            d += 1
        self.slot_pack = slot_pack
        self.hash_quantum_bits = bits
        self.table_size = M
        self.probe_depth = max(d, 1)

    @property
    def row_by_key(self) -> dict:
        """Host-side exact map for finalize / reference impl, keyed in the
        chunk-layer value domain (raw int64/float64; decimals scaled) to
        match host expression eval output. Built lazily — the device path
        only touches it for a handful of representative rows, and a large
        dimension table (orders at SF>=1) costs seconds to enumerate."""
        if self._row_by_key is None:
            m = {}
            for i in range(self.n):
                m[tuple(d[i].item() for d, _v in self._key_lanes)] = i
            self._row_by_key = m
        return self._row_by_key

    def device_arrays(self, sharding=None):
        """Build lanes on device (replicated under `sharding`), memoized:
        one batched device_put on first use, zero transfer when a cached
        kernel re-executes against unchanged dimension data. Keyed by the
        mesh GENERATION (id(mesh) could be recycled after a reconfigure)."""
        key = devplane.mesh_generation() if sharding is not None else None
        if self._dev is None or self._dev[0] != key:
            tree = (self.slot_pack, tuple(self.key_bits),
                    tuple(self.pay_data), tuple(self.pay_valid))
            self._dev = (key, jax.device_put(tree, sharding))
        return self._dev[1]


def _probe_build(xp, bt, b, key_cols, ph, mask, ln):
    """Shared traced probe of one build table -> (hit, row)."""
    slot_pack, key_bits, _pay_data, _pay_valid = b
    hit = mask
    for d, v in key_cols:
        hit = hit & v                   # NULL keys match nothing
    if bt.n == 0:
        return hit & False, xp.zeros(ln, dtype=jnp.int32)
    # open-addressing probe, ONE packed gather per step, with a GLOBAL
    # early exit: the while_loop stops as soon as every row found its
    # slot (or proved absence), so the typical batch pays ~2 steps
    # instead of the worst-case displacement. Random gathers are the
    # dominant cost on both backends.
    M1 = np.int64(bt.table_size - 1)
    B = np.int64(bt.hash_quantum_bits)
    Q = np.int64(1) << B
    eq = (_EMPTY_SLOT >> B) << B
    phq = (ph >> B) << B
    phq = xp.where(phq == eq, eq - Q, phq)
    base = ph & M1
    empty = np.int64(int(_EMPTY_SLOT))

    def probe_step(st):
        j, row, found, done = st
        cand = (base + j) & M1
        pk = slot_pack[cand]
        newhit = (~done) & (((pk >> B) << B) == phq)
        row = xp.where(newhit, (pk & (Q - 1)).astype(jnp.int32), row)
        found = found | newhit
        # an empty slot on the probe path proves absence
        done = done | newhit | (pk == empty)
        return j + np.int64(1), row, found, done

    def probe_cond(st):
        j, _row, _found, done = st
        return (j < bt.probe_depth) & ~done.all()

    _j, row, found, _done = lax.while_loop(
        probe_cond, probe_step,
        (jnp.int64(0), xp.zeros(ln, dtype=jnp.int32),
         xp.zeros(ln, dtype=bool), xp.zeros(ln, dtype=bool)))
    hit = hit & found
    # exact verify: quantized-hash equality is not key equality
    for (d, _v), bb in zip(key_cols, key_bits):
        hit = hit & (_key_bits(xp, d) == bb[row])
    return hit, row


def _lookup_step(xp, lk, bt, b, virt, mask, ln):
    """One lookup of the chain: probe + payload appends -> new mask."""
    _slot, _kb, pay_data, pay_valid = b
    key_cols = [e.eval_xp(xp, virt, ln) for e in lk.key_exprs]
    ph = _lookup_hash(xp, key_cols, ln)
    hit, row = _probe_build(xp, bt, b, key_cols, ph, mask, ln)
    safe = xp.where(hit, row, 0)
    appended = [(d[safe], v[safe] & hit)
                for d, v in zip(pay_data, pay_valid)]
    if not appended:
        return hit
    # materialize between lookups: without the barrier XLA's producer-
    # consumer fusion re-evaluates the whole gather chain once per
    # downstream use (measured 3-4x on Q5's lookup chain, CPU backend)
    barred = lax.optimization_barrier(
        (hit, tuple(x for pair in appended for x in pair)))
    flat = barred[1]
    for i in range(0, len(flat), 2):
        virt.append((flat[i], flat[i + 1]))
    return barred[0]


class MeshLookupAggKernel(MeshKernelBase):
    """filter -> unique-key lookup chain -> group-by agg over the device
    plane, in TWO compiled stages with a compaction between them:

      stage 1: filter + FIRST lookup, then prefix-sum compaction of the
               surviving rows (the first lookup is usually the selective
               one — a filtered dimension like orders-by-date kills most
               fact rows, exactly like the reference's first HashJoin).
      stage 2: remaining lookups + group-by agg over the compacted rows,
               padded to a power-of-two bucket so a handful of compiled
               shapes serve any selectivity.

    Static XLA shapes cannot shrink mid-program, so without the split
    every lookup and the aggregation pay full-width work regardless of
    selectivity; the split costs one scalar device->host sync (the
    survivor count) and wins the whole compaction factor on everything
    after the first probe. Original probe row indices ride along as a
    column so representative-row finalize is unchanged."""

    def __init__(self, mesh: Mesh, filter_expr: Expression | None,
                 lookups: Sequence[LookupSpec],
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096,
                 builds: list | None = None):
        self.mesh = mesh
        self.filter_expr = filter_expr
        self.lookups = list(lookups)
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        _validate_device_exprs(filter_expr, self.group_exprs, self.aggs)
        for lk in self.lookups:
            _validate_device_exprs(None, lk.key_exprs, [])
        self.builds = builds if builds is not None \
            else [_BuildTable(lk) for lk in self.lookups]
        self._setup_sizes(mesh, capacity)
        self._stage1_jit = None
        self._stage2_jits: dict = {}
        self._stage3_jits: dict = {}

    # -- traced programs -----------------------------------------------------

    def _compact(self, xp, virt, mask, row_ids, ln):
        """Prefix-sum compaction of the surviving rows ->
        (compacted (data, valid) pairs, live flag, row ids, global max
        survivor count)."""
        s_local = mask.sum()
        pos = xp.cumsum(mask.astype(jnp.int32)) - 1
        idx = xp.where(mask, pos, ln)      # OOB -> dropped by scatter
        compacted = []
        for d, v in virt:
            cd = xp.zeros(ln, dtype=d.dtype).at[idx].set(d, mode="drop")
            cv = xp.zeros(ln, dtype=bool).at[idx].set(v, mode="drop")
            compacted.append((cd, cv))
        live = xp.zeros(ln, dtype=bool).at[idx].set(mask, mode="drop")
        # lint: exempt[dtype-discipline] compacted row ids stay exact int64 (global offsets exceed int32)
        rid = xp.zeros(ln, dtype=jnp.int64).at[idx].set(row_ids,
                                                        mode="drop")
        smax = s_local if self.ndev == 1 else \
            lax.pmax(s_local, (AXIS,))
        return tuple(compacted), live, rid, smax

    def _stage1(self, cols, nrows, build0):
        """filter + first lookup + compaction."""
        ln = cols[0][0].shape[0]
        xp = jnp
        bi = lax.axis_index(AXIS)
        # lint: exempt[dtype-discipline] global row offsets are exact int64 (shard base can exceed int32 on big superchunks)
        offs = bi.astype(jnp.int64) * ln
        alive = (offs + xp.arange(ln)) < nrows
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, ln) & alive
        virt = list(cols)
        mask = _lookup_step(xp, self.lookups[0], self.builds[0], build0,
                            virt, mask, ln)
        row_ids = offs + xp.arange(ln)
        return self._compact(xp, virt, mask, row_ids, ln)

    def _stage2_fn(self, bucket: int):
        """Remaining lookups, then compact AGAIN: the chain's total
        selectivity (a 20% dimension filter deep in a star join) shrinks
        the aggregation's input — the group-table sort is the next cost
        center after the probes."""
        def stage2(ccols, live, rid, builds_rest):
            xp = jnp
            b = bucket
            virt = [(d[:b], v[:b]) for d, v in ccols]
            mask = live[:b]
            rids = rid[:b]
            for lk, bt, bd in zip(self.lookups[1:], self.builds[1:],
                                  builds_rest):
                mask = _lookup_step(xp, lk, bt, bd, virt, mask, b)
            return self._compact(xp, virt, mask, rids, b)
        return stage2

    def _stage3_fn(self, bucket: int):
        def stage3(ccols, live, rid):
            xp = jnp
            b = bucket
            virt = [(d[:b], v[:b]) for d, v in ccols]
            return group_merge_program(
                xp, virt, live[:b], b, jnp.int64(0),
                self.group_exprs, self.aggs, self._C, self.ndev,
                row_ids=rid[:b])
        return stage3

    # -- host driver ---------------------------------------------------------

    def _get_stage1(self):
        if self._stage1_jit is None:
            sm = devplane.shard_map(
                self._stage1, self.mesh,
                in_specs=(self._row_spec, P(), P()),
                out_specs=(self._row_spec, self._row_spec,
                           self._row_spec, P()))
            self._stage1_jit = devplane.plane_jit(sm)
        return self._stage1_jit

    def _get_stage2(self, bucket: int):
        j = self._stage2_jits.get(bucket)
        if j is None:
            sm = devplane.shard_map(
                self._stage2_fn(bucket), self.mesh,
                in_specs=(self._row_spec, self._row_spec,
                          self._row_spec, P()),
                out_specs=(self._row_spec, self._row_spec,
                           self._row_spec, P()))
            j = self._stage2_jits[bucket] = devplane.plane_jit(sm)
        return j

    def _get_stage3(self, bucket: int):
        j = self._stage3_jits.get(bucket)
        if j is None:
            sm = devplane.shard_map(
                self._stage3_fn(bucket), self.mesh,
                in_specs=(self._row_spec, self._row_spec,
                          self._row_spec),
                out_specs=(P(), P(), P(), P(), P(), P(), P()))
            j = self._stage3_jits[bucket] = devplane.plane_jit(sm)
        return j

    @staticmethod
    def _bucket(s: int, ln: int) -> int:
        b = 8
        while b < s:
            b <<= 1
        return min(b, ln)

    def launch(self, probe: Chunk, bucket: bool = False):
        """Dispatches stage 1 (filter + first lookup + compact), reads
        back one survivor-count scalar, dispatches stage 2 (remaining
        lookups + compact), reads one more, then stage 3 (aggregation)
        on the chain-selectivity-sized bucket. Build tables are
        device-memoized by _BuildTable.device_arrays, so per-batch
        launches re-send nothing."""
        cols, ln = self._shard_probe(probe, bucket=bucket)
        rep_sh = devplane.replicated(self.mesh)
        builds = tuple(b.device_arrays(rep_sh) for b in self.builds)
        ccols, live, rid, smax = self._get_stage1()(
            cols, jnp.int64(probe.num_rows), builds[0])
        bkt = self._bucket(int(smax), ln)
        if len(self.lookups) > 1:
            ccols, live, rid, smax2 = self._get_stage2(bkt)(
                ccols, live, rid, builds[1:])
            bkt = self._bucket(int(smax2), bkt)
        return self._get_stage3(bkt)(ccols, live, rid)

    def finish(self, outs, probe: Chunk):
        gidx, rep_rows, lanes_at, counts = self.finalize(outs)
        return self._finalize(probe, gidx, rep_rows, lanes_at, counts)

    def __call__(self, probe: Chunk):
        return self.finish(self.launch(probe), probe)

    def _finalize(self, probe: Chunk, gidx, rep_rows, lanes_at, counts):
        """Re-run the lookup chain on the handful of representative rows
        (and FIRST_ROW rows) host-side so group keys / first values come
        back as exact original values, strings included."""
        needed = set(int(r) for r in rep_rows)
        for a, ls in zip(self.aggs, lanes_at):
            if a.fn == AggFunc.FIRST_ROW:
                for i, has in zip(ls[0], ls[1]):
                    if has > 0:
                        needed.add(int(i))
        order = sorted(needed)
        pos = {g: i for i, g in enumerate(order)}
        mini = self._host_chain(probe.take(np.array(order, dtype=np.int64)))
        rep_local = np.array([pos[int(r)] for r in rep_rows],
                             dtype=np.int64)
        fixed_lanes = []
        for a, ls in zip(self.aggs, lanes_at):
            if a.fn == AggFunc.FIRST_ROW:
                idx = np.array([pos.get(int(i), 0) for i in ls[0]],
                               dtype=np.int64)
                fixed_lanes.append([idx, ls[1]])
            else:
                fixed_lanes.append(ls)
        return finalize_group_result(mini, self.group_exprs, self.aggs,
                                     gidx, rep_local, fixed_lanes, counts)

    def _host_chain(self, mini: Chunk) -> Chunk:
        """Append payload columns for the (matched) mini rows on the host,
        with original (undecoded) build values."""
        out_cols = list(mini.columns)
        for lk, b in zip(self.lookups, self.builds):
            virt = Chunk(out_cols)
            n = virt.num_rows
            keyvals = []
            for e in lk.key_exprs:
                d, v = e.eval(virt)
                keyvals.append([None if not v[i] else
                                (d[i].item() if hasattr(d[i], "item")
                                 else d[i]) for i in range(n)])
            rows = []
            for i in range(n):
                rows.append(b.row_by_key.get(
                    tuple(kv[i] for kv in keyvals)))
            for o in lk.payload_offsets:
                src = b.chunk.columns[o]
                vals = [None if r is None else src.get(r) for r in rows]
                out_cols.append(Column.from_values(src.ft, vals))
        return Chunk(out_cols)


def host_lookup_agg(probe: Chunk, filter_expr, lookups: Sequence[LookupSpec],
                    group_exprs, aggs, builds=None):
    """Pure-host reference implementation (ground truth for tests, the
    dryrun cross-check, and the per-batch fallback of the streaming mesh
    path — which passes its prebuilt `builds` so dimension hash tables
    are not rebuilt per batch)."""
    from tidb_tpu.ops.hostagg import host_hash_agg
    mask = runtime.eval_filter_host(filter_expr, probe)
    ch = probe.filter(mask)
    if builds is None:
        builds = [_BuildTable(lk) for lk in lookups]
    cols = list(ch.columns)
    for lk, b in zip(lookups, builds):
        virt = Chunk(cols)
        n = virt.num_rows
        keyvals = []
        for e in lk.key_exprs:
            d, v = e.eval(virt)
            keyvals.append([None if not v[i] else
                            (d[i].item() if hasattr(d[i], "item") else d[i])
                            for i in range(n)])
        # lint: exempt[memtrack-alloc] host-fallback row gather bounded by the probe chunk the statement already tracks
        rows = np.empty(n, dtype=object)
        keep = np.zeros(n, dtype=bool)
        for i in range(n):
            r = b.row_by_key.get(tuple(kv[i] for kv in keyvals))
            rows[i] = r
            keep[i] = r is not None
        cols = [c.take(np.flatnonzero(keep)) for c in cols]
        matched = [int(r) for r in rows[keep]]
        for o in lk.payload_offsets:
            src = b.chunk.columns[o]
            cols.append(Column.from_values(
                src.ft, [src.get(r) for r in matched]))
    combined = Chunk(cols)
    return host_hash_agg(combined, None, group_exprs, aggs)
