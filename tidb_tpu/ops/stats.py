"""Device kernels for ANALYZE: whole-column sort on the accelerator.

The reference's ANALYZE builds samples row-at-a-time inside each storage
node (mocktikv/analyze.go). Here the histogram build is one XLA sort over
the full column — the MXU doesn't help, but the vector units + HBM
bandwidth make multi-million-row sorts far faster than numpy, and the
sorted array round-trips through the same host buffers the chunk layer
already uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_jit_sort = jax.jit(jnp.sort)   # jit caches one executable per dtype/shape


def device_sort(data: np.ndarray) -> np.ndarray:
    """Sort a numeric column on the default device; returns numpy."""
    return np.asarray(_jit_sort(data))
