"""Device kernels for ANALYZE: whole-column sort on the accelerator.

The reference's ANALYZE builds samples row-at-a-time inside each storage
node (mocktikv/analyze.go). Here the histogram build is one XLA sort over
the full column — the MXU doesn't help, but the vector units + HBM
bandwidth make multi-million-row sorts far faster than numpy, and the
sorted array round-trips through the same host buffers the chunk layer
already uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_sort_cache: dict = {}


def _sort_fn(dtype):
    fn = _sort_cache.get(dtype)
    if fn is None:
        fn = jax.jit(jnp.sort)
        _sort_cache[dtype] = fn
    return fn


def device_sort(data: np.ndarray) -> np.ndarray:
    """Sort a numeric column on the default device; returns numpy."""
    out = _sort_fn(data.dtype)(data)
    return np.asarray(out)
