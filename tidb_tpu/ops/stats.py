"""Device kernels for ANALYZE: whole-column sort on the accelerator.

The reference's ANALYZE builds samples row-at-a-time inside each storage
node (mocktikv/analyze.go). Here the histogram build is one XLA sort over
the full column — the MXU doesn't help, but the vector units + HBM
bandwidth make multi-million-row sorts far faster than numpy, and the
sorted array round-trips through the same host buffers the chunk layer
already uses.

Inputs ride the pow2 superchunk buckets (runtime.bucket_size) before
dispatch: jit caches one executable per dtype/shape, so a raw-length
sort would recompile per distinct column length. Padding values are
chosen to sort AFTER every real element (NaN for inexact dtypes, the
dtype max for integers), so the first n lanes of the sorted bucket are
exactly the sorted input.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.ops import runtime

_jit_sort = jax.jit(jnp.sort)


def device_sort(data: np.ndarray) -> np.ndarray:
    """Sort a numeric column on the default device; returns numpy."""
    n = data.shape[0]
    cap = runtime.bucket_size(n)
    if cap != n:
        if np.issubdtype(data.dtype, np.inexact):
            fill = np.array(np.nan, dtype=data.dtype)
        else:
            fill = np.array(np.iinfo(data.dtype).max, dtype=data.dtype)
        # lint: exempt[memtrack-alloc] pow2 pad of the ANALYZE column the statement already bills; at most 2x the tracked input
        padded = np.empty(cap, dtype=data.dtype)
        padded[:n] = data
        padded[n:] = fill
        data = padded
    return np.asarray(_jit_sort(data))[:n]
