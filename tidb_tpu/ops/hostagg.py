"""Host (numpy/python) aggregation fallback.

Used when a pushed aggregate can't ride the device kernel: DISTINCT aggs,
string MIN/MAX, hash-collision or capacity fallback (ops/hashagg.py), and
tiny chunks where jit dispatch overhead would dominate. Produces the same
GroupResult partial-state protocol, so the final merge path is identical.
"""

from __future__ import annotations

import numpy as np

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops.hashagg import GroupResult
from tidb_tpu.ops.runtime import eval_filter_host

__all__ = ["host_hash_agg", "host_scalar_agg"]


def _eval_cols(exprs, chunk):
    out = []
    for e in exprs:
        d, v = e.eval(chunk)
        out.append((d, v))
    return out


def host_hash_agg(chunk: Chunk, filter_expr: Expression | None,
                  group_exprs: list[Expression],
                  aggs: list[AggDesc]) -> GroupResult:
    mask = eval_filter_host(filter_expr, chunk)
    gcols = _eval_cols(group_exprs, chunk)
    acols = [(None, None) if a.arg is None else a.arg.eval(chunk)
             for a in aggs]

    groups: dict[tuple, int] = {}
    keys: list[tuple] = []
    states: list[list] = []     # per group: per agg: lanes
    counts: list[int] = []

    n = chunk.num_rows
    for i in range(n):
        if not mask[i]:
            continue
        key = tuple(
            None if not v[i] else (d[i].item() if hasattr(d[i], "item")
                                   else d[i])
            for d, v in gcols)
        gi = groups.get(key)
        if gi is None:
            gi = len(keys)
            groups[key] = gi
            keys.append(key)
            counts.append(0)
            states.append([_init_state(a) for a in aggs])
        counts[gi] += 1
        for ai, a in enumerate(aggs):
            _update_state(a, states[gi][ai], acols[ai], i)

    partials = []
    for ai, a in enumerate(aggs):
        lanes = _states_to_lanes(a, [s[ai] for s in states])
        partials.append(lanes)
    return GroupResult(keys=keys, partials=partials,
                       counts=np.array(counts, dtype=np.int64))


def host_scalar_agg(chunk: Chunk, filter_expr: Expression | None,
                    aggs: list[AggDesc]) -> GroupResult:
    mask = eval_filter_host(filter_expr, chunk)
    acols = [(None, None) if a.arg is None else a.arg.eval(chunk)
             for a in aggs]
    states = [_init_state(a) for a in aggs]
    cnt = 0
    for i in range(chunk.num_rows):
        if not mask[i]:
            continue
        cnt += 1
        for ai, a in enumerate(aggs):
            _update_state(a, states[ai], acols[ai], i)
    partials = [_states_to_lanes(a, [states[ai]])
                for ai, a in enumerate(aggs)]
    return GroupResult(keys=[()], partials=partials,
                       counts=np.array([cnt], dtype=np.int64))


def _init_state(a: AggDesc):
    if a.distinct:
        return {"seen": set(), "sum": 0, "cnt": 0, "min": None, "max": None}
    return {"sum": 0, "cnt": 0, "min": None, "max": None, "first": None,
            "has": False}


def _update_state(a: AggDesc, st, col, i):
    fn = a.fn
    if a.arg is None:   # COUNT(*)
        st["cnt"] += 1
        return
    d, v = col
    if not v[i]:
        return
    val = d[i].item() if hasattr(d[i], "item") else d[i]
    if a.distinct:
        if val in st["seen"]:
            return
        st["seen"].add(val)
    st["has"] = True if "has" in st else None
    if fn in (AggFunc.SUM, AggFunc.AVG):
        st["sum"] += val
        st["cnt"] += 1
    elif fn == AggFunc.COUNT:
        st["cnt"] += 1
    elif fn == AggFunc.MIN:
        st["min"] = val if st["min"] is None else min(st["min"], val)
    elif fn == AggFunc.MAX:
        st["max"] = val if st["max"] is None else max(st["max"], val)
    elif fn == AggFunc.FIRST_ROW:
        if st.get("first") is None:
            st["first"] = val
    else:
        raise NotImplementedError(fn)


def _states_to_lanes(a: AggDesc, sts: list[dict]):
    """Convert host states into the kernel's partial-lane layout so
    HashAggregator merges both identically."""
    fn = a.fn
    n = len(sts)
    if fn == AggFunc.COUNT:
        return [np.array([s["cnt"] for s in sts], dtype=np.int64)]
    if fn == AggFunc.SUM:
        dtype = np.float64 if any(isinstance(s["sum"], float) for s in sts) \
            else np.int64
        return [np.array([s["sum"] for s in sts], dtype=dtype),
                np.array([1 if s["cnt"] else 0 for s in sts],
                         dtype=np.int64)]
    if fn == AggFunc.AVG:
        dtype = np.float64 if any(isinstance(s["sum"], float) for s in sts) \
            else np.int64
        return [np.array([s["sum"] for s in sts], dtype=dtype),
                np.array([s["cnt"] for s in sts], dtype=np.int64)]
    if fn in (AggFunc.MIN, AggFunc.MAX):
        key = "min" if fn == AggFunc.MIN else "max"
        has = [0 if sts[i][key] is None else 1 for i in range(n)]
        vals = [sts[i][key] if has[i] else 0 for i in range(n)]
        arr = np.array(vals, dtype=object) \
            if any(isinstance(v, (str, bytes)) for v in vals) else \
            np.asarray(vals)
        return [arr, np.array(has, dtype=np.int64)]
    if fn == AggFunc.FIRST_ROW:
        has = [0 if s.get("first") is None else 1 for s in sts]
        vals = [s.get("first") if has[i] else 0
                for i, s in enumerate(sts)]
        arr = np.array(vals, dtype=object) \
            if any(isinstance(v, (str, bytes)) for v in vals) else \
            np.asarray(vals)
        return [arr, np.array(has, dtype=np.int64)]
    raise NotImplementedError(fn)
