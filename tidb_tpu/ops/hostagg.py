"""Host (numpy/python) aggregation fallback.

Used when a pushed aggregate can't ride the device kernel: DISTINCT aggs,
string MIN/MAX, hash-collision or capacity fallback (ops/hashagg.py), and
tiny chunks where jit dispatch overhead would dominate. Produces the same
GroupResult partial-state protocol, so the final merge path is identical.
"""

from __future__ import annotations

import numpy as np

from tidb_tpu.chunk import Chunk
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops.hashagg import GroupResult
from tidb_tpu.ops.runtime import eval_filter_host

__all__ = ["host_hash_agg", "host_scalar_agg"]


def _eval_cols(exprs, chunk):
    out = []
    for e in exprs:
        d, v = e.eval(chunk)
        out.append((d, v))
    return out


def host_hash_agg(chunk: Chunk, filter_expr: Expression | None,
                  group_exprs: list[Expression],
                  aggs: list[AggDesc]) -> GroupResult:
    mask = eval_filter_host(filter_expr, chunk)
    if not any(a.distinct for a in aggs):
        return _host_agg_vectorized(chunk, mask, group_exprs, aggs)
    return _host_agg_rowloop(chunk, mask, group_exprs, aggs)


def _lex_key(d: np.ndarray, v: np.ndarray):
    """Sortable, NULL-safe lexsort lanes for one group column."""
    if d.dtype == np.dtype(object):
        # strings: convert to a fixed 'U' dtype once (C-speed compares)
        s = np.where(v, d, "")
        return [s.astype("U"), ~v]
    safe = np.where(v, d, d.dtype.type(0))
    return [safe, ~v]


def _host_agg_vectorized(chunk: Chunk, mask, group_exprs, aggs
                         ) -> GroupResult:
    """Sort-based group-by, fully vectorized (np.lexsort + ufunc.reduceat):
    the numpy mirror of the device segment-reduce kernel, and the measured
    CPU baseline of bench.py — kept honest by being a real columnar
    engine, not a per-row interpreter (the reference's chunk executor is
    compiled Go; a Python row loop would flatter the device numbers)."""
    live = np.flatnonzero(mask)
    nlive = len(live)
    gcols = [(d, v) for d, v in _eval_cols(group_exprs, chunk)]
    if nlive == 0:
        return GroupResult(keys=[], partials=[
            _states_to_lanes(a, []) for a in aggs],
            counts=np.zeros(0, dtype=np.int64))
    lanes = []
    for (d, v), e in zip(gcols, group_exprs):
        darr = np.asarray(d)[live]
        if e.ft.is_ci and darr.dtype == np.dtype(object):
            # _ci collation groups by the casefolded key; the surfaced
            # value stays the representative row's original variant
            from tidb_tpu.sqltypes import fold_column
            darr = fold_column(darr)
        lanes.extend(_lex_key(darr, np.asarray(v)[live]))
    if lanes:
        order = np.lexsort(lanes[::-1])   # first col is primary
        sorted_lanes = [l[order] for l in lanes]
        new = np.zeros(nlive, dtype=bool)
        new[0] = True
        for l in sorted_lanes:
            new[1:] |= l[1:] != l[:-1]
    else:
        order = np.arange(nlive)
        new = np.zeros(nlive, dtype=bool)
        new[0] = True
    starts = np.flatnonzero(new)
    gid = np.cumsum(new) - 1
    ngroups = len(starts)
    rows = live[order]                    # original row index per position
    counts = np.add.reduceat(np.ones(nlive, dtype=np.int64), starts)

    # group keys from each segment's first row
    rep = rows[starts]
    keys_cols = []
    for d, v in gcols:
        dv, vv = np.asarray(d)[rep], np.asarray(v)[rep]
        keys_cols.append([None if not vv[i] else
                          (dv[i].item() if hasattr(dv[i], "item") else dv[i])
                          for i in range(ngroups)])
    keys = list(zip(*keys_cols)) if keys_cols else [()] * ngroups

    partials = []
    for a in aggs:
        partials.append(_agg_lanes_vectorized(a, chunk, rows, starts, gid,
                                              ngroups, counts))
    return GroupResult(keys=keys, partials=partials, counts=counts)


# lint: exempt[memtrack-alloc] group-count-scaled agg outputs, bounded by the tracked agg state
def _agg_lanes_vectorized(a: AggDesc, chunk, rows, starts, gid, ngroups,
                          counts):
    """One aggregate's partial lanes over sorted segments (layout matches
    _states_to_lanes / the device kernel's finalized lanes)."""
    fn = a.fn
    if a.arg is None:     # COUNT(*)
        return [counts.copy()]
    d, v = a.arg.eval(chunk)
    d, v = np.asarray(d)[rows], np.asarray(v)[rows]
    has = (np.maximum.reduceat(v.astype(np.int64), starts)
           if len(rows) else np.zeros(ngroups, dtype=np.int64))
    if fn == AggFunc.COUNT:
        return [np.add.reduceat(v.astype(np.int64), starts)]
    if fn in (AggFunc.SUM, AggFunc.AVG):
        if d.dtype == np.dtype(object):
            # decimal/object sums fall back per-group (rare path)
            sums = np.array([sum(_sum_num(x) for x, ok in
                                 zip(d[s:e], v[s:e]) if ok)
                             for s, e in _seg_bounds(starts, len(rows))],
                            dtype=object)
        else:
            zero = d.dtype.type(0)
            sums = np.add.reduceat(np.where(v, d, zero), starts)
        if fn == AggFunc.SUM:
            return [sums, has]
        return [sums, np.add.reduceat(v.astype(np.int64), starts)]
    if fn in (AggFunc.MIN, AggFunc.MAX):
        red = np.minimum if fn == AggFunc.MIN else np.maximum
        if d.dtype == np.dtype(object):
            pick = min if fn == AggFunc.MIN else max  # strings: python
            vals = []
            for s, e in _seg_bounds(starts, len(rows)):
                seg = [x for x, ok in zip(d[s:e], v[s:e]) if ok]
                vals.append(pick(seg) if seg else 0)
            arr = np.array(vals, dtype=object)
        elif d.dtype == np.float64:
            ident = np.inf if fn == AggFunc.MIN else -np.inf
            arr = red.reduceat(np.where(v, d, ident), starts)
            arr = np.where(has > 0, arr, 0.0)
        else:
            ident = np.iinfo(np.int64).max if fn == AggFunc.MIN \
                else np.iinfo(np.int64).min
            arr = red.reduceat(np.where(v, d, ident), starts)
            arr = np.where(has > 0, arr, 0)
        return [arr, has]
    if fn == AggFunc.GROUP_CONCAT:
        vals, hasv = [], []
        for s, e in _seg_bounds(starts, len(rows)):
            parts = [_display_str(x, a.arg.ft)
                     for x, ok in zip(d[s:e], v[s:e]) if ok]
            hasv.append(1 if parts else 0)
            vals.append(a.sep.join(parts) if parts else "")
        return [np.array(vals, dtype=object),
                np.array(hasv, dtype=np.int64)]
    if fn == AggFunc.FIRST_ROW:
        n = len(rows)
        pos = np.where(v, np.arange(n), n)
        first = np.minimum.reduceat(pos, starts) if n else \
            np.zeros(ngroups, dtype=np.int64)
        idx = np.clip(first, 0, max(n - 1, 0))
        vals = d[idx] if n else np.zeros(ngroups, dtype=np.int64)
        if vals.dtype != np.dtype(object):
            vals = np.where(has > 0, vals, 0)
        return [vals, has]
    raise NotImplementedError(fn)


def _display_str(v, ft) -> str:
    """Chunk-layer value -> its SQL display text (GROUP_CONCAT
    concatenates DISPLAY values, not internal encodings: scaled decimal
    ints and epoch-micros datetimes must format like SELECT would)."""
    from tidb_tpu.sqltypes import (EvalType, format_datetime,
                                   scaled_to_decimal)
    et = ft.eval_type
    if et == EvalType.DECIMAL:
        return str(scaled_to_decimal(int(v), max(ft.frac, 0)))
    if et == EvalType.DATETIME:
        return format_datetime(int(v), ft.tp)
    if isinstance(v, float):
        return str(int(v)) if v == int(v) else str(v)
    if isinstance(v, bytes):
        return v.decode("utf8", "replace")
    return str(v)


_NUM_PREFIX = None


def _sum_num(x):
    """SUM coercion for object lanes: exact ints (decimal scaled /
    bignum) pass through; strings take MySQL's leading-numeric-prefix
    cast to double ('1ff' -> 1.0, 'x' -> 0)."""
    if isinstance(x, str):
        global _NUM_PREFIX
        if _NUM_PREFIX is None:
            import re
            _NUM_PREFIX = re.compile(
                r"\s*[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?")
        m = _NUM_PREFIX.match(x)
        return float(m.group(0)) if m else 0.0
    return int(x)


def _seg_bounds(starts, n):
    ends = np.append(starts[1:], n)
    return zip(starts, ends)


def _host_agg_rowloop(chunk: Chunk, mask, group_exprs,
                      aggs: list[AggDesc]) -> GroupResult:
    """Row-at-a-time path for DISTINCT aggregates (set state per group)."""
    gcols = _eval_cols(group_exprs, chunk)
    acols = [(None, None) if a.arg is None else a.arg.eval(chunk)
             for a in aggs]

    groups: dict[tuple, int] = {}
    keys: list[tuple] = []
    states: list[list] = []     # per group: per agg: lanes
    counts: list[int] = []

    from tidb_tpu.sqltypes import collation_key
    ci = [e.ft.is_ci for e in group_exprs]
    n = chunk.num_rows
    for i in range(n):
        if not mask[i]:
            continue
        key = tuple(
            None if not v[i] else (d[i].item() if hasattr(d[i], "item")
                                   else d[i])
            for d, v in gcols)
        # group under the collation key; surface the first-seen variant
        gkey = tuple(collation_key(x) if c and x is not None else x
                     for x, c in zip(key, ci))
        gi = groups.get(gkey)
        if gi is None:
            gi = len(keys)
            groups[gkey] = gi
            keys.append(key)
            counts.append(0)
            states.append([_init_state(a) for a in aggs])
        counts[gi] += 1
        for ai, a in enumerate(aggs):
            _update_state(a, states[gi][ai], acols[ai], i)

    partials = []
    for ai, a in enumerate(aggs):
        lanes = _states_to_lanes(a, [s[ai] for s in states])
        partials.append(lanes)
    return GroupResult(keys=keys, partials=partials,
                       counts=np.array(counts, dtype=np.int64))


def host_scalar_agg(chunk: Chunk, filter_expr: Expression | None,
                    aggs: list[AggDesc]) -> GroupResult:
    mask = eval_filter_host(filter_expr, chunk)
    if mask.any() and not any(a.distinct for a in aggs):
        # one all-rows segment through the vectorized group-by
        return _host_agg_vectorized(chunk, mask, [], aggs)
    acols = [(None, None) if a.arg is None else a.arg.eval(chunk)
             for a in aggs]
    states = [_init_state(a) for a in aggs]
    cnt = 0
    for i in range(chunk.num_rows):
        if not mask[i]:
            continue
        cnt += 1
        for ai, a in enumerate(aggs):
            _update_state(a, states[ai], acols[ai], i)
    partials = [_states_to_lanes(a, [states[ai]])
                for ai, a in enumerate(aggs)]
    return GroupResult(keys=[()], partials=partials,
                       counts=np.array([cnt], dtype=np.int64))


def _init_state(a: AggDesc):
    if a.distinct:
        return {"seen": set(), "sum": 0, "cnt": 0, "min": None, "max": None}
    return {"sum": 0, "cnt": 0, "min": None, "max": None, "first": None,
            "has": False}


def _update_state(a: AggDesc, st, col, i):
    fn = a.fn
    if a.arg is None:   # COUNT(*)
        st["cnt"] += 1
        return
    d, v = col
    if not v[i]:
        return
    val = d[i].item() if hasattr(d[i], "item") else d[i]
    if a.distinct:
        if val in st["seen"]:
            return
        st["seen"].add(val)
    st["has"] = True if "has" in st else None
    if fn in (AggFunc.SUM, AggFunc.AVG):
        st["sum"] += val
        st["cnt"] += 1
    elif fn == AggFunc.COUNT:
        st["cnt"] += 1
    elif fn == AggFunc.MIN:
        st["min"] = val if st["min"] is None else min(st["min"], val)
    elif fn == AggFunc.MAX:
        st["max"] = val if st["max"] is None else max(st["max"], val)
    elif fn == AggFunc.FIRST_ROW:
        if st.get("first") is None:
            st["first"] = val
    elif fn == AggFunc.GROUP_CONCAT:
        st.setdefault("parts", []).append(_display_str(val, a.arg.ft))
    else:
        raise NotImplementedError(fn)


def _states_to_lanes(a: AggDesc, sts: list[dict]):
    """Convert host states into the kernel's partial-lane layout so
    HashAggregator merges both identically."""
    fn = a.fn
    n = len(sts)
    if fn == AggFunc.COUNT:
        return [np.array([s["cnt"] for s in sts], dtype=np.int64)]
    if fn == AggFunc.SUM:
        dtype = np.float64 if any(isinstance(s["sum"], float) for s in sts) \
            else np.int64
        return [np.array([s["sum"] for s in sts], dtype=dtype),
                np.array([1 if s["cnt"] else 0 for s in sts],
                         dtype=np.int64)]
    if fn == AggFunc.AVG:
        dtype = np.float64 if any(isinstance(s["sum"], float) for s in sts) \
            else np.int64
        return [np.array([s["sum"] for s in sts], dtype=dtype),
                np.array([s["cnt"] for s in sts], dtype=np.int64)]
    if fn in (AggFunc.MIN, AggFunc.MAX):
        key = "min" if fn == AggFunc.MIN else "max"
        has = [0 if sts[i][key] is None else 1 for i in range(n)]
        vals = [sts[i][key] if has[i] else 0 for i in range(n)]
        arr = np.array(vals, dtype=object) \
            if any(isinstance(v, (str, bytes)) for v in vals) else \
            np.asarray(vals)
        return [arr, np.array(has, dtype=np.int64)]
    if fn == AggFunc.GROUP_CONCAT:
        has = [1 if s.get("parts") else 0 for s in sts]
        vals = [a.sep.join(s.get("parts", [])) for s in sts]
        return [np.array(vals, dtype=object),
                np.array(has, dtype=np.int64)]
    if fn == AggFunc.FIRST_ROW:
        has = [0 if s.get("first") is None else 1 for s in sts]
        vals = [s.get("first") if has[i] else 0
                for i, s in enumerate(sts)]
        arr = np.array(vals, dtype=object) \
            if any(isinstance(v, (str, bytes)) for v in vals) else \
            np.asarray(vals)
        return [arr, np.array(has, dtype=np.int64)]
    raise NotImplementedError(fn)
