"""Device runtime helpers: padding, transfer, kernel caching.

The reference streams 1024-row chunks through goroutine pipelines
(util/chunk, distsql); a TPU wants large static-shape batches. Chunks are
padded to bucketed sizes (powers of two) so each physical plan compiles a
small, reusable set of XLA programs; padding rows carry valid=False so every
kernel treats them as NULLs that match no filter and join no group.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk, dict_encode
from tidb_tpu.expression import Expression

__all__ = ["bucket_size", "pad_column", "device_put_chunk",
           "eval_filter_host", "super_batches", "MIN_BUCKET"]

MIN_BUCKET = 1024


def super_batches(first_parts, rest, limit: int):
    """Re-batch a chunk stream into ~limit-row super-batches: device
    dispatches stay large while host memory stays O(limit) — the
    TPU-sized form of the reference's bounded chunk channels
    (distsql/distsql.go:92). Oversize chunks are sliced so one storage
    chunk cannot break the memory bound."""
    import itertools
    limit = max(int(limit), 1)    # a 0/negative sysvar must not hang
    buf, total = [], 0
    for c in itertools.chain(first_parts, rest):
        start = 0
        while start < c.num_rows:
            take = min(c.num_rows - start, limit - total)
            piece = c if (start == 0 and take == c.num_rows) \
                else c.slice(start, start + take)
            buf.append(piece)
            total += take
            start += take
            if total >= limit:
                big = Chunk.concat_all(buf)
                if big is not None:
                    yield big
                buf, total = [], 0
    if buf:
        big = Chunk.concat_all(buf)
        if big is not None:
            yield big


def bucket_size(n: int) -> int:
    """Next power of two >= n (min MIN_BUCKET): the static shape bucket."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def pad_column(data: np.ndarray, valid: np.ndarray, size: int):
    n = len(data)
    if n == size:
        return data, valid
    pd = np.zeros(size, dtype=data.dtype)
    pd[:n] = data
    pv = np.zeros(size, dtype=bool)
    pv[:n] = valid
    return pd, pv


def device_put_chunk(chunk: Chunk, size: int | None = None,
                     to_device: bool = True):
    """-> (cols, dicts): cols is a list of (data, valid) per column, padded
    to a bucketed static size; varlen columns are dict-encoded and their
    dictionaries returned in `dicts[col_idx]` for host-side decode.
    With to_device=False the arrays stay numpy so the caller can issue one
    jax.device_put with an explicit sharding (no double transfer).

    Device transfers are memoized on the chunk (keyed by padded size):
    chunks served repeatedly from the storage-side columnar cache keep
    their columns resident in HBM, so a hot analytical query pays zero
    host->device bytes. Callers must treat chunks as immutable."""
    size = size or bucket_size(chunk.num_rows)
    if to_device:
        hit = dev_cache_get(chunk, size)
        if hit is not None:
            return hit
    cols = []
    dicts: dict[int, list] = {}
    for j, c in enumerate(chunk.columns):
        if c.fixed_width:
            data, valid = c.data, c.valid
        else:
            codes, values = dict_encode(c)
            dicts[j] = values
            data, valid = codes, c.valid & (codes >= 0)
        data, valid = pad_column(np.ascontiguousarray(data), valid, size)
        cols.append((data, valid))
    if to_device:
        cols = jax.device_put(cols)   # one batched transfer
        dev_cache_put(chunk, size, (cols, dicts))
    return cols, dicts


# a chunk may be consumed by both the single-chip path (int size key) and
# a mesh path (('shard', mesh, size) key); a tiny per-chunk dict lets the
# two memos coexist instead of evicting each other
_DEV_CACHE_SLOTS = 2


def dev_cache_get(chunk, key):
    cache = getattr(chunk, "_dev_cache", None)
    if isinstance(cache, dict):
        return cache.get(key)
    return None


def dev_cache_put(chunk, key, value) -> None:
    cache = getattr(chunk, "_dev_cache", None)
    if not isinstance(cache, dict):
        cache = {}
        chunk._dev_cache = cache
    while len(cache) >= _DEV_CACHE_SLOTS:
        cache.pop(next(iter(cache)))
    cache[key] = value


def eval_filter_host(expr: Expression | None, chunk: Chunk) -> np.ndarray:
    """Host-path filter: bool mask over rows (NULL -> False).
    Mirror of the device mask used inside kernels."""
    if expr is None:
        return np.ones(chunk.num_rows, dtype=bool)
    d, v = expr.eval(chunk)
    return v & (d != 0)


def filter_mask_xp(xp, expr: Expression | None, cols, n):
    """Device-path filter mask inside a traced kernel."""
    if expr is None:
        return xp.ones(n, dtype=bool)
    d, v = expr.eval_xp(xp, cols, n)
    return v & (d != 0)
