"""Device runtime helpers: padding, transfer, kernel caching.

The reference streams 1024-row chunks through goroutine pipelines
(util/chunk, distsql); a TPU wants large static-shape batches. Chunks are
padded to bucketed sizes (powers of two) so each physical plan compiles a
small, reusable set of XLA programs; padding rows carry valid=False so every
kernel treats them as NULLs that match no filter and join no group.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk, dict_encode
from tidb_tpu.expression import Expression

__all__ = ["bucket_size", "pad_column", "device_put_chunk",
           "eval_filter_host", "super_batches", "MIN_BUCKET",
           "Superchunk", "superchunk_batches", "pipeline_map",
           "donation_supported", "plan_fingerprint"]

MIN_BUCKET = 1024


class Superchunk:
    """One coalesced batch: a chunk re-assembled from `sources` storage
    chunks, destined for a single padded-bucket device dispatch.
    `sources` counts the chunks that CONTRIBUTED rows to this batch — a
    chunk spanning a coalesce boundary feeds (and counts in) each
    superchunk it touches, so per-superchunk attribution stays honest
    even though the per-query sum can exceed the distinct chunk count.
    The fill ratio (rows over the padded bucket) is the fraction of
    device work spent on live rows — the number EXPLAIN ANALYZE
    surfaces."""

    __slots__ = ("chunk", "sources")

    def __init__(self, chunk: Chunk, sources: int):
        self.chunk = chunk
        self.sources = sources

    @property
    def num_rows(self) -> int:
        return self.chunk.num_rows

    @property
    def bucket(self) -> int:
        return bucket_size(self.chunk.num_rows)

    @property
    def fill(self) -> float:
        return self.chunk.num_rows / self.bucket


def superchunk_batches(chunks, limit: int, tracker=None):
    """Coalesce a chunk stream into ~limit-row Superchunks: device
    dispatches stay large while host memory stays O(limit) — the
    TPU-sized form of the reference's bounded chunk channels
    (distsql/distsql.go:92). Oversize chunks are sliced so one storage
    chunk cannot break the memory bound; 0-row chunks fold away.
    A `limit` that is a power of two keeps every full superchunk on ONE
    bucket shape; only the tail pays a smaller power-of-two bucket.

    `tracker` (a memtrack.MemTracker) accounts the staging buffer: bytes
    are held while chunks sit in the assembly buffer and credited back
    when the superchunk is yielded — ownership passes to the consumer
    (pipeline_map's in-flight slots pick it up from there)."""
    from tidb_tpu import memtrack
    limit = max(int(limit), 1)    # a 0/negative sysvar must not hang
    buf, total, srcs, staged = [], 0, 0, 0

    def emit():
        nonlocal staged
        big = Chunk.concat_all(buf)
        if tracker is not None and staged:
            tracker.release(host=staged)
            staged = 0
        return Superchunk(big, srcs) if big is not None else None

    try:
        for c in chunks:
            if c.num_rows == 0:
                continue
            srcs += 1
            start = 0
            while start < c.num_rows:
                take = min(c.num_rows - start, limit - total)
                piece = c if (start == 0 and take == c.num_rows) \
                    else c.slice(start, start + take)
                buf.append(piece)
                if tracker is not None:
                    b = memtrack.chunk_bytes(piece)
                    tracker.consume(host=b)
                    staged += b
                total += take
                start += take
                if total >= limit:
                    sc = emit()
                    if sc is not None:
                        yield sc
                    buf, total, srcs = [], 0, \
                        1 if start < c.num_rows else 0
        if buf:
            sc = emit()
            if sc is not None:
                yield sc
    finally:
        # abandoned/raised mid-assembly: whatever still sits in the
        # buffer was never handed to a consumer — credit it back now
        # instead of waiting for the statement root's detach
        if tracker is not None and staged:
            tracker.release(host=staged)
            staged = 0


def super_batches(first_parts, rest, limit: int):
    """Chunk-only view of superchunk_batches (legacy callers)."""
    import itertools
    for sc in superchunk_batches(itertools.chain(first_parts, rest),
                                 limit):
        yield sc.chunk


def pipeline_map(items, dispatch, finalize, depth: int,
                 tracker=None, cost=None, profile=None):
    """Depth-N dispatch-ahead map over an item stream: up to `depth`
    dispatched items are in flight before the oldest is finalized, so
    item k+1's host-side prep (padding, dict-encode, device_put) and its
    async XLA dispatch overlap item k's device execution — the double
    buffer at depth 2. Results come back in item order.

    dispatch(item) -> token must only ENQUEUE work (jax dispatch is
    async; nothing here may force a sync). finalize(item, token) is the
    one blocking point (device_get at the operator output boundary);
    callers that want stall attribution time their device readback
    inside finalize (runtime_stats.note_finalize_wait), where they can
    tell device tokens from host-fallback ones.

    With `tracker`/`cost` set, each in-flight slot holds cost(item) host
    bytes from dispatch until its finalize returns — the depth-N window
    is exactly the memory the pipeline pins beyond one batch.

    `depth` is this STATEMENT's window; the server-wide window belongs
    to the device scheduler (tidb_tpu/sched.py): every dispatch takes a
    global slot first, granted round-robin across concurrent
    statements. Under contention the pipeline drains its own oldest
    in-flight token before asking again — shrinking its local window to
    its fair share — and past the scheduler's bypass valve the dispatch
    proceeds unscheduled, so the global window can throttle but never
    hang a statement.

    With `profile` set (a profiler.KernelProfile), each device token's
    enqueue interval records as one dispatch and its blocking readback
    as busy-ns on that profile row — the pipelined seam of the kernel
    profiling plane (the sync seams use profiler.dispatch_section);
    bytes are billed by the dispatch closures, which know them."""
    import time as _time

    from tidb_tpu import meter, profiler, sched, trace
    from tidb_tpu.util import failpoint
    scheduler = sched.device_scheduler()
    depth = max(int(depth), 1)
    pending: deque = deque()
    track = tracker is not None and cost is not None

    def _token_kind(tok) -> str:
        # host-path items: None (the common convention) or the fused
        # probe-agg's explicit ("host", ...) token — everything else
        # really enqueued device work
        if tok is None or (isinstance(tok, tuple) and tok
                           and tok[0] == "host"):
            return "host"
        return "device"

    def pop_finalize():
        prev, seq, tok, held, slot = pending.popleft()
        kind = _token_kind(tok)
        try:
            # the watchdog bounds the blocking readback: past
            # tidb_tpu_dispatch_timeout_ms the statement cancels with
            # the retryable device-fault error, and the finally below
            # (plus each kernel's own finalize-path credit) drains the
            # slot and the staged bytes exactly as on any error
            with sched.finalize_watch("pipeline-finalize"):
                failpoint.eval("device/finalize")
                # the blocking readback at the output boundary — the
                # per-superchunk finalize serialization the Chrome
                # export makes visible next to the dispatch-ahead
                # lanes. The interval bills to the tenant's work
                # ledger (meter.py) as a SECTION: escalation retries
                # and degraded partitions inside the finalize meter
                # themselves, and the section charges the remainder
                with meter.busy_section(kind), \
                        trace.span("finalize", superchunk=seq,
                                   host=int(kind == "host")):
                    t0p = _time.perf_counter_ns()
                    out = finalize(prev, tok)
                    if profile is not None and kind == "device":
                        profiler.note_busy(
                            profile, _time.perf_counter_ns() - t0p)
                    return out
        finally:
            scheduler.release(slot)
            if held:
                tracker.release(host=held)

    def acquire_slot(bypass: bool):
        # the global round-robin slot wait, traced per attempt so slot
        # stalls attribute to THIS statement's timeline (and to the
        # tenant's slot-wait ledger)
        t0 = _time.perf_counter_ns()
        try:
            with trace.span("sched.slot"):
                return scheduler.acquire_or_bypass() if bypass \
                    else scheduler.acquire()
        finally:
            meter.note_slot_wait(_time.perf_counter_ns() - t0)

    seq = -1
    try:
        for it in items:
            seq += 1
            while len(pending) >= depth:
                yield pop_finalize()
            slot = acquire_slot(False)
            while slot is None and pending:
                yield pop_finalize()
                slot = acquire_slot(False)
            if slot is None:
                slot = acquire_slot(True)
            held = cost(it) if track else 0
            if held:
                tracker.consume(host=held)
            try:
                failpoint.eval("device/dispatch")
                # the enqueue interval (pad/transfer/launch) meters as
                # device time for device tokens, host-fallback time for
                # host-path items — the kind is only known once
                # dispatch() returns, so it is assigned on the section
                busy = meter.busy_section()
                cc = profiler.cc_probe(profile)
                t0p = _time.perf_counter_ns()
                with busy, trace.span("dispatch", superchunk=seq):
                    tok = dispatch(it)
                    busy.kind = _token_kind(tok)
                if profile is not None and busy.kind == "device":
                    profiler.note_dispatch(
                        profile, _time.perf_counter_ns() - t0p,
                        cc_before=cc)
            except BaseException as e:
                # executor-plane device faults feed the same health
                # tracker as the copr sites, so repeated pipeline
                # faults still quarantine the device — the fault
                # itself propagates (retryable 9009 at the client;
                # the per-dispatch retry/degrade chain lives on the
                # copr path)
                if isinstance(e, failpoint.DeviceFaultError) and not \
                        isinstance(e, failpoint.DispatchTimeoutError):
                    sched.device_health().note_fault()
                scheduler.release(slot)
                if held:
                    tracker.release(host=held)
                raise
            if tok is None:
                # host-path item: nothing went to the device — hand the
                # slot back now instead of across its (host) finalize
                scheduler.release(slot)
                slot = None
            pending.append((it, seq, tok, held, slot))
        while pending:
            yield pop_finalize()
    finally:
        # a consumer that stops early (limit hit, error upstream)
        # abandons the generator with dispatched slots still in flight:
        # neither their held host bytes nor the device bytes their
        # dispatch charged may linger until statement detach. Every
        # kernel credits dispatch_nbytes back on its finalize path, so
        # each abandoned token is finalized (result discarded); a slot
        # whose finalize fails still releases its host bytes
        while pending:
            prev, _seq, tok, held, slot = pending.popleft()
            try:
                # abandoned tokens still occupied the device until this
                # drain — their finalize interval meters like any other
                with meter.busy_section(_token_kind(tok)):
                    finalize(prev, tok)
            except Exception:
                pass    # the slot is dead either way; ledger cleanup
                #         continues with the remaining slots
            finally:
                scheduler.release(slot)
                if held:
                    tracker.release(host=held)


_donation_supported: bool | None = None


def donation_supported() -> bool:
    """True when the active backend honors input-buffer donation (TPU /
    GPU). XLA:CPU ignores donations with a per-call warning, so the
    donating jit variants only engage off-CPU."""
    global _donation_supported
    if _donation_supported is None:
        try:
            _donation_supported = jax.default_backend() not in ("cpu",)
        except Exception:  # noqa: BLE001 - no backend: treat as host-only
            _donation_supported = False
    return _donation_supported


def bucket_size(n: int) -> int:
    """Next power of two >= n (min MIN_BUCKET): the static shape bucket."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


# lint: exempt[memtrack-alloc] callers bill padded superchunk staging at dispatch (superchunk_batches tracker)
def pad_column(data: np.ndarray, valid: np.ndarray, size: int):
    n = len(data)
    if n == size:
        return data, valid
    pd = np.zeros(size, dtype=data.dtype)
    pd[:n] = data
    pv = np.zeros(size, dtype=bool)
    pv[:n] = valid
    return pd, pv


def device_put_chunk(chunk: Chunk, size: int | None = None,
                     to_device: bool = True, memo: bool = True):
    """-> (cols, dicts): cols is a list of (data, valid) per column, padded
    to a bucketed static size; varlen columns are dict-encoded and their
    dictionaries returned in `dicts[col_idx]` for host-side decode.
    With to_device=False the arrays stay numpy so the caller can issue one
    jax.device_put with an explicit sharding (no double transfer).

    Device transfers are memoized on the chunk (keyed by padded size):
    chunks served repeatedly from the storage-side columnar cache keep
    their columns resident in HBM, so a hot analytical query pays zero
    host->device bytes. Callers must treat chunks as immutable.
    memo=False skips the memo entirely — REQUIRED when the caller will
    donate the transferred buffers to a kernel (a memoized donated
    buffer would be read after free) or when the chunk is a transient
    superchunk that no one will ever present again."""
    size = size or bucket_size(chunk.num_rows)
    if to_device and memo:
        hit = dev_cache_get(chunk, size)
        if hit is not None:
            return hit
    cols = []
    dicts: dict[int, list] = {}
    for j, c in enumerate(chunk.columns):
        if c.fixed_width:
            data, valid = c.data, c.valid
        else:
            codes, values = dict_encode(c)
            dicts[j] = values
            data, valid = codes, c.valid & (codes >= 0)
        data, valid = pad_column(np.ascontiguousarray(data), valid, size)
        cols.append((data, valid))
    if to_device:
        cols = jax.device_put(cols)   # one batched transfer
        if memo:
            dev_cache_put(chunk, size, (cols, dicts))
    return cols, dicts


# a chunk may be consumed by both the single-chip path (int size key) and
# a mesh path (('shard', mesh, size) key); a tiny per-chunk dict lets the
# two memos coexist instead of evicting each other
_DEV_CACHE_SLOTS = 2


def dev_cache_get(chunk, key):
    cache = getattr(chunk, "_dev_cache", None)
    if isinstance(cache, OrderedDict):
        hit = cache.get(key)
        if hit is not None:
            # true LRU: a hit refreshes the entry's position, so the
            # entry that actually gets evicted is the LEAST recently
            # used one, not merely the oldest inserted
            cache.move_to_end(key)
        return hit
    return None


def dev_cache_put(chunk, key, value) -> None:
    cache = getattr(chunk, "_dev_cache", None)
    if not isinstance(cache, OrderedDict):
        cache = OrderedDict()
        chunk._dev_cache = cache
    while len(cache) >= _DEV_CACHE_SLOTS:
        cache.popitem(last=False)
    cache[key] = value


def eval_filter_host(expr: Expression | None, chunk: Chunk) -> np.ndarray:
    """Host-path filter: bool mask over rows (NULL -> False).
    Mirror of the device mask used inside kernels."""
    if expr is None:
        return np.ones(chunk.num_rows, dtype=bool)
    d, v = expr.eval(chunk)
    return v & (d != 0)


def filter_mask_xp(xp, expr: Expression | None, cols, n):
    """Device-path filter mask inside a traced kernel."""
    if expr is None:
        return xp.ones(n, dtype=bool)
    d, v = expr.eval_xp(xp, cols, n)
    return v & (d != 0)


# -- plan fingerprints (executable-cache keys) -------------------------------


class FingerprintCache:
    """Thread-safe LRU keyed by plan fingerprint: ONE implementation for
    every process-wide kernel cache (hashagg, streamagg), so the true-LRU
    contract (a hit refreshes the entry) holds everywhere. Initialized
    at module level by its owners — no lazy check-then-create races."""

    def __init__(self, capacity: int = 64):
        self._cap = capacity
        self._d: OrderedDict = OrderedDict()
        self._mu = threading.Lock()

    def get_or_create(self, key, factory):
        """Cached value for `key`, else factory() (called OUTSIDE the
        lock — kernel construction may validate expressions; a racing
        duplicate is discarded in favor of the first insert). factory
        exceptions propagate without touching the cache."""
        with self._mu:
            hit = self._d.get(key)
            if hit is not None:
                self._d.move_to_end(key)
                return hit
        obj = factory()
        with self._mu:
            cur = self._d.setdefault(key, obj)
            self._d.move_to_end(key)
            while len(self._d) > self._cap:
                old = next(iter(self._d))
                if old == key:      # never evict the entry just touched
                    break
                self._d.pop(old)
            return cur


class _Unfingerprintable(Exception):
    """Expression tree contains a node whose device behavior cannot be
    captured structurally (correlated cells, unknown extensions)."""


def _ft_fp(ft) -> str:
    if ft is None:
        return "?"
    return (f"{ft.tp}:{getattr(ft, 'flen', 0)}:{getattr(ft, 'frac', 0)}:"
            f"{int(bool(getattr(ft, 'is_ci', False)))}:"
            f"{int(bool(getattr(ft, 'is_wide_decimal', False)))}")


def _extra_fp(extra) -> str:
    """ScalarFunc.extra carries eval-relevant payload (IN value lists,
    LIKE patterns, cast target types) that MUST distinguish kernels."""
    if extra is None:
        return ""
    if hasattr(extra, "tp"):          # a FieldType (cast target)
        return _ft_fp(extra)
    if isinstance(extra, (list, tuple)):
        return repr([repr(x) for x in extra])
    if isinstance(extra, (str, bytes, int, float, bool)):
        return repr(extra)
    # arbitrary payload (GENERIC handlers): no structural identity
    raise _Unfingerprintable(type(extra).__name__)


def _expr_fp(e) -> str:
    from tidb_tpu.expression.core import ColumnRef, Constant, ScalarFunc
    if e is None:
        return "~"
    ft = _ft_fp(getattr(e, "ft", None))
    if isinstance(e, ColumnRef):
        return f"c{e.idx}|{ft}"
    if isinstance(e, Constant):
        return f"k{e.value!r}|{ft}"
    if isinstance(e, ScalarFunc):
        args = ",".join(_expr_fp(a) for a in e.args)
        return f"f{e.op.value}({args})|x{_extra_fp(e.extra)}|{ft}"
    raise _Unfingerprintable(type(e).__name__)


def plan_fingerprint(filter_expr, group_exprs, aggs) -> str | None:
    """Structural identity of a pushed (filter, group-by, agg) subplan —
    the process-wide executable-cache key. Two plans with the same
    fingerprint trace to IDENTICAL device programs: the walk encodes
    everything a kernel's eval_xp depends on (column indices, field
    types incl. frac/collation, operator tree shape, literal values).
    Returns None when any node falls outside the structural vocabulary
    (then the caller builds an uncached kernel — correct, just slower on
    a plan-cache miss)."""
    try:
        parts = [_expr_fp(filter_expr),
                 ";".join(_expr_fp(g) for g in group_exprs)]
        for a in aggs:
            parts.append(f"{a.fn.value}|{int(bool(a.distinct))}|"
                         f"{_expr_fp(a.arg)}|{a.sep!r}")
        return "#".join(parts)
    except _Unfingerprintable:
        return None
