"""Binlog: transaction change-capture stream.

Reference: /root/reference/sessionctx/binloginfo (pump client hook,
binloginfo.go:40-61), the 2PC prewrite/commit binlog writes
(store/tikv/2pc.go:664-697) and tidb.go:275 (pump gRPC client). The
reference ships every txn's prewrite payload plus a commit record to an
external "pump" process; here the pump is a pluggable sink interface
fed once per successfully committed transaction with (start_ts,
commit_ts, mutations) — the same information content, one event instead
of two wire messages (no external pump process to coordinate with).

Consumers decode row-level changes with `decode_row_events`: record-key
mutations become (table_id, handle, op, column values)."""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass

from tidb_tpu import tablecodec
from tidb_tpu.kv import Mutation, MutationOp

__all__ = ["BinlogEvent", "MemoryPump", "RowChange", "decode_row_events"]


@dataclass(frozen=True)
class BinlogEvent:
    start_ts: int
    commit_ts: int
    mutations: tuple          # ((op_name, key, value|None), ...)


@dataclass(frozen=True)
class RowChange:
    table_id: int
    handle: int
    op: str                   # "PUT" | "DELETE"
    values: dict | None       # column_id -> datum (None for DELETE)


class MemoryPump:
    """Bounded in-process sink (the test/devel pump; a network pump
    implements the same write())."""

    def __init__(self, cap: int = 4096):
        self._mu = threading.Lock()
        self._events: deque = deque(maxlen=cap)
        self._subs: list = []

    def write(self, event: BinlogEvent) -> None:
        with self._mu:
            self._events.append(event)
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(event)
            except Exception:   # noqa: BLE001 - sinks never break commits
                pass

    def subscribe(self, fn) -> None:
        with self._mu:
            self._subs.append(fn)

    def events(self, since_commit_ts: int = 0) -> list[BinlogEvent]:
        """Events in commit_ts order. Concurrent committers may ARRIVE
        out of ts order (commit_ts allocation and the pump write are not
        one atomic step); readers see the sorted stream, subscribers get
        best-effort arrival order."""
        with self._mu:
            return sorted((e for e in self._events
                           if e.commit_ts > since_commit_ts),
                          key=lambda e: e.commit_ts)


def make_event(start_ts: int, commit_ts: int,
               mutations: dict[bytes, Mutation]):
    """-> BinlogEvent, or None when nothing changed (a FOR UPDATE txn's
    LOCK mutations are concurrency control, not data changes — CDC
    consumers must never see phantom rows for them)."""
    muts = tuple(sorted(
        (m.op.name, k, m.value if m.op == MutationOp.PUT else None)
        for k, m in mutations.items() if m.op != MutationOp.LOCK))
    if not muts:
        return None
    return BinlogEvent(start_ts=start_ts, commit_ts=commit_ts,
                       mutations=muts)


def decode_row_events(event: BinlogEvent) -> list[RowChange]:
    """Record-key mutations -> row changes (index/meta keys skipped:
    consumers reconstruct indexes from row values, as CDC sinks do)."""
    out = []
    for op, key, value in event.mutations:
        try:
            table_id, handle = tablecodec.decode_record_key(key)
        except (ValueError, IndexError):
            continue
        values = None
        if op == "PUT" and value is not None:
            try:
                values = tablecodec.decode_row(value)
            except (ValueError, IndexError):
                values = None
        out.append(RowChange(table_id=table_id, handle=handle, op=op,
                             values=values))
    return out
