"""Fleet orchestration harness: one store plane + N stateless SQL servers.

The deployment shape of the source system (a stateless SQL layer scaling
horizontally over one shared MVCC store): this module spawns

  * one store-plane server (`python -m tidb_tpu storeserve`) hosting the
    MVCCStore + TSO + region map behind the wire protocol
    (store/remote.py), with a delta-journal retention window so SQL
    servers can pull coherence deltas (store/fleetcop.py), and
  * N SQL-server processes (`python -m tidb_tpu --store HOST:PORT`),
    each a full wire server with its own coherent chunk/HBM caches,

health-checks members over their status ports, hands out round-robin
client connections, and supports killing/restarting a member — the
chaos surface the fleet tests and `bench.py fleet` drive. Every fleet
fault degrades to a slower correct mode: killing a SQL server yields
retryable errors on ITS clients only (errcode.ER_STORE_UNAVAILABLE
class), survivors keep serving, and the DDL owner lease fails over
within one lease interval (owner.py over the shared store).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from tidb_tpu.util import statusclient

__all__ = ["Fleet", "SQLMember"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env(extra=None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    if extra:
        env.update(extra)
    return env


def _spawn(cmd: list, extra_env=None) -> subprocess.Popen:
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=_REPO_ROOT, env=_child_env(extra_env))


def _await_line(proc: subprocess.Popen, needle: str,
                timeout: float = 60.0) -> str:
    """Read child stdout until a line contains `needle` (ports are
    reported this way: the children bind port 0). Line-buffered reads —
    the child prints the marker during startup, long before any output
    volume could matter."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet member exited (rc={proc.returncode}) before "
                    f"reporting {needle!r}")
            time.sleep(0.01)
            continue
        if needle in line:
            return line
    raise TimeoutError(f"no {needle!r} line within {timeout}s")


def _port_of(line: str) -> int:
    return int(line.strip().rsplit(":", 1)[1])


class SQLMember:
    """One SQL-server process of the fleet."""

    def __init__(self, index: int, proc: subprocess.Popen, port: int,
                 status_port: int):
        self.index = index
        self.proc = proc
        self.port = port
        self.status_port = status_port

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Fleet:
    """Spawns and supervises the store plane + SQL servers.

    Usage::

        with Fleet(n_sql=4) as f:
            c = f.client()          # round-robin MiniClient
            c.query("SELECT 1")
            f.kill(0)               # SIGKILL one SQL server
            f.restart(0)
    """

    def __init__(self, n_sql: int = 2, host: str = "127.0.0.1",
                 retain_ms: int = 5000, sql_args=(), env=None):
        self.host = host
        self.n_sql = n_sql
        self.retain_ms = retain_ms
        self.sql_args = list(sql_args)
        self.env = dict(env or {})
        self.store_proc: subprocess.Popen | None = None
        self.store_port: int | None = None
        self.store_status_port: int | None = None
        self.members: list[SQLMember] = []
        self._rr = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Fleet":
        self.store_proc = _spawn(
            [sys.executable, "-m", "tidb_tpu", "storeserve",
             "--host", self.host, "--port", "0",
             "--retain-ms", str(self.retain_ms)], self.env)
        line = _await_line(self.store_proc, "storage listening on")
        self.store_port = _port_of(line)
        # the store plane is a fleet member too: its status port serves
        # /cluster/state so cluster_* queries see store-side traces
        self.store_status_port = _port_of(
            _await_line(self.store_proc, "status API on"))
        for i in range(self.n_sql):
            self.members.append(self._spawn_sql(i))
        return self

    def _spawn_sql(self, index: int) -> SQLMember:
        proc = _spawn(
            [sys.executable, "-m", "tidb_tpu",
             "--host", self.host, "--port", "0", "--status-port", "0",
             "--no-mesh", "--store", f"{self.host}:{self.store_port}",
             *self.sql_args], self.env)
        port = _port_of(_await_line(proc, "MySQL protocol on"))
        status_port = _port_of(_await_line(proc, "status API on"))
        return SQLMember(index, proc, port, status_port)

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *_exc) -> None:
        self.stop()

    def stop(self) -> None:
        for m in self.members:
            if m.alive():
                m.proc.terminate()
        for m in self.members:
            if m.proc is not None:
                try:
                    m.proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    m.proc.kill()
                    m.proc.wait(timeout=10)
                m.proc.stdout.close()
        self.members.clear()
        if self.store_proc is not None:
            self.store_proc.terminate()
            try:
                self.store_proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                self.store_proc.kill()
                self.store_proc.wait(timeout=10)
            self.store_proc.stdout.close()
            self.store_proc = None

    # -- chaos surface -------------------------------------------------------

    def kill(self, index: int, sig: int = signal.SIGKILL) -> None:
        """Forcibly kill one SQL member (default SIGKILL: no graceful
        close, in-flight statements die with it)."""
        m = self.members[index]
        if m.alive():
            m.proc.send_signal(sig)
            m.proc.wait(timeout=20)

    def restart(self, index: int) -> SQLMember:
        """Replace a (dead or alive) member with a fresh process on new
        ports, reconnected to the same store plane."""
        if self.members[index].alive():
            self.kill(index, signal.SIGTERM)
        if self.members[index].proc is not None:
            self.members[index].proc.stdout.close()
        self.members[index] = self._spawn_sql(index)
        return self.members[index]

    # -- health + routing ----------------------------------------------------

    def health(self, index: int, timeout: float = 5.0) -> dict:
        """GET /status of one SQL member (the liveness probe)."""
        m = self.members[index]
        return statusclient.get_json(self.host, m.status_port,
                                     "/status", timeout=timeout)

    def wait_healthy(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        for i in range(len(self.members)):
            while True:
                try:
                    self.health(i)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"member {i} not healthy in {timeout}s")
                    time.sleep(0.1)

    def client(self, index: int | None = None, db: str = "",
               **kw):
        """MiniClient to one member — round-robin over live members
        when `index` is None."""
        if index is None:
            live = [m for m in self.members if m.alive()]
            if not live:
                raise RuntimeError("no live SQL members")
            m = live[self._rr % len(live)]
            self._rr += 1
        else:
            m = self.members[index]
        if _REPO_ROOT not in sys.path:
            sys.path.insert(0, _REPO_ROOT)
        from tests.mysql_client import MiniClient
        return MiniClient(self.host, m.port, db=db, **kw)
