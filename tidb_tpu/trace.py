"""Lightweight statement tracing: span trees per statement.

Reference: the OpenTracing spans threaded through the reference stack —
dispatch (server/conn.go:559), session.Execute (session.go:692), Compile
(executor/compiler.go:34), runStmt (tidb.go:156), TSO wait
(session.go:1198-1206). Here spans are in-process structures: each
non-internal statement runs under a root span, phases annotate
themselves via the `span()` context manager, and the finished tree feeds
PERFORMANCE_SCHEMA statement events (perfschema.py) and, when
tidb_tpu_trace_log is on, the log.

Thread-local: spans opened on worker threads attach to nothing rather
than corrupting another statement's tree (the coprocessor fan-out's
per-task work is aggregated by its dispatching span instead)."""

from __future__ import annotations

import contextlib
import logging
import threading
import time

__all__ = ["begin", "end", "span", "annotate", "current_root", "phase_ns"]

log = logging.getLogger("tidb_tpu.trace")

_tl = threading.local()


class Span:
    __slots__ = ("name", "tags", "start_ns", "end_ns", "children")

    def __init__(self, name: str, tags: dict | None = None):
        self.name = name
        self.tags = tags or {}
        self.start_ns = time.perf_counter_ns()
        self.end_ns = 0
        self.children: list[Span] = []

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or time.perf_counter_ns()) - self.start_ns

    def to_dict(self) -> dict:
        d = {"name": self.name, "duration_ns": self.duration_ns}
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def begin(name: str, **tags) -> Span:
    """Open a root span for the current thread's statement."""
    root = Span(name, tags)
    _tl.cur = root
    return root


def end(root: Span) -> Span:
    root.end_ns = time.perf_counter_ns()
    if getattr(_tl, "cur", None) is root:
        _tl.cur = None
    return root


def current_root():
    return getattr(_tl, "cur", None)


def detach():
    """Suspend the thread's trace (internal bookkeeping sessions run
    inside a client statement but must not pollute its phase breakdown).
    -> token for restore()."""
    cur = getattr(_tl, "cur", None)
    _tl.cur = None
    return cur


def restore(token) -> None:
    _tl.cur = token


@contextlib.contextmanager
def span(name: str, **tags):
    """Child span under the thread's current span; a no-op (still timed,
    but unattached) when no trace is active — internal sessions and
    worker threads pay one thread-local read."""
    parent = getattr(_tl, "cur", None)
    s = Span(name, tags)
    if parent is not None:
        parent.children.append(s)
        _tl.cur = s
    try:
        yield s
    finally:
        s.end_ns = time.perf_counter_ns()
        if parent is not None:
            _tl.cur = parent


def active() -> bool:
    """True when the calling thread is inside a traced statement."""
    return getattr(_tl, "cur", None) is not None


def annotate(**tags) -> None:
    """Merge tags into the thread's CURRENT span without opening a child
    — safe from inside generators (a `with span(...)` wrapped around a
    `yield` would interleave restores with the consumer's own spans).
    Used by the streaming coprocessor to stamp per-stream frame/byte/
    stall counts onto the dispatching span. No-op untraced."""
    cur = getattr(_tl, "cur", None)
    if cur is not None:
        cur.tags.update(tags)


def attach_remote(d: dict) -> None:
    """Graft a span tree returned by another PROCESS (the storage node's
    side of an RPC — store/remote.py) under the current span. Remote
    clocks don't align, so only names/tags/durations carry over; the
    child is pinned at the current moment with its reported duration.
    Ref: the reference's cross-process span propagation
    (session.go:692 opentracing context over gRPC)."""
    parent = getattr(_tl, "cur", None)
    if parent is None:
        return

    def build(node: dict) -> Span:
        s = Span(node.get("name", "remote"), node.get("tags"))
        dur = int(node.get("duration_ns", 0))
        # end at "now" (the Span's birth instant), duration preserved
        s.end_ns, s.start_ns = s.start_ns, s.start_ns - dur
        for c in node.get("children", ()):
            s.children.append(build(c))
        return s

    parent.children.append(build(d))


def phase_ns(root: Span | None, name: str) -> int:
    """Sum of top-level child spans with `name` (a statement's parse /
    plan / execute / commit phase totals)."""
    if root is None:
        return 0
    return sum(c.duration_ns for c in root.children if c.name == name)


def log_tree(root: Span, sql: str) -> None:
    parts: list[str] = []

    def walk(s: Span, depth: int) -> None:
        parts.append("%s%s %.3fms %s" % (
            "  " * depth, s.name, s.duration_ns / 1e6,
            s.tags if s.tags else ""))
        for c in s.children:
            walk(c, depth + 1)

    walk(root, 0)
    log.info("trace for %r:\n%s", sql[:256], "\n".join(parts))
