"""Statement tracing: lifecycle span trees, sampling, slow-trace
capture, and the device-timeline export.

Reference: the OpenTracing spans threaded through the reference stack —
dispatch (server/conn.go:559), session.Execute (session.go:692), Compile
(executor/compiler.go:34), runStmt (tidb.go:156), TSO wait
(session.go:1198-1206) — and the F1/Spanner practice of making the
per-request trace tree the primary tool for debugging a distributed SQL
path. Here spans are in-process structures: each non-internal statement
runs under a root span, every subsystem annotates itself via the
`span()` context manager (session phases, admission wait, scheduler
slot waits, per-superchunk dispatch/finalize, coprocessor pool and
stream workers, HBM fill/patch, delta fold/merge, hybrid-join partition
chains), and device-plane recovery transitions (fault retry, degrade,
quarantine, watchdog) land as point EVENTS on the span they interrupted.

Retention: every statement gets a tree (perfschema's phase breakdown
reads it), but only some trees are RETAINED into the bounded server
ring (`tidb_tpu/trace.py:_Ring`) that the `TRACE` statement,
`information_schema.statement_traces`, `GET /trace` and the Chrome
trace-event export serve:

  * 1-in-N deterministic sampling (`tidb_tpu_trace_sample`, always on);
  * threshold capture (`tidb_tpu_slow_trace_ms`: any statement over the
    threshold keeps its full tree — the slow log and the digest summary
    carry the trace id, so a digest hot spot links to a timeline);
  * the `TRACE <stmt>` statement forces retention.

The ring is billed to a `trace-ring` memtrack SERVER node with a
registered shed action, so admission shedding and `GET /shed` reclaim
retained trees like any other server-scope residency.

Cross-thread propagation follows the house pattern (the runtime_stats
collector and the memtrack tracker): the coprocessor fan-out captures
the dispatching span with `propagate()` and re-installs it inside every
pool/stream worker with `attached()`, so storage-side spans hang off
the reader that issued them. Span names at `trace.begin`/`trace.span`
call sites are literals declared in SPAN_NAMES (lint rule
`trace-names`), the same registry discipline metric names and
failpoints already follow."""

from __future__ import annotations

import contextlib
import logging
import threading
import time

__all__ = ["Span", "SPAN_NAMES", "begin", "end", "span", "event",
           "annotate", "current_root", "active", "detach", "restore",
           "attached", "propagate", "attach_remote", "origin",
           "phase_ns", "log_tree", "ensure_id", "finish_statement",
           "tree", "validate", "phases_of", "ring_snapshot",
           "ring_records", "ring_get", "to_chrome", "reset_for_tests"]

log = logging.getLogger("tidb_tpu.trace")

_tl = threading.local()

# declared span vocabulary: every trace.begin / trace.span call site in
# the package names one of these, as a string literal (lint rule
# trace-names — tidb_tpu/lint/rules/tracenames.py). One table so the
# docs (docs/OBSERVABILITY.md), the Chrome export and the bench
# attribution all read the same names.
SPAN_NAMES = {
    # statement lifecycle (session/__init__.py)
    "statement": "root of one non-internal statement execution",
    "parse": "this statement's share of the batch parse",
    "plan": "logical+physical planning (plan-cache miss)",
    "execute": "executor tree drive, operator output boundary to rows",
    "commit": "2PC commit incl. optimistic replay retries",
    "admission": "wait in the server admission controller",
    # device plane (sched.py, ops/runtime.py, store/copr.py)
    "sched.slot": "wait for a global device dispatch slot",
    "dispatch": "kernel dispatch: pad/transfer/async enqueue",
    "finalize": "blocking device readback at the output boundary",
    "host.fallback": "host-path aggregation of device-planned work",
    # coprocessor fan-out (store/copr.py)
    "copr.task": "one region task on a coprocessor pool worker",
    "copr.stream": "one streaming fan-out worker's frame production",
    # storage-side caches and deltas (store/device_cache.py, delta.py)
    "hbm.fill": "HBM region-block cache upload",
    "hbm.patch": "in-place delta patch of a resident HBM block",
    "delta.fold": "base-chunk ⋈ delta-journal merge on the read path",
    "delta.merge": "delta-store merge into new base blocks",
    # hybrid join/agg partition phases (ops/hybrid.py)
    "join.partition": "one radix partition's device chain",
    # cross-process storage roots (store/remote.py)
    "storage:coprocessor_stream": "storage-side root of one COP stream",
    # cluster observability fan-out (util/statusclient.fetch_all): one
    # bounded-timeout sweep over live members' status ports serving a
    # cluster_* memtable or a /fleet/* endpoint
    "cluster.fetch": "fan-out fetch over live members' status ports",
}

# retention bounds of the server-scope trace ring: records and an
# estimated-bytes budget, billed to the trace-ring memtrack node
_RING_CAP = 256
_RING_BYTES_CAP = 16 << 20
_SPAN_EST_BYTES = 256          # rough per-span record cost estimate


class Span:
    # the last three slots are ROOT-ONLY retention state (sampling
    # decided at begin(), TRACE forces, ids assigned on first need):
    # begin() writes them; child spans leave them unset — the hot
    # constructor must not pay three dead writes per span
    __slots__ = ("name", "tags", "start_ns", "end_ns", "children",
                 "events", "tid", "sampled", "forced", "trace_id")

    def __init__(self, name: str, tags: dict | None = None):
        self.name = name
        self.tags = tags or {}
        self.start_ns = time.perf_counter_ns()
        self.end_ns = 0
        self.children: list[Span] = []
        self.events: list | None = None   # (name, t_ns, tags), lazy
        self.tid = threading.get_ident()

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or time.perf_counter_ns()) - self.start_ns

    def event(self, name: str, **tags) -> None:
        """Point event on THIS span (fault retries, degrade/quarantine
        transitions, watchdog fires — the PR-13 state machine on the
        statement timeline)."""
        ev = (name, time.perf_counter_ns(), tags or None)
        if self.events is None:
            self.events = [ev]
        else:
            self.events.append(ev)

    def to_dict(self) -> dict:
        d = {"name": self.name, "duration_ns": self.duration_ns}
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.events:
            d["events"] = [{"name": n, "tags": t} if t else {"name": n}
                           for n, _t_ns, t in self.events]
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def begin(name: str, **tags) -> Span:
    """Open a root span for the current thread's statement. Statement
    roots (`name == "statement"`) take the deterministic 1-in-N
    sampling decision here — `tidb_tpu_trace_sample` — so the whole
    tree below either records for retention or is a pure phase-
    breakdown skeleton."""
    root = Span(name, tags)
    root.sampled = _sample_next() if name == "statement" else False
    root.forced = False
    root.trace_id = None
    _tl.cur = root
    # the ROOT is tracked separately from the current span: origin()
    # must name the enclosing statement from arbitrarily deep inside
    # its tree (spans carry no parent pointers), and the store-RPC
    # client fires from exactly there
    _tl.root = root
    return root


def end(root: Span) -> Span:
    root.end_ns = time.perf_counter_ns()
    if getattr(_tl, "cur", None) is root:
        _tl.cur = None
    if getattr(_tl, "root", None) is root:
        _tl.root = None
    return root


def current_root():
    return getattr(_tl, "cur", None)


def detach():
    """Suspend the thread's trace (internal bookkeeping sessions run
    inside a client statement but must not pollute its phase breakdown).
    -> opaque token for restore()."""
    token = (getattr(_tl, "cur", None), getattr(_tl, "root", None))
    _tl.cur = None
    _tl.root = None
    return token


def restore(token) -> None:
    _tl.cur, _tl.root = token


def propagate():
    """Opaque token naming the current span AND its statement root, for
    re-installation inside worker threads with `attached()` — the trace
    twin of runtime_stats.current() / memtrack.current() riding into
    the coprocessor fan-out. The root rides along so store RPCs issued
    from pool/stream workers still know which statement they originate
    from (origin())."""
    return (getattr(_tl, "cur", None), getattr(_tl, "root", None))


@contextlib.contextmanager
def attached(token):
    """Install a propagate() token (possibly None) as this thread's
    current span + root: spans the worker opens hang off the
    dispatching statement's tree. Child appends are GIL-atomic list
    ops, so concurrent workers may attach under one parent."""
    prev_cur = getattr(_tl, "cur", None)
    prev_root = getattr(_tl, "root", None)
    cur, root = token if token is not None else (None, None)
    _tl.cur = cur if cur is not None else prev_cur
    _tl.root = root if root is not None else prev_root
    try:
        yield
    finally:
        _tl.cur = prev_cur
        _tl.root = prev_root


class span:
    """Child span under the thread's current span; a no-op (still timed,
    but unattached) when no trace is active — internal sessions and
    worker threads pay one thread-local read. A plain slotted context
    manager, not @contextmanager: this sits on the per-statement and
    per-dispatch hot paths, and the generator machinery would double
    the disarmed cost (pinned <5us/statement by TestOverhead). The
    span opens in __init__ — legal because a `with` statement calls
    __enter__ immediately after evaluating the expression, with no
    user code in between; use only as `with trace.span(...)`."""

    __slots__ = ("_span", "_parent")

    def __init__(self, name: str, **tags):
        parent = getattr(_tl, "cur", None)
        s = Span(name, tags)
        self._span = s
        self._parent = parent
        if parent is not None:
            parent.children.append(s)
            _tl.cur = s

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb):
        self._span.end_ns = time.perf_counter_ns()
        if self._parent is not None:
            _tl.cur = self._parent
        return False


def active() -> bool:
    """True when the calling thread is inside a traced statement."""
    return getattr(_tl, "cur", None) is not None


def annotate(**tags) -> None:
    """Merge tags into the thread's CURRENT span without opening a child
    — safe from inside generators (a `with span(...)` wrapped around a
    `yield` would interleave restores with the consumer's own spans).
    Used by the streaming coprocessor to stamp per-stream frame/byte/
    stall counts onto the dispatching span. No-op untraced."""
    cur = getattr(_tl, "cur", None)
    if cur is not None:
        cur.tags.update(tags)


def event(name: str, **tags) -> None:
    """Point event on the thread's current span (no-op untraced): the
    call-site form for the device-plane recovery transitions."""
    cur = getattr(_tl, "cur", None)
    if cur is not None:
        cur.event(name, **tags)


def origin() -> dict | None:
    """Forward propagation context of the statement enclosing this
    thread: the fleet-unique trace id of its ROOT plus the retention
    flags, shipped inside traced store RPCs (store/remote.py request
    flags) so anything the store plane retains on its own — slow
    handler roots, forced traces — carries the originating statement's
    id and member instead of being unjoinable. None when untraced."""
    root = getattr(_tl, "root", None)
    if root is None:
        return None
    return {"trace_id": ensure_id(root),
            "sampled": bool(root.sampled),
            "forced": bool(root.forced),
            "member": _member().member_id()}


def attach_remote(d: dict) -> None:
    """Graft a span tree returned by another PROCESS (the storage node's
    side of an RPC — store/remote.py) under the current span. Remote
    clocks don't align, so only names/tags/durations carry over; the
    child is pinned at the current moment with its reported duration.
    Ref: the reference's cross-process span propagation
    (session.go:692 opentracing context over gRPC)."""
    parent = getattr(_tl, "cur", None)
    if parent is None:
        return

    def build(node: dict) -> Span:
        s = Span(node.get("name", "remote"), node.get("tags"))
        dur = int(node.get("duration_ns", 0))
        # end at "now" (the Span's birth instant), duration preserved
        s.end_ns, s.start_ns = s.start_ns, s.start_ns - dur
        for c in node.get("children", ()):
            s.children.append(build(c))
        return s

    parent.children.append(build(d))


def phase_ns(root: Span | None, name: str) -> int:
    """Sum of top-level child spans with `name` (a statement's parse /
    plan / execute / commit phase totals)."""
    if root is None:
        return 0
    return sum(c.duration_ns for c in root.children if c.name == name)


def log_tree(root: Span, sql: str) -> None:
    parts: list[str] = []

    def walk(s: Span, depth: int) -> None:
        parts.append("%s%s %.3fms %s" % (
            "  " * depth, s.name, s.duration_ns / 1e6,
            s.tags if s.tags else ""))
        for c in s.children:
            walk(c, depth + 1)

    walk(root, 0)
    log.info("trace for %r:\n%s", sql[:256], "\n".join(parts))


# -- sampling ----------------------------------------------------------------

_seq_lock = threading.Lock()
_stmt_seq = 0
_id_seq = 0

# lazy config binding: trace.py keeps zero package imports at module
# level (it loads before most of the package), and a per-statement
# `from tidb_tpu import config` would dominate the disarmed cost
_config = None


def _cfg():
    global _config
    if _config is None:
        from tidb_tpu import config
        _config = config
    return _config


_member_mod = None


def _member():
    global _member_mod
    if _member_mod is None:
        from tidb_tpu import member
        _member_mod = member
    return _member_mod


def _sample_next() -> bool:
    """Deterministic 1-in-N: the N-th, 2N-th, ... statement since
    process start (or reset) is sampled. One lock'd int increment per
    statement — the whole disarmed cost besides the skeleton spans the
    phase breakdown needs anyway."""
    n = _cfg().trace_sample()
    if n <= 0:
        return False
    global _stmt_seq
    with _seq_lock:
        _stmt_seq += 1
        return _stmt_seq % n == 0


def ensure_id(root: Span) -> int:
    """The root's FLEET-UNIQUE trace id, assigned on first need (the
    TRACE statement reads it before retention runs). The process's
    32-bit member start nonce (member.py) occupies the high bits over
    a 24-bit per-process sequence: two members minting concurrently
    never collide, a restarted member never reuses its predecessor's
    id space, and ids stay monotonic within one process — min_id
    filtering (ring_records) keeps working."""
    if root.trace_id is None:
        global _id_seq
        with _seq_lock:
            _id_seq += 1
            seq = _id_seq
        root.trace_id = (_member().nonce() << 24) | (seq & 0xFFFFFF)
    return root.trace_id


# -- the bounded, memtrack-billed trace ring ---------------------------------


class _Ring:
    """Finished trace records, newest last, bounded by count AND an
    estimated-bytes budget billed to a `trace-ring` memtrack SERVER
    node. The registered shed action clears the ring, so admission
    shedding / GET /shed reclaim retained trees."""

    def __init__(self):
        self._mu = threading.Lock()
        self._records: list[dict] = []    # guarded-by: _mu
        self._bytes = 0                   # guarded-by: _mu
        self._node = None                 # guarded-by: _mu (memtrack)

    def _tracker(self):
        """Lazy node creation (imports memtrack on first retention)."""
        from tidb_tpu import memtrack
        with self._mu:
            if self._node is None:
                self._node = memtrack.server_node("trace-ring")
                self._node.add_spill_action(self.shed)
            return self._node

    def append(self, rec: dict) -> None:
        node = self._tracker()
        # lint: exempt[paired-resource] ownership transfer: ring bytes release on evict (below) / shed / reset
        node.consume(host=rec["cost"])
        evicted = 0
        with self._mu:
            self._records.append(rec)
            self._bytes += rec["cost"]
            while len(self._records) > _RING_CAP or \
                    self._bytes > _RING_BYTES_CAP:
                old = self._records.pop(0)
                self._bytes -= old["cost"]
                evicted += old["cost"]
        if evicted:
            node.release(host=evicted)

    def shed(self) -> int:
        """Drop every retained record (the memtrack shed action).
        -> bytes freed."""
        with self._mu:
            freed = self._bytes
            self._records.clear()
            self._bytes = 0
            node = self._node
        if node is not None and freed:
            node.release(host=freed)
        return freed

    def get(self, trace_id: int) -> dict | None:
        with self._mu:
            for rec in self._records:
                if rec["trace_id"] == trace_id:
                    return rec
        return None

    def records(self, min_id: int = 0) -> list[dict]:
        with self._mu:
            return [r for r in self._records if r["trace_id"] > min_id]

    def snapshot(self) -> dict:
        with self._mu:
            return {"records": len(self._records), "bytes": self._bytes}


_RING = _Ring()


def _span_count(root: Span) -> int:
    n = 1
    for c in root.children:
        n += _span_count(c)
    return n


def finish_statement(root: Span, sql: str, error: str | None = None,
                     slow_ms: int | None = None,
                     origin: dict | None = None) -> int | None:
    """Retention decision for one ENDED statement root: keep the full
    tree in the ring when the statement was sampled, forced (TRACE), or
    ran past `tidb_tpu_slow_trace_ms`. -> trace id when retained, else
    None. The untraced path is one flag test + one sysvar read.
    `slow_ms` overrides the registry read — the session passes its
    shadowed (session-SET) value, captured while its overlay was still
    installed. `origin` is the forward-propagated context of a
    CROSS-PROCESS caller (trace.origin() shipped in store-RPC flags):
    the record's origin_trace_id/origin_member then name the SQL
    statement that caused this store-plane root, instead of defaulting
    to the local identity — the join key cluster_statement_traces and
    /fleet/trace search on."""
    if root.forced:
        reason = "forced"
    elif root.sampled:
        reason = "sampled"
    else:
        if slow_ms is None:
            slow_ms = _cfg().slow_trace_ms()
        if slow_ms <= 0 or root.duration_ns < slow_ms * 1_000_000:
            return None
        reason = "slow"
    dur_ns = root.duration_ns
    from tidb_tpu import metrics, perfschema
    tid = ensure_id(root)
    rec = {
        "trace_id": tid,
        "sql": sql[:512],
        "digest": perfschema.sql_digest(sql)[0],
        "start_unix": time.time() - dur_ns / 1e9,
        "duration_ns": dur_ns,
        "reason": reason,
        "error": error and error[:256],
        "span_count": _span_count(root),
        "origin_trace_id": int(origin["trace_id"]) if origin else tid,
        "origin_member": (origin.get("member") or "") if origin
        else _member().member_id(),
        "root": root,
    }
    rec["cost"] = rec["span_count"] * _SPAN_EST_BYTES + len(rec["sql"])
    _RING.append(rec)
    metrics.counter(metrics.TRACES, {"reason": reason})
    return tid


def ring_snapshot() -> list[dict]:
    """Summaries of retained traces, oldest first (the
    information_schema.statement_traces rows and GET /trace list)."""
    out = []
    for rec in _RING.records():
        out.append({k: rec[k] for k in
                    ("trace_id", "digest", "sql", "start_unix",
                     "duration_ns", "span_count", "reason", "error",
                     "origin_trace_id", "origin_member")})
    return out


def ring_records(min_id: int = 0) -> list[dict]:
    """Full retained records (bench attribution walks their trees)."""
    return _RING.records(min_id)


def ring_get(trace_id: int) -> dict | None:
    return _RING.get(trace_id)


def ring_stats() -> dict:
    return _RING.snapshot()


def reset_for_tests() -> None:
    """Clear the ring and the sampling counters (test isolation)."""
    global _stmt_seq, _id_seq
    _RING.shed()
    with _seq_lock:
        _stmt_seq = 0
        _id_seq = 0


# -- exports -----------------------------------------------------------------


def tree(root: Span, base_ns: int | None = None) -> dict:
    """Nested export of one span tree with start offsets: start_us is
    relative to the ROOT's start, so the JSON is self-contained and a
    still-open span (the TRACE statement snapshots its own live root)
    reads as closed at "now"."""
    base = root.start_ns if base_ns is None else base_ns

    def walk(s: Span) -> dict:
        d = {"name": s.name,
             "start_us": round((s.start_ns - base) / 1e3, 3),
             "duration_us": round(s.duration_ns / 1e3, 3)}
        if s.tags:
            d["tags"] = {k: v for k, v in s.tags.items()}
        if s.events:
            d["events"] = [
                {"name": n, "at_us": round((t - base) / 1e3, 3),
                 **({"tags": tg} if tg else {})}
                for n, t, tg in s.events]
        if s.children:
            d["children"] = [walk(c) for c in s.children]
        return d

    return walk(root)


def validate(root: Span) -> list[str]:
    """Structural problems of a FINISHED tree: begin-without-end spans
    and negative durations (the balance check the trace bench and the
    TRACE tests assert empty)."""
    problems: list[str] = []

    def walk(s: Span) -> None:
        if not s.end_ns:
            problems.append(f"span {s.name!r} has no end (begin "
                            f"without end)")
        elif s.end_ns < s.start_ns:
            problems.append(f"span {s.name!r} ends before it starts")
        for c in s.children:
            walk(c)

    walk(root)
    return problems


# the bench attribution's phase buckets: span names summed per trace.
# "other" is the statement remainder — with no cross-thread overlap the
# per-trace phase sum equals the statement duration exactly.
_PHASE_SPANS = {
    "parse": ("parse",),
    "plan": ("plan",),
    "admission_wait": ("admission",),
    "sched_stall": ("sched.slot",),
    "device_dispatch": ("dispatch",),
    "finalize": ("finalize",),
    "host_fallback": ("host.fallback",),
    "commit": ("commit",),
}


def phases_of(root: Span) -> dict:
    """Per-phase nanosecond sums for one finished statement tree — the
    latency-attribution input (bench serve/chaos blocks, ROADMAP item
    2's p99 breakdown). Spans sum BY NAME across the whole tree (pool
    workers included), so concurrent workers can push a phase past the
    wall-clock statement time; "other" floors at zero."""
    sums: dict[str, int] = {}

    def walk(s: Span) -> None:
        sums[s.name] = sums.get(s.name, 0) + s.duration_ns
        for c in s.children:
            walk(c)

    for c in root.children:
        walk(c)
    out = {phase: sum(sums.get(n, 0) for n in names)
           for phase, names in _PHASE_SPANS.items()}
    total = root.duration_ns
    out["total"] = total
    out["other"] = max(0, total - sum(
        v for k, v in out.items() if k != "total"))
    return out


def to_chrome(rec: dict) -> dict:
    """Chrome trace-event JSON for one retained record: complete ("X")
    events per span in µs relative to the root, instant ("i") events
    for the recovery transitions, one lane per OS thread — load it in
    Perfetto / chrome://tracing to SEE dispatch-ahead depth, slot waits
    and finalize serialization across the statement's threads."""
    root: Span = rec["root"]
    base = root.start_ns
    events = [{"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
               "args": {"name": f"tidb-tpu trace {rec['trace_id']}"}}]

    def walk(s: Span) -> None:
        ev = {"ph": "X", "pid": 1, "tid": s.tid, "name": s.name,
              "cat": "statement",
              "ts": round((s.start_ns - base) / 1e3, 3),
              "dur": round(s.duration_ns / 1e3, 3)}
        if s.tags:
            ev["args"] = {k: str(v) for k, v in s.tags.items()}
        events.append(ev)
        for n, t, tg in s.events or ():
            ie = {"ph": "i", "pid": 1, "tid": s.tid, "name": n,
                  "cat": "fault", "s": "t",
                  "ts": round((t - base) / 1e3, 3)}
            if tg:
                ie["args"] = {k: str(v) for k, v in tg.items()}
            events.append(ie)
        for c in s.children:
            walk(c)

    walk(root)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": rec["trace_id"],
                          "sql": rec["sql"],
                          "digest": rec["digest"],
                          "reason": rec["reason"]}}
