"""Non-transactional raw KV client.

Reference: /root/reference/store/tikv/rawkv.go — RawGet/Put/Delete/
BatchGet/BatchPut/Scan/DeleteRange routed per region with the same
region-cache + backoff machinery as the transactional client, but no
timestamps, no locks, no MVCC. The raw namespace lives beside the
transactional one in the storage engine (mockstore/mvcc.py raw_*),
mirroring TiKV's separate raw column family."""

from __future__ import annotations

from tidb_tpu.kv import NotLeaderError, RegionError, ServerBusyError
from tidb_tpu.store.backoff import (BO_REGION_MISS, BO_SERVER_BUSY,
                                    Backoffer, GET_MAX_BACKOFF,
                                    SCAN_MAX_BACKOFF)

__all__ = ["RawKVClient"]

_SCAN_BATCH = 256


class RawKVClient:
    """Raw ops over a storage's region topology (works against both the
    in-process MockStorage and the out-of-process RemoteStorage — the
    shim methods ride the same RPC surface)."""

    def __init__(self, storage):
        self.cache = storage.region_cache
        self.shim = storage.shim

    # -- single key ----------------------------------------------------------

    def _one_key(self, key: bytes, fn_name: str, *args):
        bo = Backoffer(GET_MAX_BACKOFF)
        while True:
            loc = self.cache.locate(key)
            try:
                return getattr(self.shim, fn_name)(loc.ctx, key, *args)
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)

    def get(self, key: bytes):
        return self._one_key(key, "raw_get")

    def put(self, key: bytes, value: bytes) -> None:
        self._one_key(key, "raw_put", value)

    def delete(self, key: bytes) -> None:
        self._one_key(key, "raw_delete")

    # -- batches (group by region, retry the failed groups) ------------------

    def _grouped(self, keys, run):
        bo = Backoffer(GET_MAX_BACKOFF)
        pending = list(keys)
        while pending:
            groups = self.cache.group_keys_by_region(
                [k if isinstance(k, bytes) else k[0] for k in pending])
            by_key = {(k if isinstance(k, bytes) else k[0]): k
                      for k in pending}
            pending = []
            for _rid, (loc, ks) in groups.items():
                items = [by_key[k] for k in ks]
                try:
                    run(loc, items)
                except NotLeaderError as e:
                    self.cache.on_not_leader(e)
                    bo.backoff(BO_REGION_MISS, e)
                    pending.extend(items)
                except RegionError as e:
                    self.cache.invalidate(loc.region.id)
                    bo.backoff(BO_REGION_MISS, e)
                    pending.extend(items)
                except ServerBusyError as e:
                    bo.backoff(BO_SERVER_BUSY, e)
                    pending.extend(items)

    def batch_get(self, keys: list[bytes]) -> dict:
        out: dict = {}
        self._grouped(keys, lambda loc, ks: out.update(
            self.shim.raw_batch_get(loc.ctx, ks)))
        return out

    def batch_put(self, pairs: list[tuple]) -> None:
        self._grouped(pairs, lambda loc, items: self.shim.raw_batch_put(
            loc.ctx, items))

    # -- ranges --------------------------------------------------------------

    def scan(self, start: bytes, end: bytes = b"",
             limit: int = _SCAN_BATCH) -> list[tuple]:
        """Up to `limit` pairs in [start, end), crossing region
        boundaries (ref: rawkv.go Scan)."""
        out: list[tuple] = []
        cur = start
        bo = Backoffer(SCAN_MAX_BACKOFF)
        while len(out) < limit:
            loc = self.cache.locate(cur)
            try:
                part = self.shim.raw_scan(loc.ctx, cur, end,
                                          limit - len(out))
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
                continue
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                continue
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)
                continue
            out.extend(part)
            rend = loc.region.end
            if not rend or (end and rend >= end):
                break
            cur = rend
        return out

    def delete_range(self, start: bytes, end: bytes) -> None:
        bo = Backoffer(SCAN_MAX_BACKOFF)
        cur = start
        while True:
            loc = self.cache.locate(cur)
            try:
                self.shim.raw_delete_range(loc.ctx, cur, end)
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
                continue
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                continue
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)
                continue
            rend = loc.region.end
            if not rend or (end and rend >= end):
                return
            cur = rend