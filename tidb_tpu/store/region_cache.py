"""Client-side region cache: key -> region routing with invalidation.

Reference: /root/reference/store/tikv/region_cache.go:49,137,200,326 —
sorted-key lookup, miss -> PD load, invalidation on region errors, leader
switch on NotLeader, GroupKeysByRegion for 2PC batching.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from tidb_tpu.util.sorteddict import SortedDict

from tidb_tpu.kv import KVRange, NotLeaderError
from tidb_tpu.mockstore.cluster import Cluster, Region
from tidb_tpu.mockstore.rpc import RegionCtx

__all__ = ["RegionCache", "KeyLocation"]


@dataclass
class KeyLocation:
    region: Region
    ctx: RegionCtx


class RegionCache:
    """Caches Region objects; the Cluster plays PD for cache misses.

    Insertion evicts STALE OVERLAPS (after a split, the old wide region
    overlaps both halves; ref: region_cache.go:326 insertRegionToCache
    dropping intersecting items) and is epoch-aware: an older
    (version, conf_ver) never replaces a newer cached epoch. An
    id -> start index keeps invalidation O(log n) under churn with
    thousands of regions."""

    def __init__(self, pd: Cluster):
        self.pd = pd
        self._mu = threading.RLock()
        self._by_start: SortedDict[bytes, Region] = \
            SortedDict()                     # guarded-by: _mu
        self._start_by_id: dict[int, bytes] = {}   # guarded-by: _mu
        # region_id -> learned leader store
        self._leaders: dict[int, int] = {}         # guarded-by: _mu

    def _ctx(self, r: Region) -> RegionCtx:
        leader = self._leaders.get(r.id, r.leader_store)
        return RegionCtx(r.id, r.version, r.conf_ver, leader)

    def _insert(self, r: Region) -> None:
        """Called under _mu. Evict every cached region intersecting
        [r.start, r.end) unless it carries a NEWER epoch (in which case
        the incoming region is the stale one and is dropped)."""
        # walk left to the first region that could overlap, then right
        idx = max(self._by_start.bisect_right(r.start) - 1, 0)
        keys = self._by_start.keys()
        stale = []
        i = idx
        while i < len(keys):
            cur = self._by_start[keys[i]]
            if r.end and cur.start >= r.end:
                break
            overlaps = (not cur.end or cur.end > r.start) and \
                (not r.end or cur.start < r.end)
            if overlaps:
                if (cur.version, cur.conf_ver) > (r.version, r.conf_ver):
                    return          # incoming region is older news
                if cur.id != r.id or cur.start != r.start:
                    stale.append(cur)
            i += 1
        for cur in stale:
            del self._by_start[cur.start]
            self._start_by_id.pop(cur.id, None)
            self._leaders.pop(cur.id, None)
        old_start = self._start_by_id.get(r.id)
        if old_start is not None and old_start != r.start and \
                old_start in self._by_start and \
                self._by_start[old_start].id == r.id:
            del self._by_start[old_start]
        self._by_start[r.start] = r
        self._start_by_id[r.id] = r.start

    def locate(self, key: bytes) -> KeyLocation:
        with self._mu:
            idx = self._by_start.bisect_right(key) - 1
            if idx >= 0:
                r = self._by_start.values()[idx]
                if r.contains(key):
                    return KeyLocation(r, self._ctx(r))
            r = self.pd.region_by_key(key)  # "PD RPC"
            self._insert(r)
            return KeyLocation(r, self._ctx(r))

    def invalidate(self, region_id: int) -> None:
        with self._mu:
            start = self._start_by_id.pop(region_id, None)
            if start is not None and start in self._by_start and \
                    self._by_start[start].id == region_id:
                del self._by_start[start]
            self._leaders.pop(region_id, None)

    def invalidate_all(self) -> None:
        """Drop every cached epoch and learned leader. Fired when a
        store-plane connection is lost (store/remote.py disconnect
        listener): the plane we reconnect to may have split/moved
        regions while we were gone, and resuming with stale epochs
        loops on ER_REGION_STREAM_INTERRUPTED instead of re-resolving."""
        with self._mu:
            self._by_start.clear()
            self._start_by_id.clear()
            self._leaders.clear()

    def on_not_leader(self, err: NotLeaderError) -> None:
        """Switch leader in place when the error names one, else invalidate.
        Ref: region_cache.go UpdateLeader."""
        with self._mu:
            if err.leader_store is not None:
                self._leaders[err.region_id] = err.leader_store
            else:
                self.invalidate(err.region_id)

    def group_keys_by_region(self, keys: list[bytes]) -> dict[int, tuple[KeyLocation, list[bytes]]]:
        """Ref: region_cache.go:200 GroupKeysByRegion."""
        groups: dict[int, tuple[KeyLocation, list[bytes]]] = {}
        for k in sorted(keys):
            loc = self.locate(k)
            if loc.region.id not in groups:
                groups[loc.region.id] = (loc, [])
            groups[loc.region.id][1].append(k)
        return groups

    def split_ranges_by_region(self, ranges: list[KVRange]
                               ) -> list[tuple[KeyLocation, KVRange]]:
        """Split [start, end) ranges along region boundaries, in key order.
        Ref: store/tikv/coprocessor.go:263 buildCopTasks."""
        out = []
        for rg in ranges:
            cur = rg.start
            while True:
                loc = self.locate(cur)
                r_end = loc.region.end
                if r_end and (not rg.end or r_end < rg.end):
                    out.append((loc, KVRange(cur, r_end)))
                    cur = r_end
                else:
                    out.append((loc, KVRange(cur, rg.end)))
                    break
        return out
