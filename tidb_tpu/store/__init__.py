from tidb_tpu.store.storage import MockStorage, new_mock_storage

__all__ = ["MockStorage", "new_mock_storage"]
