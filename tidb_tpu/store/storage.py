"""Storage facade: composes cluster, engine, rpc shim, caches, oracle.

Reference: /root/reference/store/tikv/kv.go:138-157 (tikvStore composition)
and test_util.go:122 NewMockTikvStore.
"""

from __future__ import annotations

from tidb_tpu import kv
from tidb_tpu.mockstore.cluster import Cluster
from tidb_tpu.mockstore.mvcc import MVCCStore
from tidb_tpu.mockstore.rpc import RPCShim
from tidb_tpu.store.oracle import PDOracle
from tidb_tpu.store.region_cache import RegionCache
from tidb_tpu.store.txn import KVTxn, LockResolver, TxnSnapshot

__all__ = ["MockStorage", "new_mock_storage"]


class MockStorage(kv.Storage):
    """In-process distributed-store simulation behind the kv.Storage API."""

    def __init__(self, cluster: Cluster, engine: MVCCStore):
        self.cluster = cluster
        self.engine = engine
        self.shim = RPCShim(cluster, engine)
        self.region_cache = RegionCache(cluster)
        self.oracle = PDOracle(cluster)
        self.resolver = LockResolver(self.shim, self.region_cache, self.oracle)
        self.async_commit_secondaries = True
        self._client = None
        self.safepoint = 0   # GC safepoint (ref: safepoint.go watcher)
        # storage-node columnar cache for the coprocessor read path
        from tidb_tpu.store.chunk_cache import ChunkCache
        self.chunk_cache = ChunkCache()
        # HBM-resident region-block cache: the device-side tier of the
        # same hierarchy (store/device_cache.py) — fused agg dispatches
        # read cached blocks straight from device memory
        from tidb_tpu.store.device_cache import DeviceCache
        self.device_cache = DeviceCache()
        # MVCC delta store (store/delta.py): committed row mutations
        # journal here (the engine calls ingest under its lock) and
        # both cache tiers serve base ⋈ delta instead of re-colding on
        # every OLTP write
        from tidb_tpu.store.delta import DeltaStore
        self.delta_store = DeltaStore(self)
        engine.set_delta_sink(self.delta_store)
        # the journal-window command serves remote fleet caches from
        # this node's delta store; the shim only holds cluster+engine
        self.shim.bind_storage(self)

    def begin(self, start_ts: int | None = None) -> KVTxn:
        return KVTxn(self, start_ts if start_ts is not None
                     else self.oracle.get_timestamp())

    def snapshot(self, ts: int) -> TxnSnapshot:
        return TxnSnapshot(self.shim, self.region_cache, self.resolver, ts,
                           storage=self)

    def update_safepoint(self, sp: int) -> None:
        self.safepoint = max(self.safepoint, sp)

    def check_visibility(self, ts: int) -> None:
        """Reject snapshots the GC may already have pruned under
        (ref: tikvStore.CheckVisibility)."""
        if ts < self.safepoint:
            raise kv.GCTooEarlyError(
                f"snapshot ts {ts} is below GC safepoint {self.safepoint}")

    def current_ts(self) -> int:
        return self.oracle.get_timestamp()

    def client(self):
        """Coprocessor client; installed by tidb_tpu.store.copr."""
        if self._client is None:
            from tidb_tpu.store.copr import CopClient
            self._client = CopClient(self)
        return self._client

    def close(self) -> None:
        self.oracle.close()
        # return the HBM cache's and delta journal's ledger shares
        # eagerly (GC would, later)
        self.device_cache.shed()
        self.delta_store.close()


def new_mock_storage(num_stores: int = 1) -> MockStorage:
    """Hermetic store for tests (ref: NewMockTikvStore)."""
    cluster = Cluster()
    cluster.bootstrap(num_stores)
    return MockStorage(cluster, MVCCStore())
