"""Typed exponential backoff with jitter and per-request sleep budgets.

Reference: /root/reference/store/tikv/backoff.go:80-126 — per-cause configs
{tikvRPC, TxnLock, RegionMiss, PDRPC, ServerBusy}, total-sleep caps per
request type, forkable contexts for parallel batches (2pc.go:267-289).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

__all__ = ["BackoffConfig", "Backoffer", "BackoffExhausted",
           "BO_RPC", "BO_TXN_LOCK", "BO_REGION_MISS", "BO_SERVER_BUSY",
           "GET_MAX_BACKOFF", "SCAN_MAX_BACKOFF", "COP_MAX_BACKOFF",
           "PREWRITE_MAX_BACKOFF", "COMMIT_MAX_BACKOFF"]


class BackoffExhausted(Exception):
    def __init__(self, cause: str, total_ms: int, errors: list):
        super().__init__(f"backoff budget exhausted after {total_ms}ms "
                         f"(last cause: {cause}); errors: {errors[-3:]}")
        self.errors = errors


@dataclass(frozen=True)
class BackoffConfig:
    name: str
    base_ms: int
    cap_ms: int
    # jitter styles: "full" = U(0, current), "equal" = current/2 + U(0, current/2)
    jitter: str = "full"


BO_RPC = BackoffConfig("rpc", 100, 2000, "equal")
BO_TXN_LOCK = BackoffConfig("txnLock", 200, 3000, "equal")
BO_REGION_MISS = BackoffConfig("regionMiss", 2, 500, "full")
BO_SERVER_BUSY = BackoffConfig("serverBusy", 2000, 10000, "equal")

# per-request budgets (ms). Ref: backoff.go:100-126
GET_MAX_BACKOFF = 20_000
SCAN_MAX_BACKOFF = 20_000
COP_MAX_BACKOFF = 20_000
PREWRITE_MAX_BACKOFF = 20_000
COMMIT_MAX_BACKOFF = 41_000


class Backoffer:
    """Tracks cumulative sleep across retries of one logical request."""

    def __init__(self, max_sleep_ms: int, sleep_fn=time.sleep):
        self.max_sleep_ms = max_sleep_ms
        self.total_ms = 0
        self.errors: list = []
        self._attempts: dict[str, int] = {}
        self._sleep = sleep_fn

    def backoff(self, cfg: BackoffConfig, err: Exception) -> None:
        """Sleep per cfg; raise BackoffExhausted past the budget."""
        self.errors.append(err)
        n = self._attempts.get(cfg.name, 0)
        self._attempts[cfg.name] = n + 1
        cur = min(cfg.base_ms * (2 ** n), cfg.cap_ms)
        if cfg.jitter == "full":
            ms = random.uniform(0, cur)
        else:
            ms = cur / 2 + random.uniform(0, cur / 2)
        self.total_ms += ms
        if self.total_ms > self.max_sleep_ms:
            raise BackoffExhausted(cfg.name, int(self.total_ms), self.errors)
        self._sleep(ms / 1000.0)

    def fork(self) -> "Backoffer":
        """Child with the remaining budget (ref: Backoffer.Fork)."""
        b = Backoffer(self.max_sleep_ms - int(self.total_ms), self._sleep)
        return b
