"""Columnar region-chunk cache: decode KV rows into columns once.

The reference decodes row bytes into Datums on every coprocessor request
(/root/reference/store/tikv/mocktikv/executor.go row loop; TiKV does the
same server-side). Repeated analytical scans — the HTAP read pattern this
framework is built for — re-pay that decode on every query. Here the
storage side keeps the DECODED columnar chunk per (region, column-layout,
range) and serves subsequent scans straight from it: the TPU-first
analogue of TiFlash's columnar replica, collapsed into the storage node.

MVCC correctness: an entry records the engine state version and the fill
snapshot ts. It is served only when
  * the engine's data_version is unchanged (data_version bumps on EVERY
    state change — prewrite/commit/rollback/lock ops/GC/delete-range —
    so a pending lock forces the real scan path, which raises
    KeyLockedError for resolution exactly as an uncached read would), and
  * read_ts >= fill_ts (with no state change since the fill, any newer
    snapshot sees byte-identical data; an OLDER snapshot may not).
The filler must additionally guarantee fill_ts covers every commit in the
store (store/copr.py checks MVCCStore.max_commit_ts): a long-running old
snapshot's scan is correct for ITS ts but would poison newer readers if
cached. Transaction-local dirty reads never reach the coprocessor path at
all (executor TableReaderExec falls back to the union store).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

__all__ = ["ChunkCache"]


def _chunk_bytes(chunk) -> int:
    """Estimated host footprint: numpy buffers at their real size, object
    (string) columns at pointer + payload length."""
    total = 0
    for c in chunk.columns:
        data = c.data
        if getattr(data, "dtype", None) is not None and \
            data.dtype != object:
            total += data.nbytes
        else:
            total += 8 * len(data)
            total += sum(len(x) for x in data
                         if isinstance(x, (str, bytes)))
        total += len(c.valid)          # bool mask
    return total


class ChunkCache:
    """LRU over decoded region chunks, bounded by estimated BYTES (rows
    alone under-count wide/string layouts by orders of magnitude).

    The budget must hold every layout a hot analytical mix scans —
    entries are keyed per column layout, so one table queried three ways
    costs three entries. Undersizing is silent but expensive: each
    evicted layout re-decodes AND re-uploads to HBM every execution
    (device chunks are memoized on the cached chunk objects)."""

    def __init__(self, max_bytes: int = 4 << 30):
        self.max_bytes = max_bytes
        self._mu = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(region, plan, s: bytes, e: bytes):
        return (region.id, region.version, plan.table.id,
                plan.index.id if plan.index is not None else None,
                tuple(c.id for c in plan.cols), plan.handle_col, s, e)

    def get(self, key, data_version: int, read_ts: int):
        hit = self.lookup(key, data_version, read_ts)
        return None if hit is None else hit[1]

    def peek(self, key, data_version: int, read_ts: int) -> int | None:
        """Would lookup() hit? -> the entry's budgeted size in bytes, or
        None on a miss. No stats bump, no LRU reorder, no stale drop —
        for route decisions (e.g. the streaming producer picking the
        served-from-residency shape, sized against its frame cap) whose
        real lookup follows and does the counting."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or ent[0] != data_version or read_ts < ent[1]:
                return None
            return ent[3]

    def lookup(self, key, data_version: int, read_ts: int):
        """Like get() but returns (fill_ts, chunk): the entry's fill
        snapshot rides along so derived caches (the HBM device cache)
        can record the SAME validity window as the host entry."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            fill_version, fill_ts, chunk = ent[0], ent[1], ent[2]
            if fill_version != data_version or read_ts < fill_ts:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return fill_ts, chunk

    def put(self, key, data_version: int, fill_ts: int, chunk) -> None:
        size = _chunk_bytes(chunk)
        if size > self.max_bytes:
            return
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[3]
            self._entries[key] = (data_version, fill_ts, chunk, size)
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _k, (_v, _t, _ch, sz) = self._entries.popitem(last=False)
                self._bytes -= sz

    def add_cost(self, key, extra: int) -> None:
        """Charge derived data (e.g. memoized filter results riding the
        cached chunk) to the entry's budget share."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return
            self._entries[key] = (ent[0], ent[1], ent[2], ent[3] + extra)
            self._bytes += extra
            while self._bytes > self.max_bytes and self._entries:
                _k, (_v, _t, _ch, sz) = self._entries.popitem(last=False)
                self._bytes -= sz

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bytes = 0
