"""Columnar region-chunk cache: decode KV rows into columns once.

The reference decodes row bytes into Datums on every coprocessor request
(/root/reference/store/tikv/mocktikv/executor.go row loop; TiKV does the
same server-side). Repeated analytical scans — the HTAP read pattern this
framework is built for — re-pay that decode on every query. Here the
storage side keeps the DECODED columnar chunk per (region, column-layout,
range) and serves subsequent scans straight from it: the TPU-first
analogue of TiFlash's columnar replica, collapsed into the storage node.

MVCC correctness — the (fill_version, fill_ts, delta_watermark)
freshness contract. An entry records the engine's STRUCTURAL state
version and the fill snapshot ts, and is served only when
  * the engine's data_version is unchanged. The version now bumps only
    on structural changes (meta/DDL writes, GC, delete-range, bulk
    import, anything outside the record/index key namespaces): with the
    delta store active (store/delta.py), committed ROW mutations are
    journaled per table instead, and the serve path (store/copr.py)
    applies the journal window (fill_ts, read_ts] on top of the cached
    base — base + delta — rather than discarding the entry. Pending
    Percolator locks are handled by a serve-time range veto
    (MVCCStore.locked_in_range): a lock a reader must observe forces
    the real scan path, which raises KeyLockedError for resolution
    exactly as an uncached read would; and
  * read_ts >= fill_ts (the base reflects every commit up to fill_ts;
    an OLDER snapshot must not see them).
The filler must additionally guarantee fill_ts covers every commit in the
store (store/copr.py checks MVCCStore.max_commit_ts): a long-running old
snapshot's scan is correct for ITS ts but would poison newer readers if
cached — and every commit AFTER fill_ts is then either in the journal
(record keys) or bumps the version (everything else), so 'base at
fill_ts plus journal window' is exact. Transaction-local dirty reads
never reach the coprocessor path at all (executor TableReaderExec falls
back to the union store).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

__all__ = ["ChunkCache"]


def _chunk_bytes(chunk) -> int:
    """Estimated host footprint: numpy buffers at their real size, object
    (string) columns at pointer + payload length."""
    total = 0
    for c in chunk.columns:
        data = c.data
        if getattr(data, "dtype", None) is not None and \
            data.dtype != object:
            total += data.nbytes
        else:
            total += 8 * len(data)
            total += sum(len(x) for x in data
                         if isinstance(x, (str, bytes)))
        total += len(c.valid)          # bool mask
    return total


class ChunkCache:
    """LRU over decoded region chunks, bounded by estimated BYTES (rows
    alone under-count wide/string layouts by orders of magnitude).

    The budget must hold every layout a hot analytical mix scans —
    entries are keyed per column layout, so one table queried three ways
    costs three entries. Undersizing is silent but expensive: each
    evicted layout re-decodes AND re-uploads to HBM every execution
    (device chunks are memoized on the cached chunk objects)."""

    def __init__(self, max_bytes: int = 4 << 30):
        self.max_bytes = max_bytes
        self._mu = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(region, plan, s: bytes, e: bytes):
        return (region.id, region.version, plan.table.id,
                plan.index.id if plan.index is not None else None,
                tuple(c.id for c in plan.cols), plan.handle_col, s, e)

    @staticmethod
    def _fresh(ent, data_version: int, read_ts: int) -> bool:
        """THE freshness predicate, shared by peek() and lookup() (and
        mirrored by the delta-aware serve path in store/copr.py): an
        entry serves a reader iff its fill version matches the engine's
        structural data_version AND the reader's snapshot is at/after
        the fill snapshot. Committed row writes no longer bump the
        version (store/delta.py journals them instead), so 'fresh' here
        means 'fresh up to fill_ts' — the serve path then applies the
        journal window (fill_ts, read_ts] on top."""
        return ent[0] == data_version and read_ts >= ent[1]

    def get(self, key, data_version: int, read_ts: int):
        hit = self.lookup(key, data_version, read_ts)
        return None if hit is None else hit[1]

    def peek(self, key, data_version: int, read_ts: int) -> int | None:
        """Would lookup() hit? -> the entry's budgeted size in bytes, or
        None on a miss. No stats bump, no LRU reorder, no stale drop —
        for route decisions (e.g. the streaming producer picking the
        served-from-residency shape, sized against its frame cap) whose
        real lookup follows and does the counting."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or not self._fresh(ent, data_version, read_ts):
                return None
            return ent[3]

    def entry_state(self, key):
        """(fill_version, fill_ts) of the resident entry, or None —
        freshness is NOT checked and no stats/LRU effects apply. The
        fleet read path (store/fleetcop.py) uses this to prime one
        journal-window RPC with the entry's own fill snapshot before
        deciding whether the block is patchable in place."""
        with self._mu:
            ent = self._entries.get(key)
            return None if ent is None else (ent[0], ent[1])

    def lookup(self, key, data_version: int, read_ts: int):
        """Like get() but returns (fill_ts, chunk): the entry's fill
        snapshot rides along so derived caches (the HBM device cache)
        can record the SAME validity window as the host entry."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            if not self._fresh(ent, data_version, read_ts):
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[1], ent[2]

    def put(self, key, data_version: int, fill_ts: int, chunk) -> None:
        size = _chunk_bytes(chunk)
        if size > self.max_bytes:
            return
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[3]
            self._entries[key] = (data_version, fill_ts, chunk, size)
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _k, (_v, _t, _ch, sz) = self._entries.popitem(last=False)
                self._bytes -= sz

    def add_cost(self, key, extra: int) -> None:
        """Charge derived data (e.g. memoized filter results riding the
        cached chunk) to the entry's budget share."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return
            self._entries[key] = (ent[0], ent[1], ent[2], ent[3] + extra)
            self._bytes += extra
            while self._bytes > self.max_bytes and self._entries:
                _k, (_v, _t, _ch, sz) = self._entries.popitem(last=False)
                self._bytes -= sz

    def drop(self, key, if_chunk=None) -> None:
        """Remove one entry (delta-staleness invalidation: an index
        scan whose table took index-key commits, or a base whose
        journal window was truncated under it). With `if_chunk`, drop
        only while the entry still holds that exact chunk — a reader
        invalidating a lagging base must not discard the fresher merged
        base a concurrent merge just promoted into the slot."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or (if_chunk is not None and
                               ent[2] is not if_chunk):
                return
            self._entries.pop(key)
            self._bytes -= ent[3]

    def snapshot_table(self, table_id: int) -> list:
        """[(key, fill_version, fill_ts, chunk)] for every entry of one
        table — the delta store's merge walks this to fold staged
        deltas into new base blocks. Cache keys embed the table id at
        position 2 (see key())."""
        with self._mu:
            return [(k, ent[0], ent[1], ent[2])
                    for k, ent in self._entries.items()
                    if k[2] == table_id]

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bytes = 0
