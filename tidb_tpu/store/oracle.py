"""Timestamp oracle (TSO).

Reference: /root/reference/store/tikv/oracle/oracle.go:23-35 — Oracle
{GetTimestamp(Async), IsExpired}; hybrid ts = physical_ms << 18 | logical;
impls oracles/pd.go (batched from PD) and oracles/local.go (tests).
Here the Cluster plays PD; async prefetch uses a single worker thread
(the reference prefetches the commit/start ts while parsing, session.go:1198).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

__all__ = ["Oracle", "PDOracle", "LocalOracle", "physical_ms", "compose_ts",
           "retention_ts"]


def physical_ms(ts: int) -> int:
    """Physical milliseconds of a hybrid timestamp."""
    return ts >> 18


def compose_ts(ms: int, logical: int = 0) -> int:
    return (ms << 18) | logical


def retention_ts(retain_ms: int) -> int:
    """Hybrid timestamp `retain_ms` behind the wall clock. The TSO is
    wall-clock-ms based, so a store-plane merge clamping its journal
    floor to this keeps a pull window open for remote fleet caches
    whose fill snapshots are at most `retain_ms` old."""
    return compose_ts(max(0, int(time.time() * 1000) - retain_ms))


class Oracle:
    def get_timestamp(self) -> int:
        raise NotImplementedError

    def get_timestamp_async(self) -> Future:
        raise NotImplementedError

    def is_expired(self, lock_ts: int, ttl_ms: int) -> bool:
        phys = self.get_timestamp() >> 18
        return phys >= (lock_ts >> 18) + ttl_ms

    def close(self) -> None:
        pass


class PDOracle(Oracle):
    """TSO from the (mock) PD = Cluster."""

    def __init__(self, pd):
        self.pd = pd
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="tso")

    def get_timestamp(self) -> int:
        return self.pd.tso()

    def get_timestamp_async(self) -> Future:
        return self._pool.submit(self.pd.tso)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class LocalOracle(Oracle):
    """Process-local clock oracle for unit tests (oracles/local.go)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._last_phys = 0
        self._logical = 0

    def get_timestamp(self) -> int:
        with self._mu:
            ms = int(time.time() * 1000)
            if ms > self._last_phys:
                self._last_phys = ms
                self._logical = 0
            self._logical += 1
            return (self._last_phys << 18) | self._logical

    def get_timestamp_async(self) -> Future:
        f: Future = Future()
        f.set_result(self.get_timestamp())
        return f
