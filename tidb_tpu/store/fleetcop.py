"""Fleet-side coprocessor serving: a stateless SQL server's OWN caches.

In fleet mode (store/remote.py `connect(..., local_cache=True)`) each
SQL-server process keeps its own columnar chunk cache and HBM device
cache, exactly the hierarchy a storage node runs (store/copr.py
exec_cached_cop). What a remote process cannot see is the store plane's
engine state — so before serving a region task locally it issues ONE
journal-window RPC (mockstore/rpc.py `journal_window`, Cmd 80) that
returns the engine's freshness meta (data_version / max_commit_ts /
lock state) plus the delta-journal window (fill_ts, read_ts] for the
task's range. The reply primes lookalike views of the engine and the
delta store, and the UNCHANGED cached-serve path runs against them:

  * resident block + empty window        -> serve as-is
  * resident block + shipped window      -> patch in place (base ⋈ delta,
                                            store/delta.py semantics)
  * journal truncated under the fill     -> STALE: drop, re-scan remotely
  * no resident block                    -> remote kv_scan fills the local
                                            cache (MVCC fill conditions
                                            re-checked against the meta)

MVCC correctness is the single-process argument verbatim: data_version
and max_commit_ts are sampled (via the meta RPC) BEFORE any scan, row
commits landing after the sample carry commit_ts > start_ts and ride
the journal, structural writes bump the version so the filled entry can
never serve a newer reader. A reader at T applies only deltas with
commit_ts <= T — the window the RPC ships is exactly (fill_ts, T].
Region epoch is checked by the store plane on the journal-window RPC
itself, so split/truncation races surface as RegionError into the
coprocessor client's existing re-locate/retry loop.
"""

from __future__ import annotations

from tidb_tpu import config, metrics
from tidb_tpu.store import copr
from tidb_tpu.store import delta as deltamod

__all__ = ["exec_local"]


def _decode_wire_delta(d):
    """Wire-native journal window -> delta.py's read-side vocabulary.
    The STALE sentinel cannot cross the wire; it travels as "stale"."""
    if d is None:
        return None
    if d == "stale":
        return deltamod.STALE
    _tag, watermark, upsert_rows, upsert_handles, delete_handles = d
    return deltamod.PendingDelta(watermark, list(upsert_rows),
                                 upsert_handles, delete_handles)


def _pull_outcome(meta, fill_ts) -> str:
    if fill_ts is None:
        return "meta"
    d = meta.get("delta")
    if d == "stale" or meta.get("index_stale"):
        return "stale"
    return "empty" if d is None else "window"


class _EngineView:
    """MVCCStore-lookalike over one meta sample + remote scans. The
    freshness fields are frozen at the RPC: that IS the version-sampled-
    before-scan discipline of the local path."""

    def __init__(self, shim, ctx, meta):
        self._shim = shim
        self._ctx = ctx
        self.data_version = meta["data_version"]
        self.max_commit_ts = meta["max_commit_ts"]
        # truthiness is all the serve path consults (cacheable veto)
        self._locked_keys = ("remote",) if meta["any_locks"] else ()
        self._range_locked = meta["locked"]

    def locked_in_range(self, s, e, ts) -> bool:
        return self._range_locked

    def scan(self, start, end, limit, ts, isolation, desc=False):
        # KeyLockedError raised store-side rides the wire typed and
        # reaches the cop client's resolve loop unchanged
        return self._shim.kv_scan(self._ctx, start, end, limit, ts,
                                  isolation=isolation, desc=desc)


class _DeltaView:
    """DeltaStore-lookalike serving journal windows pulled over the
    wire. The task's own (fill_ts, read_ts] window arrives primed on
    the meta RPC; any other window a consumer asks for — the HBM entry
    may lag or lead the host entry — is one more pull."""

    def __init__(self, shim, ctx, table_id, s, e, index_id):
        self._shim = shim
        self._ctx = ctx
        self._table_id = table_id
        self._s = s
        self._e = e
        self._index_id = index_id
        self._windows: dict = {}    # (s, e, lo, hi) -> pending|None|STALE
        self._index: dict = {}      # (lo, hi) -> bool

    def enabled(self) -> bool:
        return True

    def prime(self, s, e, lo_ts, hi_ts, wire_delta) -> None:
        self._windows[(s, e, lo_ts, hi_ts)] = _decode_wire_delta(wire_delta)

    def prime_index(self, lo_ts, hi_ts, stale) -> None:
        self._index[(lo_ts, hi_ts)] = bool(stale)

    def pending(self, table_id, s, e, lo_ts, hi_ts):
        k = (s, e, lo_ts, hi_ts)
        if k not in self._windows:
            meta = self._shim.journal_window(self._ctx, table_id, s, e,
                                             lo_ts, hi_ts)
            outcome = _pull_outcome(meta, lo_ts)
            metrics.counter(metrics.FLEET_JOURNAL_PULLS,
                            {"outcome": outcome})
            self._windows[k] = _decode_wire_delta(meta.get("delta"))
        return self._windows[k]

    def index_stale(self, table_id, fill_ts, read_ts) -> bool:
        k = (fill_ts, read_ts)
        if k not in self._index:
            meta = self._shim.journal_window(
                self._ctx, table_id, self._s, self._e, fill_ts, read_ts,
                index_id=self._index_id)
            outcome = _pull_outcome(meta, fill_ts)
            metrics.counter(metrics.FLEET_JOURNAL_PULLS,
                            {"outcome": outcome})
            self._index[k] = bool(meta.get("index_stale"))
        return self._index[k]

    def note_base_rows(self, table_id, nrows) -> None:
        # merge-trigger feedback is the store plane's concern; remote
        # base sizes reach it via the scans themselves
        pass

    def patch_chunk(self, cache, key, plan, chunk, pend):
        # the fold itself is pure host-side chunk algebra + per-chunk
        # memoization: borrow the real implementation unbound
        merged = deltamod.DeltaStore.patch_chunk(self, cache, key, plan,
                                                 chunk, pend)
        if merged is not None:
            metrics.counter(metrics.FLEET_PATCHED_ROWS,
                            inc=len(pend.upsert_handles) +
                            len(pend.delete_handles))
        return merged


class _StoreView:
    """The storage-shaped bundle exec_cached_cop consumes: this
    process's caches, the meta-frozen engine view, the wire-backed
    delta view (None when the store plane runs with delta capture
    off — version-bump coherence then applies unchanged)."""

    def __init__(self, storage, engine, dstore):
        self.chunk_cache = storage.chunk_cache
        self.device_cache = getattr(storage, "device_cache", None)
        self.engine = engine
        self.delta_store = dstore


def exec_local(storage, shim, ctx, req):
    """Serve one region cop task from this SQL server's caches, primed
    by a single journal-window RPC. -> (list[CopResponse], s, e) with
    the clamped range (the streaming shim's frame boundary), or None
    when the task is not locally servable (caller executes it on the
    store plane). Typed KV errors (RegionError, KeyLockedError, ...)
    propagate exactly as the remote path raises them."""
    plan = req.plan
    if not config.fleet_local_cache() or \
            not copr.use_cached_path(storage, plan):
        return None
    loc = storage.region_cache.locate(req.ranges[0].start)
    region = loc.region
    if region.id != ctx.region_id or region.version != ctx.version:
        # routing raced a split/reload: the store plane's own epoch
        # check must arbitrate
        return None
    s, e = copr.clamp_range(region, req.ranges[0])
    from tidb_tpu.store.chunk_cache import ChunkCache
    ent = storage.chunk_cache.entry_state(ChunkCache.key(region, plan,
                                                         s, e))
    # prime the pull with the resident entry's own fill snapshot; an
    # entry the freshness predicate would reject anyway (reader older
    # than the fill) gets a meta-only pull
    fill_ts = ent[1] if ent is not None and req.start_ts >= ent[1] \
        else None
    index_id = plan.index.id if plan.index is not None else None
    meta = shim.journal_window(ctx, plan.table.id, s, e, fill_ts,
                               req.start_ts, index_id=index_id)
    outcome = _pull_outcome(meta, fill_ts)
    metrics.counter(metrics.FLEET_JOURNAL_PULLS,
                    {"outcome": outcome})
    dstore = None
    if meta["delta_enabled"]:
        dstore = _DeltaView(shim, ctx, plan.table.id, s, e, index_id)
        if fill_ts is not None:
            if index_id is not None:
                dstore.prime_index(fill_ts, req.start_ts,
                                   meta["index_stale"])
            else:
                dstore.prime(s, e, fill_ts, req.start_ts, meta["delta"])
    view = _StoreView(storage, _EngineView(shim, ctx, meta), dstore)
    out = copr.exec_cached_cop(view, region, plan, s, e, req)
    metrics.counter(metrics.FLEET_LOCAL_COP, {"path": "cached"})
    return out, s, e
