"""On-disk state snapshots for the storage-node process.

Pickle lives HERE, off the wire path: snapshots are trusted local files
this process wrote itself (the same trust domain as the process image),
while everything crossing a socket rides the closed typed contract of
store/wire.py. The `wire-discipline` lint rule (tidb_tpu/lint, see
docs/LINTS.md) pins that split — wire-path modules (wire, remote,
stream, copr, mockstore.rpc) must never import pickle, so a refactor
cannot silently reopen the decode-executes-code hole the typed codec
closed.
"""

from __future__ import annotations

import os
import pickle


def load(path: str):
    """-> (cluster, engine) from a snapshot file written by save()."""
    with open(path, "rb") as f:
        return pickle.load(f)


def save(path: str, cluster, engine) -> None:
    """Atomic write (tmp + rename): a crash mid-save leaves the old
    snapshot intact."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump((cluster, engine), f)
    os.replace(tmp, path)
