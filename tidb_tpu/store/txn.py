"""Snapshot reads, lock resolution, and Percolator 2PC commit.

Reference: /root/reference/store/tikv/ —
  snapshot.go:63-276   per-region batched reads, lock encounters -> resolve
  lock_resolver.go:158 check primary txn status, roll forward/back
  2pc.go:65-697        twoPhaseCommitter: group mutations by region, batch,
                       primary batch first, parallel workers with forked
                       backoffers, async secondary commit, undetermined error
  txn.go               tikvTxn = unionstore + committer
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Iterator, Optional

from tidb_tpu import kv
from tidb_tpu.kv import (IsolationLevel, KeyLockedError, KVError, LockInfo,
                         Mutation, MutationOp, RegionError, NotLeaderError,
                         ServerBusyError, TxnAbortedError, UndeterminedError)
from tidb_tpu.mockstore.rpc import RPCShim, TimeoutError_
from tidb_tpu.store.backoff import (BO_REGION_MISS, BO_SERVER_BUSY,
                                    BO_TXN_LOCK, Backoffer,
                                    COMMIT_MAX_BACKOFF, GET_MAX_BACKOFF,
                                    PREWRITE_MAX_BACKOFF, SCAN_MAX_BACKOFF)
from tidb_tpu.store.region_cache import RegionCache

log = logging.getLogger("tidb_tpu.store")

# ref: 2pc.go txnCommitBatchSize = 16 * 1024 bytes; we batch by key count
COMMIT_BATCH_SIZE = 256
SCAN_BATCH_SIZE = 1024
DEFAULT_LOCK_TTL_MS = 3000
MAX_TXN_TTL_MS = 120_000


def txn_lock_ttl(num_keys: int) -> int:
    """TTL scales with txn size (ref: 2pc.go:185-186)."""
    return min(DEFAULT_LOCK_TTL_MS + num_keys * 2, MAX_TXN_TTL_MS)


class LockResolver:
    """Ref: lock_resolver.go — any reader can resolve a dead writer's locks:
    check the primary's status; expired -> roll the whole txn forward (if the
    primary committed) or back (otherwise)."""

    def __init__(self, shim: RPCShim, cache: RegionCache, oracle):
        self.shim = shim
        self.cache = cache
        self.oracle = oracle
        self._resolved: dict[int, int] = {}  # start_ts -> commit_ts (0=rolled back)
        self._mu = threading.Lock()

    def resolve(self, bo: Backoffer, locks: list[LockInfo]) -> bool:
        """Try to resolve; returns True if all were cleaned (caller may
        retry immediately), False if some lock is still alive (caller backs
        off)."""
        all_cleaned = True
        for lock in locks:
            with self._mu:
                known = self._resolved.get(lock.start_ts)
            if known is None:
                try:
                    status = self._get_txn_status(bo, lock)
                except KeyLockedError:
                    all_cleaned = False  # primary lock still alive
                    continue
                with self._mu:
                    self._resolved[lock.start_ts] = status
                    if len(self._resolved) > 2048:
                        self._resolved.pop(next(iter(self._resolved)))
                known = status
            self._resolve_region_lock(bo, lock, known)
        return all_cleaned

    def _get_txn_status(self, bo: Backoffer, lock: LockInfo) -> int:
        """Cleanup RPC on the primary: returns commit_ts (>0 committed,
        0 rolled back); raises KeyLockedError if still alive."""
        current = self.oracle.get_timestamp()
        while True:
            loc = self.cache.locate(lock.primary)
            try:
                return self.shim.kv_cleanup(loc.ctx, lock.primary,
                                            lock.start_ts, current)
            except RegionError as e:
                self._on_region_err(bo, e, loc.region.id)

    def _resolve_region_lock(self, bo: Backoffer, lock: LockInfo,
                             commit_ts: int) -> None:
        while True:
            loc = self.cache.locate(lock.key)
            try:
                self.shim.kv_resolve_lock(loc.ctx, lock.start_ts, commit_ts)
                return
            except RegionError as e:
                self._on_region_err(bo, e, loc.region.id)

    def _on_region_err(self, bo: Backoffer, e: RegionError, region_id: int):
        if isinstance(e, NotLeaderError):
            self.cache.on_not_leader(e)
        else:
            self.cache.invalidate(region_id)
        bo.backoff(BO_REGION_MISS, e)


class TxnSnapshot(kv.Snapshot):
    """MVCC snapshot at start_ts with region retry + lock resolution.
    Ref: snapshot.go tikvSnapshot."""

    def __init__(self, shim: RPCShim, cache: RegionCache, resolver: LockResolver,
                 ts: int, isolation: IsolationLevel = IsolationLevel.SI,
                 storage=None):
        self.shim = shim
        self.cache = cache
        self.resolver = resolver
        self.ts = ts
        self.isolation = isolation
        self.storage = storage

    # -- retry wrapper -------------------------------------------------------

    def _with_retry(self, bo: Backoffer, key_for_route: bytes, fn):
        """fn(loc) with region-error and lock handling."""
        if self.storage is not None:
            self.storage.check_visibility(self.ts)
        while True:
            loc = self.cache.locate(key_for_route)
            try:
                return fn(loc)
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)
            except KeyLockedError as e:
                cleaned = self.resolver.resolve(bo, [e.lock])
                if not cleaned:
                    bo.backoff(BO_TXN_LOCK, e)

    # -- reads ---------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        bo = Backoffer(GET_MAX_BACKOFF)
        return self._with_retry(
            bo, key,
            lambda loc: self.shim.kv_get(loc.ctx, key, self.ts, self.isolation))

    def batch_get(self, keys: list[bytes]) -> dict[bytes, bytes]:
        """Per-region parallel batches (ref: snapshot.go:95)."""
        out: dict[bytes, bytes] = {}
        pending = list(dict.fromkeys(keys))
        bo = Backoffer(GET_MAX_BACKOFF)
        while pending:
            groups = self.cache.group_keys_by_region(pending)
            pending = []
            for _rid, (loc, ks) in groups.items():
                try:
                    out.update(self.shim.kv_batch_get(
                        loc.ctx, ks, self.ts, self.isolation))
                except NotLeaderError as e:
                    self.cache.on_not_leader(e)
                    bo.backoff(BO_REGION_MISS, e)
                    pending.extend(ks)
                except RegionError as e:
                    self.cache.invalidate(loc.region.id)
                    bo.backoff(BO_REGION_MISS, e)
                    pending.extend(ks)
                except ServerBusyError as e:
                    bo.backoff(BO_SERVER_BUSY, e)
                    pending.extend(ks)
                except KeyLockedError as e:
                    if not self.resolver.resolve(bo, [e.lock]):
                        bo.backoff(BO_TXN_LOCK, e)
                    pending.extend(ks)
        return out

    def iter_range(self, start: bytes | None, end: bytes | None
                   ) -> Iterator[tuple[bytes, bytes]]:
        """Chunked scanner across regions (ref: scan.go Scanner)."""
        cur = start or b""
        end = end or b""
        bo = Backoffer(SCAN_MAX_BACKOFF)
        while True:
            # own retry loop: the region actually answering must supply the
            # continuation point (a stale cached end would skip keys if the
            # region split mid-scan)
            while True:
                loc = self.cache.locate(cur)
                try:
                    batch = self.shim.kv_scan(
                        loc.ctx, cur, end, SCAN_BATCH_SIZE, self.ts,
                        self.isolation)
                    break
                except NotLeaderError as e:
                    self.cache.on_not_leader(e)
                    bo.backoff(BO_REGION_MISS, e)
                except RegionError as e:
                    self.cache.invalidate(loc.region.id)
                    bo.backoff(BO_REGION_MISS, e)
                except ServerBusyError as e:
                    bo.backoff(BO_SERVER_BUSY, e)
                except KeyLockedError as e:
                    if not self.resolver.resolve(bo, [e.lock]):
                        bo.backoff(BO_TXN_LOCK, e)
            yield from batch
            region_end = loc.region.end
            if len(batch) == SCAN_BATCH_SIZE:
                cur = batch[-1][0] + b"\x00"
            elif region_end and (not end or region_end < end):
                cur = region_end  # region exhausted: continue into the next
            else:
                return


# ---------------------------------------------------------------------------
# 2PC

@dataclass
class _Batch:
    loc: object          # KeyLocation
    keys: list


# Set by every committer pool worker at thread start. _on_batches keys
# its no-nested-submit guard on this flag, NOT on the thread's display
# name: a worker that submits sub-batches to its own bounded pool and
# blocks on the results deadlocks once every worker is a blocked parent
# (and the stuck workers then hang interpreter shutdown).
_2PC_WORKER = threading.local()


def _mark_2pc_worker() -> None:
    _2PC_WORKER.flag = True


class TwoPhaseCommitter:
    """Percolator optimistic commit. Ref: 2pc.go twoPhaseCommitter."""

    def __init__(self, shim: RPCShim, cache: RegionCache, oracle,
                 resolver: LockResolver, mutations: dict[bytes, Mutation],
                 start_ts: int, concurrency: int = 8,
                 async_secondaries: bool = True, schema_checker=None):
        self.schema_checker = schema_checker
        self.shim = shim
        self.cache = cache
        self.oracle = oracle
        self.resolver = resolver
        self.mutations = mutations
        self.keys = list(mutations.keys())
        self.start_ts = start_ts
        self.commit_ts = 0
        self.primary = self.keys[0] if self.keys else b""
        self.ttl_ms = txn_lock_ttl(len(self.keys))
        self.concurrency = concurrency
        self.async_secondaries = async_secondaries
        self.undetermined = False
        self._pool = ThreadPoolExecutor(max_workers=concurrency,
                                        thread_name_prefix="2pc",
                                        initializer=_mark_2pc_worker)

    # -- batching ------------------------------------------------------------

    def _group(self, keys: list[bytes]) -> list[_Batch]:
        """Group by region then split into size-capped batches; the batch
        containing the primary key goes first (ref: doActionOnKeys
        2pc.go:192-236)."""
        groups = self.cache.group_keys_by_region(keys)
        batches: list[_Batch] = []
        for _rid, (loc, ks) in groups.items():
            for i in range(0, len(ks), COMMIT_BATCH_SIZE):
                batches.append(_Batch(loc, ks[i:i + COMMIT_BATCH_SIZE]))
        batches.sort(key=lambda b: 0 if self.primary in b.keys else 1)
        return batches

    def _on_batches(self, bo: Backoffer, keys: list[bytes], action,
                    primary_first: bool) -> None:
        """Run `action(bo, batch)` over batches; primary batch runs alone
        first, the rest in parallel with forked backoffers and first-error
        cancel (ref: doActionOnBatches 2pc.go:239-305)."""
        if not keys:
            return
        batches = self._group(keys)
        if primary_first and batches and self.primary in batches[0].keys:
            action(bo, batches[0])
            batches = batches[1:]
        if not batches:
            return
        if len(batches) == 1:
            action(bo, batches[0])
            return
        first_err = None
        if getattr(_2PC_WORKER, "flag", False):
            # Already on a pool worker (async secondaries, or a
            # RegionError re-split inside a batch action): fan out
            # inline. Submitting to the same bounded pool and blocking
            # on the results deadlocks once every worker is a blocked
            # parent — the queued children then never run, and the
            # stuck workers hang interpreter shutdown.
            for b in batches:
                try:
                    action(bo.fork(), b)
                except Exception as e:  # noqa: BLE001 - propagate first error
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
            return
        futures = [self._pool.submit(action, bo.fork(), b) for b in batches]
        for f in futures:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001 - propagate first error
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    # -- actions -------------------------------------------------------------

    def _prewrite_batch(self, bo: Backoffer, batch: _Batch) -> None:
        muts = [self.mutations[k] for k in batch.keys]
        while True:
            loc = self.cache.locate(batch.keys[0])
            try:
                self.shim.kv_prewrite(loc.ctx, muts, self.primary,
                                      self.start_ts, self.ttl_ms)
                return
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                # region changed: re-split this batch (ref: 2pc.go:319-355)
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                self._on_batches(bo, batch.keys, self._prewrite_batch, False)
                return
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)
            except KeyLockedError as e:
                if not self.resolver.resolve(bo, [e.lock]):
                    bo.backoff(BO_TXN_LOCK, e)

    def _commit_batch(self, bo: Backoffer, batch: _Batch) -> None:
        is_primary = self.primary in batch.keys
        while True:
            loc = self.cache.locate(batch.keys[0])
            try:
                self.shim.kv_commit(loc.ctx, batch.keys, self.start_ts,
                                    self.commit_ts)
                return
            except TimeoutError_ as e:
                if is_primary:
                    # outcome unknown: surface undetermined (2pc.go:421-431)
                    self.undetermined = True
                    raise UndeterminedError(str(e)) from e
                raise
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                self._on_batches(bo, batch.keys, self._commit_batch, False)
                return
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)

    def _cleanup_batch(self, bo: Backoffer, batch: _Batch) -> None:
        while True:
            loc = self.cache.locate(batch.keys[0])
            try:
                self.shim.kv_batch_rollback(loc.ctx, batch.keys,
                                            self.start_ts)
                return
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                self._on_batches(bo, batch.keys, self._cleanup_batch, False)
                return

    # -- protocol ------------------------------------------------------------

    def execute(self) -> int:
        """Prewrite all -> get commit ts -> commit primary -> commit
        secondaries (async by default). Returns commit_ts.
        Ref: 2pc.go execute()."""
        if not self.keys:
            return self.start_ts
        try:
            bo = Backoffer(PREWRITE_MAX_BACKOFF)
            self._on_batches(bo, self.keys, self._prewrite_batch,
                             primary_first=False)
        except Exception:
            self._cleanup_async()
            raise
        self.commit_ts = self.oracle.get_timestamp()
        if self.schema_checker is not None:
            # revalidate the schema lease between prewrite and the point of
            # no return (ref: 2pc.go:653 checkSchemaValid)
            try:
                self.schema_checker()
            except Exception:
                self._cleanup_async()
                raise
        cbo = Backoffer(COMMIT_MAX_BACKOFF)
        try:
            self._on_batches(cbo, [self.primary], self._commit_batch,
                             primary_first=True)
        except UndeterminedError:
            raise
        except Exception:
            self._cleanup_async()
            raise
        secondaries = [k for k in self.keys if k != self.primary]
        if secondaries:
            if self.async_secondaries:
                # ref: 2pc.go:224-231 commit secondaries in background
                self._pool.submit(self._commit_secondaries, secondaries)
            else:
                self._commit_secondaries(secondaries)
        return self.commit_ts

    def _commit_secondaries(self, keys: list[bytes]) -> None:
        try:
            bo = Backoffer(COMMIT_MAX_BACKOFF)
            self._on_batches(bo, keys, self._commit_batch, primary_first=False)
        except Exception as e:  # noqa: BLE001
            # safe to leave: readers will resolve via the committed primary
            log.warning("async secondary commit failed (resolvable): %s", e)

    def _cleanup_async(self) -> None:
        keys = list(self.keys)

        def run():
            try:
                bo = Backoffer(COMMIT_MAX_BACKOFF)
                self._on_batches(bo, keys, self._cleanup_batch,
                                 primary_first=False)
            except Exception as e:  # noqa: BLE001
                log.warning("2pc cleanup failed (left to resolver): %s", e)

        self._pool.submit(run)

    def close(self):
        self._pool.shutdown(wait=True)


class KVTxn(kv.Transaction):
    """Transaction = UnionStore over a snapshot + 2PC on commit.
    Ref: store/tikv/txn.go tikvTxn."""

    def __init__(self, storage, start_ts: int):
        self.storage = storage
        self.start_ts = start_ts
        self.snapshot = storage.snapshot(start_ts)
        self.us = kv.UnionStore(self.snapshot)
        self.valid = True
        self.committed = False
        # schema-lease check hook, set by the session (ref: kv.Options
        # SchemaLeaseChecker, kv/kv.go:38; checked at 2pc.go:653)
        self.schema_checker = None
        self.related_tables: set[int] = set()
        self.lock_keys: set[bytes] = set()   # SELECT ... FOR UPDATE
        self.for_update = False              # disables optimistic replay

    def get(self, key: bytes) -> Optional[bytes]:
        return self.us.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self.us.set(key, value)

    def delete(self, key: bytes) -> None:
        self.us.delete(key)

    def iter_range(self, start, end):
        return self.us.iter_range(start, end)

    def presume_not_exists(self, key: bytes) -> None:
        self.us.presumed_not_exists.add(key)

    def lock_key(self, key: bytes) -> None:
        """SELECT ... FOR UPDATE: buffer a prewrite-only LOCK on the row
        key (ref: Txn.LockKeys, executor/executor.go:389 SelectLockExec).
        Commit conflicts if another txn wrote the key after start_ts."""
        self.lock_keys.add(key)
        self.for_update = True

    def mutations(self) -> dict[bytes, Mutation]:
        """Walk the membuffer into 2PC mutations (ref: 2pc.go:118-158)."""
        muts: dict[bytes, Mutation] = {}
        for k, v in self.us.membuf.items():
            if v is kv._TOMBSTONE:
                muts[k] = Mutation(MutationOp.DELETE, k)
            else:
                muts[k] = Mutation(MutationOp.PUT, k, v)
        for k in self.lock_keys:
            if k not in muts:     # a real write supersedes the lock
                muts[k] = Mutation(MutationOp.LOCK, k)
        return muts

    def commit(self) -> None:
        if not self.valid:
            raise KVError("txn invalid")
        self.valid = False
        muts = self.mutations()
        if not muts:
            self.committed = True
            return
        committer = TwoPhaseCommitter(
            self.storage.shim, self.storage.region_cache, self.storage.oracle,
            self.storage.resolver, muts, self.start_ts,
            async_secondaries=self.storage.async_commit_secondaries,
            schema_checker=self.schema_checker)
        try:
            committer.execute()
            self.committed = True
            pump = getattr(self.storage, "binlog_pump", None)
            if pump is not None:
                # change capture on commit success (ref: binloginfo pump
                # hook, 2pc.go:664 — prewrite payload + commit record,
                # collapsed into one event here). Sinks never fail txns.
                from tidb_tpu.binlog import make_event
                try:
                    ev = make_event(self.start_ts, committer.commit_ts,
                                    muts)
                    if ev is not None:
                        pump.write(ev)
                except Exception:   # noqa: BLE001
                    pass
        finally:
            if not self.storage.async_commit_secondaries:
                committer.close()

    def rollback(self) -> None:
        self.valid = False
